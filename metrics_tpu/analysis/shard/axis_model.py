"""The axis/placement model: which mesh axes are bound where, and what flows in.

Phase A parses every module of the analyzed tree into a
:class:`ShardModuleModel`: the function index (methods and nested defs), the
import table, the class index, and the module-level assignment table (so a
module-level ``MESH = jax.make_mesh((8,), ("data",))`` resolves as a mapped
entry's mesh) — the same skeleton tmown builds, but the per-function pass here
collects *SPMD facts* instead of a provenance walk:

- mapped entries: ``shard_map``/``pmap``/``jax.vmap(..., axis_name=)`` launch
  sites and decorated bodies, with their bound axis names and per-parameter
  in-spec axes when the mesh / specs are statically resolvable;
- collective sites: ``psum``/``pmean``/``pmax``/``pmin``/``all_gather``/
  ``pvary``/``pcast``/... with the axis argument classified as literal,
  parameter-fed, or opaque;
- placements: ``jax.device_put(x, NamedSharding(mesh, P(...)))`` with the
  normalized spec text, plus every ``PartitionSpec``/``NamedSharding``
  construction and ``.sharding`` read (the mesh-contract evidence);
- donating wrappers with ``in_shardings`` and executable-cache key traffic
  (the TMH-DONATE-RESHARD / TMH-KEY-SHARD inputs);
- replica-divergent host reads (``jax.process_index``, wall clock, host RNG,
  ``jax.devices()``-family) and the local names they taint.

Phase B (:class:`ShardModel`) links the package and runs two fixpoints:

- ``axis_params``: which parameters transitively reach a collective's axis
  slot (so ``sync_array(x, fx, axis_name)`` three calls deep still classifies
  a caller-side literal axis as a *derived* collective site);
- ``bound``: a must-analysis of the axis names guaranteed bound when each
  function runs — mapped bodies are pinned to their entry's axes (or TOP when
  the mesh is dynamic), everything else is the intersection over its callers,
  and a function no mapped context reaches ends at the empty set.  A literal
  collective axis outside its function's bound set is TMH-AXIS-UNBOUND.

``spec_rules.py`` turns the linked model into findings (facts vs policy, the
same split every sibling tier uses).
"""
import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.jitmap import dotted_name

#: collective primitives reached through jax.lax (axis slot: positional index
#: 1 except ``axis_index``, whose only argument is the axis).
_COLLECTIVE_PRIMS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute", "all_to_all",
    "psum_scatter", "pvary", "pcast", "pbroadcast", "axis_index",
}
#: the reduce family: cross-shard combine of the operand (TMH-SPEC-ALGEBRA).
_REDUCE_PRIMS = {"psum", "pmean", "pmax", "pmin"}

#: map-launch callables (last path component).
_MAP_LAUNCHERS = {"shard_map", "pmap", "vmap"}

_TIME_READS = {
    "time", "monotonic", "perf_counter", "time_ns", "monotonic_ns",
    "perf_counter_ns",
}

#: key fields whose text proves the cache key covers placement (TMH-KEY-SHARD).
import re

_KEY_SHARD_RE = re.compile(r"shard|mesh|topo|layout", re.IGNORECASE)


# ------------------------------------------------------------------ records


@dataclass
class ShardEvent:
    """One rule-relevant fact found by the walk (pre-finding)."""

    kind: str  # donate_reshard | key_shard
    path: str
    line: int
    col: int
    symbol: str
    detail: str


@dataclass
class MapEntry:
    """One shard_map/pmap/vmap launch site (decorator or call form)."""

    kind: str  # shard_map | pmap | vmap
    line: int
    #: axis names the entry binds; None when the mesh/axis_name is dynamic
    axes: Optional[FrozenSet[str]]
    #: qualname of the mapped body when it is a package function, else None
    target: Optional[str]
    #: per-positional-parameter in-spec axes (None = spec not a literal P())
    in_spec_axes: Tuple[Optional[FrozenSet[str]], ...] = ()


@dataclass
class CollectiveSite:
    """One collective call (or a derived caller-side wrapper site)."""

    op: str
    line: int
    col: int
    #: literal axis names at the site; None when the axis value is dynamic
    axes: Optional[FrozenSet[str]]
    #: parameter name feeding the axis slot, when the axis is a bare param
    axis_param: Optional[str]
    #: operand (arg 0) when it is a bare parameter name
    operand_param: Optional[str]
    #: every Name appearing in the operand expression (divergence taint check)
    operand_names: FrozenSet[str] = frozenset()
    #: callee qualname for derived wrapper sites (literal axis into axis_param)
    derived_from: Optional[str] = None


@dataclass
class CallFact:
    """One resolved in-package call with per-callee-parameter arg summaries."""

    target_path: str
    target_qual: str
    line: int
    #: callee param -> ("lit", frozenset[str]) | ("name", caller local name)
    args: Dict[str, Tuple[str, object]] = field(default_factory=dict)


@dataclass
class ShardFunc:
    """Per-function facts: identity plus the Phase B analysis output."""

    qualname: str
    modname: str
    path: str
    line: int
    cls: Optional[str]
    params: Tuple[str, ...] = ()
    nested: Tuple[str, ...] = ()  # immediate child def qualnames
    # filled by the walk:
    map_entries: List[MapEntry] = field(default_factory=list)
    collectives: List[CollectiveSite] = field(default_factory=list)
    calls: List[CallFact] = field(default_factory=list)
    divergent_calls: List[Tuple[int, int, str, str]] = field(default_factory=list)
    divergent_names: Set[str] = field(default_factory=set)
    placements: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    spec_ctors: int = 0
    device_puts: int = 0
    touches_sharding: bool = False
    cache_get: bool = False
    cache_store: bool = False
    key_fields: List[str] = field(default_factory=list)
    events: List[ShardEvent] = field(default_factory=list)
    # filled by the link fixpoints:
    is_mapped_body: bool = False
    body_axes: Optional[FrozenSet[str]] = None  # None = dynamic entry (TOP)
    in_spec_axes: Dict[str, Optional[FrozenSet[str]]] = field(default_factory=dict)
    axis_params: Set[str] = field(default_factory=set)
    #: must-bound axis set: None = TOP (unknown/universe), frozenset otherwise
    bound: Optional[FrozenSet[str]] = None

    def key(self) -> Tuple[str, str]:
        return (self.path, self.qualname)


# ------------------------------------------------------------- module model


class ShardModuleModel:
    """Phase A: one file's function index, import table, module assigns."""

    def __init__(self, path: str, modname: str, source: str) -> None:
        self.path = path
        self.modname = modname
        self.short = modname.split(".")[-1]
        self.tree = ast.parse(source)
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, ShardFunc] = {}
        self.classes: Set[str] = set()
        self.module_assigns: Dict[str, ast.expr] = {}
        # imports are collected from the WHOLE tree, not just module scope:
        # the repo routinely does `from metrics_tpu.core import fused as
        # _fused` inside function bodies to break import cycles (fleet.py,
        # serve/*), and those aliases must still resolve cross-module calls
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.imports[local] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = f"{node.module}:{alias.name}"
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Name):
                    self.module_assigns[tgt.id] = stmt.value
        self._walk_defs(self.tree.body, prefix="", cls=None)

    def _walk_defs(self, body: Sequence[ast.stmt], prefix: str, cls: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                args = stmt.args
                params = tuple(
                    a.arg
                    for a in (args.posonlyargs + args.args + args.kwonlyargs)
                ) + tuple(a.arg for a in (args.vararg, args.kwarg) if a)
                nested = tuple(
                    qual + "." + s.name
                    for s in stmt.body
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                )
                self.functions[qual] = ShardFunc(
                    qualname=qual, modname=self.modname, path=self.path,
                    line=stmt.lineno, cls=cls, params=params, nested=nested,
                )
                self._walk_defs(stmt.body, prefix=qual + ".", cls=cls)
            elif isinstance(stmt, ast.ClassDef):
                self.classes.add(stmt.name)
                self._walk_defs(stmt.body, prefix=prefix + stmt.name + ".", cls=stmt.name)

    def find_def(self, qualname: str):
        """Locate the (possibly nested) def node for a dotted qualname."""
        parts = qualname.split(".")
        scope: Sequence[ast.stmt] = self.tree.body
        node = None
        for part in parts:
            node = None
            for stmt in scope:
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                    and stmt.name == part
                ):
                    node = stmt
                    break
            if node is None:
                return None
            scope = node.body
        return node if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None

    # ---- name classification through the import table

    def _base_of(self, name: str) -> str:
        return name.split(".")[0]

    def is_lax_prim(self, name: str) -> bool:
        """Whether ``name`` denotes a jax.lax collective primitive."""
        last = name.split(".")[-1]
        if last not in _COLLECTIVE_PRIMS:
            return False
        if name.startswith("jax.lax."):
            return True
        base = self._base_of(name)
        imported = self.imports.get(base, "")
        if "." in name:
            # lax.psum with `from jax import lax` / `import jax.lax as lax`
            return imported in ("jax.lax",) or imported == "jax:lax"
        # bare psum with `from jax.lax import psum`
        return imported == f"jax.lax:{last}"

    def is_map_launcher(self, name: str) -> Optional[str]:
        """shard_map/pmap/vmap launcher kind for a callable name, or None."""
        last = name.split(".")[-1]
        if last not in _MAP_LAUNCHERS:
            return None
        if last == "shard_map":
            return "shard_map"  # only jax exports this name in practice
        base = self._base_of(name)
        imported = self.imports.get(base, "")
        if base == "jax" or imported.startswith("jax"):
            return last
        return last if self.imports.get(last, "").startswith("jax") else None

    def is_spec_ctor(self, name: str) -> bool:
        last = name.split(".")[-1]
        if last in ("PartitionSpec", "NamedSharding"):
            return True
        if last == "P":
            imported = self.imports.get("P", "")
            return imported.endswith(":PartitionSpec") or imported == ""
        return False

    def divergent_kind(self, name: str) -> Optional[str]:
        """Classify a call name as a replica-divergent host read, or None."""
        parts = name.split(".")
        base, last = parts[0], parts[-1]
        imported = self.imports.get(base, "")
        if last in ("process_index", "process_count", "host_id"):
            if base == "jax" or imported == "jax" or imported == f"jax:{last}":
                return "process identity"
            return None
        if base == "jax" or imported == "jax":
            if last in ("devices", "local_devices", "device_count", "local_device_count"):
                return "device topology"
            return None
        if last in _TIME_READS and (base == "time" or imported == "time"):
            return "wall clock"
        if last in ("now", "utcnow") and "datetime" in parts:
            return "wall clock"
        if last == "uuid4":
            return "fresh uuid"
        if last in ("getpid", "gethostname"):
            return "host identity"
        if base == "random" and imported in ("", "random") and len(parts) > 1:
            return "host RNG"
        if len(parts) >= 2 and parts[-2] == "random" and (
            base in ("np", "numpy") or imported.startswith("numpy")
        ):
            return "host RNG"
        return None


# ------------------------------------------------------------ package model


class ShardModel:
    """Phase B: linked package + axis_params / bound fixpoints."""

    def __init__(self, files: Dict[str, Tuple[str, str]]) -> None:
        self.modules: Dict[str, ShardModuleModel] = {}
        self.errors: Dict[str, str] = {}
        for path, (modname, source) in files.items():
            try:
                self.modules[path] = ShardModuleModel(path, modname, source)
            except SyntaxError as err:
                self.errors[path] = f"SyntaxError: {err}"
        self.by_modname = {m.modname: m for m in self.modules.values()}
        self.class_index: Dict[str, ShardModuleModel] = {}
        for m in self.modules.values():
            for cls in m.classes:
                self.class_index.setdefault(cls, m)
        self.link()

    def all_functions(self):
        for m in self.modules.values():
            for func in m.functions.values():
                yield m, func

    # ------------------------------------------------------------ resolver

    def resolve_call(
        self, module: ShardModuleModel, symbol: str, caller: ShardFunc
    ) -> Optional[Tuple[ShardModuleModel, ShardFunc]]:
        """Resolve a call symbol to a package function, or None (external)."""
        if symbol.startswith("self."):
            rest = symbol[5:]
            if caller.cls:
                hit = module.functions.get(f"{caller.cls}.{rest}")
                if hit:
                    return module, hit
            return None
        if "." not in symbol:
            prefix = caller.qualname.rsplit(".", 1)[0] + "." if "." in caller.qualname else ""
            for cand in (
                prefix + symbol,
                (caller.cls + "." + symbol) if caller.cls else "",
                symbol,
            ):
                if cand and cand in module.functions:
                    return module, module.functions[cand]
            imported = module.imports.get(symbol)
            if imported and ":" in imported:
                modname, _, name = imported.partition(":")
                other = self.by_modname.get(modname)
                if other and name in other.functions:
                    return other, other.functions[name]
            return None
        base, _, attr = symbol.partition(".")
        imported = module.imports.get(base)
        if imported:
            if ":" in imported:
                mn, _, nm = imported.partition(":")
                sub = self.by_modname.get(f"{mn}.{nm}")
                if sub and attr in sub.functions:
                    return sub, sub.functions[attr]
                if nm in self.class_index:
                    tmod = self.class_index[nm]
                    hit = tmod.functions.get(f"{nm}.{attr.split('.')[-1]}")
                    if hit:
                        return tmod, hit
                return None
            other = self.by_modname.get(imported)
            if other:
                hit = other.functions.get(attr)
                if hit:
                    return other, hit
        if base in self.class_index:
            tmod = self.class_index[base]
            hit = tmod.functions.get(symbol)
            if hit:
                return tmod, hit
        return None

    def find_func(self, path: str, qualname: str) -> Optional[ShardFunc]:
        m = self.modules.get(path)
        return m.functions.get(qualname) if m else None

    # ------------------------------------------------------------- linking

    def link(self) -> None:
        # one raw fact walk per function (no summaries feed back into it)
        for m, func in self.all_functions():
            _AxisWalker(self, m, func).run()
        self._mark_mapped_bodies()
        self._axis_param_fixpoint()
        self._derive_wrapper_sites()
        self._bound_fixpoint()

    def _mark_mapped_bodies(self) -> None:
        """Pin every resolvable mapped body to its entry's axes + in-specs."""
        for m, func in self.all_functions():
            for entry in func.map_entries:
                if entry.target is None:
                    continue
                body = m.functions.get(entry.target)
                if body is None:
                    continue
                body.is_mapped_body = True
                # two entries mapping one body: keep the less-precise axes
                if body.body_axes is not None and body.body_axes != entry.axes:
                    body.body_axes = None
                else:
                    body.body_axes = entry.axes
                offset = 1 if body.params[:1] in (("self",), ("cls",)) else 0
                for i, axes in enumerate(entry.in_spec_axes):
                    if i + offset < len(body.params):
                        p = body.params[i + offset]
                        if p in body.in_spec_axes and body.in_spec_axes[p] != axes:
                            body.in_spec_axes[p] = None
                        else:
                            body.in_spec_axes[p] = axes

    def _callee_of(self, fact: CallFact) -> Optional[ShardFunc]:
        return self.find_func(fact.target_path, fact.target_qual)

    def _axis_param_fixpoint(self) -> None:
        """Params that transitively reach a collective's axis slot."""
        for _m, func in self.all_functions():
            for site in func.collectives:
                if site.axis_param and site.axis_param in func.params:
                    func.axis_params.add(site.axis_param)
        for _ in range(8):
            changed = False
            for _m, func in self.all_functions():
                for fact in func.calls:
                    callee = self._callee_of(fact)
                    if callee is None or not callee.axis_params:
                        continue
                    for p in callee.axis_params:
                        summary = fact.args.get(p)
                        if (
                            summary is not None
                            and summary[0] == "name"
                            and summary[1] in func.params
                            and summary[1] not in func.axis_params
                        ):
                            func.axis_params.add(summary[1])
                            changed = True
            if not changed:
                break

    def _derive_wrapper_sites(self) -> None:
        """A literal axis passed into a callee's axis param is a collective
        site *at the caller* — the caller's bound set governs it."""
        for _m, func in self.all_functions():
            for fact in func.calls:
                callee = self._callee_of(fact)
                if callee is None:
                    continue
                for p in callee.axis_params:
                    summary = fact.args.get(p)
                    if summary is not None and summary[0] == "lit":
                        func.collectives.append(
                            CollectiveSite(
                                op=callee.qualname.split(".")[-1],
                                line=fact.line, col=0,
                                axes=summary[1], axis_param=None,
                                operand_param=None,
                                derived_from=callee.qualname,
                            )
                        )

    def _bound_fixpoint(self) -> None:
        """Must-bound axes: intersection over callers; mapped bodies pinned."""
        callers: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for m, func in self.all_functions():
            for fact in func.calls:
                callee = self._callee_of(fact)
                if callee is not None:
                    callers.setdefault(callee.key(), set()).add(func.key())
            for qual in func.nested:
                child = m.functions.get(qual)
                if child is not None:
                    callers.setdefault(child.key(), set()).add(func.key())
        TOP = None
        bound: Dict[Tuple[str, str], Optional[FrozenSet[str]]] = {}
        for _m, func in self.all_functions():
            bound[func.key()] = func.body_axes if func.is_mapped_body else TOP
        for _ in range(8):
            changed = False
            for _m, func in self.all_functions():
                if func.is_mapped_body:
                    continue
                ins = [bound.get(c, TOP) for c in callers.get(func.key(), ())]
                if not ins:
                    new: Optional[FrozenSet[str]] = frozenset()
                elif all(b is TOP for b in ins):
                    new = TOP
                else:
                    acc: Optional[FrozenSet[str]] = None
                    for b in ins:
                        if b is TOP:
                            continue
                        acc = b if acc is None else (acc & b)
                    new = acc
                if new != bound[func.key()]:
                    bound[func.key()] = new
                    changed = True
            if not changed:
                break
        for _m, func in self.all_functions():
            func.bound = bound[func.key()]

    # -------------------------------------------------------- reachability

    def reachable_from(self, module: ShardModuleModel, qualname: Optional[str]):
        """Functions reachable from an anchor (whole module when qualname is
        None), following resolved calls and lexical nesting."""
        seeds: List[ShardFunc] = []
        if qualname is None:
            seeds = [f for f in module.functions.values()]
        else:
            f = module.functions.get(qualname)
            if f is not None:
                seeds = [f]
        seen: Dict[Tuple[str, str], ShardFunc] = {}
        stack = list(seeds)
        while stack:
            func = stack.pop()
            if func.key() in seen:
                continue
            seen[func.key()] = func
            m = self.modules.get(func.path)
            for qual in func.nested:
                child = m.functions.get(qual) if m else None
                if child is not None:
                    stack.append(child)
            for fact in func.calls:
                callee = self._callee_of(fact)
                if callee is not None:
                    stack.append(callee)
        return list(seen.values())

    def mapped_reachable(self):
        """Functions reachable from any mapped body (traced under a map)."""
        seen: Dict[Tuple[str, str], ShardFunc] = {}
        stack = [f for _m, f in self.all_functions() if f.is_mapped_body]
        while stack:
            func = stack.pop()
            if func.key() in seen:
                continue
            seen[func.key()] = func
            m = self.modules.get(func.path)
            for qual in func.nested:
                child = m.functions.get(qual) if m else None
                if child is not None:
                    stack.append(child)
            for fact in func.calls:
                callee = self._callee_of(fact)
                if callee is not None:
                    stack.append(callee)
        return seen


# ----------------------------------------------------------------- walkers


def _own_nodes(def_node: ast.AST):
    """Every node lexically owned by a def: nested def/class bodies are their
    own functions, but their *decorators* evaluate in this scope."""
    stack = list(ast.iter_child_nodes(def_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            stack.extend(getattr(node, "decorator_list", ()))
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return "<expr>"


def _literal_axes(node: ast.AST) -> Optional[FrozenSet[str]]:
    """Axis names when the expression is a literal str / tuple of strs."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
            elif isinstance(elt, ast.Constant) and elt.value is None:
                continue
            else:
                return None
        return frozenset(out)
    return None


def _parse_donate_positions(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """The donate_argnums value as concrete positions; (0,) when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    if isinstance(node, ast.IfExp):
        for branch in (node.body, node.orelse):
            pos = _parse_donate_positions(branch)
            if pos:
                return pos
        return ()
    return (0,)


class _AxisWalker:
    """One function's fact walk: fills every raw field of its ShardFunc."""

    def __init__(self, model: ShardModel, module: ShardModuleModel, func: ShardFunc) -> None:
        self.model = model
        self.module = module
        self.func = func
        self.node = module.find_def(func.qualname)
        self.assigns: Dict[str, List[ast.expr]] = {}
        #: wrapper name -> (donate positions, per-position in-spec text or None)
        self.wrappers: Dict[str, Tuple[Tuple[int, ...], Dict[int, str]]] = {}
        self.cache_key_nodes: List[ast.AST] = []
        self.placed_arg_uses: List[Tuple[str, ast.AST]] = []

    # ---------------------------------------------------------------- run

    def run(self) -> None:
        if self.node is None:
            return
        f = self.func
        # prepass: local assignment table
        for node in _own_nodes(self.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    self.assigns.setdefault(tgt.id, []).append(node.value)
        # own decorators: a partial(shard_map, ...) on this def marks *itself*
        for deco in getattr(self.node, "decorator_list", ()):
            entry = self._map_entry_of(deco, target=f.qualname)
            if entry is not None:
                f.map_entries.append(entry)
        # main walk, two passes: assignments register wrappers/placements
        # first so an exec site is recognized regardless of lexical order
        for node in _own_nodes(self.node):
            if isinstance(node, ast.Assign):
                self._scan_assign(node)
        for node in _own_nodes(self.node):
            if isinstance(node, ast.Call):
                self._scan_call(node)
            elif isinstance(node, ast.Attribute):
                if node.attr == "sharding":
                    f.touches_sharding = True
        # decorators of nested defs: partial(shard_map, ...) in this scope
        for child in ast.iter_child_nodes(self.node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in child.decorator_list:
                    entry = self._map_entry_of(
                        deco, target=f.qualname + "." + child.name
                    )
                    if entry is not None:
                        f.map_entries.append(entry)
        f.key_fields = self._key_fields()
        self._finish_events()

    # -------------------------------------------------------- assignments

    def _scan_assign(self, node: ast.Assign) -> None:
        f = self.func
        value = node.value
        # cache stores: cache[key] = compiled
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                recv = dotted_name(tgt.value) or ""
                if "cache" in recv.lower():
                    f.cache_store = True
                    self.cache_key_nodes.append(tgt.slice)
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        # replica-divergent taint
        for call in ast.walk(value):
            if isinstance(call, ast.Call):
                cn = dotted_name(call.func) or ""
                if cn and self.module.divergent_kind(cn):
                    f.divergent_names.add(name)
                    break
        # placements: x = jax.device_put(y, <sharding>)
        if isinstance(value, ast.Call):
            vn = dotted_name(value.func) or ""
            if vn.split(".")[-1] == "device_put" and len(value.args) >= 2:
                spec = self._spec_text(value.args[1])
                if spec is not None:
                    f.placements[name] = (spec, value.lineno)
            # donating wrappers: run = jax.jit(step, donate_argnums=..,
            #                                  in_shardings=(...))
            wrapper = self._wrapper_of(value)
            if wrapper is not None:
                self.wrappers[name] = wrapper

    # --------------------------------------------------------------- calls

    def _scan_call(self, call: ast.Call) -> None:
        f = self.func
        name = dotted_name(call.func) or ""
        last = name.split(".")[-1]

        # spec constructions + device_put evidence (contract components)
        if name and self.module.is_spec_ctor(name):
            f.spec_ctors += 1
        if last == "device_put":
            if len(call.args) >= 2 or any(
                kw.arg in ("device", "sharding") for kw in call.keywords
            ):
                f.device_puts += 1
        if last == "getattr" and len(call.args) >= 2:
            key = call.args[1]
            if isinstance(key, ast.Constant) and key.value in ("sharding", "spec"):
                f.touches_sharding = True

        # cache gets
        if isinstance(call.func, ast.Attribute) and call.func.attr == "get":
            recv = dotted_name(call.func.value) or ""
            if "cache" in recv.lower() and call.args:
                f.cache_get = True
                self.cache_key_nodes.append(call.args[0])

        # replica-divergent host reads
        kind = self.module.divergent_kind(name) if name else None
        if kind:
            f.divergent_calls.append((call.lineno, call.col_offset, name, kind))

        # map launches (call form): shard_map(body, mesh=..., in_specs=...)
        launcher = self.module.is_map_launcher(name) if name else None
        if launcher and call.args:
            entry = self._map_entry_from_call(launcher, call)
            if entry is not None:
                f.map_entries.append(entry)

        # collective sites
        if name and self.module.is_lax_prim(name):
            hit = self.model.resolve_call(self.module, name, f)
            if hit is None:  # a real primitive, not a shadowing package def
                f.collectives.append(self._collective_site(last, call))

        # resolved in-package calls -> CallFacts
        if name and not name.startswith(("jax.", "jnp.", "np.", "numpy.")):
            hit = self.model.resolve_call(self.module, name, f)
            if hit is not None:
                f.calls.append(self._call_fact(hit[1], call))

        # donating executions of known wrappers: run(x, ...)
        if isinstance(call.func, ast.Name) and call.func.id in self.wrappers:
            self._check_donate_reshard(call, self.wrappers[call.func.id])
        elif isinstance(call.func, ast.Call):
            wrapper = self._wrapper_of(call.func)
            if wrapper is not None:
                self._check_donate_reshard(call, wrapper)

        # placed arrays flowing into any call (TMH-KEY-SHARD evidence)
        for arg in call.args:
            if isinstance(arg, ast.Name) and arg.id in f.placements:
                self.placed_arg_uses.append((arg.id, arg))

    def _collective_site(self, op: str, call: ast.Call) -> CollectiveSite:
        f = self.func
        axis_node: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axes"):
                axis_node = kw.value
        if axis_node is None:
            idx = 0 if op == "axis_index" else 1
            if idx < len(call.args):
                axis_node = call.args[idx]
        axes = _literal_axes(axis_node) if axis_node is not None else None
        axis_param: Optional[str] = None
        if axes is None and isinstance(axis_node, ast.Name):
            if axis_node.id in f.params:
                axis_param = axis_node.id
            else:
                for value in self.assigns.get(axis_node.id, ()):
                    lit = _literal_axes(value)
                    if lit is not None:
                        axes = lit
                        break
        operand = call.args[0] if (call.args and op != "axis_index") else None
        operand_param = (
            operand.id
            if isinstance(operand, ast.Name) and operand.id in f.params
            else None
        )
        return CollectiveSite(
            op=op, line=call.lineno, col=call.col_offset,
            axes=axes, axis_param=axis_param, operand_param=operand_param,
            operand_names=frozenset(_names_in(operand)) if operand is not None else frozenset(),
        )

    def _call_fact(self, callee: ShardFunc, call: ast.Call) -> CallFact:
        offset = 1 if callee.params[:1] in (("self",), ("cls",)) else 0
        args: Dict[str, Tuple[str, object]] = {}

        def summarize(node: ast.AST) -> Optional[Tuple[str, object]]:
            lit = _literal_axes(node)
            if lit is not None:
                return ("lit", lit)
            if isinstance(node, ast.Name):
                if node.id in self.func.params:
                    return ("name", node.id)
                for value in self.assigns.get(node.id, ()):
                    vlit = _literal_axes(value)
                    if vlit is not None:
                        return ("lit", vlit)
            return None

        for i, arg in enumerate(call.args):
            if i + offset < len(callee.params):
                s = summarize(arg)
                if s is not None:
                    args[callee.params[i + offset]] = s
        for kw in call.keywords:
            if kw.arg:
                s = summarize(kw.value)
                if s is not None:
                    args[kw.arg] = s
        return CallFact(
            target_path=callee.path, target_qual=callee.qualname,
            line=call.lineno, args=args,
        )

    # -------------------------------------------------------- map entries

    def _map_entry_of(self, deco: ast.AST, target: str) -> Optional[MapEntry]:
        """partial(shard_map, mesh=..., in_specs=...) / jax.pmap(...) deco."""
        if not isinstance(deco, ast.Call):
            return None
        name = dotted_name(deco.func) or ""
        last = name.split(".")[-1]
        if last == "partial" and deco.args:
            inner = dotted_name(deco.args[0]) or ""
            launcher = self.module.is_map_launcher(inner) if inner else None
            if launcher is None:
                return None
            return self._entry(launcher, deco, target, lineno=deco.lineno)
        launcher = self.module.is_map_launcher(name) if name else None
        if launcher is not None:
            return self._entry(launcher, deco, target, lineno=deco.lineno)
        return None

    def _map_entry_from_call(self, launcher: str, call: ast.Call) -> Optional[MapEntry]:
        body = call.args[0]
        target: Optional[str] = None
        if isinstance(body, ast.Name):
            hit = self.model.resolve_call(self.module, body.id, self.func)
            if hit is not None and hit[0] is self.module:
                target = hit[1].qualname
        entry = self._entry(launcher, call, target, lineno=call.lineno)
        return entry

    def _entry(
        self, launcher: str, call: ast.Call, target: Optional[str], lineno: int
    ) -> Optional[MapEntry]:
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        axes: Optional[FrozenSet[str]] = None
        if launcher == "shard_map":
            mesh = kw.get("mesh")
            if mesh is None and len(call.args) >= 2:
                mesh = call.args[1]  # shard_map(f, mesh, in_specs, out_specs)
            if mesh is not None:
                axes = self._mesh_axes(mesh)
            if "in_specs" not in kw and len(call.args) >= 3:
                kw["in_specs"] = call.args[2]
        else:
            axis_name = kw.get("axis_name")
            if axis_name is not None:
                axes = _literal_axes(axis_name)
            elif launcher == "vmap":
                return None  # positional vmap without axis_name binds nothing
        in_specs = kw.get("in_specs")
        spec_axes: List[Optional[FrozenSet[str]]] = []
        if in_specs is not None:
            elts = (
                list(in_specs.elts)
                if isinstance(in_specs, (ast.Tuple, ast.List))
                else [in_specs]
            )
            for elt in elts:
                spec_axes.append(self._p_axes(elt))
        return MapEntry(
            kind=launcher, line=lineno, axes=axes, target=target,
            in_spec_axes=tuple(spec_axes),
        )

    def _p_axes(self, node: ast.AST) -> Optional[FrozenSet[str]]:
        """Axis names in a literal P(...)/PartitionSpec(...) expression."""
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if self.module.is_spec_ctor(name) and name.split(".")[-1] != "NamedSharding":
                out: Set[str] = set()
                for arg in node.args:
                    lit = _literal_axes(arg)
                    if lit is None and not (
                        isinstance(arg, ast.Constant) and arg.value is None
                    ):
                        return None
                    out |= lit or set()
                return frozenset(out)
        return None

    def _mesh_axes(self, node: ast.AST, depth: int = 0) -> Optional[FrozenSet[str]]:
        """Axis names of a mesh expression, through <=2 levels of local or
        module-level assignment; None when the mesh is dynamic."""
        if depth > 2:
            return None
        if isinstance(node, ast.Name):
            for value in self.assigns.get(node.id, ()):
                axes = self._mesh_axes(value, depth + 1)
                if axes is not None:
                    return axes
            mod_value = self.module.module_assigns.get(node.id)
            if mod_value is not None:
                return self._mesh_axes(mod_value, depth + 1)
            return None
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            last = name.split(".")[-1]
            if last in ("make_mesh", "Mesh") and len(node.args) >= 2:
                return _literal_axes(node.args[1])
            if last == "make_data_mesh":
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        return _literal_axes(kw.value)
                return frozenset({"data"})
        return None

    # ------------------------------------------- wrappers / donate-reshard

    def _wrapper_of(
        self, expr: ast.AST
    ) -> Optional[Tuple[Tuple[int, ...], Dict[int, str]]]:
        """(donate positions, {position: in-spec text}) for a jax.jit call
        with donate_argnums, following .lower/.compile chains."""
        if isinstance(expr, ast.Name):
            return self.wrappers.get(expr.id)
        if not isinstance(expr, ast.Call):
            return None
        fn = expr.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("lower", "compile"):
            return self._wrapper_of(fn.value)
        name = dotted_name(fn) or ""
        if name.split(".")[-1] != "jit":
            return None
        donate: Optional[Tuple[int, ...]] = None
        specs: Dict[int, str] = {}
        for kw in expr.keywords:
            if kw.arg == "donate_argnums":
                donate = _parse_donate_positions(kw.value)
            elif kw.arg in ("in_shardings", "in_specs"):
                elts = (
                    list(kw.value.elts)
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value]
                )
                for i, elt in enumerate(elts):
                    text = self._spec_text(elt)
                    if text is not None:
                        specs[i] = text
        if not donate:
            return None
        return donate, specs

    def _spec_text(self, node: ast.AST, depth: int = 0) -> Optional[str]:
        """Normalized text of the P(...) inside a sharding expression."""
        if depth > 2:
            return None
        if isinstance(node, ast.Name):
            for value in self.assigns.get(node.id, ()):
                text = self._spec_text(value, depth + 1)
                if text is not None:
                    return text
            mod_value = self.module.module_assigns.get(node.id)
            if mod_value is not None:
                return self._spec_text(mod_value, depth + 1)
            return None
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            last = name.split(".")[-1]
            if last == "NamedSharding" and len(node.args) >= 2:
                return self._spec_text(node.args[1], depth + 1)
            if self.module.is_spec_ctor(name) and last != "NamedSharding":
                return _safe_unparse(node).replace(" ", "").replace(
                    "PartitionSpec(", "P("
                )
        return None

    def _check_donate_reshard(
        self, call: ast.Call, wrapper: Tuple[Tuple[int, ...], Dict[int, str]]
    ) -> None:
        positions, specs = wrapper
        for pos in positions:
            if pos >= len(call.args) or any(
                isinstance(a, ast.Starred) for a in call.args[: pos + 1]
            ):
                continue
            arg = call.args[pos]
            if not isinstance(arg, ast.Name) or arg.id not in self.func.placements:
                continue
            placed_spec, _line = self.func.placements[arg.id]
            wrapper_spec = specs.get(pos)
            if wrapper_spec is not None and wrapper_spec != placed_spec:
                self.func.events.append(
                    ShardEvent(
                        "donate_reshard", self.func.path, arg.lineno,
                        arg.col_offset, self.func.qualname,
                        f"`{arg.id}` is placed {placed_spec} but donated into a"
                        f" launch whose in-spec is {wrapper_spec}; XLA inserts a"
                        " resharding copy, so the donation frees nothing",
                    )
                )

    # ------------------------------------------------------------- finish

    def _key_fields(self) -> List[str]:
        """Cache-key tuple components with one level of name expansion."""
        for node in self.cache_key_nodes:
            tup = node
            if isinstance(node, ast.Name):
                for value in self.assigns.get(node.id, ()):
                    if isinstance(value, ast.Tuple):
                        tup = value
                        break
            if not isinstance(tup, ast.Tuple):
                continue
            fields: List[str] = []
            for elt in tup.elts:
                if isinstance(elt, ast.Name) and elt.id in self.assigns:
                    alts = " | ".join(
                        sorted({_safe_unparse(v) for v in self.assigns[elt.id]})
                    )
                    fields.append(f"{elt.id} := {alts}")
                else:
                    fields.append(_safe_unparse(elt))
            return fields
        return []

    def _finish_events(self) -> None:
        """TMH-KEY-SHARD: a cached launch consumes placed arrays, but no key
        component covers their sharding/mesh/topology."""
        f = self.func
        if not self.cache_key_nodes or not self.placed_arg_uses:
            return
        key_text = " ".join(f.key_fields) or " ".join(
            _safe_unparse(n) for n in self.cache_key_nodes
        )
        if _KEY_SHARD_RE.search(key_text):
            return
        name, node = self.placed_arg_uses[0]
        f.events.append(
            ShardEvent(
                "key_shard", f.path, node.lineno, node.col_offset,
                f"{f.qualname}.sharding",
                f"cache key ({key_text}) has no sharding/mesh facet but the"
                f" launch consumes placed array `{name}`; a mesh or placement"
                " change replays a stale executable",
            )
        )


def build_model(files: Dict[str, Tuple[str, str]]) -> ShardModel:
    """Build the linked axis/placement model for ``load_package`` output."""
    return ShardModel(files)
