"""Jaxpr-walking rules: TMS-CALLBACK, TMS-F64, TMS-UPCAST, TMS-BIGCONST,
TMS-COLLECTIVE.

These operate on the ground truth the AST tier approximates: the closed jaxpr
of a metric's ``local_update``/``compute_from`` traced under abstract inputs.
Every equation of every nested sub-jaxpr (pjit bodies, cond branches, scan
bodies, custom_jvp calls) is visited; findings are attributed to repo source
via jax's per-equation ``source_info`` when a user frame inside the repo
exists, else to the metric entry that was traced.
"""
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Set, Tuple

import numpy as np

from metrics_tpu.analysis.findings import Finding

#: host-callback primitives — device-pure graphs must not contain these
CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback", "debug_callback"})
#: named-axis collectives — unreachable from a correct single-host trace
COLLECTIVE_PRIMS = frozenset(
    {
        "psum", "pmax", "pmin", "pmean", "ppermute", "pbroadcast", "pgather",
        "all_gather", "all_to_all", "reduce_scatter", "psum_scatter", "axis_index",
    }
)
#: constants at or above this size are "baked in" findings (per-executable HBM)
BIGCONST_BYTES = 1 << 16  # 64 KiB

_WIDE_FLOATS = ("float64", "complex128")
_NARROW_FLOATS = ("bfloat16", "float16")


@dataclass
class TraceAnchor:
    """Where findings for one traced entry are pinned (waiver-stable symbol)."""

    path: str  # repo-relative defining file of the traced entry
    line: int
    symbol: str  # "ClassName.update" / "ops.binary_auroc_exact"


@dataclass
class GraphFacts:
    """Everything one walk of a closed jaxpr extracts (rules + crosscheck)."""

    #: (primitive_name, repo_path, line, function_name) for callback eqns;
    #: path may be "" when no repo frame exists
    callbacks: List[Tuple[str, str, int, str]] = field(default_factory=list)
    #: (primitive_name, axis_names, repo_path, line) for collective eqns
    collectives: List[Tuple[str, str, str, int]] = field(default_factory=list)
    #: dtype-offending avals: (dtype_str, repo_path, line, prim)
    f64s: List[Tuple[str, str, int, str]] = field(default_factory=list)
    #: (nbytes, shape, dtype_str) for big consts/literals
    bigconsts: List[Tuple[int, Tuple[int, ...], str]] = field(default_factory=list)
    #: every repo (path, line) any equation's user stack touches — the traced
    #: source footprint crosscheck.py corroborates TM-HOSTSYNC waivers against
    footprint: Set[Tuple[str, int]] = field(default_factory=set)


def _iter_jaxprs(jaxpr) -> Iterator[Any]:
    """The jaxpr plus every nested sub-jaxpr reachable through eqn params."""
    try:
        from jax._src import core as jcore
    except ImportError:  # pragma: no cover — fallback for jax layout changes
        import jax.core as jcore

    seen: Set[int] = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                for sub in _as_jaxprs(val, jcore):
                    stack.append(sub)


def _as_jaxprs(val: Any, jcore) -> Iterable[Any]:
    if isinstance(val, jcore.Jaxpr):
        yield val
    elif isinstance(val, jcore.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _as_jaxprs(v, jcore)


def _iter_consts(closed_jaxpr) -> Iterator[Any]:
    """Consts of the closed jaxpr and of every nested closed sub-jaxpr."""
    try:
        from jax._src import core as jcore
    except ImportError:  # pragma: no cover
        import jax.core as jcore

    seen: Set[int] = set()
    stack = [closed_jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield from getattr(j, "consts", ())
        core_j = getattr(j, "jaxpr", j)
        for eqn in getattr(core_j, "eqns", ()):
            for val in eqn.params.values():
                vals = val if isinstance(val, (tuple, list)) else [val]
                for v in vals:
                    if isinstance(v, jcore.ClosedJaxpr):
                        stack.append(v)


def _repo_frames(eqn, repo_root: str) -> List[Tuple[str, int, str]]:
    """(repo_relative_path, line, function_name) user frames for one equation."""
    from jax._src import source_info_util

    out: List[Tuple[str, int, str]] = []
    try:
        frames = source_info_util.user_frames(eqn.source_info)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return out
    for fr in frames:
        fname = getattr(fr, "file_name", "") or ""
        if not fname.startswith(repo_root):
            continue
        rel = os.path.relpath(fname, repo_root).replace(os.sep, "/")
        out.append((rel, int(getattr(fr, "start_line", 0) or 0), getattr(fr, "function_name", "") or ""))
    return out


def _axis_names(params: Dict[str, Any]) -> str:
    for key in ("axes", "axis_name", "named_axes"):
        if key in params and params[key]:
            val = params[key]
            if isinstance(val, (tuple, list)):
                names = [str(v) for v in val if isinstance(v, (str,)) or v is not None]
                if names:
                    return ",".join(names)
            else:
                return str(val)
    return ""


def collect_graph_facts(closed_jaxpr, repo_root: str, *, footprint: bool = True) -> GraphFacts:
    """One walk over every (nested) equation of a traced entry."""
    facts = GraphFacts()
    core_jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    # big consts captured by the closure (the canonical BIGCONST source) —
    # including consts of nested closed jaxprs (pjit usually hoists them to
    # the top level, but custom primitives may not)
    for const in _iter_consts(closed_jaxpr):
        nbytes = getattr(const, "nbytes", None)
        if nbytes is not None and nbytes >= BIGCONST_BYTES:
            arr = np.asarray(const) if not hasattr(const, "dtype") else const
            facts.bigconsts.append((int(nbytes), tuple(arr.shape), str(arr.dtype)))

    for j in _iter_jaxprs(core_jaxpr):
        for var in getattr(j, "constvars", ()):
            dt = str(getattr(getattr(var, "aval", None), "dtype", ""))
            if dt in _WIDE_FLOATS:
                facts.f64s.append((dt, "", 0, "constvar"))
        for eqn in j.eqns:
            prim = eqn.primitive.name
            frames = _repo_frames(eqn, repo_root) if footprint else []
            facts.footprint.update((p, ln) for p, ln, _ in frames)
            top = frames[0] if frames else ("", 0, "")

            if prim in CALLBACK_PRIMS:
                facts.callbacks.append((prim, top[0], top[1], top[2]))
            if prim in COLLECTIVE_PRIMS:
                facts.collectives.append((prim, _axis_names(eqn.params), top[0], top[1]))

            for var in eqn.outvars:
                dt = str(getattr(getattr(var, "aval", None), "dtype", ""))
                if dt in _WIDE_FLOATS:
                    facts.f64s.append((dt, top[0], top[1], prim))
                    break  # one report per equation is enough

            for invar in eqn.invars:
                val = getattr(invar, "val", None)  # Literal operands
                if val is None:
                    continue
                dt = str(getattr(val, "dtype", ""))
                if dt in _WIDE_FLOATS:
                    facts.f64s.append((dt, top[0], top[1], prim))
                nbytes = getattr(val, "nbytes", 0)
                if nbytes and nbytes >= BIGCONST_BYTES:
                    facts.bigconsts.append((int(nbytes), tuple(np.shape(val)), dt or "?"))
    return facts


# ---------------------------------------------------------------------------
# findings from facts
# ---------------------------------------------------------------------------

def _mk(rule: str, anchor: TraceAnchor, path: str, line: int, message: str) -> Finding:
    return Finding(
        rule=rule,
        path=path or anchor.path,
        line=line or anchor.line,
        col=0,
        symbol=anchor.symbol,
        message=message,
    )


def findings_from_facts(facts: GraphFacts, anchor: TraceAnchor, case: str) -> List[Finding]:
    out: List[Finding] = []
    for prim, path, line, func in facts.callbacks:
        where = f" (host code: {func})" if func else ""
        out.append(
            _mk(
                "TMS-CALLBACK",
                anchor,
                path,
                line,
                f"`{prim}` equation in the traced graph of {anchor.symbol} [{case}]{where}: "
                "the compiled program round-trips to the host on EVERY execution",
            )
        )
    for prim, axes, path, line in facts.collectives:
        ax = f" over axis `{axes}`" if axes else ""
        out.append(
            _mk(
                "TMS-COLLECTIVE",
                anchor,
                path,
                line,
                f"collective `{prim}`{ax} reachable from the single-host trace of "
                f"{anchor.symbol} [{case}]: unbound axes deadlock under real sharding — "
                "collectives belong in sync_state/compute_from(axis_name=...)",
            )
        )
    for dt, path, line, prim in facts.f64s:
        out.append(
            _mk(
                "TMS-F64",
                anchor,
                path,
                line,
                f"{dt} value (primitive `{prim}`) in the traced graph of {anchor.symbol} "
                f"[{case}] without explicit x64 intent: 2x HBM and emulated arithmetic on TPU",
            )
        )
    for nbytes, shape, dt in facts.bigconsts:
        out.append(
            _mk(
                "TMS-BIGCONST",
                anchor,
                "",
                0,
                f"constant {dt}{list(shape)} ({nbytes} B >= {BIGCONST_BYTES} B) baked into the "
                f"jaxpr of {anchor.symbol} [{case}]: costs HBM per compiled executable and is "
                "re-materialized on every retrace — pass it as a traced operand or build it on device",
            )
        )
    # one finding per (rule, message) — the same hazard at two shapes is one triage
    seen: Set[Tuple[str, str]] = set()
    unique: List[Finding] = []
    for f in out:
        k = (f.rule, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique


def upcast_findings(
    in_state: Dict[str, Any],
    out_state: Dict[str, Any],
    anchor: TraceAnchor,
    case: str,
) -> List[Finding]:
    """TMS-UPCAST: compare declared (input) vs produced (output) state dtypes.

    Consumes the ``jax.eval_shape`` result of the bf16 trace variant: a state
    leaf that enters update as bf16/f16 and leaves as f32/f64 breaks the
    dtype half of the state contract (ckpt manifests validate it).
    """
    import jax

    out: List[Finding] = []
    in_leaves = dict(_leaves_by_path(in_state, jax))
    for key, leaf_out in _leaves_by_path(out_state, jax):
        leaf_in = in_leaves.get(key)
        if leaf_in is None:
            continue
        din = str(getattr(leaf_in, "dtype", ""))
        dout = str(getattr(leaf_out, "dtype", ""))
        if din in _NARROW_FLOATS and dout not in _NARROW_FLOATS and dout.startswith(("float", "complex")):
            out.append(
                _mk(
                    "TMS-UPCAST",
                    anchor,
                    "",
                    0,
                    f"state `{key}` enters update as {din} but leaves as {dout} [{case}]: "
                    "a strongly-typed wide constant in the accumulation promotes the "
                    "declared state dtype (2x HBM; ckpt DtypeDrift on restore). Use weak "
                    "python scalars or cast back with .astype(<state>.dtype)",
                )
            )
    return out


def _leaves_by_path(tree: Any, jax) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out.append((key, leaf))
    return out
