"""metrics_tpu.analysis.san — **tmsan**, the jaxpr/HLO tier of the analyzer.

tmlint (the AST tier, ``metrics_tpu.analysis``) predicts trace hazards from
source text; its jit-boundary model is an approximation. tmsan gets ground
truth from the tracer and the compiler: every constructible registered Metric's
``update``/``compute`` (and the exact-kernel functional entrypoints in
``ops/``) is traced under abstract ``jax.ShapeDtypeStruct`` inputs at canonical
shapes, the closed jaxprs are walked for rule families the AST cannot decide,
and ``.lower().compile().cost_analysis()`` maintains a checked-in per-metric
compile-cost budget (``tmsan_costs.json``) that fails CI on unexplained >15%
growth — a static perf-regression gate that runs before any benchmark.

==================  =========================================================
rule                what it catches (in the TRACED GRAPH, not the source)
==================  =========================================================
TMS-CALLBACK        pure_callback/io_callback/debug_callback equations
TMS-F64             float64 avals/constants without explicit x64 intent
TMS-UPCAST          bf16/f16 state promoted to a wider dtype by update
TMS-BIGCONST        constants above a byte threshold baked into the jaxpr
TMS-COLLECTIVE      psum/all_gather reachable from a single-host path
TMS-DYNSHAPE        trace failures tmlint should have predicted (verification)
TMS-LINTGAP         callback in a tmlint-clean function (crosscheck)
TMS-STALE-WAIVER    TM-HOSTSYNC waiver contradicted by jaxpr evidence
TMS-BUDGET          compile cost grew >15% over tmsan_costs.json
==================  =========================================================

CLI::

    python -m metrics_tpu.analysis --san               # full two-tier run
    python -m metrics_tpu.analysis --san --write-costs # refresh the budget
    python -m metrics_tpu.analysis --explain TMS-BUDGET

Waivers share ``tmlint_baseline.json`` (same (rule, path, symbol) schema);
obs counters live in the ``san.*`` namespace.
"""
from metrics_tpu.analysis.san.costs import COSTS_FILENAME, load_costs, write_costs
from metrics_tpu.analysis.san.runner import SanReport, run_san

__all__ = ["COSTS_FILENAME", "SanReport", "load_costs", "run_san", "write_costs"]
