"""tmsan orchestration: registry -> abstract traces -> jaxpr rules -> costs ->
crosscheck -> baseline -> report.

The sweep is pure host work: ``jax.make_jaxpr`` under ``ShapeDtypeStruct``
inputs never materializes data, and the cost tier stops at
``.lower().compile()`` — nothing executes. Everything degrades per-entry: a
ctor failure, a missing input spec, or an unexpected trace error becomes a
recorded skip, while *classified* trace failures (concretization / dynamic
shape) become TMS-DYNSHAPE findings — those are exactly what the AST tier
claims cannot happen.
"""
import os
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import SAN_RULES, Finding
from metrics_tpu.analysis.runner import Report, _find_repo_root, analyze
from metrics_tpu.analysis.san import costs as costs_mod
from metrics_tpu.analysis.san.abstract_inputs import SIZES, cases_for, ops_cases
from metrics_tpu.analysis.san.jaxpr_rules import (
    GraphFacts,
    TraceAnchor,
    collect_graph_facts,
    findings_from_facts,
    upcast_findings,
)

#: trace-failure types that are findings (tmlint should have predicted them),
#: matched by exception class NAME so jax version drift cannot break the gate
_DYNSHAPE_ERRORS = (
    "TracerBoolConversionError",
    "TracerArrayConversionError",
    "TracerIntegerConversionError",
    "ConcretizationTypeError",
    "NonConcreteBooleanIndexError",
)


@dataclass
class SanReport:
    """Combined two-tier report: tmlint's AST run + the jaxpr/cost sweep."""

    lint: Optional[Report] = None
    findings: List[Finding] = field(default_factory=list)  # san tier, waived included
    new_findings: List[Finding] = field(default_factory=list)
    unused_waivers: List[Tuple[str, str, str]] = field(default_factory=list)
    #: export name -> traced entry count (update/compute x sizes x cases)
    traced: Dict[str, int] = field(default_factory=dict)
    skipped: Dict[str, str] = field(default_factory=dict)
    costs: Dict[str, Dict[str, float]] = field(default_factory=dict)
    budget_notes: List[str] = field(default_factory=list)
    waiver_status: Dict[str, str] = field(default_factory=dict)
    stats: Dict[str, float] = field(default_factory=dict)

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        lint_new = self.lint.new_findings if self.lint is not None else []
        return 1 if (self.new_findings or lint_new) else 0


def _fresh(inst: Any) -> Any:
    """Per-trace instance isolation: wrapper metrics mutate their (unregistered)
    child metrics during update, so a trace would leak tracers into the shared
    registry instance and poison the next trace. Falls back to the original
    when a metric cannot be deep-copied (the trace then owns the instance)."""
    import copy

    try:
        return copy.deepcopy(inst)
    except Exception:  # noqa: BLE001
        return inst


def _obs_inc(name: str, value: float = 1) -> None:
    from metrics_tpu.obs import registry as _obs

    if _obs._ENABLED:
        _obs.REGISTRY.inc("san", name, value)


def _to_sds(tree: Any):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)), tree
    )


def _bf16_tree(tree: Any):
    import jax
    import jax.numpy as jnp

    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype in (jnp.float32, jnp.float64):
            return jax.ShapeDtypeStruct(x.shape, jnp.bfloat16)
        return x

    return jax.tree_util.tree_map(cast, tree)


def _has_narrow_or_float_state(state_sds: Any) -> bool:
    import jax
    import jax.numpy as jnp

    return any(
        jnp.issubdtype(leaf.dtype, jnp.floating)
        for leaf in jax.tree_util.tree_leaves(state_sds)
    )


def _method_anchor(cls: type, method: str, repo_root: str) -> Optional[TraceAnchor]:
    import inspect

    from metrics_tpu.core.metric import Metric

    for base in cls.__mro__:
        if base is Metric or method not in base.__dict__:
            continue
        fn = base.__dict__[method]
        try:
            path = inspect.getsourcefile(fn)
            _, line = inspect.getsourcelines(fn)
        except (OSError, TypeError):
            return None
        if path is None:
            return None
        rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
        if rel.startswith(".."):
            return None
        return TraceAnchor(path=rel, line=line, symbol=f"{cls.__name__}.{method}")
    return None


def _fn_anchor(fn: Callable, key: str, repo_root: str) -> TraceAnchor:
    import inspect

    try:
        path = inspect.getsourcefile(fn)
        _, line = inspect.getsourcelines(fn)
        rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
    except (OSError, TypeError):
        rel, line = "", 0
    return TraceAnchor(path=rel, line=line, symbol=key)


@dataclass
class _TraceOutcome:
    facts: Optional[GraphFacts] = None
    out_shape: Any = None
    error: Optional[BaseException] = None
    skip: str = ""


def _trace(fn: Callable, args: tuple, repo_root: str) -> _TraceOutcome:
    """make_jaxpr under abstract inputs; classified errors become findings."""
    import jax

    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    except Exception as err:  # noqa: BLE001 — every failure is data, not a crash
        if type(err).__name__ in _DYNSHAPE_ERRORS:
            return _TraceOutcome(error=err)
        return _TraceOutcome(skip=f"trace failed: {type(err).__name__}: {err}")
    return _TraceOutcome(facts=collect_graph_facts(closed, repo_root), out_shape=out_shape)


def _dynshape_finding(anchor: TraceAnchor, case: str, err: BaseException) -> Finding:
    msg = str(err).split("\n", 1)[0][:300]
    return Finding(
        rule="TMS-DYNSHAPE",
        path=anchor.path,
        line=anchor.line,
        col=0,
        symbol=anchor.symbol,
        message=(
            f"abstract trace of {anchor.symbol} [{case}] failed with "
            f"{type(err).__name__}: {msg} — ground truth that the body is not "
            "trace-safe; tmlint's AST tier should have predicted this"
        ),
    )


def run_san(
    target: str = "metrics_tpu",
    baseline_path: Optional[str] = None,
    costs_path: Optional[str] = None,
    repo_root: Optional[str] = None,
    with_costs: bool = True,
    with_lint: bool = True,
) -> SanReport:
    """Full two-tier run over the live registry (see module docstring)."""
    import jax

    from metrics_tpu.analysis.registry import introspect_classes
    from metrics_tpu.core.metric import Metric

    t0 = time.perf_counter()
    report = SanReport()
    repo_root = repo_root or _find_repo_root(target)

    if with_lint:
        report.lint = analyze(target, baseline_path=baseline_path, repo_root=repo_root)

    footprint: set = set()
    all_callbacks: List[Tuple[str, str, int, str]] = []
    cost_current: Dict[str, Dict[str, float]] = {}
    cost_anchors: Dict[str, Tuple[str, int]] = {}
    n_traces = 0
    t_trace = time.perf_counter()

    # ---------------------------------------------------------- metric classes
    traced_cls: Dict[type, int] = {}
    cls_findings: Dict[type, List[Finding]] = {}
    for item in introspect_classes():
        if item.instance is None:
            report.skipped[item.name] = item.skip_reason
            continue
        if item.host_side:
            report.skipped[item.name] = "declared _host_side_update (host code by contract)"
            continue
        if item.cls in traced_cls:  # dispatcher alias: reuse the class's traces
            if traced_cls[item.cls] > 0:
                report.traced[item.name] = traced_cls[item.cls]
            else:
                report.skipped[item.name] = report.skipped.get(item.cls.__name__, "trace failed")
            continue

        inst = item.instance
        sizes = cases_for(item.name, inst)
        if sizes is None:
            traced_cls[item.cls] = 0
            report.skipped[item.name] = "no abstract input spec (add _san_input_specs or a table entry)"
            continue

        up_anchor = _method_anchor(item.cls, "update", repo_root) or TraceAnchor(
            "", 0, f"{item.cls.__name__}.update"
        )
        cp_anchor = _method_anchor(item.cls, "compute", repo_root) or TraceAnchor(
            "", 0, f"{item.cls.__name__}.compute"
        )
        try:
            state_sds = _to_sds(inst.init_state())
        except Exception as err:  # noqa: BLE001
            traced_cls[item.cls] = 0
            report.skipped[item.name] = f"init_state failed: {type(err).__name__}: {err}"
            continue

        found: List[Finding] = []
        entry_count = 0
        for size_tag, cases in sizes.items():
            for case in cases:
                inst_u = _fresh(inst)

                def upd(s, *a, _kw=case.kwargs, _m=inst_u):
                    return _m.local_update(s, *a, **_kw)

                outcome = _trace(upd, (state_sds, *case.args), repo_root)
                if outcome.error is not None:
                    found.append(_dynshape_finding(up_anchor, case.tag, outcome.error))
                    _obs_inc("trace_failures")
                    continue
                if outcome.skip:
                    report.skipped.setdefault(item.name, f"update[{case.tag}]: {outcome.skip}")
                    continue
                entry_count += 1
                n_traces += 1
                footprint |= outcome.facts.footprint
                all_callbacks.extend(outcome.facts.callbacks)
                found.extend(findings_from_facts(outcome.facts, up_anchor, case.tag))

                out_state = outcome.out_shape
                # compute on the POST-update state shapes (cat states have rows now)
                if not getattr(item.cls, "_host_side_compute", False):
                    inst_c = _fresh(inst)
                    c_outcome = _trace(lambda s, _m=inst_c: _m.compute_from(s), (out_state,), repo_root)
                    if c_outcome.error is not None:
                        found.append(_dynshape_finding(cp_anchor, case.tag, c_outcome.error))
                        _obs_inc("trace_failures")
                    elif c_outcome.skip:
                        report.skipped.setdefault(item.name, f"compute[{case.tag}]: {c_outcome.skip}")
                    else:
                        entry_count += 1
                        n_traces += 1
                        footprint |= c_outcome.facts.footprint
                        all_callbacks.extend(c_outcome.facts.callbacks)
                        found.extend(findings_from_facts(c_outcome.facts, cp_anchor, case.tag))

                # bf16 variant: does update preserve a narrow state dtype?
                if size_tag == "canon" and _has_narrow_or_float_state(state_sds):
                    bf_state, bf_args = _bf16_tree(state_sds), _bf16_tree(case.args)
                    inst_b = _fresh(inst)
                    try:
                        with warnings.catch_warnings():
                            warnings.simplefilter("ignore")
                            bf_out = jax.eval_shape(
                                lambda s, *a, _m=inst_b, _kw=case.kwargs: _m.local_update(s, *a, **_kw),
                                bf_state,
                                *bf_args,
                            )
                        found.extend(
                            upcast_findings(bf_state, bf_out, up_anchor, f"{case.tag}:bf16")
                        )
                    except Exception:  # noqa: BLE001 — bf16 support is opportunistic
                        pass

                # cost budget at the canonical shape
                if with_costs and size_tag == "canon":
                    key = f"{item.cls.__name__}.update[{case.tag}]"
                    inst_k = _fresh(inst)
                    try:
                        measured = costs_mod.measure_entry(
                            lambda s, *a, _m=inst_k, _kw=case.kwargs: _m.local_update(s, *a, **_kw),
                            (state_sds, *case.args),
                            {},
                        )
                    except Exception as err:  # noqa: BLE001
                        report.budget_notes.append(
                            f"cost measurement failed for {key}: {type(err).__name__}: {err}"
                        )
                        measured = None
                    if measured is not None:
                        cost_current[key] = measured
                        cost_anchors[key] = (up_anchor.path, up_anchor.line)

        traced_cls[item.cls] = entry_count
        cls_findings[item.cls] = found
        if entry_count > 0:
            report.traced[item.name] = entry_count
            _obs_inc("traced")
        elif item.name not in report.skipped:
            report.skipped[item.name] = "no entry traced"
        report.findings.extend(found)

    # ------------------------------------------------------- ops/ entrypoints
    for key, (fn, sizes) in sorted(ops_cases().items()):
        anchor = _fn_anchor(fn, key, repo_root)
        entry_count = 0
        for size_tag, cases in sizes.items():
            for case in cases:
                outcome = _trace(
                    lambda *a, _kw=case.kwargs: fn(*a, **_kw), case.args, repo_root
                )
                if outcome.error is not None:
                    report.findings.append(_dynshape_finding(anchor, case.tag, outcome.error))
                    _obs_inc("trace_failures")
                    continue
                if outcome.skip:
                    report.skipped.setdefault(key, f"[{case.tag}]: {outcome.skip}")
                    continue
                entry_count += 1
                n_traces += 1
                footprint |= outcome.facts.footprint
                all_callbacks.extend(outcome.facts.callbacks)
                report.findings.extend(findings_from_facts(outcome.facts, anchor, case.tag))
                if with_costs and size_tag == "canon":
                    ckey = f"{key}[{case.tag}]"
                    try:
                        measured = costs_mod.measure_entry(fn, case.args, case.kwargs)
                    except Exception as err:  # noqa: BLE001
                        report.budget_notes.append(
                            f"cost measurement failed for {ckey}: {type(err).__name__}: {err}"
                        )
                        measured = None
                    if measured is not None:
                        cost_current[ckey] = measured
                        cost_anchors[ckey] = (anchor.path, anchor.line)
        if entry_count:
            report.traced[key] = entry_count
            _obs_inc("traced")
    t_trace = time.perf_counter() - t_trace

    # ------------------------------------------------------------- crosscheck
    from metrics_tpu.analysis.san.crosscheck import corroborate_waivers, lintgap_findings

    lint_findings = report.lint.findings if report.lint is not None else []
    report.findings.extend(lintgap_findings(all_callbacks, lint_findings))

    if baseline_path is None:
        baseline_path = baseline_mod.default_baseline_path(repo_root)
    waivers = baseline_mod.load_baseline(baseline_path) if baseline_path else {}
    stale, status = corroborate_waivers(waivers, lint_findings, footprint, all_callbacks)
    report.findings.extend(stale)
    report.waiver_status = status

    # ------------------------------------------------------------ cost budget
    report.costs = cost_current
    if with_costs:
        budget_path = costs_path or costs_mod.default_costs_path(repo_root)
        if budget_path is not None:
            budget = costs_mod.load_costs(budget_path)
            budget_findings, notes = costs_mod.compare_costs(cost_current, budget, cost_anchors)
            report.findings.extend(budget_findings)
            report.budget_notes.extend(notes)
            _obs_inc("budget_breaches", len(budget_findings))
        else:
            report.budget_notes.append(
                f"no {costs_mod.COSTS_FILENAME} at the repo root: bootstrap with --write-costs"
            )

    # ---------------------------------------------------------------- baseline
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    san_waivers = baseline_mod.scope_waivers(waivers, SAN_RULES)
    report.new_findings, report.unused_waivers = baseline_mod.apply_baseline(
        report.findings, san_waivers
    )
    _obs_inc("findings", len(report.findings))
    for f in report.findings:
        if f.rule == "TMS-CALLBACK":
            _obs_inc("callbacks")
        elif f.rule == "TMS-F64":
            _obs_inc("f64")
        elif f.rule == "TMS-UPCAST":
            _obs_inc("upcasts")
        elif f.rule == "TMS-BIGCONST":
            _obs_inc("bigconsts")
        elif f.rule == "TMS-COLLECTIVE":
            _obs_inc("collectives")
        elif f.rule == "TMS-LINTGAP":
            _obs_inc("lintgaps")
        elif f.rule == "TMS-STALE-WAIVER":
            _obs_inc("stale_waivers")

    report.stats = {
        "classes_traced": len(report.traced),
        "entries_traced": n_traces,
        "skipped": len(report.skipped),
        "findings": len(report.findings),
        "waived": len(report.waived),
        "new": len(report.new_findings),
        "cost_entries": len(cost_current),
        "trace_seconds": round(t_trace, 3),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    return report
