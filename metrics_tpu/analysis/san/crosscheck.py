"""Crosscheck: tmlint's source-text verdicts vs tmsan's jaxpr ground truth.

Two directions:

1. **TMS-LINTGAP** — every host-callback equation tmsan finds in a traced
   graph must correspond to a TM-HOSTSYNC finding (waived or not) at the same
   source location. A callback in a function tmlint considered clean means the
   AST model has a blind spot: fix the code AND the model.

2. **TM-HOSTSYNC waiver corroboration** — a waiver asserts the flagged host
   work stays off traced paths. tmsan checks each one against the traced
   source footprint (every repo line any traced equation attributes to):

   - *corroborated-by-absence*: none of the waived finding's lines appear in
     any traced jaxpr — the "eager-only / guarded" claim holds;
   - *corroborated-by-presence*: the line appears, but as an explicit callback
     equation — host work is at least visible to the compiler;
   - **TMS-STALE-WAIVER** otherwise: the waived line participates in traced
     graphs as ordinary device computation, so the waiver's claim no longer
     describes the code. Re-triage it.
"""
from typing import Dict, List, Set, Tuple

from metrics_tpu.analysis.findings import Finding

#: how far (in lines) a callback may sit from the TM-HOSTSYNC finding that
#: covers it — callbacks usually trace through a helper one expression away
_LINE_SLACK = 2


def lintgap_findings(
    callbacks: List[Tuple[str, str, int, str]],
    lint_findings: List[Finding],
) -> List[Finding]:
    """Callbacks in traced graphs that no TM-HOSTSYNC finding/waiver covers."""
    hostsync = [f for f in lint_findings if f.rule == "TM-HOSTSYNC"]
    out: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for prim, path, line, func in callbacks:
        if not path or (path, line) in seen:
            continue  # no repo attribution -> already reported as TMS-CALLBACK
        seen.add((path, line))
        covered = any(
            f.path == path
            and (
                abs(f.line - line) <= _LINE_SLACK
                or (func and (f.symbol.endswith(func) or f.symbol.split(".")[-1] == func))
            )
            for f in hostsync
        )
        if not covered:
            out.append(
                Finding(
                    rule="TMS-LINTGAP",
                    path=path,
                    line=line,
                    col=0,
                    symbol=func or "<unknown>",
                    message=(
                        f"jaxpr-level `{prim}` at {path}:{line} but tmlint reports no "
                        "TM-HOSTSYNC there: the AST tier has a blind spot — fix the host "
                        "call AND extend trace_rules.py so the cheap tier catches it"
                    ),
                )
            )
    return out


def corroborate_waivers(
    waivers: Dict[Tuple[str, str, str], str],
    lint_findings: List[Finding],
    footprint: Set[Tuple[str, int]],
    callbacks: List[Tuple[str, str, int, str]],
) -> Tuple[List[Finding], Dict[str, str]]:
    """(stale_findings, {waiver_key_str: status}) for every TM-HOSTSYNC waiver."""
    callback_lines = {(p, ln) for _, p, ln, _ in callbacks if p}
    status: Dict[str, str] = {}
    stale: List[Finding] = []
    for key in sorted(k for k in waivers if k[0] == "TM-HOSTSYNC"):
        rule, path, symbol = key
        key_str = ":".join(key)
        matched = [f for f in lint_findings if f.key() == key]
        if not matched:
            status[key_str] = "unused (no current TM-HOSTSYNC finding; tmlint reports it stale)"
            continue
        traced_hits = [
            f for f in matched if (f.path, f.line) in footprint and (f.path, f.line) not in callback_lines
        ]
        as_callback = [f for f in matched if (f.path, f.line) in callback_lines]
        if traced_hits:
            f0 = traced_hits[0]
            status[key_str] = f"STALE: waived line {f0.path}:{f0.line} participates in traced graphs"
            stale.append(
                Finding(
                    rule="TMS-STALE-WAIVER",
                    path=path,
                    line=f0.line,
                    col=0,
                    symbol=symbol,
                    message=(
                        f"TM-HOSTSYNC waiver for `{symbol}` claims host-only execution, but "
                        f"{f0.path}:{f0.line} appears in the traced source footprint as device "
                        "computation: the code moved under the waiver — re-triage it"
                    ),
                )
            )
        elif as_callback:
            status[key_str] = "corroborated-by-presence (traced as an explicit callback equation)"
        else:
            status[key_str] = "corroborated-by-absence (waived lines in no traced jaxpr)"
    return stale, status
