"""Compile-cost budget: XLA's own cost model as a static perf-regression gate.

For every traced entry at the canonical shape, ``jax.jit(fn).lower(*abstract)
.compile().cost_analysis()`` yields flops and bytes-accessed, and
``memory_analysis()`` the transient footprint — all WITHOUT executing anything
or materializing data. ``tmsan_costs.json`` at the repo root records them;
:func:`compare_costs` fails CI (TMS-BUDGET findings) on unexplained growth
above :data:`BUDGET_TOLERANCE`.

The recorded numbers come from one XLA version's cost model, so the file
stamps ``jax``/``jaxlib``: on a version mismatch the comparison still runs but
degrades to warnings (notes) instead of findings — cross-version cost drift is
XLA's business, same-version drift is a regression in THIS repo. Refresh after
an intended change with ``python -m metrics_tpu.analysis --san --write-costs``
and commit the diff alongside its explanation.
"""
import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.analysis.findings import Finding

COSTS_FILENAME = "tmsan_costs.json"
#: growth beyond this fraction of the recorded budget is a TMS-BUDGET finding
BUDGET_TOLERANCE = 0.15
#: the cost dimensions the budget tracks, in report order
COST_KEYS = ("flops", "bytes_accessed", "peak_bytes")


def measure_entry(fn, args, kwargs) -> Optional[Dict[str, float]]:
    """Lower+compile one entry under abstract inputs; never executes it.

    ``peak_bytes`` is the executable's transient footprint beyond its inputs:
    XLA temp allocations plus outputs (CompiledMemoryStats).
    """
    import warnings

    import jax

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        lowered = jax.jit(lambda *a: fn(*a, **kwargs)).lower(*args)
        compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    peak = 0.0
    try:
        mem = compiled.memory_analysis()
        peak = float(
            (getattr(mem, "temp_size_in_bytes", 0) or 0)
            + (getattr(mem, "output_size_in_bytes", 0) or 0)
        )
    except Exception:  # noqa: BLE001 — peak is best-effort on exotic backends
        pass
    return {"flops": flops, "bytes_accessed": nbytes, "peak_bytes": peak}


def load_costs(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_costs(path: str, entries: Dict[str, Dict[str, float]]) -> int:
    import jax

    payload = {
        "version": 1,
        "comment": (
            "tmsan compile-cost budget: flops / bytes-accessed / peak transient"
            " bytes per (entry, canonical shape) from XLA cost analysis."
            " CI fails on >15% unexplained growth (same jax version); refresh"
            " with `python -m metrics_tpu.analysis --san --write-costs` and"
            " commit the diff with its explanation."
        ),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    return len(payload["entries"])


def default_costs_path(repo_root: str) -> Optional[str]:
    cand = os.path.join(repo_root, COSTS_FILENAME)
    return cand if os.path.exists(cand) else None


def _breaches(current: Dict[str, float], budget: Dict[str, float]) -> List[str]:
    out = []
    for key in COST_KEYS:
        cur, ref = float(current.get(key, 0.0)), float(budget.get(key, 0.0))
        if ref <= 0.0:
            continue  # zero-cost reference: nothing meaningful to gate on
        growth = cur / ref - 1.0
        if growth > BUDGET_TOLERANCE and not math.isclose(cur, ref):
            out.append(f"{key} {ref:.0f} -> {cur:.0f} (+{growth * 100:.0f}%)")
    return out


def compare_costs(
    current: Dict[str, Dict[str, float]],
    budget_payload: Dict[str, Any],
    anchors: Dict[str, Tuple[str, int]],
) -> Tuple[List[Finding], List[str]]:
    """(findings, notes) comparing measured costs against the checked-in budget.

    ``anchors``: entry key -> (repo_relative_path, line) for finding placement.
    """
    import jax

    findings: List[Finding] = []
    notes: List[str] = []
    budget: Dict[str, Dict[str, float]] = budget_payload.get("entries", {})
    version_ok = budget_payload.get("jax") == jax.__version__ and (
        budget_payload.get("backend") == jax.default_backend()
    )
    if not version_ok:
        notes.append(
            f"budget recorded on jax={budget_payload.get('jax')}/"
            f"{budget_payload.get('backend')} but running jax={jax.__version__}/"
            f"{jax.default_backend()}: cost drift reported as warnings, not failures"
        )

    def emit(entry: str, message: str) -> None:
        path, line = anchors.get(entry, ("", 0))
        f = Finding(
            rule="TMS-BUDGET", path=path or COSTS_FILENAME, line=line, col=0,
            symbol=entry, message=message,
        )
        if version_ok:
            findings.append(f)
        else:
            notes.append(f"(version-skew warning) {f.format()}")

    for entry in sorted(current):
        if entry not in budget:
            emit(
                entry,
                f"no budget recorded for `{entry}`: run `python -m metrics_tpu.analysis"
                " --san --write-costs` and commit tmsan_costs.json",
            )
            continue
        over = _breaches(current[entry], budget[entry])
        if over:
            emit(
                entry,
                f"compile cost of `{entry}` grew past the +{BUDGET_TOLERANCE * 100:.0f}% "
                f"budget: {'; '.join(over)} — fix the regression or refresh the budget "
                "(--write-costs) with an explanation",
            )
            continue
        shrunk = [
            f"{k} {budget[entry].get(k, 0):.0f} -> {current[entry].get(k, 0):.0f}"
            for k in COST_KEYS
            if float(budget[entry].get(k, 0.0)) > 0.0
            and float(current[entry].get(k, 0.0)) < float(budget[entry].get(k, 0.0)) * (1 - BUDGET_TOLERANCE)
        ]
        if shrunk:
            notes.append(
                f"`{entry}` improved >{BUDGET_TOLERANCE * 100:.0f}% below budget"
                f" ({'; '.join(shrunk)}): refresh with --write-costs to lock in the gain"
            )
    for entry in sorted(set(budget) - set(current)):
        notes.append(
            f"budget entry `{entry}` no longer traced (metric removed or renamed):"
            " refresh with --write-costs"
        )
    return findings, notes
