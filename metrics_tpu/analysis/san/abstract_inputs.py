"""Abstract input model: ShapeDtypeStruct update arguments per metric class.

tmsan traces metric state transitions without ever materializing data: each
registered class gets a small set of :class:`TraceCase`\\ s — tuples of
``jax.ShapeDtypeStruct`` update arguments (plus static python kwargs) at the
canonical batch sizes in :data:`SIZES`. Two sizes are traced so shape-
specialized constants and size-dependent dispatch both show up; the cost
budget (costs.py) is recorded at the ``canon`` size only.

Resolution order for a class's specs:

1. the ``Metric._san_input_specs(n)`` instance hook (core/metric.py) — for
   metrics whose update signature is not inferable from tables (wrappers whose
   shapes depend on the wrapped metric);
2. the per-name table below (mirrors the contract sweep's PER_NAME);
3. the task-family prefix rule (Binary*/Multiclass*/Multilabel*/Retrieval*).

A class with no spec is recorded as a skip (never a crash): tmsan degrades the
same way the tmlint registry does on a ctor failure.
"""
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

#: canonical batch sizes; "canon" is also the cost-budget shape
SIZES: Dict[str, int] = {"small": 8, "canon": 64}


def f32(*shape: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def bf16(*shape: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, jnp.bfloat16)


def i32(*shape: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def u8(*shape: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, jnp.uint8)


def b8(*shape: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, jnp.bool_)


@dataclass(frozen=True)
class TraceCase:
    """One (args, kwargs) update invocation to trace at one canonical size."""

    tag: str  # "canon" / "small" (+ ":variant" for kwarg variants)
    args: Tuple[jax.ShapeDtypeStruct, ...]
    kwargs: Dict[str, Any] = field(default_factory=dict)


def _one(*args: jax.ShapeDtypeStruct, **kwargs: Any):
    return [(args, kwargs)]


# ---------------------------------------------------------------------------
# shape builders: name -> fn(n) -> list of (args, kwargs)
# (mirrors tests/unittests/bases/test_contract_sweep.py PER_NAME, shapes only)
# ---------------------------------------------------------------------------

def _binary(n):
    return _one(f32(n), i32(n))


def _multiclass(n):
    return _one(f32(n, 5), i32(n))


def _multilabel(n):
    return _one(f32(n, 3), i32(n, 3))


def _retrieval(n):
    return _one(f32(n), i32(n), i32(n))


def _pairs(n):
    return _one(f32(n), f32(n))


def _single(n):
    return _one(f32(n))


def _img(n, c=3, hw=16):
    b = max(1, n // 32)  # canonical batch: 2 at canon, 1 at small
    return b, c, hw, hw


def _img_pair(n, c=3, hw=16):
    shape = _img(n, c, hw)
    return _one(f32(*shape), f32(*shape))


def _sig_pair(n, t=32):
    b = max(1, n // 32)
    return _one(f32(b, t), f32(b, t))


#: family prefix -> builder (matches registry.FAMILY_KWARGS order)
FAMILY_BUILDERS: Tuple[Tuple[str, Callable[[int], list]], ...] = (
    ("Binary", _binary),
    ("Multiclass", _multiclass),
    ("Multilabel", _multilabel),
    ("Retrieval", _retrieval),
)

#: per-name builders (checked before the family prefix)
PER_NAME: Dict[str, Callable[[int], list]] = {
    # __new__-routing dispatchers (registry constructs their task= form)
    "Accuracy": _binary,
    "AUROC": _binary,
    "AveragePrecision": _binary,
    "CalibrationError": _binary,
    "CohenKappa": _binary,
    "ConfusionMatrix": _binary,
    "F1Score": _binary,
    "FBetaScore": _binary,
    "HammingDistance": _binary,
    "JaccardIndex": _binary,
    "MatthewsCorrCoef": _binary,
    "Precision": _binary,
    "PrecisionRecallCurve": _binary,
    "Recall": _binary,
    "ROC": _binary,
    "Specificity": _binary,
    "StatScores": _binary,
    "RecallAtFixedPrecision": _binary,
    "PrecisionAtFixedRecall": _binary,
    "SpecificityAtSensitivity": _binary,
    "HingeLoss": _binary,
    "ExactMatch": lambda n: _one(i32(n), i32(n)),
    "MulticlassExactMatch": lambda n: _one(i32(n), i32(n)),
    "MultilabelExactMatch": _multilabel,
    # regression & aggregation
    "CosineSimilarity": lambda n: _one(f32(max(2, n // 16), 8), f32(max(2, n // 16), 8)),
    "KLDivergence": lambda n: _one(f32(max(2, n // 8), 4), f32(max(2, n // 8), 4)),
    "KendallRankCorrCoef": _pairs,
    "SpearmanCorrCoef": _pairs,
    "PearsonCorrCoef": _pairs,
    "ConcordanceCorrCoef": _pairs,
    "ExplainedVariance": _pairs,
    "LogCoshError": _pairs,
    "MeanAbsoluteError": _pairs,
    "MeanAbsolutePercentageError": _pairs,
    "MeanSquaredError": _pairs,
    "MeanSquaredLogError": _pairs,
    "MinkowskiDistance": _pairs,
    "R2Score": _pairs,
    "SymmetricMeanAbsolutePercentageError": _pairs,
    "TweedieDevianceScore": _pairs,
    "WeightedMeanAbsolutePercentageError": _pairs,
    "MaxMetric": _single,
    "MinMetric": _single,
    "MeanMetric": _single,
    "SumMetric": _single,
    "CatMetric": _single,
    "RunningMean": _single,
    "RunningSum": _single,
    # image (pairs)
    "ErrorRelativeGlobalDimensionlessSynthesis": _img_pair,
    "MultiScaleStructuralSimilarityIndexMeasure": lambda n: _img_pair(n, hw=24),
    "PeakSignalNoiseRatio": _img_pair,
    "PeakSignalNoiseRatioWithBlockedEffect": lambda n: _img_pair(n, c=1),
    "RelativeAverageSpectralError": _img_pair,
    "RootMeanSquaredErrorUsingSlidingWindow": _img_pair,
    "SpectralAngleMapper": _img_pair,
    "SpectralDistortionIndex": _img_pair,
    "StructuralSimilarityIndexMeasure": _img_pair,
    "TotalVariation": lambda n: _one(f32(*_img(n))),
    "UniversalImageQualityIndex": _img_pair,
    # audio
    "ScaleInvariantSignalDistortionRatio": _sig_pair,
    "ScaleInvariantSignalNoiseRatio": _sig_pair,
    "SignalNoiseRatio": _sig_pair,
    "SignalDistortionRatio": lambda n: _sig_pair(n, t=64),
    "PermutationInvariantTraining": lambda n: _one(
        f32(max(1, n // 32), 2, 32), f32(max(1, n // 32), 2, 32)
    ),
    # text-adjacent device metric
    "Perplexity": lambda n: _one(f32(max(1, n // 32), 6, 8), i32(max(1, n // 32), 6)),
    # sketches (mergeable streaming telemetry; sketches/) — HistogramDrift's
    # reference/live branches are distinct traces like FID's real/fake
    "QuantileSketch": _single,
    "DistinctCount": lambda n: _one(i32(n)),
    "HistogramDrift": lambda n: [
        ((f32(n),), {"reference": True}),
        ((f32(n),), {"reference": False}),
    ],
    "StreamingAUROCBound": _binary,
    # nominal (update is device-side; compute is declared host-side)
    "CramersV": lambda n: _one(i32(n), i32(n)),
    "PearsonsContingencyCoefficient": lambda n: _one(i32(n), i32(n)),
    "TheilsU": lambda n: _one(i32(n), i32(n)),
    "TschuprowsT": lambda n: _one(i32(n), i32(n)),
    # image-gen metrics with injected feature extractors (registry supplies a
    # weight-free 8-feature stand-in): real/fake branches are distinct traces
    "FrechetInceptionDistance": lambda n: [
        ((u8(max(2, n // 16), 3, 8, 8),), {"real": True}),
        ((u8(max(2, n // 16), 3, 8, 8),), {"real": False}),
    ],
    "KernelInceptionDistance": lambda n: [
        ((u8(max(2, n // 16), 3, 8, 8),), {"real": True}),
        ((u8(max(2, n // 16), 3, 8, 8),), {"real": False}),
    ],
    "InceptionScore": lambda n: _one(u8(max(2, n // 16), 3, 8, 8)),
}


def _normalize(raw: Any, tag: str) -> List[TraceCase]:
    """Accept builder/hook output shapes: list of (args, kwargs) pairs, a bare
    args tuple, or a list of (tag, args, kwargs) triples."""
    cases: List[TraceCase] = []
    if raw is None:
        return cases
    if isinstance(raw, tuple) and all(isinstance(a, jax.ShapeDtypeStruct) for a in raw):
        raw = [(raw, {})]
    for i, entry in enumerate(raw):
        if len(entry) == 3 and isinstance(entry[0], str):
            sub, args, kwargs = entry
            cases.append(TraceCase(f"{tag}:{sub}", tuple(args), dict(kwargs)))
            continue
        args, kwargs = entry
        sub = ""
        if kwargs:
            sub = ":" + ",".join(f"{k}={v}" for k, v in sorted(kwargs.items()))
        cases.append(TraceCase(tag + sub, tuple(args), dict(kwargs)))
    return cases


def inner_spec(metric: Any, n: int) -> Optional[list]:
    """Raw spec list for a WRAPPED metric instance, resolved by class name.

    Wrapper classes implement their ``_san_input_specs`` hook with this: the
    wrapped metric's own hook wins, then the tables above (class names match
    the family prefixes — ``MulticlassAccuracy`` hits the ``Multiclass`` rule).
    """
    hook = getattr(metric, "_san_input_specs", None)
    raw = hook(n) if hook is not None else None
    if raw is not None:
        return raw
    name = type(metric).__name__.lstrip("_")
    builder = PER_NAME.get(name)
    if builder is None:
        for prefix, fam in FAMILY_BUILDERS:
            if name.startswith(prefix):
                builder = fam
                break
    return builder(n) if builder is not None else None


def cases_for(name: str, instance: Any) -> Optional[Dict[str, List[TraceCase]]]:
    """``{size_tag: [TraceCase, ...]}`` for one registered metric, or None when
    no spec exists (hook, table, and family all miss)."""
    out: Dict[str, List[TraceCase]] = {}
    hook = getattr(instance, "_san_input_specs", None)
    for tag, n in SIZES.items():
        raw = hook(n) if hook is not None else None
        if raw is None:
            builder = PER_NAME.get(name)
            if builder is None:
                for prefix, fam in FAMILY_BUILDERS:
                    if name.startswith(prefix):
                        builder = fam
                        break
            if builder is None:
                return None
            raw = builder(n)
        out[tag] = _normalize(raw, tag)
    return out


# ---------------------------------------------------------------------------
# ops/ exact-kernel functional entrypoints (traced + budgeted like metrics)
# ---------------------------------------------------------------------------

def _ops_entrypoints() -> Dict[str, Tuple[Callable, Callable[[int], list]]]:
    from metrics_tpu.core import fleet, fused
    from metrics_tpu.ops import clf_curve, confmat, rank, segment
    from metrics_tpu.ops import sketch as sketch_ops

    return {
        # the fused-collection entrypoint (core/fused.py): the canonical
        # five-group chained update traced/compiled as ONE executable, plus a
        # same-constructor stand-alone entry per leader — together the
        # budget-gated proof that the fused path is fewer executables / lower
        # total bytes-accessed than five eager launches
        "fused.collection_update": (fused.canonical_fused_update, fused.canonical_fused_case),
        **fused.canonical_eager_entries(),
        # the fleet-axis entrypoints (core/fleet.py): one routed update over a
        # 16-stream fleet and one vmapped per-stream compute — the budget-gated
        # proof that N concurrent streams cost one executable, not N
        "fleet.update": (fleet.canonical_fleet_update, fleet.canonical_fleet_update_case),
        "fleet.compute": (fleet.canonical_fleet_compute, fleet.canonical_fleet_compute_case),
        "ops.binary_auroc_exact": (clf_curve.binary_auroc_exact, _pairs_it),
        "ops.binary_average_precision_exact": (clf_curve.binary_average_precision_exact, _pairs_it),
        "ops.multiclass_auroc_exact": (clf_curve.multiclass_auroc_exact, lambda n: _one(f32(n, 5), i32(n))),
        "ops.multiclass_average_precision_exact": (
            clf_curve.multiclass_average_precision_exact, lambda n: _one(f32(n, 5), i32(n))
        ),
        "ops.multilabel_auroc_exact": (clf_curve.multilabel_auroc_exact, lambda n: _one(f32(n, 3), i32(n, 3))),
        "ops.multilabel_average_precision_exact": (
            clf_curve.multilabel_average_precision_exact, lambda n: _one(f32(n, 3), i32(n, 3))
        ),
        "ops.binary_precision_recall_curve_padded": (
            clf_curve.binary_precision_recall_curve_padded, _pairs_it
        ),
        "ops.binary_roc_curve_padded": (clf_curve.binary_roc_curve_padded, _pairs_it),
        "ops.grouped_retrieval_scores": (
            segment.grouped_retrieval_scores,
            lambda n: _one(i32(n), f32(n), i32(n), metric="precision", top_k=2),
        ),
        # the fused segmented multi-scan (ops/segment.py): two statistics in
        # one pass — the round-10 fusion every post-sort curve/retrieval
        # consumer routes through
        "ops.segment_multi_scan": (
            segment.segment_multi_scan,
            lambda n: _one((i32(n), i32(n)), b8(n), ops=("sum", "min")),
        ),
        "ops.confusion_counts": (
            confmat.confusion_counts,
            lambda n: _one(i32(n), i32(n), b8(n), num_classes=5),
        ),
        "ops.ranked_targets": (rank.ranked_targets, lambda n: _one(f32(n), i32(n))),
        "ops.monotone_key_descending": (rank.monotone_key_descending, lambda n: _one(f32(n))),
        # sketch kernels (ops/sketch.py + the histogram-form rank bounds):
        # the hash mixer scales with n; the bounds run at the shipping
        # 2^12-bucket resolution (state-shaped, n-independent)
        "ops.sketch_hash_u32": (sketch_ops.hash_u32, lambda n: _one(f32(n))),
        "ops.average_precision_bounds_from_hists": (
            rank.average_precision_bounds_from_hists,
            lambda n: _one(i32(1 << 12), i32(1 << 12)),
        ),
    }


def _pairs_it(n):
    return _one(f32(n), i32(n))


def ops_cases() -> Dict[str, Tuple[Callable, Dict[str, List[TraceCase]]]]:
    """``{entry_key: (fn, {size_tag: cases})}`` for the ops/ kernels."""
    out = {}
    for key, (fn, builder) in _ops_entrypoints().items():
        out[key] = (fn, {tag: _normalize(builder(n), tag) for tag, n in SIZES.items()})
    return out
