"""State-contract rules via import-time introspection: TM-STATE-UNREG,
TM-REDUCE-MISMATCH, TM-PERSIST.

These rules need a *live* instance (the ``add_state`` registry only exists at
runtime) plus the AST of the class's ``update`` — exactly the combination no
pure type checker sees. The constructor specs come from
:mod:`metrics_tpu.analysis.registry` (the contract-sweep mirror).

Introspection hooks consumed here (declared on ``core/metric.py``):

- ``_host_side_update`` — class's update/compute are host code by contract
  (text/detection); skips the *trace* rules, not these state rules.
- ``_ckpt_exempt_attrs`` — array attrs intentionally outside the ckpt registry.
- ``_update_signature_attrs`` — constructor knobs; re-derived at construction,
  so the serializer dropping them is correct, not a finding.
"""
import ast
import inspect
import os
import textwrap
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.registry import IntrospectedClass

#: runtime bookkeeping attributes Metric.__init__/_wrap_* own — never state
_RUNTIME_ATTRS = frozenset(
    {
        "_computed", "_forward_cache", "_update_count", "_cache", "_is_synced",
        "_to_sync", "_should_unsync", "_device", "compute_on_cpu", "update",
        "compute", "_defaults", "_persistent", "_reductions", "_cat_meta",
        "_obs_fingerprints", "_obs_retrace_warned",
    }
)
_ARRAY_REDUCTIONS = frozenset({"sum", "mean", "max", "min"})


def _is_array_value(value: Any) -> bool:
    from metrics_tpu.core.state import CatBuffer

    if isinstance(value, CatBuffer):
        return True
    if isinstance(value, np.ndarray):
        return True
    if type(value).__module__.startswith("jax") and hasattr(value, "dtype") and hasattr(value, "shape"):
        return True
    if isinstance(value, (list, tuple)) and value:
        return all(_is_array_value(v) for v in value)
    return False


def _class_anchor(cls: type, repo_root: str) -> Optional[Tuple[str, int]]:
    try:
        path = inspect.getsourcefile(cls)
        _, line = inspect.getsourcelines(cls)
    except (OSError, TypeError):
        return None
    if path is None:
        return None
    rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
    if rel.startswith(".."):
        return None
    return rel, line


def _method_def(cls: type, name: str):
    """(plain function, defining class) for a method, walking the MRO."""
    for base in cls.__mro__:
        if name in base.__dict__:
            fn = base.__dict__[name]
            if callable(fn):
                return fn, base
    return None, None


def _update_self_assigns(fn) -> Iterable[Tuple[str, int]]:
    """(attr, absolute line) for every ``self.X = ...`` in a method body."""
    try:
        lines, start = inspect.getsourcelines(fn)
    except (OSError, TypeError):
        return []
    try:
        tree = ast.parse(textwrap.dedent("".join(lines)))
    except SyntaxError:
        return []
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Tuple):
                elts = list(t.elts)
            else:
                elts = [t]
            for el in elts:
                if (
                    isinstance(el, ast.Attribute)
                    and isinstance(el.value, ast.Name)
                    and el.value.id == "self"
                ):
                    out.append((el.attr, start + el.lineno - 1))
    return out


def _declared_state_names(cls: type) -> set:
    """Literal first arguments of every ``add_state("...")`` call in the class
    source, walking the MRO — catches conditionally-registered states (e.g. the
    curve metrics register either cat states or a confmat depending on the
    ``thresholds`` ctor arg, so one constructed instance never shows both)."""
    from metrics_tpu.core.metric import Metric

    names: set = set()
    for base in cls.__mro__:
        if base is Metric or base is object:
            continue
        try:
            src = textwrap.dedent(inspect.getsource(base))
            tree = ast.parse(src)
        except (OSError, TypeError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_state"
            ):
                arg = node.args[0] if node.args else None
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    names.add(arg.value)
    return names


def class_findings(item: IntrospectedClass, repo_root: str) -> List[Finding]:
    """All state-contract findings for one introspected metric class."""
    from metrics_tpu.core.metric import Metric
    from metrics_tpu.core.state import CatBuffer

    findings: List[Finding] = []
    instance = item.instance
    if instance is None:
        return findings
    cls = item.cls
    anchor = _class_anchor(cls, repo_root)
    if anchor is None:
        return findings
    cls_path, cls_line = anchor

    defaults: Dict[str, Any] = dict(getattr(instance, "_defaults", {}))
    reductions: Dict[str, Any] = dict(getattr(instance, "_reductions", {}))
    exempt = set(getattr(cls, "_ckpt_exempt_attrs", ()) or ())
    sig_attrs = set(getattr(cls, "_update_signature_attrs", ()) or ())

    # ------------------------------------------------------ TM-STATE-UNREG
    fn, defining = _method_def(cls, "update")
    if fn is not None and defining is not Metric:
        declared = _declared_state_names(cls)
        def_anchor = _class_anchor(defining, repo_root)
        for attr, line in _update_self_assigns(fn):
            if attr in defaults or attr in _RUNTIME_ATTRS or attr in exempt or attr in declared:
                continue
            path = def_anchor[0] if def_anchor else cls_path
            findings.append(
                Finding(
                    rule="TM-STATE-UNREG",
                    path=path,
                    line=line,
                    col=0,
                    symbol=f"{defining.__name__}.update.{attr}",
                    message=(
                        f"`update` assigns `self.{attr}` but it was never registered via "
                        "add_state: it will not sync across hosts, survives reset(), and a "
                        "checkpoint restore silently recomputes from defaults (the "
                        "RASE/RMSE-SW lazy-init bug class)"
                    ),
                )
            )

    # -------------------------------------------------- TM-REDUCE-MISMATCH
    for state, reduce_fx in reductions.items():
        default = defaults.get(state)
        sym = f"{cls.__name__}.{state}"
        if reduce_fx == "cat" and not isinstance(default, (list, CatBuffer)):
            findings.append(
                Finding(
                    rule="TM-REDUCE-MISMATCH",
                    path=cls_path,
                    line=cls_line,
                    col=0,
                    symbol=sym,
                    message=(
                        f"state `{state}` declares dist_reduce_fx='cat' over a dense array "
                        "default: cat sync concatenates along dim 0, which changes the state "
                        "shape the ckpt manifest validates against"
                    ),
                )
            )
        elif reduce_fx in _ARRAY_REDUCTIONS and isinstance(default, list):
            findings.append(
                Finding(
                    rule="TM-REDUCE-MISMATCH",
                    path=cls_path,
                    line=cls_line,
                    col=0,
                    symbol=sym,
                    message=(
                        f"state `{state}` declares dist_reduce_fx='{reduce_fx}' over a list "
                        "default: element-wise reductions need a fixed-shape array state"
                    ),
                )
            )
        elif reduce_fx == "mean" and _is_array_value(default) and not isinstance(default, (list, CatBuffer)):
            dtype = np.asarray(default).dtype
            if np.issubdtype(dtype, np.integer) or dtype == np.bool_:
                findings.append(
                    Finding(
                        rule="TM-REDUCE-MISMATCH",
                        path=cls_path,
                        line=cls_line,
                        col=0,
                        symbol=sym,
                        message=(
                            f"state `{state}` declares dist_reduce_fx='mean' over integer dtype "
                            f"{dtype}: the cross-host mean (and the ckpt topology re-reduce) is "
                            "fractional and cannot be stored exactly"
                        ),
                    )
                )
        elif callable(reduce_fx) and not isinstance(reduce_fx, str):
            findings.append(
                Finding(
                    rule="TM-REDUCE-MISMATCH",
                    path=cls_path,
                    line=cls_line,
                    col=0,
                    symbol=sym,
                    message=(
                        f"state `{state}` uses a custom callable dist_reduce_fx: "
                        "ckpt/restore.py's topology re-reduce cannot honor it when restoring "
                        "onto a different host count (only sum/mean/max/min/cat re-reduce)"
                    ),
                )
            )

    # ---------------------------------------------------------- TM-PERSIST
    for attr, value in vars(instance).items():
        if attr in defaults or attr in _RUNTIME_ATTRS or attr in exempt or attr in sig_attrs:
            continue
        if isinstance(value, Metric):
            continue  # child metrics are serialized via ckpt child_metrics()
        if isinstance(value, (list, tuple)) and value and all(isinstance(v, Metric) for v in value):
            continue
        if callable(value):
            continue
        if _is_array_value(value):
            findings.append(
                Finding(
                    rule="TM-PERSIST",
                    path=cls_path,
                    line=cls_line,
                    col=0,
                    symbol=f"{cls.__name__}.{attr}",
                    message=(
                        f"array-valued attribute `self.{attr}` is outside the add_state "
                        "registry: ckpt/serializer.py silently drops it on save. Register it, "
                        "name it in `_update_signature_attrs` (ctor knob), or declare it in "
                        "`_ckpt_exempt_attrs`"
                    ),
                )
            )

    return findings


def run_contract_rules(repo_root: str) -> Tuple[List[Finding], Dict[str, str]]:
    """(findings, {class_name: skip_reason}) over every introspectable class."""
    from metrics_tpu.analysis.registry import introspect_classes, introspect_fleet_variants

    findings: List[Finding] = []
    skipped: Dict[str, str] = {}
    seen_classes: set = set()
    for item in introspect_classes():
        if item.instance is None:
            skipped[item.name] = item.skip_reason
            continue
        if item.cls in seen_classes:
            continue  # dispatcher duplicates (Accuracy -> BinaryAccuracy)
        seen_classes.add(item.cls)
        findings.extend(class_findings(item, repo_root))
    # fleet-axis variants re-run the contract rules over a live (N, *base)
    # state registry — same classes, so any repeat finding collapses in the
    # key+line dedup below and only fleet-specific drift would surface
    for item in introspect_fleet_variants():
        if item.instance is None:
            skipped[item.name] = item.skip_reason
            continue
        findings.extend(class_findings(item, repo_root))
    # several exported classes share one defining update (AUROC inherits the
    # curve update): identical (key, line) findings collapse to one
    seen_keys = set()
    unique: List[Finding] = []
    for f in findings:
        k = f.key() + (f.line,)
        if k not in seen_keys:
            seen_keys.add(k)
            unique.append(f)
    return unique, skipped
