"""``python -m metrics_tpu.analysis`` — the tmlint/tmsan/tmrace/tmown/tmshard CLI.

Usage:
    python -m metrics_tpu.analysis metrics_tpu/            # lint, baseline-aware
    python -m metrics_tpu.analysis --san                   # + jaxpr/HLO tier (tmsan)
    python -m metrics_tpu.analysis --race                  # thread-safety tier (tmrace)
    python -m metrics_tpu.analysis --own                   # buffer-ownership tier (tmown)
    python -m metrics_tpu.analysis --shard                 # sharding/collective tier (tmshard)
    python -m metrics_tpu.analysis --own --write-drift     # refresh tmown_engine_drift.json
    python -m metrics_tpu.analysis --shard --write-plan    # refresh tmshard_state_plan.json
    python -m metrics_tpu.analysis --san --write-costs     # refresh tmsan_costs.json
    python -m metrics_tpu.analysis --explain TM-HOSTSYNC   # rule rationale
    python -m metrics_tpu.analysis metrics_tpu/ --write-baseline  # bootstrap waivers
    python -m metrics_tpu.analysis metrics_tpu/ --json     # machine-readable

Exit codes: 0 = clean (or fully baselined), 1 = new findings or budget breach,
2 = usage error.
"""
import argparse
import json
import sys

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import RULES, explain
from metrics_tpu.analysis.runner import analyze


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m metrics_tpu.analysis",
        description=(
            "tmlint: JAX/TPU-aware static analysis for trace safety (TM-HOSTSYNC, "
            "TM-PYBRANCH, TM-DYNSHAPE), the Metric state contract (TM-STATE-UNREG, "
            "TM-REDUCE-MISMATCH, TM-PERSIST), and retrace hazards (TM-RETRACE). "
            "Findings are cross-linked to metrics_tpu.obs counter names."
        ),
    )
    parser.add_argument("paths", nargs="*", help="package dirs or files to lint (default: metrics_tpu/)")
    parser.add_argument("--explain", metavar="RULE", help="print a rule's rationale and obs cross-link, then exit")
    parser.add_argument("--baseline", metavar="FILE", help="waiver file (default: tmlint_baseline.json at the repo root)")
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write/overwrite the baseline waiving every current finding (bootstrap; edit reasons in afterwards)",
    )
    parser.add_argument("--select", metavar="RULES", help="comma-separated rule ids to report (default: all)")
    parser.add_argument("--json", action="store_true", help="machine-readable report on stdout")
    parser.add_argument("--no-introspect", action="store_true", help="AST rules only (skip importing the metric registry)")
    parser.add_argument(
        "--san",
        action="store_true",
        help="also run tmsan, the jaxpr/HLO tier: trace every registered metric "
        "under abstract inputs, walk the jaxprs (TMS-* rules), check the "
        "compile-cost budget (tmsan_costs.json), and crosscheck tmlint's "
        "TM-HOSTSYNC waivers against jaxpr evidence",
    )
    parser.add_argument(
        "--race",
        action="store_true",
        help="run tmrace, the concurrency tier: build the thread-role model "
        "(spawns, handler installs, @thread_role/@locked_by annotations), "
        "check lock discipline (TMR-UNLOCKED), the lock-order deadlock graph "
        "(TMR-ORDER), host work under hot locks (TMR-HOLD-HOST), "
        "signal/atexit/excepthook safety (TMR-HANDLER), and thread leaks "
        "(TMR-LEAK)",
    )
    parser.add_argument(
        "--own",
        action="store_true",
        help="run tmown, the buffer-ownership tier: model the lifetime of "
        "array values through donate_argnums boundaries — aliased buffers "
        "reaching a donated position (TMO-DONATE-ALIAS, the PR 16 class), "
        "reads of donated-and-dead state (TMO-USE-AFTER-DONATE), duplicate "
        "donation (TMO-DOUBLE-DONATE), missing snapshot-before-donate guards "
        "(TMO-SNAPSHOT-GAP), executable-cache key gaps (TMO-KEY-GAP), and "
        "launch-engine contract drift (TMO-ENGINE-DRIFT)",
    )
    parser.add_argument(
        "--shard",
        action="store_true",
        help="run tmshard, the sharding/collective tier: build the axis/"
        "placement model (shard_map/pmap entries, collective sites, "
        "PartitionSpec placements, donating launches) and check axis "
        "binding (TMH-AXIS-UNBOUND), reduction-vs-spec algebra "
        "(TMH-SPEC-ALGEBRA), replica-divergent host reads "
        "(TMH-REPLICA-DIVERGE), donation across a reshard "
        "(TMH-DONATE-RESHARD), sharding-blind cache keys (TMH-KEY-SHARD), "
        "and per-engine mesh-awareness drift (TMH-MESH-DRIFT)",
    )
    parser.add_argument(
        "--write-plan",
        action="store_true",
        help="with --shard: write/refresh tmshard_state_plan.json, the "
        "per-state shard-plan worksheet for ROADMAP items 1 & 4 (commit "
        "the diff)",
    )
    parser.add_argument(
        "--write-drift",
        action="store_true",
        help="with --own: write/refresh tmown_engine_drift.json, the "
        "per-engine contract worksheet for ROADMAP item 5 (commit the diff)",
    )
    parser.add_argument(
        "--write-costs",
        action="store_true",
        help="with --san: write/refresh tmsan_costs.json from the measured "
        "compile costs (commit the diff with its explanation)",
    )
    parser.add_argument("--costs", metavar="FILE", help="cost-budget file (default: tmsan_costs.json at the repo root)")
    parser.add_argument("--no-costs", action="store_true", help="with --san: skip the compile/cost tier (trace rules only)")
    parser.add_argument("-v", "--verbose", action="store_true", help="also list waived findings and skipped classes")
    args = parser.parse_args(argv)

    if args.explain:
        rule = args.explain.upper()
        if rule not in RULES:
            print(f"unknown rule {args.explain!r}; known: {', '.join(sorted(RULES))}", file=sys.stderr)
            return 2
        print(explain(rule))
        return 0

    paths = args.paths or ["metrics_tpu"]
    if len(paths) != 1:
        # one tree per run keeps repo-relative baseline keys unambiguous
        print("lint exactly one root per run (got: %s)" % ", ".join(paths), file=sys.stderr)
        return 2

    if args.san:
        return _main_san(args, paths[0])
    if args.race:
        return _main_race(args, paths[0])
    if args.own:
        return _main_own(args, paths[0])
    if args.shard:
        return _main_shard(args, paths[0])

    try:
        report = analyze(
            paths[0],
            baseline_path=args.baseline,
            introspect=not args.no_introspect,
        )
    except FileNotFoundError as err:
        print(f"tmlint: {err}", file=sys.stderr)
        return 2

    selected = None
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",")}
        unknown = selected - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    def keep(f):
        return selected is None or f.rule in selected

    if args.write_baseline:
        import os

        from metrics_tpu.analysis.runner import _find_repo_root

        out = args.baseline or os.path.join(_find_repo_root(paths[0]), baseline_mod.BASELINE_FILENAME)
        n = baseline_mod.write_baseline(
            out,
            [f for f in report.findings if keep(f)],
            reason="bootstrap waiver: pre-existing finding, triage pending",
        )
        print(f"tmlint: wrote {n} waivers to {out}")
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "stats": report.stats,
                    "new": [vars(f) for f in report.new_findings if keep(f)],
                    "waived": [vars(f) for f in report.waived if keep(f)],
                    "unused_waivers": [list(k) for k in report.unused_waivers],
                    "skipped_classes": report.skipped_classes,
                    "parse_errors": report.parse_errors,
                },
                indent=2,
            )
        )
        return 1 if [f for f in report.new_findings if keep(f)] else 0

    new = [f for f in report.new_findings if keep(f)]
    for f in new:
        print(f.format())
    if args.verbose:
        for f in report.waived:
            if keep(f):
                print(f.format() + f"  # reason: {f.waive_reason}")
        for name, reason in sorted(report.skipped_classes.items()):
            print(f"# not introspected: {name}: {reason}")
    for key in report.unused_waivers:
        print(f"# stale waiver (no matching finding): {':'.join(key)}")
    for path, err in sorted(report.parse_errors.items()):
        print(f"# parse error: {path}: {err}")
    s = report.stats
    print(
        f"tmlint: {s['files']} files, {s['functions']} functions "
        f"({s['jit_reachable']} jit-reachable), {s['findings']} findings "
        f"({s['waived']} waived, {len(new)} new) in {s['seconds']}s"
    )
    return 1 if new else 0


def _main_race(args, target: str) -> int:
    """The --race path: the tmrace concurrency tier on its own."""
    import os

    from metrics_tpu.analysis.race.runner import run_race
    from metrics_tpu.analysis.runner import _find_repo_root

    selected = None
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",")}
        unknown = selected - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    def keep(f):
        return selected is None or f.rule in selected

    try:
        report = run_race(target, baseline_path=args.baseline)
    except FileNotFoundError as err:
        print(f"tmrace: {err}", file=sys.stderr)
        return 2

    if args.write_baseline:
        out = args.baseline or os.path.join(
            _find_repo_root(target), baseline_mod.BASELINE_FILENAME
        )
        n = baseline_mod.write_baseline(
            out,
            [f for f in report.findings if keep(f)],
            reason="bootstrap waiver: pre-existing finding, triage pending",
        )
        print(f"tmrace: wrote {n} waivers to {out}")
        return 0

    new = [f for f in report.new_findings if keep(f)]
    if args.json:
        print(
            json.dumps(
                {
                    "stats": report.stats,
                    "roles": report.roles,
                    "new": [vars(f) for f in new],
                    "waived": [vars(f) for f in report.waived if keep(f)],
                    "unused_waivers": [list(k) for k in report.unused_waivers],
                    "parse_errors": report.parse_errors,
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.format())
    if args.verbose:
        for f in report.waived:
            if keep(f):
                print(f.format() + f"  # reason: {f.waive_reason}")
        for role, n in sorted(report.roles.items()):
            print(f"# role {role}: {n} functions")
    for key in report.unused_waivers:
        print(f"# stale waiver (no matching finding): {':'.join(key)}")
    for path, err in sorted(report.parse_errors.items()):
        print(f"# parse error: {path}: {err}")
    s = report.stats
    print(
        f"tmrace: {s['files']} files, {s['functions']} functions, "
        f"{s['locks']} locks, {s['roles']} roles, {s['threads']} thread spawns, "
        f"{s['findings']} findings ({s['waived']} waived, {len(new)} new) "
        f"in {s['seconds']}s"
    )
    return 1 if new else 0


def _main_own(args, target: str) -> int:
    """The --own path: the tmown buffer-ownership tier on its own."""
    import os

    from metrics_tpu.analysis.own import engine_contract
    from metrics_tpu.analysis.own.runner import run_own
    from metrics_tpu.analysis.runner import _find_repo_root

    selected = None
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",")}
        unknown = selected - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    def keep(f):
        return selected is None or f.rule in selected

    try:
        report = run_own(target, baseline_path=args.baseline)
    except FileNotFoundError as err:
        print(f"tmown: {err}", file=sys.stderr)
        return 2

    if args.write_drift:
        out = os.path.join(_find_repo_root(target), engine_contract.DRIFT_FILENAME)
        engine_contract.write_worksheet(out, report.drift_worksheet())
        print(f"tmown: wrote {len(report.contract)} engine contracts to {out}")

    if args.write_baseline:
        out = args.baseline or os.path.join(
            _find_repo_root(target), baseline_mod.BASELINE_FILENAME
        )
        n = baseline_mod.write_baseline(
            out,
            [f for f in report.findings if keep(f)],
            reason="bootstrap waiver: pre-existing finding, triage pending",
        )
        print(f"tmown: wrote {n} waivers to {out}")
        return 0

    new = [f for f in report.new_findings if keep(f)]
    if args.json:
        print(
            json.dumps(
                {
                    "stats": report.stats,
                    "contract": report.contract,
                    "new": [vars(f) for f in new],
                    "waived": [vars(f) for f in report.waived if keep(f)],
                    "unused_waivers": [list(k) for k in report.unused_waivers],
                    "parse_errors": report.parse_errors,
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.format())
    if args.verbose:
        for f in report.waived:
            if keep(f):
                print(f.format() + f"  # reason: {f.waive_reason}")
        for engine, facts in sorted(report.contract.items()):
            have = [c for c, ev in facts["components"].items() if ev]
            print(f"# engine {engine}: {len(have)}/{len(facts['components'])} components")
    for key in report.unused_waivers:
        print(f"# stale waiver (no matching finding): {':'.join(key)}")
    for path, err in sorted(report.parse_errors.items()):
        print(f"# parse error: {path}: {err}")
    s = report.stats
    print(
        f"tmown: {s['files']} files, {s['functions']} functions, "
        f"{s['donating']} donating, {s['exec_sites']} exec sites, "
        f"{s['engines']} engines, {s['findings']} findings "
        f"({s['waived']} waived, {len(new)} new) in {s['seconds']}s"
    )
    return 1 if new else 0


def _main_shard(args, target: str) -> int:
    """The --shard path: the tmshard sharding/collective tier on its own."""
    import os

    from metrics_tpu.analysis.runner import _find_repo_root
    from metrics_tpu.analysis.shard import plan as plan_mod
    from metrics_tpu.analysis.shard.runner import run_shard

    selected = None
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",")}
        unknown = selected - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    def keep(f):
        return selected is None or f.rule in selected

    try:
        report = run_shard(target, baseline_path=args.baseline)
    except FileNotFoundError as err:
        print(f"tmshard: {err}", file=sys.stderr)
        return 2

    if args.write_plan:
        out = os.path.join(_find_repo_root(target), plan_mod.PLAN_FILENAME)
        payload = report.plan_worksheet()
        plan_mod.write_worksheet(out, payload)
        print(
            f"tmshard: wrote {len(payload['classes'])} class plans"
            f" ({len(payload['skipped'])} skipped) to {out}"
        )

    if args.write_baseline:
        out = args.baseline or os.path.join(
            _find_repo_root(target), baseline_mod.BASELINE_FILENAME
        )
        n = baseline_mod.write_baseline(
            out,
            [f for f in report.findings if keep(f)],
            reason="bootstrap waiver: pre-existing finding, triage pending",
        )
        print(f"tmshard: wrote {n} waivers to {out}")
        return 0

    new = [f for f in report.new_findings if keep(f)]
    if args.json:
        print(
            json.dumps(
                {
                    "stats": report.stats,
                    "mesh_matrix": report.mesh_matrix,
                    "new": [vars(f) for f in new],
                    "waived": [vars(f) for f in report.waived if keep(f)],
                    "unused_waivers": [list(k) for k in report.unused_waivers],
                    "parse_errors": report.parse_errors,
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.format())
    if args.verbose:
        for f in report.waived:
            if keep(f):
                print(f.format() + f"  # reason: {f.waive_reason}")
        for engine, facts in sorted(report.mesh_matrix.items()):
            have = [c for c, ev in facts["components"].items() if ev]
            print(f"# engine {engine}: {len(have)}/{len(facts['components'])} components")
    for key in report.unused_waivers:
        print(f"# stale waiver (no matching finding): {':'.join(key)}")
    for path, err in sorted(report.parse_errors.items()):
        print(f"# parse error: {path}: {err}")
    s = report.stats
    print(
        f"tmshard: {s['files']} files, {s['functions']} functions, "
        f"{s['mapped_bodies']} mapped bodies, {s['collectives']} collectives, "
        f"{s['placements']} placements, {s['engines']} engines, "
        f"{s['findings']} findings ({s['waived']} waived, {len(new)} new) "
        f"in {s['seconds']}s"
    )
    return 1 if new else 0


def _main_san(args, target: str) -> int:
    """The --san path: full two-tier run (tmlint + tmsan)."""
    import os

    from metrics_tpu.analysis.runner import _find_repo_root
    from metrics_tpu.analysis.san import costs as costs_mod
    from metrics_tpu.analysis.san.runner import run_san

    selected = None
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",")}
        unknown = selected - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    def keep(f):
        return selected is None or f.rule in selected

    report = run_san(
        target,
        baseline_path=args.baseline,
        costs_path=args.costs,
        with_costs=not args.no_costs,
    )

    if args.write_costs:
        repo_root = _find_repo_root(target)
        out = args.costs or os.path.join(repo_root, costs_mod.COSTS_FILENAME)
        n = costs_mod.write_costs(out, report.costs)
        print(f"tmsan: wrote {n} cost-budget entries to {out}")

    if args.write_baseline:
        from metrics_tpu.analysis import baseline as baseline_mod
        from metrics_tpu.analysis.runner import _find_repo_root as _frr

        out = args.baseline or os.path.join(_frr(target), baseline_mod.BASELINE_FILENAME)
        lint_findings = report.lint.findings if report.lint is not None else []
        n = baseline_mod.write_baseline(
            out,
            [f for f in lint_findings + report.findings if keep(f) and f.rule != "TMS-BUDGET"],
            reason="bootstrap waiver: pre-existing finding, triage pending",
        )
        print(f"tmsan: wrote {n} waivers to {out}")
        return 0

    lint_new = [f for f in (report.lint.new_findings if report.lint else []) if keep(f)]
    san_new = [f for f in report.new_findings if keep(f)]
    unused = sorted(set(report.lint.unused_waivers if report.lint else []) | set(report.unused_waivers))

    if args.json:
        print(
            json.dumps(
                {
                    "stats": {**(report.lint.stats if report.lint else {}), **{f"san_{k}": v for k, v in report.stats.items()}},
                    "new": [vars(f) for f in lint_new + san_new],
                    "waived": [vars(f) for f in (report.lint.waived if report.lint else []) + report.waived if keep(f)],
                    "unused_waivers": [list(k) for k in unused],
                    "skipped": report.skipped,
                    "costs": report.costs,
                    "budget_notes": report.budget_notes,
                    "waiver_status": report.waiver_status,
                },
                indent=2,
            )
        )
        return 1 if (lint_new or san_new) else 0

    for f in lint_new + san_new:
        print(f.format())
    if args.verbose:
        for f in (report.lint.waived if report.lint else []) + report.waived:
            if keep(f):
                print(f.format() + f"  # reason: {f.waive_reason}")
        for name, reason in sorted(report.skipped.items()):
            print(f"# not traced: {name}: {reason}")
    for key_str, status in sorted(report.waiver_status.items()):
        print(f"# waiver {key_str}: {status}")
    for note in report.budget_notes:
        print(f"# budget: {note}")
    for key in unused:
        print(f"# stale waiver (no matching finding): {':'.join(key)}")
    s, ls = report.stats, (report.lint.stats if report.lint else {})
    print(
        f"tmsan: {s['classes_traced']} classes traced ({s['entries_traced']} abstract "
        f"traces, {s['skipped']} skipped), {s['cost_entries']} cost entries, "
        f"{s['findings']} san findings ({s['waived']} waived, {len(san_new)} new) "
        f"+ {ls.get('new', 0):.0f} lint new, in {s['seconds']}s "
        f"(trace+analyze {s['trace_seconds']}s)"
    )
    return 1 if (lint_new or san_new) else 0


if __name__ == "__main__":
    sys.exit(main())
