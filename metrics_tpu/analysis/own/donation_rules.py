"""tmown dataflow rules: turn the flow walk's events into findings.

The model (``buffer_model.py``) records *facts*; this module is the *policy*
layer — which events become findings, under which rule id, with what message.
The split mirrors tmrace's model / rule-module layering, and keeps the
fixture-facing behavior (exact rule id + symbol) in one place.

Symbols: the function qualname for value-lifetime rules, and
``qualname.<name>`` for TMO-KEY-GAP (one waiver per missing key input, so a
triaged by-design gap — fused's ``fresh``, ingest's ``filter_kwargs`` — stays
waived when a new gap appears in the same function).
"""
from typing import List

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.own.buffer_model import OwnModel

#: event kind -> (rule id, message prefix)
_EVENT_RULES = {
    "donate_alias": (
        "TMO-DONATE-ALIAS",
        "possibly-aliasing buffer donated without an owning copy "
        "(materialize with jnp.array(..., copy=True) / ckpt.restore._owned): ",
    ),
    "use_after_donate": (
        "TMO-USE-AFTER-DONATE",
        "read after donation, before re-pointing: ",
    ),
    "double_donate": (
        "TMO-DOUBLE-DONATE",
        "one buffer donated twice in one call: ",
    ),
    "snapshot_gap": (
        "TMO-SNAPSHOT-GAP",
        "snapshot-before-donate guard missing: ",
    ),
    "key_gap": (
        "TMO-KEY-GAP",
        "executable-cache key gap: ",
    ),
}


def dataflow_findings(model: OwnModel) -> List[Finding]:
    """All findings from the five per-function dataflow rules, deduplicated
    on (rule, path, symbol, line) and sorted for stable output."""
    out: List[Finding] = []
    seen = set()
    for _m, func in model.all_functions():
        for event in func.events:
            rule, prefix = _EVENT_RULES[event.kind]
            key = (rule, event.path, event.symbol, event.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(
                Finding(
                    rule=rule,
                    path=event.path,
                    line=event.line,
                    col=event.col,
                    symbol=event.symbol,
                    message=prefix + event.detail,
                )
            )
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return out
