"""TMO-ENGINE-DRIFT: machine-checked inventory of the four launch engines.

fused, fleet, ingest, and the rank dispatch each hand-roll the same launch
contract — donation shielding, a keyed executable cache, demote-on-failure,
warm-manifest record/replay. ROADMAP item 5 wants them collapsed into one
``serve/engine.py``; this module extracts each engine's implementation of
every contract component from the ownership model and flags divergence, and
its full per-engine matrix is the checked-in design worksheet
(``tmown_engine_drift.json``) the unification refactor starts from.

A component is *drifted* when an engine lacks it while at least two other
engines implement it — "everyone but you" is the signal that one copy of the
contract went its own way (a component nobody has is just not part of the
contract yet).
"""
import json
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.own.buffer_model import OwnFunc, OwnModel, OwnModuleModel

DRIFT_FILENAME = "tmown_engine_drift.json"

#: engine -> (repo-relative path, anchor qualname or None for whole-module).
#: The anchor is the donating launch path; component evidence is gathered over
#: the anchor plus its transitively-called package functions.
ENGINES: Dict[str, Tuple[str, Optional[str]]] = {
    "fused": ("metrics_tpu/core/fused.py", "FusedCollectionUpdate._launch"),
    "fleet": ("metrics_tpu/core/fleet.py", "run_step"),
    "ingest": ("metrics_tpu/serve/ingest.py", "IngestQueue._launch_chain"),
    "rank": ("metrics_tpu/ops/clf_curve.py", None),  # module-level jit kernels
}

#: the shared contract: component -> human description (worksheet rows)
COMPONENTS: Dict[str, str] = {
    "donation": "in-place accumulation via donate_argnums on the launch step",
    "donation_guard": "duplicate-buffer dedup before donation (_donation_guard)",
    "snapshot_before_donate": "materialize pending async-ckpt snapshots first",
    "default_shield": "registered-default leaves copied before donation (_protected_ids)",
    "executable_cache": "keyed AOT executable cache (.lower().compile() reuse)",
    "demote_on_failure": "broken-key sentinel: failed signature degrades, never retries",
    "warm_manifest_record": "compile recorded for excache prewarm replay (record_*_compile)",
}


def _reachable(
    model: OwnModel, module: OwnModuleModel, func: OwnFunc, _seen=None
) -> List[OwnFunc]:
    """The anchor plus every package function it transitively calls — walked
    over the raw AST call symbols so helper evidence (``_gather_states`` ->
    ``_protected_ids``) counts toward its engine."""
    import ast

    from metrics_tpu.analysis.jitmap import dotted_name

    if _seen is None:
        _seen = set()
    key = (module.path, func.qualname)
    if key in _seen:
        return []
    _seen.add(key)
    out = [func]
    node = module.find_def(func.qualname)
    if node is None:
        return out
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if not name:
                continue
            hit = model.resolve_call(module, name, func)
            if hit:
                out.extend(_reachable(model, hit[0], hit[1], _seen))
    return out


def _engine_facts(
    model: OwnModel, path: str, anchor: Optional[str]
) -> Optional[Dict]:
    module = model.modules.get(path)
    if module is None:
        return None
    if anchor is not None:
        root = module.functions.get(anchor)
        if root is None:
            return None
        funcs = _reachable(model, module, root)
        anchor_line = root.line
    else:
        funcs = list(module.functions.values())
        anchor_line = 1

    def evidence(pred) -> Optional[str]:
        for f in funcs:
            if pred(f):
                return f.qualname
        return None

    present: Dict[str, Optional[str]] = {
        "donation": evidence(lambda f: f.exec_sites > 0 or f.builds_donating),
        "donation_guard": evidence(lambda f: "dedup" in f.shield_calls or f.dedup_shield),
        "snapshot_before_donate": evidence(
            lambda f: "snapshot" in f.shield_calls or f.snapshot_shield
        ),
        "default_shield": evidence(
            lambda f: "_protected_ids" in f.qualname
            or any("_protected_ids" in e for e in _called_names(model, module, f))
        ),
        "executable_cache": evidence(lambda f: f.cache_get or f.cache_store),
        "demote_on_failure": evidence(lambda f: f.demote_sentinel),
        "warm_manifest_record": evidence(lambda f: bool(f.warm_records)),
    }
    key_fields: List[str] = []
    for f in funcs:
        if f.key_fields:
            key_fields = f.key_fields
            break
    return {
        "path": path,
        "anchor": anchor or "<module>",
        "anchor_line": anchor_line,
        "components": present,
        "key_fields": key_fields,
    }


def _called_names(model: OwnModel, module: OwnModuleModel, func: OwnFunc) -> List[str]:
    import ast

    from metrics_tpu.analysis.jitmap import dotted_name

    node = module.find_def(func.qualname)
    if node is None:
        return []
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name:
                out.append(name)
    return out


def extract_contract(
    model: OwnModel, engines: Optional[Dict[str, Tuple[str, Optional[str]]]] = None
) -> Dict[str, Dict]:
    """Per-engine component matrix; engines whose anchor file is absent from
    the analyzed tree are skipped (fixture runs never see the repo anchors)."""
    engines = ENGINES if engines is None else engines
    out: Dict[str, Dict] = {}
    for name, (path, anchor) in engines.items():
        facts = _engine_facts(model, path, anchor)
        if facts is not None:
            out[name] = facts
    return out


def drift_findings(matrix: Dict[str, Dict]) -> List[Finding]:
    """One finding per (engine, component absent while >= 2 peers have it)."""
    out: List[Finding] = []
    for component, description in COMPONENTS.items():
        holders = [e for e, facts in matrix.items() if facts["components"].get(component)]
        if len(holders) < 2:
            continue
        for engine, facts in sorted(matrix.items()):
            if facts["components"].get(component):
                continue
            out.append(
                Finding(
                    rule="TMO-ENGINE-DRIFT",
                    path=facts["path"],
                    line=facts["anchor_line"],
                    col=0,
                    symbol=f"{engine}.{component}",
                    message=(
                        f"engine contract drift: {engine} lacks "
                        f"'{component}' ({description}) implemented by "
                        f"{', '.join(sorted(holders))} — ROADMAP item 5 input, "
                        f"see {DRIFT_FILENAME}"
                    ),
                )
            )
    out.sort(key=lambda f: (f.path, f.symbol))
    return out


def worksheet(matrix: Dict[str, Dict], findings: List[Finding]) -> Dict:
    """The checked-in ROADMAP-item-5 worksheet payload (deterministic)."""
    return {
        "version": 1,
        "comment": (
            "tmown engine-contract worksheet: what the unified serve/engine.py"
            " (ROADMAP item 5) must absorb from each launch engine. Regenerate"
            " with `python -m metrics_tpu.analysis --own --write-drift` after"
            " engine changes; test_tmown.py compares this file to a fresh run."
        ),
        "contract": COMPONENTS,
        "engines": {
            name: {
                "path": facts["path"],
                "anchor": facts["anchor"],
                "components": {
                    comp: facts["components"].get(comp) for comp in COMPONENTS
                },
                "key_fields": facts["key_fields"],
            }
            for name, facts in sorted(matrix.items())
        },
        "divergences": [
            {"symbol": f.symbol, "message": f.message} for f in findings
        ],
    }


def write_worksheet(path: str, payload: Dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


def load_worksheet(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
