"""tmown — the buffer-ownership & donation-lifetime tier (TMO-* rules).

The fourth analysis tier: tmlint reasons about traces, tmsan about jaxprs,
tmrace about threads; tmown reasons about *device-buffer ownership* — the
lifetime of every array value flowing through a ``donate_argnums`` boundary.
Born from the PR 16 incident: ``jnp.asarray`` over numpy-backed restored
state zero-copy aliased host memory, and donating that buffer into an
executable deserialized from the persistent compile cache corrupted the heap.
No existing tier could see it; this one exists so nothing like it lands again.

Entry point: ``metrics_tpu.analysis.own.runner.run_own`` /
``python -m metrics_tpu.analysis --own``. Kept import-light like the san and
race tiers — importing ``metrics_tpu.analysis`` does not pull this package.
"""

from metrics_tpu.analysis.own.runner import OwnReport, run_own  # noqa: F401

__all__ = ["OwnReport", "run_own"]
