"""The buffer-ownership model: who owns which device buffer, and for how long.

Phase A parses every module of the analyzed tree into an
:class:`OwnModuleModel`: the function index (methods and nested defs), the
import table, and the class index — the same skeleton tmrace builds, but the
per-function pass here is a *provenance* dataflow instead of a lock walk.

Phase B (:class:`OwnModel`) links the package and runs an interprocedural
summary fixpoint: per-function summaries (``returns_owned``,
``returns_alias``, ``returns_donating``, snapshot/dedup shield) feed back into
every function's flow walk until stable, so ``compiled = self._compile(...)``
resolves to a donating executable because ``_compile`` returns
``jitted.lower(...).compile()`` of a ``donate_argnums`` jit two modules away.

The ownership lattice (per local name, flow-sensitive):

- ``OWNED``   — a fresh device buffer XLA may consume: ``jnp.array`` (copies
  by default), explicit ``copy=True``, ``.copy()``, ``jnp.zeros``-family,
  ``jax.random.*``, or the result of executing a compiled step.
- ``HOST``    — host-allocated numpy memory (``np.asarray``/``np.zeros``/...):
  ``jnp.asarray`` over it may produce a ZERO-COPY device view on CPU.
- ``ALIAS``   — a buffer known to alias memory the program does not own:
  ``np.frombuffer`` payload views, ``memoryview``, ``jnp.asarray``/
  ``jnp.array(copy=False)`` over HOST/ALIAS values, views of ALIAS values.
  Donating one is the PR 16 heap-corruption class (TMO-DONATE-ALIAS).
- ``UNKNOWN`` — anything else; never flagged (low-FP by construction).
- ``DONATED`` — flowed into a donated position of an executed donating call;
  dead until the name is re-pointed by reassignment (TMO-USE-AFTER-DONATE).

The walk emits :class:`OwnEvent` records; ``donation_rules.py`` turns them
into findings (separating facts from policy/phrasing, like tmrace's model /
rule-module split).
"""
import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.jitmap import dotted_name

# ------------------------------------------------------------------ lattice

OWNED = "owned"
HOST = "host"
ALIAS = "alias"
UNKNOWN = "unknown"
DONATED = "donated"

#: merge severity: the worst provenance wins at a control-flow join
_SEVERITY = {DONATED: 4, ALIAS: 3, HOST: 2, UNKNOWN: 1, OWNED: 0}

#: call last-components that materialize pending async-ckpt snapshots
_SNAPSHOT_SHIELDS = {
    "secure_pending_snapshots", "_secure_ckpt_snapshots", "_shield_donation",
}
#: call last-components that dedup duplicate buffers before donation
_DEDUP_SHIELDS = {"_donation_guard", "_shield_donation"}

#: numpy constructors that allocate (or wrap) host memory
_NP_HOST_CTORS = {
    "asarray", "array", "zeros", "ones", "empty", "full", "arange",
    "linspace", "copy", "ascontiguousarray", "stack", "concatenate",
}
#: numpy constructors that *wrap existing memory* without owning it
_NP_ALIAS_CTORS = {"frombuffer"}


def _merge_prov(*provs: str) -> str:
    return max(provs, key=lambda p: _SEVERITY.get(p, 1))


# ------------------------------------------------------------------ records


@dataclass
class OwnEvent:
    """One rule-relevant fact found by the flow walk (pre-finding)."""

    kind: str  # donate_alias | use_after_donate | double_donate | snapshot_gap | key_gap
    path: str
    line: int
    col: int
    symbol: str  # function qualname (key_gap: qualname.<missing name>)
    detail: str  # human fragment for the finding message


@dataclass
class OwnFunc:
    """Per-function facts: identity plus the Phase B analysis output."""

    qualname: str
    modname: str
    path: str
    line: int
    cls: Optional[str]
    params: Tuple[str, ...] = ()
    # filled per Phase B pass:
    events: List[OwnEvent] = field(default_factory=list)
    exec_sites: int = 0  # donating executions seen (engine_contract input)
    exec_lines: List[int] = field(default_factory=list)
    builds_donating: bool = False  # constructs a donate_argnums jit
    cache_get: bool = False
    cache_store: bool = False
    demote_sentinel: bool = False  # references a *broken* key/sentinel
    warm_records: List[str] = field(default_factory=list)  # record_*_compile
    shield_calls: Set[str] = field(default_factory=set)  # snapshot | dedup
    key_exprs: List[str] = field(default_factory=list)  # unparse of cache keys
    key_fields: List[str] = field(default_factory=list)  # expanded key tuple
    # summary (interprocedural fixpoint state):
    returns_owned: bool = False
    returns_alias: bool = False
    returns_donating: Optional[Tuple[int, ...]] = None
    snapshot_shield: bool = False
    dedup_shield: bool = False

    def summary_key(self) -> Tuple:
        return (
            self.returns_owned, self.returns_alias, self.returns_donating,
            self.snapshot_shield, self.dedup_shield,
        )


# ------------------------------------------------------------- module model


class OwnModuleModel:
    """Phase A: one file's function index + import table."""

    def __init__(self, path: str, modname: str, source: str) -> None:
        self.path = path
        self.modname = modname
        self.short = modname.split(".")[-1]
        self.tree = ast.parse(source)
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, OwnFunc] = {}
        self.classes: Set[str] = set()
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.imports[local] = alias.name
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                for alias in stmt.names:
                    local = alias.asname or alias.name
                    self.imports[local] = f"{stmt.module}:{alias.name}"
        self._walk_defs(self.tree.body, prefix="", cls=None)

    def _walk_defs(self, body: Sequence[ast.stmt], prefix: str, cls: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                args = stmt.args
                params = tuple(
                    a.arg
                    for a in (args.posonlyargs + args.args + args.kwonlyargs)
                ) + tuple(a.arg for a in (args.vararg, args.kwarg) if a)
                self.functions[qual] = OwnFunc(
                    qualname=qual, modname=self.modname, path=self.path,
                    line=stmt.lineno, cls=cls, params=params,
                )
                self._walk_defs(stmt.body, prefix=qual + ".", cls=cls)
            elif isinstance(stmt, ast.ClassDef):
                self.classes.add(stmt.name)
                self._walk_defs(stmt.body, prefix=prefix + stmt.name + ".", cls=stmt.name)

    def find_def(self, qualname: str):
        """Locate the (possibly nested) def node for a dotted qualname."""
        parts = qualname.split(".")
        scope: Sequence[ast.stmt] = self.tree.body
        node = None
        for part in parts:
            node = None
            for stmt in scope:
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                    and stmt.name == part
                ):
                    node = stmt
                    break
            if node is None:
                return None
            scope = node.body
        return node if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None

    # ---- numpy / jax.numpy recognition through the import table

    def _base_of(self, name: str) -> str:
        return name.split(".")[0]

    def is_numpy(self, name: str) -> bool:
        base = self._base_of(name)
        imported = self.imports.get(base, "")
        return base in ("np", "numpy") or imported.startswith("numpy")

    def is_jnp(self, name: str) -> bool:
        base = self._base_of(name)
        imported = self.imports.get(base, "")
        return (
            base == "jnp"
            or imported.startswith("jax.numpy")
            or name.startswith("jax.numpy.")
        )

    def is_jax_fresh(self, name: str) -> bool:
        """jax.random / jax.lax / jnp compute — fresh device results."""
        return name.startswith(("jax.random.", "jax.lax.")) or (
            self.is_jnp(name) and name.split(".")[-1] not in ("asarray", "array")
        )


# ------------------------------------------------------------ package model


class OwnModel:
    """Phase B: linked package + summary fixpoint + flow walks."""

    def __init__(self, files: Dict[str, Tuple[str, str]]) -> None:
        self.modules: Dict[str, OwnModuleModel] = {}
        self.errors: Dict[str, str] = {}
        for path, (modname, source) in files.items():
            try:
                self.modules[path] = OwnModuleModel(path, modname, source)
            except SyntaxError as err:
                self.errors[path] = f"SyntaxError: {err}"
        self.by_modname = {m.modname: m for m in self.modules.values()}
        self.class_index: Dict[str, OwnModuleModel] = {}
        for m in self.modules.values():
            for cls in m.classes:
                self.class_index.setdefault(cls, m)
        self.link()

    def all_functions(self):
        for m in self.modules.values():
            for func in m.functions.values():
                yield m, func

    # ------------------------------------------------------------ resolver

    def resolve_call(
        self, module: OwnModuleModel, symbol: str, caller: OwnFunc
    ) -> Optional[Tuple[OwnModuleModel, OwnFunc]]:
        """Resolve a call symbol to a package function, or None (external)."""
        if symbol.startswith("self."):
            rest = symbol[5:]
            if caller.cls:
                hit = module.functions.get(f"{caller.cls}.{rest}")
                if hit:
                    return module, hit
            return None
        if "." not in symbol:
            prefix = caller.qualname.rsplit(".", 1)[0] + "." if "." in caller.qualname else ""
            for cand in (
                prefix + symbol,
                (caller.cls + "." + symbol) if caller.cls else "",
                symbol,
            ):
                if cand and cand in module.functions:
                    return module, module.functions[cand]
            imported = module.imports.get(symbol)
            if imported and ":" in imported:
                modname, _, name = imported.partition(":")
                other = self.by_modname.get(modname)
                if other and name in other.functions:
                    return other, other.functions[name]
            return None
        base, _, attr = symbol.partition(".")
        imported = module.imports.get(base)
        if imported:
            if ":" in imported:
                mn, _, nm = imported.partition(":")
                # from pkg import mod; mod.func(...)
                sub = self.by_modname.get(f"{mn}.{nm}")
                if sub and attr in sub.functions:
                    return sub, sub.functions[attr]
                # from pkg import Class; Class.method(...)
                if nm in self.class_index:
                    tmod = self.class_index[nm]
                    hit = tmod.functions.get(f"{nm}.{attr.split('.')[-1]}")
                    if hit:
                        return tmod, hit
                return None
            other = self.by_modname.get(imported)
            if other:
                hit = other.functions.get(attr)
                if hit:
                    return other, hit
        if base in self.class_index:
            tmod = self.class_index[base]
            hit = tmod.functions.get(symbol)
            if hit:
                return tmod, hit
        return None

    # ------------------------------------------------------------- linking

    def link(self) -> None:
        """Seed shield summaries by name, then run the summary fixpoint."""
        for _m, func in self.all_functions():
            last = func.qualname.split(".")[-1]
            if last in _SNAPSHOT_SHIELDS:
                func.snapshot_shield = True
            if last in _DEDUP_SHIELDS:
                func.dedup_shield = True
        # fixpoint: each pass re-walks every function body with the current
        # summaries; summaries only grow, so this converges in a few passes
        # (the repo's deepest donating chain is _launch -> _compile, depth 2).
        for _ in range(4):
            changed = False
            for m, func in self.all_functions():
                before = func.summary_key()
                _FlowWalker(self, m, func).run()
                if func.summary_key() != before:
                    changed = True
            if not changed:
                break


# ---------------------------------------------------------------- flow walk


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _parse_donate_positions(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """The donate_argnums value as concrete positions; (0,) when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)  # () == donation disabled
    if isinstance(node, ast.IfExp):
        # donate_argnums=(0,) if donate else (): the may-donate branch governs
        for branch in (node.body, node.orelse):
            pos = _parse_donate_positions(branch)
            if pos:
                return pos
        return ()
    return (0,)  # explicit donate_argnums with an opaque value: assume pos 0


def _handler_probes_deleted(handler: ast.ExceptHandler) -> bool:
    """True when the except body consults is_deleted/_leaf_deleted — the
    sanctioned recovery idiom (the runtime twin of TMO-USE-AFTER-DONATE)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Attribute) and node.attr == "is_deleted":
            return True
        if isinstance(node, ast.Constant) and node.value == "is_deleted":
            return True
        if isinstance(node, ast.Name) and "_leaf_deleted" in node.id:
            return True
    return False


class _FlowWalker:
    """One function's provenance walk: fills func.events and the summary."""

    def __init__(self, model: OwnModel, module: OwnModuleModel, func: OwnFunc) -> None:
        self.model = model
        self.module = module
        self.func = func
        self.node = module.find_def(func.qualname)
        self.events: List[OwnEvent] = []
        self.snapshot_seen = False
        self.dedup_seen = False
        self.exempt_uad = 0  # inside an is_deleted-probing except handler
        self.uad_reported: Set[str] = set()
        self.exec_sites = 0
        self.exec_lines: List[int] = []
        self.exec_calls: List[ast.Call] = []
        self.builds_donating = False
        self.cache_get = False
        self.cache_store = False
        self.demote_sentinel = False
        self.warm_records: List[str] = []
        self.shield_calls: Set[str] = set()
        self.cache_key_nodes: List[ast.AST] = []
        self.donating_call_args: List[ast.Call] = []  # calls returning donating
        self.jit_targets: List[ast.AST] = []  # first arg of jax.jit(...)
        self.ret_provs: List[str] = []
        self.ret_donating: Optional[Tuple[int, ...]] = None
        # flow-insensitive prepasses
        self.assigns: Dict[str, List[ast.expr]] = {}
        self.nested_defs: Dict[str, ast.AST] = {}
        self.donating_names: Dict[str, Tuple[int, ...]] = {}
        if self.node is not None:
            self._prepass()

    # ------------------------------------------------------------ prepass

    def _prepass(self) -> None:
        for node in ast.walk(self.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    self.assigns.setdefault(tgt.id, []).append(node.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not self.node:
                    self.nested_defs.setdefault(node.name, node)
            if isinstance(node, (ast.Name, ast.Attribute)):
                dn = dotted_name(node) or ""
                if "broken" in dn.lower().split(".")[-1].lower():
                    self.demote_sentinel = True
        # donating-wrapper fixpoint over local assignments (cache.get can
        # lexically precede the compile assignment that types the name)
        for _ in range(4):
            changed = False
            for name, values in self.assigns.items():
                if name in self.donating_names:
                    continue
                for value in values:
                    pos = self._donating_of(value)
                    if pos:
                        self.donating_names[name] = pos
                        changed = True
                        break
            if not changed:
                break

    def _donating_of(self, expr: ast.AST) -> Optional[Tuple[int, ...]]:
        """Donate positions when ``expr`` evaluates to a donating wrapper or
        executable (jit / .lower / .compile chains / donating-returning call)."""
        if isinstance(expr, ast.Name):
            return self.donating_names.get(expr.id)
        if not isinstance(expr, ast.Call):
            return None
        fn = expr.func
        name = dotted_name(fn) or ""
        last = name.split(".")[-1]
        if last == "jit":
            for kw in expr.keywords:
                if kw.arg == "donate_argnums":
                    pos = _parse_donate_positions(kw.value)
                    if pos:
                        if expr.args:
                            self.jit_targets.append(expr.args[0])
                        self.builds_donating = True
                    return pos or None
            return None
        if isinstance(fn, ast.Attribute) and fn.attr in ("lower", "compile"):
            return self._donating_of(fn.value)
        # interprocedural: a call whose resolved summary returns an executable
        if name:
            hit = self.model.resolve_call(self.module, name, self.func)
            if hit and hit[1].returns_donating:
                self.donating_call_args.append(expr)
                return hit[1].returns_donating
        return None

    # ---------------------------------------------------------------- run

    def run(self) -> None:
        if self.node is None:
            return
        env: Dict[str, str] = {}
        self._flow(self.node.body, env)
        # summary
        f = self.func
        f.events = self.events + self._key_gap_events()
        f.exec_sites = self.exec_sites
        f.exec_lines = self.exec_lines
        f.builds_donating = f.builds_donating or self.builds_donating
        f.cache_get = self.cache_get
        f.cache_store = self.cache_store
        f.demote_sentinel = self.demote_sentinel
        f.warm_records = self.warm_records
        f.shield_calls = self.shield_calls
        f.key_exprs = [
            _safe_unparse(n) for n in self.cache_key_nodes
        ]
        f.key_fields = self._key_fields()
        if self.ret_provs:
            f.returns_owned = all(p == OWNED for p in self.ret_provs)
            f.returns_alias = f.returns_alias or any(p == ALIAS for p in self.ret_provs)
        if self.ret_donating:
            f.returns_donating = self.ret_donating
        # shield-ness propagates to callers only through dedicated helpers:
        # a function with its own donating execs consumes, not provides, it
        if self.exec_sites == 0:
            if "snapshot" in self.shield_calls:
                f.snapshot_shield = True
            if "dedup" in self.shield_calls:
                f.dedup_shield = True

    # ------------------------------------------------------ statement walk

    def _flow(self, body: Sequence[ast.stmt], env: Dict[str, str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate OwnFuncs
            if isinstance(stmt, ast.Assign):
                prov = self._scan_expr(stmt.value, env)
                for target in stmt.targets:
                    if isinstance(target, ast.Subscript):
                        recv = dotted_name(target.value) or ""
                        if "cache" in recv.lower():
                            self.cache_store = True
                            self.cache_key_nodes.append(target.slice)
                    self._assign_target(target, prov, env)
            elif isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    prov = self._scan_expr(stmt.value, env)
                    self._assign_target(stmt.target, prov, env)
            elif isinstance(stmt, ast.AugAssign):
                self._scan_expr(stmt.value, env)
                if isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = UNKNOWN
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    pos = self._donating_of(stmt.value)
                    if pos:
                        self.ret_donating = pos
                    self.ret_provs.append(self._scan_expr(stmt.value, env))
                else:
                    self.ret_provs.append(UNKNOWN)
            elif isinstance(stmt, ast.If):
                self._scan_expr(stmt.test, env)
                self._branch([stmt.body, stmt.orelse], env)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, env)
                if isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = UNKNOWN
                self._branch([stmt.body, stmt.orelse], env)
            elif isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, env)
                self._branch([stmt.body, stmt.orelse], env)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_expr(item.context_expr, env)
                    if item.optional_vars is not None and isinstance(item.optional_vars, ast.Name):
                        env[item.optional_vars.id] = UNKNOWN
                self._flow(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                self._flow(stmt.body, env)
                for handler in stmt.handlers:
                    henv = dict(env)
                    exempt = _handler_probes_deleted(handler)
                    if exempt:
                        self.exempt_uad += 1
                    try:
                        self._flow(handler.body, henv)
                    finally:
                        if exempt:
                            self.exempt_uad -= 1
                self._flow(stmt.orelse, env)
                self._flow(stmt.finalbody, env)
            elif isinstance(stmt, (ast.Expr, ast.Raise, ast.Assert, ast.Delete)):
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self._scan_expr(child, env)
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self._scan_expr(child, env)

    def _branch(self, bodies: Sequence[Sequence[ast.stmt]], env: Dict[str, str]) -> None:
        """Walk alternative bodies on copies and merge worst-case back."""
        shield0 = (self.snapshot_seen, self.dedup_seen)
        branch_envs: List[Dict[str, str]] = []
        shields: List[Tuple[bool, bool]] = []
        for body in bodies:
            benv = dict(env)
            self.snapshot_seen, self.dedup_seen = shield0
            self._flow(body, benv)
            branch_envs.append(benv)
            shields.append((self.snapshot_seen, self.dedup_seen))
        # a shield only dominates later code if every path passed it
        self.snapshot_seen = all(s for s, _ in shields)
        self.dedup_seen = all(d for _, d in shields)
        keys = set(env)
        for benv in branch_envs:
            keys |= set(benv)
        for k in keys:
            vals = [benv.get(k, env.get(k, UNKNOWN)) for benv in branch_envs]
            env[k] = _merge_prov(*vals)

    def _assign_target(self, target: ast.AST, prov: str, env: Dict[str, str]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = prov
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                # an exec result unpack re-points every target at fresh buffers
                self._assign_target(elt, prov if prov == OWNED else UNKNOWN, env)
        # attribute/subscript stores don't change local provenance

    # ----------------------------------------------------- expression walk

    def _scan_expr(self, expr: ast.AST, env: Dict[str, str]) -> str:
        """Scan for rule events; return the expression's provenance."""
        if isinstance(expr, ast.Name):
            prov = env.get(expr.id, UNKNOWN)
            if prov == DONATED and not self.exempt_uad and expr.id not in self.uad_reported:
                self.uad_reported.add(expr.id)
                self.events.append(
                    OwnEvent(
                        "use_after_donate", self.func.path, expr.lineno,
                        expr.col_offset, self.func.qualname,
                        f"`{expr.id}` was donated and is dead here",
                    )
                )
            return prov
        if isinstance(expr, ast.Call):
            return self._scan_call(expr, env)
        if isinstance(expr, ast.Attribute):
            # the sanctioned liveness probe reads a maybe-dead buffer on purpose
            if expr.attr == "is_deleted":
                return UNKNOWN
            base = self._scan_expr(expr.value, env)
            return ALIAS if base == ALIAS else UNKNOWN
        if isinstance(expr, ast.Subscript):
            base = self._scan_expr(expr.value, env)
            self._scan_expr(expr.slice, env)
            return ALIAS if base == ALIAS else UNKNOWN
        if isinstance(expr, ast.Starred):
            return self._scan_expr(expr.value, env)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            provs = [self._scan_expr(e, env) for e in expr.elts]
            return _merge_prov(UNKNOWN, *provs) if provs else UNKNOWN
        if isinstance(expr, ast.Dict):
            provs = [self._scan_expr(v, env) for v in expr.values if v is not None]
            for k in expr.keys:
                if k is not None:
                    self._scan_expr(k, env)
            return _merge_prov(UNKNOWN, *provs) if provs else UNKNOWN
        if isinstance(expr, ast.IfExp):
            self._scan_expr(expr.test, env)
            return _merge_prov(self._scan_expr(expr.body, env), self._scan_expr(expr.orelse, env))
        if isinstance(expr, (ast.Lambda,)):
            return UNKNOWN  # opaque; nested defs are separate functions
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            cenv = dict(env)
            for gen in expr.generators:
                self._scan_expr(gen.iter, cenv)
                if isinstance(gen.target, ast.Name):
                    cenv[gen.target.id] = UNKNOWN
                elif isinstance(gen.target, (ast.Tuple, ast.List)):
                    for elt in gen.target.elts:
                        if isinstance(elt, ast.Name):
                            cenv[elt.id] = UNKNOWN
                for cond in gen.ifs:
                    self._scan_expr(cond, cenv)
            for part in ("elt", "key", "value"):
                sub = getattr(expr, part, None)
                if sub is not None:
                    self._scan_expr(sub, cenv)
            return UNKNOWN
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._scan_expr(child, env)
        return UNKNOWN

    def _scan_call(self, call: ast.Call, env: Dict[str, str]) -> str:
        name = dotted_name(call.func) or ""
        last = name.split(".")[-1]

        # ---- shields (statement-order domination for later donating execs)
        is_shield = False
        if last in _SNAPSHOT_SHIELDS:
            self.snapshot_seen = True
            self.shield_calls.add("snapshot")
            is_shield = True
        if last in _DEDUP_SHIELDS:
            self.dedup_seen = True
            self.shield_calls.add("dedup")
            is_shield = True
        if not is_shield and name:
            hit = self.model.resolve_call(self.module, name, self.func)
            if hit:
                if hit[1].snapshot_shield:
                    self.snapshot_seen = True
                    self.shield_calls.add("snapshot")
                    is_shield = True
                if hit[1].dedup_shield:
                    self.dedup_seen = True
                    self.shield_calls.add("dedup")
                    is_shield = True

        # ---- warm-manifest record hook (engine_contract input)
        if last.startswith("record_") and last.endswith("_compile"):
            self.warm_records.append(last)

        # ---- executable-cache traffic
        if isinstance(call.func, ast.Attribute) and call.func.attr == "get":
            recv = dotted_name(call.func.value) or ""
            if "cache" in recv.lower() and call.args:
                self.cache_get = True
                self.cache_key_nodes.append(call.args[0])

        # ---- donating execution?
        is_transform = isinstance(call.func, ast.Attribute) and call.func.attr in (
            "lower", "compile",
        )
        positions = None if is_transform else self._donating_of_callable(call.func)
        arg_provs = [self._scan_expr(a, env) for a in call.args]
        for kw in call.keywords:
            self._scan_expr(kw.value, env)

        if positions:
            self._record_exec(call, positions, arg_provs, env)
            return OWNED  # result buffers are fresh device outputs

        # ---- provenance of ordinary calls
        return self._call_prov(call, name, last, arg_provs, env)

    def _donating_of_callable(self, fn: ast.AST) -> Optional[Tuple[int, ...]]:
        if isinstance(fn, ast.Name):
            return self.donating_names.get(fn.id)
        if isinstance(fn, ast.Call):
            return self._donating_of(fn)
        return None

    def _record_exec(
        self,
        call: ast.Call,
        positions: Tuple[int, ...],
        arg_provs: List[str],
        env: Dict[str, str],
    ) -> None:
        self.exec_sites += 1
        self.exec_lines.append(call.lineno)
        self.exec_calls.append(call)
        donated_exprs: List[Tuple[int, ast.AST, str]] = []
        for pos in positions:
            # a Starred at or before the position makes the mapping ambiguous;
            # one after it (compiled(state, *extras)) does not shift it
            if pos < len(call.args) and not any(
                isinstance(a, ast.Starred) for a in call.args[: pos + 1]
            ):
                donated_exprs.append((pos, call.args[pos], arg_provs[pos]))
        # TMO-DONATE-ALIAS
        for pos, arg, prov in donated_exprs:
            if prov in (ALIAS, HOST):
                what = (
                    "aliases host memory (np.frombuffer/memoryview/jnp.asarray-on-numpy)"
                    if prov == ALIAS
                    else "is host-allocated numpy memory (zero-copy on the CPU backend)"
                )
                self.events.append(
                    OwnEvent(
                        "donate_alias", self.func.path, arg.lineno, arg.col_offset,
                        self.func.qualname,
                        f"donated argument {pos} (`{_safe_unparse(arg)}`) {what}",
                    )
                )
        # TMO-DOUBLE-DONATE
        if len(donated_exprs) > 1 and not self.dedup_seen:
            seen_text: Dict[str, int] = {}
            for pos, arg, _prov in donated_exprs:
                text = _safe_unparse(arg)
                if text in seen_text:
                    self.events.append(
                        OwnEvent(
                            "double_donate", self.func.path, call.lineno,
                            call.col_offset, self.func.qualname,
                            f"`{text}` reaches donated positions {seen_text[text]} "
                            f"and {pos} of one call with no dedup guard",
                        )
                    )
                else:
                    seen_text[text] = pos
        # TMO-SNAPSHOT-GAP: the donated value must be shield-processed, either
        # by a dominating shield call or because it came out of one
        # (fleet: state = _shield_donation(metric, state)).
        if not self.snapshot_seen:
            shielded_args = all(
                self._from_shield(arg, env) for _pos, arg, _prov in donated_exprs
            ) and bool(donated_exprs)
            if not shielded_args:
                self.events.append(
                    OwnEvent(
                        "snapshot_gap", self.func.path, call.lineno, call.col_offset,
                        self.func.qualname,
                        "donating call not dominated by secure_pending_snapshots/"
                        "_secure_ckpt_snapshots (async ckpt may reference the buffers)",
                    )
                )
        # mark donated names dead
        for _pos, arg, _prov in donated_exprs:
            if isinstance(arg, ast.Name):
                env[arg.id] = DONATED

    def _from_shield(self, arg: ast.AST, env: Dict[str, str]) -> bool:
        """Whether a donated arg was produced by a shield call (assignment)."""
        if not isinstance(arg, ast.Name):
            return False
        for value in self.assigns.get(arg.id, ()):
            if isinstance(value, ast.Call):
                vlast = (dotted_name(value.func) or "").split(".")[-1]
                if vlast in _SNAPSHOT_SHIELDS:
                    return True
        return False

    def _call_prov(
        self, call: ast.Call, name: str, last: str, arg_provs: List[str], env: Dict[str, str]
    ) -> str:
        arg0 = _merge_prov(UNKNOWN, *arg_provs) if arg_provs else UNKNOWN
        if last == "memoryview":
            return ALIAS
        if self.module.is_numpy(name):
            if last in _NP_ALIAS_CTORS:
                return ALIAS
            if last in _NP_HOST_CTORS:
                return ALIAS if arg0 == ALIAS else HOST
            return HOST
        if self.module.is_jnp(name) and last in ("asarray", "array"):
            copy_kw = None
            for kw in call.keywords:
                if kw.arg == "copy" and isinstance(kw.value, ast.Constant):
                    copy_kw = bool(kw.value.value)
            if copy_kw is True:
                return OWNED
            if last == "array" and copy_kw is None:
                return OWNED  # jnp.array copies by default
            if arg0 in (HOST, ALIAS):
                return ALIAS  # jnp.asarray may zero-copy host memory
            return OWNED if arg0 == OWNED else UNKNOWN
        if self.module.is_jax_fresh(name):
            return OWNED
        if isinstance(call.func, ast.Attribute) and call.func.attr == "copy":
            return OWNED
        if name:
            hit = self.model.resolve_call(self.module, name, self.func)
            if hit:
                if hit[1].returns_alias:
                    return ALIAS
                if hit[1].returns_owned:
                    return OWNED
        return UNKNOWN

    def _key_fields(self) -> List[str]:
        """The cache-key tuple's components, with one level of local-name
        expansion (``sig := ('scan', ...) | tuple(...)``) — the worksheet's
        per-engine digest inventory for ROADMAP item 5."""
        for node in self.cache_key_nodes:
            tup = node
            if isinstance(node, ast.Name):
                for value in self.assigns.get(node.id, ()):
                    if isinstance(value, ast.Tuple):
                        tup = value
                        break
            if not isinstance(tup, ast.Tuple):
                continue
            fields: List[str] = []
            for elt in tup.elts:
                if isinstance(elt, ast.Name) and elt.id in self.assigns:
                    alts = " | ".join(
                        sorted({_safe_unparse(v) for v in self.assigns[elt.id]})
                    )
                    fields.append(f"{elt.id} := {alts}")
                else:
                    fields.append(_safe_unparse(elt))
            return fields
        return []

    # ------------------------------------------------------------- key gap

    def _key_gap_events(self) -> List[OwnEvent]:
        """TMO-KEY-GAP: cache key must cover everything the executable was
        specialized on — exec args, donating-call args, builder args, and the
        closed-over locals of a locally-defined step."""
        if not self.exec_calls or not self.cache_key_nodes:
            return []
        feed: Set[str] = set()
        for key in self.cache_key_nodes:
            feed |= _names_in(key)
        # transitive closure through local assignments (sig <- dyn_lists, ...)
        for _ in range(len(self.assigns) + 1):
            grew = False
            for name in list(feed):
                for value in self.assigns.get(name, ()):
                    new = _names_in(value)
                    if not new <= feed:
                        feed |= new
                        grew = True
            if not grew:
                break
        events: List[OwnEvent] = []
        reported: Set[str] = set()

        def missing(name: str, node: ast.AST, what: str) -> None:
            if name in feed or name in reported:
                return
            reported.add(name)
            events.append(
                OwnEvent(
                    "key_gap", self.func.path, node.lineno, node.col_offset,
                    f"{self.func.qualname}.{name}",
                    f"`{name}` ({what}) is not covered by the executable-cache key",
                )
            )

        for call in self.exec_calls:
            for arg in call.args:
                if isinstance(arg, ast.Name):
                    missing(arg.id, arg, "runtime argument of the compiled call")
                elif isinstance(arg, ast.Starred) and isinstance(arg.value, ast.Name):
                    missing(arg.value.id, arg, "runtime argument of the compiled call")
        for call in self.donating_call_args:
            for arg in call.args:
                if isinstance(arg, ast.Name):
                    missing(arg.id, arg, "input of the compile-producing call")
        for target in self.jit_targets:
            if not isinstance(target, ast.Name):
                continue
            # step = self._build_xxx(a, b, c): builder args specialize the trace
            for value in self.assigns.get(target.id, ()):
                if isinstance(value, ast.Call):
                    for arg in value.args:
                        if isinstance(arg, ast.Name):
                            missing(arg.id, arg, f"argument of the `{target.id}` builder")
            # def step(...) closing over outer locals/params
            nested = self.nested_defs.get(target.id)
            if nested is not None:
                self._check_closure(nested, feed, missing)
        return events

    def _check_closure(self, nested: ast.AST, feed: Set[str], missing) -> None:
        args = nested.args
        inner_bound = {
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
        } | {a.arg for a in (args.vararg, args.kwarg) if a}
        for node in ast.walk(nested):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        inner_bound.add(t.id)
        outer_names = set(self.func.params) | set(self.assigns)
        for node in ast.walk(nested):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id not in inner_bound
                and node.id in outer_names
            ):
                missing(node.id, node, "closed over by the traced step")


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return "<expr>"


def build_model(files: Dict[str, Tuple[str, str]]) -> OwnModel:
    """Build the linked ownership model for ``load_package`` output."""
    return OwnModel(files)
