"""tmown orchestration: parse -> link -> rules -> baseline -> report.

Pure host AST work — nothing imports or executes the analyzed modules, so the
sweep is CI-safe on an accelerator-free box and costs cold-start seconds (the
ISSUE budget is <= 60 s; the package parses and fixpoints in well under one).
"""
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import OWN_RULES, Finding
from metrics_tpu.analysis.jitmap import load_package
from metrics_tpu.analysis.own import donation_rules, engine_contract
from metrics_tpu.analysis.own.buffer_model import OwnModel, build_model
from metrics_tpu.analysis.runner import _find_repo_root


@dataclass
class OwnReport:
    """One tmown run: the linked model plus rule output and baseline split."""

    findings: List[Finding] = field(default_factory=list)  # waived included
    new_findings: List[Finding] = field(default_factory=list)
    unused_waivers: List[Tuple[str, str, str]] = field(default_factory=list)
    parse_errors: Dict[str, str] = field(default_factory=dict)
    #: engine -> component matrix (the ROADMAP item 5 worksheet source)
    contract: Dict[str, Dict] = field(default_factory=dict)
    stats: Dict[str, float] = field(default_factory=dict)
    model: Optional[OwnModel] = None

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.new_findings else 0

    def drift_worksheet(self) -> Dict:
        drift = [f for f in self.findings if f.rule == "TMO-ENGINE-DRIFT"]
        return engine_contract.worksheet(self.contract, drift)


def _obs_inc(name: str, value: float = 1) -> None:
    from metrics_tpu.obs import registry as _obs

    if _obs._ENABLED:
        _obs.REGISTRY.inc("own", name, value)


#: rule id -> obs counter suffix (mirrors Rule.counter in findings.py)
_RULE_COUNTERS = {
    "TMO-DONATE-ALIAS": "donate_alias",
    "TMO-USE-AFTER-DONATE": "use_after_donate",
    "TMO-DOUBLE-DONATE": "double_donate",
    "TMO-SNAPSHOT-GAP": "snapshot_gap",
    "TMO-KEY-GAP": "key_gap",
    "TMO-ENGINE-DRIFT": "engine_drift",
}


def run_own(
    target: str = "metrics_tpu",
    baseline_path: Optional[str] = None,
    repo_root: Optional[str] = None,
) -> OwnReport:
    """Analyze ``target`` (package dir or single file) for buffer ownership."""
    t0 = time.perf_counter()
    report = OwnReport()
    repo_root = repo_root or _find_repo_root(target)

    files = load_package(target, repo_root)
    model = build_model(files)
    report.model = model
    report.parse_errors = dict(model.errors)

    report.findings.extend(donation_rules.dataflow_findings(model))
    report.contract = engine_contract.extract_contract(model)
    report.findings.extend(engine_contract.drift_findings(report.contract))
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))

    if baseline_path is None:
        baseline_path = baseline_mod.default_baseline_path(repo_root)
    waivers = baseline_mod.load_baseline(baseline_path) if baseline_path else {}
    own_waivers = baseline_mod.scope_waivers(waivers, OWN_RULES)
    report.new_findings, report.unused_waivers = baseline_mod.apply_baseline(
        report.findings, own_waivers
    )

    n_funcs = 0
    n_exec = 0
    n_donating = 0
    for _m, func in model.all_functions():
        n_funcs += 1
        n_exec += func.exec_sites
        if func.builds_donating or func.returns_donating:
            n_donating += 1

    _obs_inc("findings", len(report.findings))
    for f in report.findings:
        suffix = _RULE_COUNTERS.get(f.rule)
        if suffix:
            _obs_inc(suffix)

    report.stats = {
        "files": len(model.modules),
        "functions": n_funcs,
        "donating": n_donating,
        "exec_sites": n_exec,
        "engines": len(report.contract),
        "findings": len(report.findings),
        "waived": len(report.waived),
        "new": len(report.new_findings),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    return report
