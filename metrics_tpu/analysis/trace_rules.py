"""AST rules over trace-reachable code: TM-HOSTSYNC, TM-PYBRANCH, TM-DYNSHAPE,
TM-RETRACE.

These run only on functions the jit-boundary model (jitmap.py) marked
reachable from a traced region, and only on statements on the traced side of
the repo's concreteness guards. Precision heuristics:

- a small per-function *static-name* dataflow pass marks locals derived from
  shapes/lengths/literals (``n = preds.shape[0]``; ``m = _next_pow2(int(n))``)
  so ``int(n)`` padding arithmetic is not a host sync;
- parameters annotated with Python scalar types (``int``, ``float``, ``bool``,
  ``str``, ``Optional[int]`` …) are static;
- numpy calls are exempt when the callee is a dtype/const helper or every
  argument is static (``np.prod(shape)``).
"""
import ast
from typing import List, Optional, Set

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.jitmap import (
    FuncInfo,
    ModuleModel,
    dotted_name,
    iter_trace_regions,
)

#: numpy attributes that produce static/python values (or are type objects)
_NP_STATIC = {
    "dtype", "finfo", "iinfo", "result_type", "promote_types", "issubdtype",
    "ndarray", "generic", "number", "integer", "floating", "complexfloating",
    "bool_", "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "longdouble", "complex64",
    "complex128", "isscalar", "ndim", "shape", "size", "newaxis", "errstate",
    "RandomState", "random",
}
#: jnp attributes whose results are static python values (safe in branch tests)
_JNP_STATIC = {"issubdtype", "ndim", "isscalar", "result_type", "promote_types", "dtype", "finfo", "iinfo"}
#: dynamic-output-shape jnp functions needing size=
_DYNSHAPE_FNS = {
    "unique", "nonzero", "flatnonzero", "argwhere", "unique_values",
    "unique_counts", "union1d", "intersect1d", "setdiff1d",
}
_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "bytes"}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_DTYPE_NAMES = {
    "bool_", "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "bfloat16", "float32", "float64", "complex64",
    "complex128",
}


def _annotation_is_scalar(node: Optional[ast.expr]) -> bool:
    if node is None:
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _SCALAR_ANNOTATIONS:
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) and sub.value in _SCALAR_ANNOTATIONS:
            return True
    return False


class _StaticNames:
    """Per-function set of names known to hold static (non-traced) values."""

    def __init__(self, func: ast.AST, module: ModuleModel) -> None:
        self.module = module
        self.names: Set[str] = set()
        args = getattr(func, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs) + list(getattr(args, "posonlyargs", [])):
                if _annotation_is_scalar(a.annotation):
                    self.names.add(a.arg)
        # one forward pass: assignments of static expressions create static names
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and self.is_static(node.value):
                    self.names.add(target.id)
                elif isinstance(target, ast.Tuple) and self.is_static(node.value):
                    # e.g. `_, c, h, w = x.shape` — every unpacked name is static
                    for el in target.elts:
                        if isinstance(el, ast.Name):
                            self.names.add(el.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                if node.value is not None and self.is_static(node.value):
                    self.names.add(node.target.id)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
                # comprehension vars over a static iterable are static
                for gen in node.generators:
                    if self.is_static(gen.iter) and isinstance(gen.target, ast.Name):
                        self.names.add(gen.target.id)

    def is_static(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            if node.id in self.names:
                return True
            # bare from-imports of dtype/type objects: `from numpy import float32`
            imported = self.module.imports.get(node.id, "")
            if ":" in imported:
                srcmod, _, orig = imported.partition(":")
                if srcmod == "numpy" and orig in _NP_STATIC:
                    return True
                if srcmod == "jax.numpy" and (orig in _JNP_STATIC or orig in _DTYPE_NAMES):
                    return True
            return False
        if isinstance(node, ast.Attribute):
            # x.shape / x.ndim / x.size / x.dtype are static under jit
            if node.attr in ("shape", "ndim", "size", "dtype", "itemsize"):
                return True
            # np.int32 / jnp.float32 used as dtype arguments are type objects
            if isinstance(node.value, ast.Name):
                if node.value.id in self.module.np_aliases:
                    return node.attr in _NP_STATIC
                if node.value.id in self.module.jnp_aliases:
                    return node.attr in _JNP_STATIC or node.attr in _DTYPE_NAMES
            return False
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.is_static(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.is_static(node.left) and self.is_static(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_static(node.body) and self.is_static(node.orelse)
        if isinstance(node, ast.Compare):
            return self.is_static(node.left) and all(self.is_static(c) for c in node.comparators)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is None:
                return False
            last = name.split(".")[-1]
            if last == "len":
                return True
            base = name.split(".")[0]
            if base in self.module.np_aliases:
                return last in _NP_STATIC or all(self.is_static(a) for a in node.args)
            if base in self.module.jnp_aliases:
                return last in _JNP_STATIC
            # local helper over static args (e.g. _next_pow2(int(n)))
            return bool(node.args or node.keywords) and all(
                self.is_static(a) for a in node.args
            ) and all(self.is_static(k.value) for k in node.keywords if k.value is not None)
        return False


def _call_kwarg_names(call: ast.Call) -> Set[str]:
    return {k.arg for k in call.keywords if k.arg}


class _RuleVisitor(ast.NodeVisitor):
    """Expression-level rules for one trace-reachable statement."""

    def __init__(
        self,
        module: ModuleModel,
        symbol: str,
        statics: _StaticNames,
        findings: List[Finding],
        skip_tests: Set[int],
    ) -> None:
        self.module = module
        self.symbol = symbol
        self.statics = statics
        self.findings = findings
        self.skip_tests = skip_tests  # node ids of guard-bearing branch tests

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                rule=rule,
                path=self.module.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                symbol=self.symbol,
                message=message,
            )
        )

    # ------------------------------------------------------- TM-PYBRANCH

    def _test_is_traced(self, test: ast.expr) -> Optional[ast.AST]:
        """First sub-expression proving the branch test depends on traced data.

        Recursive rather than ``ast.walk``: sub-expressions whose *consumed*
        value is static — ``jnp.asarray(x).dtype``, ``jnp.issubdtype(...)``,
        shape attributes — must not count as traced evidence.
        """

        def probe(node: ast.AST) -> Optional[ast.AST]:
            if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "size", "dtype", "itemsize",
            ):
                return None  # static attribute of whatever it hangs off
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                parts = name.split(".") if name else []
                if parts and parts[0] in self.module.jnp_aliases:
                    if parts[-1] in _JNP_STATIC:
                        return None  # jnp.issubdtype(...) etc. produce host bools
                    return node
                if (
                    parts
                    and parts[-1] in ("any", "all", "item")
                    and isinstance(node.func, ast.Attribute)
                    and not self.statics.is_static(node.func.value)
                ):
                    return node
            for child in ast.iter_child_nodes(node):
                found = probe(child)
                if found is not None:
                    return found
            return None

        return probe(test)

    def check_branch(self, stmt: ast.stmt) -> bool:
        """Returns True when the statement's test needs no further linting."""
        test = getattr(stmt, "test", None)
        if test is None or id(test) in self.skip_tests:
            return True  # guard test: exempt, and don't lint its sub-expressions
        kind = {ast.If: "if", ast.While: "while", ast.Assert: "assert"}[type(stmt)]
        evidence = self._test_is_traced(test)
        if evidence is not None:
            what = dotted_name(getattr(evidence, "func", evidence)) or "array expression"
            self._emit(
                "TM-PYBRANCH",
                stmt,
                f"`{kind}` branches on a traced value ({what}(...)): bool() on a tracer "
                "raises under jit; use jnp.where/lax.cond or an `_is_concrete` guard",
            )
            return True  # one finding per branch; skip HOSTSYNC echoes in the test
        return False

    # -------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        func = node.func
        name = dotted_name(func)

        # .item() / .tolist() on anything non-static
        if isinstance(func, ast.Attribute) and func.attr in ("item", "tolist") and not node.args:
            if not self.statics.is_static(func.value):
                self._emit(
                    "TM-HOSTSYNC",
                    node,
                    f"`.{func.attr}()` forces a device->host sync inside a jit-reachable region",
                )
            return

        # float()/int()/bool() on non-static values
        if isinstance(func, ast.Name) and func.id in _HOST_CASTS and len(node.args) == 1:
            if not self.statics.is_static(node.args[0]):
                self._emit(
                    "TM-HOSTSYNC",
                    node,
                    f"`{func.id}()` on an array value concretizes a tracer (host sync); "
                    "use jnp casts or mark the operand static",
                )
            return

        if name is None:
            return
        parts = name.split(".")
        base, last = parts[0], parts[-1]

        # bare-name from-imports: `from numpy import asarray` / `from jax
        # import device_get as dget` hide the module prefix the dotted checks
        # key on — resolve through the import table (tmsan crosscheck found
        # this gap: TMS-LINTGAP fixtures in tests/unittests/analysis)
        if len(parts) == 1:
            imported = self.module.imports.get(base, "")
            if ":" in imported:
                srcmod, _, orig = imported.partition(":")
                if srcmod == "jax" and orig == "device_get":
                    self._emit(
                        "TM-HOSTSYNC", node,
                        f"`{base}` resolves to jax.device_get: an explicit host sync",
                    )
                    return
                if srcmod == "numpy":
                    # route through the numpy branch below under the ORIGINAL
                    # name, so _NP_STATIC and the static-args exemption apply
                    parts = [base, orig]
                    last = orig

        # numpy compute calls
        if base in self.module.np_aliases and len(parts) >= 2:
            if last not in _NP_STATIC and not (
                node.args and all(self.statics.is_static(a) for a in node.args)
            ):
                self._emit(
                    "TM-HOSTSYNC",
                    node,
                    f"numpy call `{name}(...)` materializes on host inside a jit-reachable "
                    "region; use jnp, or guard the host path with `_is_concrete`",
                )
            return

        # jax.device_get
        if last == "device_get":
            self._emit("TM-HOSTSYNC", node, "`jax.device_get` is an explicit host sync")
            return

        # dynamic shapes
        if base in self.module.jnp_aliases and last in _DYNSHAPE_FNS:
            if "size" not in _call_kwarg_names(node):
                self._emit(
                    "TM-DYNSHAPE",
                    node,
                    f"`{name}` without `size=` has a data-dependent output shape; pass "
                    "`size=` (static bound + fill_value) or use a padded ops/ kernel",
                )
            return
        if base in self.module.jnp_aliases and last == "where":
            if len(node.args) == 1 and not node.keywords:
                self._emit(
                    "TM-DYNSHAPE",
                    node,
                    "single-argument `jnp.where(cond)` is `nonzero` (data-dependent shape); "
                    "pass `size=` or use the three-argument select form",
                )
            return

    # ------------------------------------------------- boolean-mask indexing

    def visit_Subscript(self, node: ast.Subscript) -> None:
        self.generic_visit(node)
        sl = node.slice
        if isinstance(sl, ast.Compare) and not self.statics.is_static(sl):
            self._emit(
                "TM-DYNSHAPE",
                node,
                "boolean-mask indexing `x[cond]` has a data-dependent shape under jit; "
                "use `jnp.where(cond, x, fill)` or a padded kernel",
            )


def run_retrace_rules(module: ModuleModel, info: FuncInfo) -> List[Finding]:
    """TM-RETRACE: jit wrappers built per call + python scalars into jit aliases.

    Unlike the trace-safety rules, these scan EVERY function: the hazard lives
    at the host-side call site feeding a jitted callable, which is usually not
    itself jit-reachable."""
    findings: List[Finding] = []
    _check_retrace(module, info, findings)
    return findings


def _check_retrace(
    module: ModuleModel,
    info: FuncInfo,
    findings: List[Finding],
) -> None:
    node = info.node
    is_setup = info.qualname in module.module_level_only
    fargs = getattr(node, "args", None)
    scalar_params = {
        a.arg for a in (fargs.args if fargs is not None else []) if _annotation_is_scalar(a.annotation)
    }

    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue

        # (a) jax.jit(...) constructed inside a function body
        if module._is_tracing_wrapper(sub.func):
            name = dotted_name(sub.func) or "jit"
            if name.split(".")[-1] in ("jit", "pjit") and not is_setup:
                findings.append(
                    Finding(
                        rule="TM-RETRACE",
                        path=module.path,
                        line=sub.lineno,
                        col=sub.col_offset,
                        symbol=info.qualname,
                        message=(
                            f"`{name}(...)` constructed inside `{info.qualname}`: a fresh "
                            "wrapper per call misses the jit dispatch cache — build it at "
                            "module scope (obs counter: jax.compile_events)"
                        ),
                    )
                )
            continue

        # (b) python-scalar params flowing into a known jit alias
        if not isinstance(sub.func, ast.Name):
            continue
        alias = module.jit_aliases.get(sub.func.id)
        if alias is None:
            continue
        target_params: List[str] = []
        if alias.target and alias.target in module.functions:
            tnode = module.functions[alias.target].node
            targs = getattr(tnode, "args", None)
            if targs is not None:
                target_params = [a.arg for a in targs.args]

        def _flag(arg_node: ast.expr, param: Optional[str]) -> None:
            if not isinstance(arg_node, ast.Name) or arg_node.id not in scalar_params:
                return
            if param is not None and param in alias.static_argnames:
                return
            findings.append(
                Finding(
                    rule="TM-RETRACE",
                    path=module.path,
                    line=arg_node.lineno,
                    col=arg_node.col_offset,
                    symbol=info.qualname,
                    message=(
                        f"python scalar `{arg_node.id}` flows into jitted `{alias.name}` as a "
                        "fresh constant per call: every new value retraces (obs counters: "
                        "<MetricClass>.retraces / .retrace_signatures, jax.compile_events). "
                        "Wrap with jnp.asarray or add to static_argnames"
                    ),
                )
            )

        for i, arg in enumerate(sub.args):
            param = target_params[i] if i < len(target_params) else None
            if param is None and i in alias.static_argnums:
                continue
            _flag(arg, param)
        for kw in sub.keywords:
            if kw.arg and kw.arg in alias.static_argnames:
                continue
            _flag(kw.value, kw.arg)


def run_trace_rules(module: ModuleModel, info: FuncInfo) -> List[Finding]:
    """All trace-safety + retrace findings for one jit-reachable function."""
    findings: List[Finding] = []
    node = info.node
    statics = _StaticNames(node, module)

    if isinstance(node, ast.Lambda):
        visitor = _RuleVisitor(module, info.qualname, statics, findings, set())
        visitor.visit(node.body)
        return findings

    regions = list(iter_trace_regions(node.body))
    skip_tests: Set[int] = set()
    for stmt, _traced, lint_test in regions:
        if not lint_test:
            test = getattr(stmt, "test", None)
            if test is not None:
                skip_tests.add(id(test))

    visitor = _RuleVisitor(module, info.qualname, statics, findings, skip_tests)
    for stmt, traced, _lint_test in regions:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue  # nested defs are separate symbols (rooted independently)
        if not traced:
            continue
        if isinstance(stmt, (ast.If, ast.While, ast.Assert)):
            handled = visitor.check_branch(stmt)
            test = getattr(stmt, "test", None)
            if not handled and test is not None:
                visitor.visit(test)
            if isinstance(stmt, ast.Assert) and stmt.msg is not None:
                visitor.visit(stmt.msg)
            continue
        # visit only this statement's own expressions, not nested blocks
        # (nested block statements appear as their own region entries)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                visitor.visit(child)

    seen = set()
    unique: List[Finding] = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.col, f.symbol)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique
