"""tmrace orchestration: parse -> link -> rules -> baseline -> report.

Pure host AST work — nothing imports or executes the analyzed modules, so the
sweep is safe to run in CI on a box with no accelerator and costs cold-start
seconds, not minutes (the ISSUE budget is <= 60 s; in practice the package
parses in well under one).
"""
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import RACE_RULES, Finding
from metrics_tpu.analysis.jitmap import load_package
from metrics_tpu.analysis.race import handler_rules, lock_rules, order_graph
from metrics_tpu.analysis.race.thread_model import RaceModel, build_model
from metrics_tpu.analysis.runner import _find_repo_root


@dataclass
class RaceReport:
    """One tmrace run: the linked model plus rule output and baseline split."""

    findings: List[Finding] = field(default_factory=list)  # waived included
    new_findings: List[Finding] = field(default_factory=list)
    unused_waivers: List[Tuple[str, str, str]] = field(default_factory=list)
    parse_errors: Dict[str, str] = field(default_factory=dict)
    #: role -> entry-point count (how the thread-role model carved the package)
    roles: Dict[str, int] = field(default_factory=dict)
    stats: Dict[str, float] = field(default_factory=dict)
    model: Optional[RaceModel] = None

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.new_findings else 0


def _obs_inc(name: str, value: float = 1) -> None:
    from metrics_tpu.obs import registry as _obs

    if _obs._ENABLED:
        _obs.REGISTRY.inc("race", name, value)


#: rule id -> obs counter suffix (mirrors Rule.counter in findings.py)
_RULE_COUNTERS = {
    "TMR-UNLOCKED": "unlocked",
    "TMR-ORDER": "order_cycles",
    "TMR-HOLD-HOST": "hold_host",
    "TMR-HANDLER": "handler",
    "TMR-LEAK": "leaks",
}


def run_race(
    target: str = "metrics_tpu",
    baseline_path: Optional[str] = None,
    repo_root: Optional[str] = None,
) -> RaceReport:
    """Analyze ``target`` (package dir or single file) for thread-safety."""
    t0 = time.perf_counter()
    report = RaceReport()
    repo_root = repo_root or _find_repo_root(target)

    files = load_package(target, repo_root)
    model = build_model(files)
    report.model = model
    report.parse_errors = dict(model.errors)

    report.findings.extend(lock_rules.unlocked_findings(model))
    report.findings.extend(lock_rules.hold_host_findings(model))
    report.findings.extend(lock_rules.leak_findings(model))
    report.findings.extend(order_graph.order_findings(model))
    report.findings.extend(handler_rules.handler_findings(model))
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))

    if baseline_path is None:
        baseline_path = baseline_mod.default_baseline_path(repo_root)
    waivers = baseline_mod.load_baseline(baseline_path) if baseline_path else {}
    race_waivers = baseline_mod.scope_waivers(waivers, RACE_RULES)
    report.new_findings, report.unused_waivers = baseline_mod.apply_baseline(
        report.findings, race_waivers
    )

    n_funcs = 0
    n_spawns = 0
    for _m, func in model.all_functions():
        n_funcs += 1
        n_spawns += len(func.spawns)
        for role in func.roles:
            report.roles[role] = report.roles.get(role, 0) + 1

    _obs_inc("findings", len(report.findings))
    for f in report.findings:
        suffix = _RULE_COUNTERS.get(f.rule)
        if suffix:
            _obs_inc(suffix)

    report.stats = {
        "files": len(model.modules),
        "functions": n_funcs,
        "locks": len(model.locks),
        "roles": len(report.roles),
        "threads": n_spawns,
        "findings": len(report.findings),
        "waived": len(report.waived),
        "new": len(report.new_findings),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    return report
