"""The thread-role model: who runs what, holding which locks.

Phase A parses every module of the analyzed tree into a
:class:`RaceModuleModel`: functions (including methods and nested defs), lock
objects (module globals and ``self._x = threading.Lock()`` instance attrs),
thread spawns, handler installs, and the ``@thread_role``/``@locked_by``
annotation vocabulary (``metrics_tpu/utils/concurrency.py``). Phase B links
the package: a cross-module class index, a call graph with attribute-typed
method resolution (``self._ring.drain()`` resolves through the
``self._ring = Ring(...)`` constructor assignment), role propagation from the
seeds, and the held-at-entry fixpoint.

Identity schemes (stable across line churn — baseline symbols build on them):

- locks:   ``ClassName._attr`` for instance locks, ``module._GLOBAL`` for
  module-level locks (module = last dotted component).
- targets: ``ClassName.attr`` / ``module.GLOBAL``; a constant-string subscript
  refines it (``IngestQueue.stats[ticks]``) so disjoint counter keys governed
  by different locks don't alias.
- roles:   the thread ``name=`` prefix when literal (``tm-ingest``,
  ``metrics-tpu-ckpt``), else the target qualname; ``user`` for the public
  API surface; ``signal``/``atexit``/``excepthook`` for handler installs.

Atomicity model (the documented GIL idioms, so ``obs/ring.py`` never FPs):
a single attribute/subscript *store* is atomic; ``deque.append`` and
``Event.set/clear`` are atomic; read-modify-write (``+=``, self-referencing
assigns) and multi-step container surgery (``extend``/``remove``/``pop``/
``clear``/``update``/...) are not.
"""
import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.jitmap import dotted_name

#: threading constructors that create a lock-like object (identity tracked)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
#: constructors whose methods are GIL-atomic signals, never lock-like
_EVENT_CTORS = {"Event"}

#: container methods that mutate the receiver (non-atomic unless excepted)
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "rotate",
}
#: (receiver type, method) pairs modeled as one GIL-atomic bytecode-ish op
_ATOMIC_MUTCALLS = {
    ("deque", "append"), ("deque", "appendleft"), ("list", "append"),
    ("set", "add"), ("set", "discard"),
}

#: dotted suffixes that block on host IO / device sync (TMR-HOLD-HOST)
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "os.fsync": "disk fsync",
    "os.listdir": "disk listdir",
    "os.scandir": "disk scandir",
    "os.makedirs": "disk makedirs",
    "os.replace": "disk rename",
    "os.rename": "disk rename",
    "os.remove": "disk unlink",
    "os.unlink": "disk unlink",
    "os.rmdir": "disk rmdir",
    "os.path.isfile": "disk stat",
    "os.path.isdir": "disk stat",
    "os.path.exists": "disk stat",
    "os.path.getsize": "disk stat",
    "shutil.rmtree": "disk rmtree",
    "shutil.copy": "disk copy",
    "shutil.copytree": "disk copy",
    "subprocess.run": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "json.dump": "disk json.dump",
    "json.load": "disk json.load",
    "jax.device_get": "device sync",
}
#: bare-name blocking calls
_BLOCKING_NAMES = {"open": "file open"}
#: attribute-method blocking calls (matched on the final attr)
_BLOCKING_ATTRS = {"block_until_ready": "device sync"}
#: numpy asarray on (possibly) device values forces a device->host transfer
_ASARRAY_FUNCS = {"asarray", "array"}

_HANDLER_KINDS = ("signal", "atexit", "excepthook")


# --------------------------------------------------------------------- records


@dataclass
class LockDecl:
    """One lock object: identity, kind, and where it was created."""

    lock_id: str
    kind: str  # Lock | RLock | Condition | Semaphore | BoundedSemaphore
    path: str
    line: int


@dataclass
class Acquire:
    """One acquisition site (``with lock:`` or ``lock.acquire()``)."""

    lock_id: str
    line: int
    col: int
    blocking: bool  # False for acquire(blocking=False) / acquire(False)
    held: Tuple[str, ...]  # locks already held locally at this point


@dataclass
class Mutation:
    """One write to a shared target (instance attr or module global)."""

    target: str
    line: int
    col: int
    kind: str  # store | rmw | augassign | mutcall:<name> | delete
    atomic: bool
    held: Tuple[str, ...]  # locks held locally at the write


@dataclass
class CallSite:
    symbol: str  # as written: "f", "self._apply", "mod.g", "obj.method"
    recv_type: Optional[str]  # inferred receiver type for obj.method calls
    line: int
    col: int
    held: Tuple[str, ...]


@dataclass
class BlockingOp:
    what: str  # human label ("disk listdir", "device sync", ...)
    expr: str  # the call as written
    line: int
    col: int
    held: Tuple[str, ...]


@dataclass
class SpawnSite:
    target_symbol: Optional[str]  # "write" | "self._loop" | None (unresolved)
    role: str  # thread-name prefix or target qualname
    daemon: bool
    joined: bool  # a .join() path exists for the stored handle
    line: int
    col: int


@dataclass
class HandlerInstall:
    kind: str  # signal | atexit | excepthook
    target_symbol: Optional[str]
    line: int


@dataclass
class RaceFunc:
    """Per-function facts, line-anchored for findings."""

    qualname: str
    modname: str
    path: str
    line: int
    cls: Optional[str]
    public: bool
    acquires: List[Acquire] = field(default_factory=list)
    mutations: List[Mutation] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    blocking_ops: List[BlockingOp] = field(default_factory=list)
    spawns: List[SpawnSite] = field(default_factory=list)
    declared_roles: Tuple[str, ...] = ()
    declared_locks: Tuple[str, ...] = ()  # @locked_by contract
    # filled by the package linker:
    roles: Set[str] = field(default_factory=set)
    entry_held: Optional[frozenset] = None  # None == top (unconstrained)


# --------------------------------------------------------------- module model


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _name_prefix(node: ast.AST) -> Optional[str]:
    """Literal prefix of a thread-name expression: ``f"tm-ingest/{x}"`` ->
    ``tm-ingest`` (separators stripped), plain strings verbatim."""
    text: Optional[str] = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            text = first.value
    if not text:
        return None
    return text.rstrip("/-_. {") or None


class RaceModuleModel:
    """Phase A: one file's threading facts."""

    def __init__(self, path: str, modname: str, source: str) -> None:
        self.path = path
        self.modname = modname
        self.short = modname.split(".")[-1]
        self.tree = ast.parse(source)
        self.imports: Dict[str, str] = {}
        self.module_locks: Dict[str, LockDecl] = {}  # global name -> decl
        self.module_globals: Set[str] = set()  # names assigned at module level
        self.module_global_types: Dict[str, str] = {}  # ctor-inferred types
        #: ClassName -> {attr: LockDecl}
        self.class_locks: Dict[str, Dict[str, LockDecl]] = {}
        #: ClassName -> {attr: type name} (constructor-inferred)
        self.class_attr_types: Dict[str, Dict[str, str]] = {}
        self.functions: Dict[str, RaceFunc] = {}
        self.handler_installs: List[HandlerInstall] = []
        self._collect()

    # ------------------------------------------------------------- phase A

    def _collect(self) -> None:
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._record_import(stmt)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._record_module_assign(stmt)
        self._walk_defs(self.tree.body, prefix="", cls=None)
        # handler installs can live anywhere (enable(), module level, ...)
        for node in ast.walk(self.tree):
            self._scan_handler_install(node)
        for func in self.functions.values():
            self._analyze_function(func)

    def _record_import(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                local = alias.asname or alias.name.split(".")[0]
                self.imports[local] = alias.name
        elif isinstance(stmt, ast.ImportFrom) and stmt.module:
            for alias in stmt.names:
                local = alias.asname or alias.name
                self.imports[local] = f"{stmt.module}:{alias.name}"

    def _lock_ctor_kind(self, call: ast.expr) -> Optional[str]:
        """'Lock' for ``threading.Lock()`` / imported ``Lock()``; None else."""
        if not isinstance(call, ast.Call):
            return None
        name = dotted_name(call.func)
        if not name:
            return None
        last = name.split(".")[-1]
        if last == "Condition":
            return "Condition"
        if last in _LOCK_CTORS:
            base = name.split(".")[0]
            imported = self.imports.get(base, "")
            if "." in name and (base == "threading" or imported.startswith("threading")):
                return last
            if "." not in name and self.imports.get(name, "").startswith("threading"):
                return last
        return None

    def _ctor_type(self, value: ast.expr) -> Optional[str]:
        """Type name when ``value`` is ``SomeName(...)`` / ``mod.SomeName(...)``."""
        if not isinstance(value, ast.Call):
            return None
        name = dotted_name(value.func)
        if not name:
            return None
        last = name.split(".")[-1]
        if last in _EVENT_CTORS:
            return "Event"
        if last == "Thread":
            return "Thread"
        if last in ("deque", "set", "dict", "list"):
            return last
        return last if last[:1].isupper() else None

    def _record_module_assign(self, stmt: ast.stmt) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        value = stmt.value
        for target in targets:
            if isinstance(target, ast.Name):
                self.module_globals.add(target.id)
                if value is not None:
                    kind = self._lock_ctor_kind(value)
                    if kind:
                        self.module_locks[target.id] = LockDecl(
                            f"{self.short}.{target.id}", kind, self.path, stmt.lineno
                        )
                    ctor = self._ctor_type(value)
                    if ctor:
                        self.module_global_types[target.id] = ctor

    def _walk_defs(self, body: Sequence[ast.stmt], prefix: str, cls: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                public = not stmt.name.startswith("_") or stmt.name in (
                    "__init__", "__enter__", "__exit__", "__call__", "__del__",
                )
                roles, locks = self._scan_annotations(stmt)
                self.functions[qual] = RaceFunc(
                    qualname=qual,
                    modname=self.modname,
                    path=self.path,
                    line=stmt.lineno,
                    cls=cls,
                    public=public,
                    declared_roles=roles,
                    declared_locks=locks,
                )
                self._walk_defs(stmt.body, prefix=qual + ".", cls=cls)
            elif isinstance(stmt, ast.ClassDef):
                self.class_locks.setdefault(stmt.name, {})
                self.class_attr_types.setdefault(stmt.name, {})
                self._walk_defs(stmt.body, prefix=prefix + stmt.name + ".", cls=stmt.name)
                self._scan_class_attrs(stmt)

    def _scan_annotations(self, node: ast.AST) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
        roles: List[str] = []
        locks: List[str] = []
        for dec in getattr(node, "decorator_list", ()):
            if not isinstance(dec, ast.Call):
                continue
            name = dotted_name(dec.func)
            last = name.split(".")[-1] if name else ""
            bucket = roles if last == "thread_role" else locks if last == "locked_by" else None
            if bucket is None:
                continue
            for arg in dec.args:
                s = _const_str(arg)
                if s:
                    bucket.append(s)
        return tuple(roles), tuple(locks)

    def _scan_class_attrs(self, cls_node: ast.ClassDef) -> None:
        """``self.x = <ctor>()`` assignments anywhere in the class's methods."""
        locks = self.class_locks[cls_node.name]
        types = self.class_attr_types[cls_node.name]
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for target in targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                kind = self._lock_ctor_kind(value)
                if kind:
                    locks[target.attr] = LockDecl(
                        f"{cls_node.name}.{target.attr}", kind, self.path, node.lineno
                    )
                ctor = self._ctor_type(value)
                if ctor:
                    types[target.attr] = ctor

    def _scan_handler_install(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            last = name.split(".")[-1]
            if last == "signal" and "." in name and len(node.args) == 2:
                sym = dotted_name(node.args[1])
                if sym and not sym.startswith("_PREV") and sym != "prev":
                    self.handler_installs.append(HandlerInstall("signal", sym, node.lineno))
            elif last == "register" and name.split(".")[0] in ("atexit",) and node.args:
                sym = dotted_name(node.args[0])
                self.handler_installs.append(HandlerInstall("atexit", sym, node.lineno))
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                tname = dotted_name(target)
                if tname and tname.endswith("excepthook"):
                    sym = dotted_name(node.value)
                    if sym and sym not in ("sys.__excepthook__",):
                        self.handler_installs.append(
                            HandlerInstall("excepthook", sym, node.lineno)
                        )

    # --------------------------------------------------- per-function walk

    def _lock_id_of(self, expr: ast.expr, func: RaceFunc, local_types: Dict[str, str]) -> Optional[Tuple[str, str]]:
        """Resolve an expression to ``(lock_id, kind)`` if lock-like."""
        if isinstance(expr, ast.Name):
            decl = self.module_locks.get(expr.id)
            if decl:
                return decl.lock_id, decl.kind
            ltype = local_types.get(expr.id)
            if ltype in _LOCK_CTORS:
                return f"{func.qualname}.<local {expr.id}>", ltype
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and func.cls:
                decl = self.class_locks.get(func.cls, {}).get(expr.attr)
                if decl:
                    return decl.lock_id, decl.kind
                return None
            # obj.lock where obj's type is a package class with that lock attr
            if isinstance(base, ast.Name):
                btype = local_types.get(base.id)
                if btype and expr.attr in self.class_locks.get(btype, {}):
                    decl = self.class_locks[btype][expr.attr]
                    return decl.lock_id, decl.kind
        return None

    def _target_id(self, node: ast.expr, func: RaceFunc) -> Optional[str]:
        """Shared-target identity for attribute/global writes (None = local)."""
        if isinstance(node, ast.Name):
            if node.id in self.module_globals:
                return f"{self.short}.{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self" and func.cls:
                return f"{func.cls}.{node.attr}"
            return None
        if isinstance(node, ast.Subscript):
            base = self._target_id(node.value, func)
            if base is None:
                return None
            key = _const_str(node.slice)
            return f"{base}[{key}]" if key is not None else base
        return None

    def _reads_target(self, value: ast.expr, target: str, func: RaceFunc) -> bool:
        for sub in ast.walk(value):
            if isinstance(sub, (ast.Name, ast.Attribute, ast.Subscript)):
                if self._target_id(sub, func) == target:
                    return True
        return False

    def _attr_type(self, recv: ast.expr, func: RaceFunc, local_types: Dict[str, str]) -> Optional[str]:
        if isinstance(recv, ast.Name):
            return local_types.get(recv.id) or self.module_global_types.get(recv.id)
        if isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name):
            if recv.value.id == "self" and func.cls:
                return self.class_attr_types.get(func.cls, {}).get(recv.attr)
        return None

    def _analyze_function(self, func: RaceFunc) -> None:
        node = None
        # locate the def node again by position-independent qualname walk
        node = _find_def(self.tree, func.qualname)
        if node is None:
            return
        local_types: Dict[str, str] = {}
        # first pass: local constructor types (snap = _PendingSnapshot(...))
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Name):
                        ctor = self._ctor_type(sub.value)
                        if ctor:
                            local_types[tgt.id] = ctor
                        kind = self._lock_ctor_kind(sub.value)
                        if kind:
                            local_types[tgt.id] = kind
        self._walk_stmts(node.body, func, held=(), local_types=local_types)

    def _walk_stmts(
        self,
        body: Sequence[ast.stmt],
        func: RaceFunc,
        held: Tuple[str, ...],
        local_types: Dict[str, str],
    ) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested defs are separate RaceFuncs
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    resolved = self._lock_id_of(item.context_expr, func, local_types)
                    if resolved:
                        lock_id, _kind = resolved
                        func.acquires.append(
                            Acquire(lock_id, stmt.lineno, stmt.col_offset, True, inner)
                        )
                        inner = inner + (lock_id,)
                    else:
                        self._scan_exprs([item.context_expr], func, held, local_types)
                self._walk_stmts(stmt.body, func, inner, local_types)
                continue
            self._scan_stmt(stmt, func, held, local_types)
            for sub_body in (
                getattr(stmt, "body", None),
                getattr(stmt, "orelse", None),
                getattr(stmt, "finalbody", None),
            ):
                if sub_body:
                    self._walk_stmts(sub_body, func, held, local_types)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._walk_stmts(handler.body, func, held, local_types)

    def _scan_stmt(
        self, stmt: ast.stmt, func: RaceFunc, held: Tuple[str, ...], local_types: Dict[str, str]
    ) -> None:
        # ---- writes
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                tid = self._target_id(target, func)
                if tid is not None:
                    rmw = self._reads_target(stmt.value, tid, func)
                    func.mutations.append(
                        Mutation(
                            tid, stmt.lineno, stmt.col_offset,
                            "rmw" if rmw else "store", atomic=not rmw, held=held,
                        )
                    )
            self._scan_exprs([stmt.value], func, held, local_types)
            return
        if isinstance(stmt, ast.AugAssign):
            tid = self._target_id(stmt.target, func)
            if tid is not None:
                func.mutations.append(
                    Mutation(tid, stmt.lineno, stmt.col_offset, "augassign", False, held)
                )
            self._scan_exprs([stmt.value], func, held, local_types)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                tid = self._target_id(target, func)
                if tid is not None:
                    func.mutations.append(
                        Mutation(tid, stmt.lineno, stmt.col_offset, "delete", False, held)
                    )
            return
        # ---- everything else: scan contained expressions
        exprs = [v for v in ast.iter_child_nodes(stmt) if isinstance(v, ast.expr)]
        self._scan_exprs(exprs, func, held, local_types)

    def _scan_exprs(
        self,
        exprs: Sequence[ast.AST],
        func: RaceFunc,
        held: Tuple[str, ...],
        local_types: Dict[str, str],
    ) -> None:
        for root in exprs:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                self._scan_call(node, func, held, local_types)

    def _scan_call(
        self, call: ast.Call, func: RaceFunc, held: Tuple[str, ...], local_types: Dict[str, str]
    ) -> None:
        name = dotted_name(call.func) or ""
        last = name.split(".")[-1] if name else ""

        # -- thread spawn
        if last == "Thread" and (
            name.startswith("threading.")
            or self.imports.get(name, "").startswith("threading")
            or self.imports.get(name.split(".")[0], "").startswith("threading")
        ):
            func.spawns.append(self._spawn_site(call, func))
            return

        # -- explicit acquire: lock.acquire(...) — try-lock when blocking=False
        if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
            resolved = self._lock_id_of(call.func.value, func, local_types)
            if resolved:
                lock_id, _kind = resolved
                blocking = True
                for kw in call.keywords:
                    if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
                        blocking = bool(kw.value.value)
                if call.args and isinstance(call.args[0], ast.Constant):
                    blocking = bool(call.args[0].value)
                func.acquires.append(
                    Acquire(lock_id, call.lineno, call.col_offset, blocking, held)
                )
                return

        # -- condition wait/notify on a held condition: releases, never blocks it
        if isinstance(call.func, ast.Attribute) and call.func.attr in ("wait", "wait_for"):
            resolved = self._lock_id_of(call.func.value, func, local_types)
            if resolved and resolved[0] in held:
                return  # Condition.wait releases its own lock while waiting
            recv_t = self._attr_type(call.func.value, func, local_types)
            if recv_t == "Event":
                if held:
                    func.blocking_ops.append(
                        BlockingOp("event wait", name, call.lineno, call.col_offset, held)
                    )
                return

        # -- blocking host ops
        what = None
        if name in _BLOCKING_CALLS:
            what = _BLOCKING_CALLS[name]
        elif any(name.endswith("." + k) for k in _BLOCKING_CALLS):
            what = next(v for k, v in _BLOCKING_CALLS.items() if name.endswith("." + k))
        elif name in _BLOCKING_NAMES:
            what = _BLOCKING_NAMES[name]
        elif last in _BLOCKING_ATTRS:
            what = _BLOCKING_ATTRS[last]
        elif last in _ASARRAY_FUNCS and "." in name:
            base = name.split(".")[0]
            if self.imports.get(base, "").startswith("numpy") or base in ("np", "numpy"):
                what = "device->host transfer (np.asarray)"
        elif last == "join" and isinstance(call.func, ast.Attribute):
            recv = call.func.value
            recv_t = self._attr_type(recv, func, local_types)
            recv_name = dotted_name(recv) or ""
            if recv_t == "Thread" or recv_name.endswith("thread") or recv_name.endswith("_thread"):
                what = "thread join"
        if what is not None:
            func.blocking_ops.append(
                BlockingOp(what, name or last, call.lineno, call.col_offset, held)
            )
            # still record as a call (join/open aren't package calls; harmless)

        # -- container mutation through a method call
        if isinstance(call.func, ast.Attribute) and last in _MUTATING_METHODS:
            recv = call.func.value
            tid = self._target_id(recv, func)
            if tid is not None:
                recv_t = self._attr_type(recv, func, local_types) or ""
                atomic = (recv_t, last) in _ATOMIC_MUTCALLS
                # Event.set/clear are signals, not shared-container surgery
                if recv_t == "Event":
                    return
                # a known package class receiver is a method CALL, analyzed on
                # its own (Ring.append's internals carry the atomicity story)
                if recv_t and recv_t not in ("deque", "list", "dict", "set"):
                    func.calls.append(
                        CallSite(f"{recv_t}.{last}", recv_t, call.lineno, call.col_offset, held)
                    )
                    return
                func.mutations.append(
                    Mutation(tid, call.lineno, call.col_offset, f"mutcall:{last}", atomic, held)
                )
                return

        # -- ordinary call edge
        if name:
            recv_t = None
            if isinstance(call.func, ast.Attribute):
                recv_t = self._attr_type(call.func.value, func, local_types)
            func.calls.append(CallSite(name, recv_t, call.lineno, call.col_offset, held))

    def _spawn_site(self, call: ast.Call, func: RaceFunc) -> SpawnSite:
        target_sym: Optional[str] = None
        role: Optional[str] = None
        daemon = False
        for kw in call.keywords:
            if kw.arg == "target":
                target_sym = dotted_name(kw.value)
            elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg == "name":
                role = _name_prefix(kw.value)
        if role is None:
            role = (target_sym or f"thread@{call.lineno}").replace("self.", "")
        joined = self._has_join_path(call, func)
        return SpawnSite(target_sym, role, daemon, joined, call.lineno, call.col_offset)

    def _has_join_path(self, call: ast.Call, func: RaceFunc) -> bool:
        """Whether the spawned handle is stored somewhere a ``.join`` reaches."""
        parent_assign: Optional[str] = None
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                tname = dotted_name(node.targets[0]) if node.targets else None
                if tname:
                    parent_assign = tname
        if parent_assign is None:
            return False
        scope = self.tree if parent_assign.startswith("self.") else _find_def(self.tree, func.qualname) or self.tree
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                recv = dotted_name(node.func.value)
                if recv == parent_assign:
                    return True
                # self._thread = Thread(...); later: thread = self._thread; thread.join()
                if parent_assign.startswith("self.") and recv == parent_assign.split(".", 1)[1]:
                    return True
        return False


def _find_def(tree: ast.AST, qualname: str):
    """Locate the (possibly nested) def node for a dotted qualname."""
    parts = qualname.split(".")
    scope: Sequence[ast.stmt] = tree.body  # type: ignore[attr-defined]
    node = None
    for part in parts:
        node = None
        for stmt in scope:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)) and stmt.name == part:
                node = stmt
                break
        if node is None:
            return None
        scope = node.body
    return node if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None


# -------------------------------------------------------------- package model


class RaceModel:
    """Phase B: linked package — roles, call graph, held-at-entry fixpoint."""

    def __init__(self, files: Dict[str, Tuple[str, str]]) -> None:
        self.modules: Dict[str, RaceModuleModel] = {}
        self.errors: Dict[str, str] = {}
        for path, (modname, source) in files.items():
            try:
                self.modules[path] = RaceModuleModel(path, modname, source)
            except SyntaxError as err:
                self.errors[path] = f"SyntaxError: {err}"
        self.by_modname = {m.modname: m for m in self.modules.values()}
        #: ClassName -> defining module (first wins; the repo has no dup classes)
        self.class_index: Dict[str, RaceModuleModel] = {}
        for m in self.modules.values():
            for cls in m.class_locks:
                self.class_index.setdefault(cls, m)
        #: all lock declarations by id
        self.locks: Dict[str, LockDecl] = {}
        for m in self.modules.values():
            for decl in m.module_locks.values():
                self.locks.setdefault(decl.lock_id, decl)
            for attrs in m.class_locks.values():
                for decl in attrs.values():
                    self.locks.setdefault(decl.lock_id, decl)
        self.link()

    # ------------------------------------------------------------- linking

    def all_functions(self):
        for m in self.modules.values():
            for func in m.functions.values():
                yield m, func

    def resolve_call(
        self, module: RaceModuleModel, site: CallSite, caller: RaceFunc
    ) -> Optional[Tuple[RaceModuleModel, RaceFunc]]:
        """Resolve one call site to a package function, or None (external)."""
        sym = site.symbol
        # receiver-typed method: Class.method
        if site.recv_type and site.recv_type in self.class_index:
            target_mod = self.class_index[site.recv_type]
            method = sym.split(".")[-1]
            hit = target_mod.functions.get(f"{site.recv_type}.{method}")
            if hit:
                return target_mod, hit
        if sym.startswith("self."):
            rest = sym[5:]
            if caller.cls:
                # self.method() or self.attr.method() via class attr types
                hit = module.functions.get(f"{caller.cls}.{rest}")
                if hit:
                    return module, hit
                if "." in rest:
                    attr, method = rest.split(".", 1)
                    atype = module.class_attr_types.get(caller.cls, {}).get(attr)
                    if atype and atype in self.class_index:
                        tmod = self.class_index[atype]
                        hit = tmod.functions.get(f"{atype}.{method.split('.')[-1]}")
                        if hit:
                            return tmod, hit
            return None
        if "." not in sym:
            # sibling nested function first (write() calling attempt_io())
            prefix = caller.qualname.rsplit(".", 1)[0] + "." if "." in caller.qualname else ""
            for cand in (prefix + sym, (caller.cls + "." + sym) if caller.cls else "", sym):
                if cand and cand in module.functions:
                    return module, module.functions[cand]
            imported = module.imports.get(sym)
            if imported and ":" in imported:
                modname, _, name = imported.partition(":")
                other = self.by_modname.get(modname)
                if other and name in other.functions:
                    return other, other.functions[name]
            return None
        base, _, attr = sym.partition(".")
        imported = module.imports.get(base)
        if imported:
            if ":" in imported:
                m, _, nm = imported.partition(":")
                sub = self.by_modname.get(f"{m}.{nm}")
                if sub and attr in sub.functions:
                    return sub, sub.functions[attr]
                # from pkg import mod as alias; alias.Class.method unlikely — skip
                return None
            other = self.by_modname.get(imported)
            if other:
                hit = other.functions.get(attr)
                if hit:
                    return other, hit
        # ClassName.method referenced directly
        if base in self.class_index:
            tmod = self.class_index[base]
            hit = tmod.functions.get(sym)
            if hit:
                return tmod, hit
        return None

    def _resolve_symbol(
        self, module: RaceModuleModel, sym: Optional[str], around: Optional[RaceFunc]
    ) -> Optional[Tuple[RaceModuleModel, RaceFunc]]:
        """Resolve a bare reference (spawn target / handler fn) to a function."""
        if not sym:
            return None
        fake = CallSite(sym, None, 0, 0, ())
        caller = around or RaceFunc("<module>", module.modname, module.path, 0, None, True)
        hit = self.resolve_call(module, fake, caller)
        if hit:
            return hit
        # nested-function suffix match (target=write inside save_checkpoint)
        tail = sym.split(".")[-1]
        for qual, func in module.functions.items():
            if qual == tail or qual.endswith("." + tail):
                return module, func
        return None

    def link(self) -> None:
        # ---- role seeds
        seeds: List[Tuple[RaceModuleModel, RaceFunc, str]] = []
        self.handler_entries: List[Tuple[RaceFunc, str]] = []
        self.spawned_entries: Set[str] = set()
        for m, func in self.all_functions():
            if func.public:
                seeds.append((m, func, "user"))
            for role in func.declared_roles:
                seeds.append((m, func, role))
                if any(role.startswith(k) or role == k for k in _HANDLER_KINDS):
                    self.handler_entries.append((func, role))
            for spawn in func.spawns:
                hit = self._resolve_symbol(m, spawn.target_symbol, func)
                if hit:
                    tmod, tfunc = hit
                    seeds.append((tmod, tfunc, spawn.role))
                    self.spawned_entries.add(tfunc.qualname)
        for m in self.modules.values():
            for install in m.handler_installs:
                hit = self._resolve_symbol(m, install.target_symbol, None)
                if hit:
                    tmod, tfunc = hit
                    seeds.append((tmod, tfunc, install.kind))
                    self.handler_entries.append((tfunc, install.kind))

        # ---- role propagation (BFS over call edges)
        work = list(seeds)
        while work:
            m, func, role = work.pop()
            if role in func.roles:
                continue
            func.roles.add(role)
            for site in func.calls:
                hit = self.resolve_call(m, site, func)
                if hit:
                    work.append((hit[0], hit[1], role))

        # ---- held-at-entry fixpoint (intersection over call sites)
        callers: Dict[str, List[Tuple[RaceFunc, CallSite]]] = {}
        key_of = lambda mm, ff: f"{mm.path}::{ff.qualname}"  # noqa: E731
        resolved_edges: Dict[str, List[str]] = {}
        funcs: Dict[str, Tuple[RaceModuleModel, RaceFunc]] = {}
        for m, func in self.all_functions():
            funcs[key_of(m, func)] = (m, func)
        for m, func in self.all_functions():
            for site in func.calls:
                hit = self.resolve_call(m, site, func)
                if hit:
                    k = key_of(hit[0], hit[1])
                    callers.setdefault(k, []).append((func, site))
                    resolved_edges.setdefault(key_of(m, func), []).append(k)
        for m, func in self.all_functions():
            if func.declared_locks:
                func.entry_held = frozenset(func.declared_locks)
            elif func.public or func.qualname in self.spawned_entries:
                func.entry_held = frozenset()
        for _ in range(len(funcs) + 2):
            changed = False
            for k, (m, func) in funcs.items():
                if func.declared_locks or func.public or func.qualname in self.spawned_entries:
                    continue
                sites = callers.get(k)
                if not sites:
                    if func.entry_held is None:
                        func.entry_held = frozenset()
                        changed = True
                    continue
                acc: Optional[frozenset] = None
                for caller, site in sites:
                    ce = caller.entry_held
                    if ce is None:
                        continue  # caller still top: skip (optimistic descent)
                    contrib = frozenset(site.held) | ce
                    acc = contrib if acc is None else (acc & contrib)
                if acc is not None and acc != func.entry_held:
                    func.entry_held = acc
                    changed = True
            if not changed:
                break
        for _, func in self.all_functions():
            if func.entry_held is None:
                func.entry_held = frozenset()

    # ------------------------------------------------- derived (rule inputs)

    def transitive_acquires(self, m: RaceModuleModel, func: RaceFunc, _seen=None) -> Set[str]:
        """Lock ids acquired by ``func`` or its package callees."""
        if _seen is None:
            _seen = set()
        k = f"{m.path}::{func.qualname}"
        if k in _seen:
            return set()
        _seen.add(k)
        out = {a.lock_id for a in func.acquires}
        for site in func.calls:
            hit = self.resolve_call(m, site, func)
            if hit:
                out |= self.transitive_acquires(hit[0], hit[1], _seen)
        return out

    def transitive_blocking(self, m: RaceModuleModel, func: RaceFunc, _seen=None) -> List[Tuple[RaceFunc, BlockingOp]]:
        """Blocking ops in ``func`` or its package callees (handler/lock sweeps)."""
        if _seen is None:
            _seen = set()
        k = f"{m.path}::{func.qualname}"
        if k in _seen:
            return []
        _seen.add(k)
        out = [(func, op) for op in func.blocking_ops]
        for site in func.calls:
            hit = self.resolve_call(m, site, func)
            if hit:
                out.extend(self.transitive_blocking(hit[0], hit[1], _seen))
        return out


def build_model(files: Dict[str, Tuple[str, str]]) -> RaceModel:
    """Build the linked thread-role model for ``load_package`` output."""
    return RaceModel(files)
