"""tmrace — the concurrency tier of the five-tier static analysis.

tmlint reads source text (trace safety), tmsan reads the traced jaxpr/HLO
(compiler tier); tmrace reads the *threading structure*: which thread roles
exist, which locks they take in what order, and which shared attributes they
mutate. Rules: TMR-UNLOCKED, TMR-ORDER, TMR-HOLD-HOST, TMR-HANDLER, TMR-LEAK
(``metrics_tpu/analysis/findings.py``), reported through the shared
``tmlint_baseline.json`` waiver machinery scoped to the ``TMR-*`` namespace.
"""
from metrics_tpu.analysis.race.runner import RaceReport, run_race
from metrics_tpu.analysis.race.thread_model import RaceModel, build_model

__all__ = ["RaceModel", "RaceReport", "build_model", "run_race"]
