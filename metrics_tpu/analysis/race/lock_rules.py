"""Lock-discipline rules: TMR-UNLOCKED, TMR-HOLD-HOST, TMR-LEAK.

All three read the linked :class:`~metrics_tpu.analysis.race.thread_model
.RaceModel`; the held set at any site is ``local_held ∪ entry_held`` — the
with-stack at the statement plus the interprocedural caller-holds contract
(inferred intersection over call sites, or the explicit ``@locked_by``).
"""
from typing import Dict, List, Tuple

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.race.thread_model import (
    BlockingOp,
    Mutation,
    RaceFunc,
    RaceModel,
    RaceModuleModel,
)


def _full_held(func: RaceFunc, local: Tuple[str, ...]) -> frozenset:
    return frozenset(local) | (func.entry_held or frozenset())


def _sym(func: RaceFunc) -> str:
    """Finding symbol: ``Class.method`` / ``func`` (nested defs keep the chain)."""
    return func.qualname


# ------------------------------------------------------------- TMR-UNLOCKED


def unlocked_findings(model: RaceModel) -> List[Finding]:
    """Shared target mutated (non-atomically) from >=2 roles with >=1 write
    outside every candidate governing lock."""
    # target -> [(module, func, mutation)]
    sites: Dict[str, List[Tuple[RaceModuleModel, RaceFunc, Mutation]]] = {}
    for m, func in model.all_functions():
        for mut in func.mutations:
            if mut.atomic:
                continue
            sites.setdefault(mut.target, []).append((m, func, mut))
    out: List[Finding] = []
    for target, entries in sorted(sites.items()):
        roles = set()
        for _m, func, _mut in entries:
            roles |= func.roles
        if len(roles) < 2:
            continue  # single-role targets cannot race
        helds = [_full_held(func, mut.held) for _m, func, mut in entries]
        governing = frozenset.intersection(*helds) if helds else frozenset()
        if governing:
            continue  # one lock covers every write
        # anchor at the least-protected write
        m, func, mut = min(entries, key=lambda e: len(_full_held(e[1], e[2].held)))
        n_unlocked = sum(1 for h in helds if not h)
        lock_names = sorted({l for h in helds for l in h})
        out.append(
            Finding(
                rule="TMR-UNLOCKED",
                path=m.path,
                line=mut.line,
                col=mut.col,
                symbol=target,
                message=(
                    f"{target} is mutated ({mut.kind}) from roles "
                    f"{{{', '.join(sorted(roles))}}} with no common governing lock "
                    f"({n_unlocked}/{len(entries)} writes hold no lock at all"
                    + (f"; locks seen: {', '.join(lock_names)}" if lock_names else "")
                    + ")"
                ),
            )
        )
    return out


# ----------------------------------------------------------- TMR-HOLD-HOST


def hold_host_findings(model: RaceModel) -> List[Finding]:
    """Host-blocking work (disk IO, device sync, sleeps, thread joins) while
    holding a lock — directly or through a call made under the lock."""
    out: List[Finding] = []
    flagged_direct = set()  # (path, qualname, line) — for call-site dedup
    for m, func in model.all_functions():
        for op in func.blocking_ops:
            held = _full_held(func, op.held)
            if not held:
                continue
            flagged_direct.add((m.path, func.qualname, op.line))
            out.append(_hold_finding(m, func, op, held))
    # interprocedural: a call under a lock into a function that blocks
    for m, func in model.all_functions():
        for site in func.calls:
            held = _full_held(func, site.held)
            if not held:
                continue
            hit = model.resolve_call(m, site, func)
            if hit is None:
                continue
            cmod, callee = hit
            for owner, op in model.transitive_blocking(cmod, callee):
                # skip ops the direct sweep already reported in the callee
                if _full_held(owner, op.held):
                    continue
                out.append(
                    Finding(
                        rule="TMR-HOLD-HOST",
                        path=m.path,
                        line=site.line,
                        col=site.col,
                        symbol=_sym(func),
                        message=(
                            f"call to {site.symbol} while holding "
                            f"{{{', '.join(sorted(held))}}} reaches {op.what} "
                            f"({owner.qualname}:{op.line})"
                        ),
                    )
                )
                break  # one finding per call site, not per reachable op
    return out


def _hold_finding(m: RaceModuleModel, func: RaceFunc, op: BlockingOp, held: frozenset) -> Finding:
    return Finding(
        rule="TMR-HOLD-HOST",
        path=m.path,
        line=op.line,
        col=op.col,
        symbol=_sym(func),
        message=f"{op.what} ({op.expr}) while holding {{{', '.join(sorted(held))}}}",
    )


# ----------------------------------------------------------------- TMR-LEAK


def leak_findings(model: RaceModel) -> List[Finding]:
    """Thread spawned with neither ``daemon=True`` nor an owned join path."""
    out: List[Finding] = []
    for m, func in model.all_functions():
        for spawn in func.spawns:
            if spawn.daemon or spawn.joined:
                continue
            out.append(
                Finding(
                    rule="TMR-LEAK",
                    path=m.path,
                    line=spawn.line,
                    col=spawn.col,
                    symbol=_sym(func),
                    message=(
                        f"thread {spawn.role!r} spawned without daemon=True and "
                        "without a join/close path for its handle"
                    ),
                )
            )
    return out
