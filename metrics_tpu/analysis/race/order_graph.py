"""TMR-ORDER: cycle detection in the interprocedural lock-acquisition graph.

An edge ``A -> B`` means some code path acquires ``B`` while holding ``A`` —
either a nested ``with``/``acquire()`` in one function (``held ∪ entry_held``
at the acquire site) or a call made under ``A`` into a function whose
transitive closure acquires ``B``. Two threads walking a cycle in opposite
directions deadlock; a cycle is a finding regardless of whether the schedule
that hits it has been observed. Reentrant self-edges on an ``RLock`` are
exempt (that is what RLock is for); a ``Lock``/``Condition`` self-edge is
self-deadlock and is reported.
"""
from typing import Dict, List, Set, Tuple

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.race.thread_model import RaceModel


def _edges(model: RaceModel) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """``(held, acquired) -> (path, line, via)`` anchor for the first witness."""
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add(a: str, b: str, path: str, line: int, via: str) -> None:
        edges.setdefault((a, b), (path, line, via))

    for m, func in model.all_functions():
        entry = func.entry_held or frozenset()
        for acq in func.acquires:
            for held in frozenset(acq.held) | entry:
                if held != acq.lock_id:
                    add(held, acq.lock_id, m.path, acq.line, func.qualname)
                elif _kind(model, held) != "RLock":
                    # non-reentrant self-acquire: immediate self-deadlock
                    add(held, held, m.path, acq.line, func.qualname)
        for site in func.calls:
            under = frozenset(site.held) | entry
            if not under:
                continue
            hit = model.resolve_call(m, site, func)
            if hit is None:
                continue
            for lock_id in model.transitive_acquires(hit[0], hit[1]):
                if lock_id in under:
                    continue  # already held on this path; the direct pass covers reentry
                for held in under:
                    add(held, lock_id, m.path, site.line,
                        f"{func.qualname} -> {site.symbol}")
    return edges


def _kind(model: RaceModel, lock_id: str) -> str:
    decl = model.locks.get(lock_id)
    return decl.kind if decl else "Lock"


def _sccs(nodes: Set[str], succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan, iterative (analyzer runs on arbitrarily deep lock graphs)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            children = sorted(succ.get(node, ()))
            for ci in range(pi, len(children)):
                child = children[ci]
                if child not in index:
                    work[-1] = (node, ci + 1)
                    work.append((child, 0))
                    advanced = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def _cycle_signature(comp: List[str]) -> str:
    """Canonical, line-churn-stable symbol: rotate so the lexicographically
    smallest lock leads, then close the loop."""
    comp = sorted(set(comp))
    return "->".join(comp + [comp[0]])


def order_findings(model: RaceModel) -> List[Finding]:
    edges = _edges(model)
    succ: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in edges:
        nodes.add(a)
        nodes.add(b)
        succ.setdefault(a, set()).add(b)
    out: List[Finding] = []
    for comp in _sccs(nodes, succ):
        cyclic = len(comp) > 1 or (comp and comp[0] in succ.get(comp[0], ()))
        if not cyclic:
            continue
        members = sorted(set(comp))
        # anchor at the first witness edge inside the component
        witness = None
        for (a, b), anchor in sorted(edges.items()):
            if a in members and b in members:
                witness = ((a, b), anchor)
                break
        if witness is None:  # pragma: no cover — SCC implies an internal edge
            continue
        (a, b), (path, line, via) = witness
        detail = ", ".join(
            f"{x}->{y} ({edges[(x, y)][2]})"
            for (x, y) in sorted(edges)
            if x in members and y in members
        )
        out.append(
            Finding(
                rule="TMR-ORDER",
                path=path,
                line=line,
                col=0,
                symbol=_cycle_signature(members),
                message=(
                    f"lock-order cycle over {{{', '.join(members)}}}: {detail}"
                    if len(members) > 1
                    else f"self-deadlock: {a} re-acquired while held ({via})"
                ),
            )
        )
    return out
