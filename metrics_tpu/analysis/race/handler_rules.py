"""TMR-HANDLER: signal / atexit / excepthook safety.

A handler runs at an arbitrary point of an arbitrary thread — including while
another (or the *same*) thread holds one of the runtime's locks. Inside code
reachable from a handler install, two things are unsafe:

- a *blocking* lock acquire (``with lock:`` or ``.acquire()`` without
  ``blocking=False``): the preempted thread may hold that lock and will never
  release it while the handler spins — deadlock at the worst possible moment
  (crash dump, SIGTERM). ``acquire(blocking=False)`` try-lock with a lock-free
  fallback is the sanctioned pattern (``obs/flight.py``).
- a non-atomic mutation of shared state: the handler interleaves with the
  very critical section it preempted.

Reachability follows the role propagation already computed in
:meth:`RaceModel.link` — any function whose role set intersects
``{signal, atexit, excepthook}`` is handler-reachable.
"""
from typing import List, Set

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.race.thread_model import _HANDLER_KINDS, RaceModel

_HANDLER_ROLES: Set[str] = set(_HANDLER_KINDS)


def handler_findings(model: RaceModel) -> List[Finding]:
    out: List[Finding] = []
    for m, func in model.all_functions():
        ctx = sorted(func.roles & _HANDLER_ROLES)
        if not ctx:
            continue
        ctx_s = "/".join(ctx)
        for acq in func.acquires:
            if not acq.blocking:
                continue  # try-lock: the sanctioned handler pattern
            out.append(
                Finding(
                    rule="TMR-HANDLER",
                    path=m.path,
                    line=acq.line,
                    col=acq.col,
                    symbol=func.qualname,
                    message=(
                        f"blocking acquire of {acq.lock_id} in {ctx_s}-reachable "
                        f"code; a preempted thread may hold it — use "
                        f"acquire(blocking=False) with a lock-free fallback"
                    ),
                )
            )
        for mut in func.mutations:
            if mut.atomic:
                continue
            out.append(
                Finding(
                    rule="TMR-HANDLER",
                    path=m.path,
                    line=mut.line,
                    col=mut.col,
                    symbol=func.qualname,
                    message=(
                        f"non-atomic mutation of {mut.target} ({mut.kind}) in "
                        f"{ctx_s}-reachable code interleaves with the preempted "
                        f"critical section"
                    ),
                )
            )
    return out
