"""Metric-class registry for the import-time introspection rules.

Mirrors the contract-sweep discovery (tests/unittests/bases/test_contract_sweep.py):
every class exported from ``metrics_tpu.__all__`` counts, constructed either by
a task-family prefix rule or a per-name constructor spec. The sweep's
exhaustiveness guard and tests/unittests/analysis keep the two tables in sync,
so a newly exported metric class reaches both the runtime contract tests and
tmlint's state-contract rules automatically.

Instances are built once per analyzer run; construction failures are recorded
(not raised) so an optional-dependency metric (pesq wheel, pretrained weights)
degrades to "not introspected" instead of killing the lint.
"""
import inspect
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional, Tuple


def _flat8_feature(x):
    """Weight-free stand-in feature extractor for FID/KID/IS construction."""
    import jax.numpy as jnp

    return jnp.asarray(x, jnp.float32).reshape(x.shape[0], -1)[:, :8]


def _ctor_specs() -> Dict[str, Callable[[], Dict[str, Any]]]:
    """Per-name constructor kwargs (lazy thunks: some need live sub-metrics)."""
    import metrics_tpu

    def kw(**kwargs):
        return lambda: kwargs

    specs: Dict[str, Callable[[], Dict[str, Any]]] = {
        # __new__-routing dispatchers
        "Accuracy": kw(task="binary"),
        "AUROC": kw(task="binary"),
        "AveragePrecision": kw(task="binary"),
        "CalibrationError": kw(task="binary"),
        "CohenKappa": kw(task="binary"),
        "ConfusionMatrix": kw(task="binary"),
        "ExactMatch": kw(task="multiclass", num_classes=5),
        "F1Score": kw(task="binary"),
        "FBetaScore": kw(task="binary", beta=0.5),
        "HammingDistance": kw(task="binary"),
        "HingeLoss": kw(task="binary"),
        "JaccardIndex": kw(task="binary"),
        "MatthewsCorrCoef": kw(task="binary"),
        "Precision": kw(task="binary"),
        "PrecisionRecallCurve": kw(task="binary", thresholds=11),
        "Recall": kw(task="binary"),
        "ROC": kw(task="binary", thresholds=11),
        "Specificity": kw(task="binary"),
        "StatScores": kw(task="binary"),
        "RecallAtFixedPrecision": kw(task="binary", min_precision=0.5, thresholds=11),
        "PrecisionAtFixedRecall": kw(task="binary", min_recall=0.5, thresholds=11),
        "SpecificityAtSensitivity": kw(task="binary", min_sensitivity=0.5, thresholds=11),
        # classes whose family prefix is not enough
        "MinkowskiDistance": kw(p=3),
        "TweedieDevianceScore": kw(power=1.5),
        "MultiScaleStructuralSimilarityIndexMeasure": kw(data_range=1.0, betas=(0.5, 0.5), kernel_size=3),
        "PeakSignalNoiseRatio": kw(data_range=1.0),
        "PeakSignalNoiseRatioWithBlockedEffect": kw(block_size=4),
        "RelativeAverageSpectralError": kw(window_size=4),
        "RootMeanSquaredErrorUsingSlidingWindow": kw(window_size=4),
        "StructuralSimilarityIndexMeasure": kw(data_range=1.0),
        "SignalDistortionRatio": kw(filter_length=4, load_diag=1e-4),
        "PanopticQuality": kw(things={0}, stuffs={1}),
        "ModifiedPanopticQuality": kw(things={0}, stuffs={1}),
        # sketches/: constructed at their telemetry defaults so tmlint's
        # state-contract rules and tmsan's trace/cost sweep see the shipping
        # bucket/register shapes
        "QuantileSketch": kw(),
        "DistinctCount": kw(),
        "HistogramDrift": kw(),
        "StreamingAUROCBound": kw(),
        "CramersV": kw(num_classes=4),
        "PearsonsContingencyCoefficient": kw(num_classes=4),
        "TheilsU": kw(num_classes=4),
        "TschuprowsT": kw(num_classes=4),
        "FrechetInceptionDistance": kw(feature=_flat8_feature, num_features=8),
        "KernelInceptionDistance": kw(feature=_flat8_feature, subset_size=4, subsets=2),
        "InceptionScore": kw(feature=_flat8_feature),
        "PermutationInvariantTraining": lambda: {
            "metric_func": metrics_tpu.functional.audio.scale_invariant_signal_noise_ratio,
            "eval_func": "max",
        },
        # wrappers: need live base metrics
        "BootStrapper": lambda: {
            "base_metric": metrics_tpu.MulticlassAccuracy(num_classes=5, average="micro", validate_args=False),
            "num_bootstraps": 4,
            "seed": 0,
        },
        "MultioutputWrapper": lambda: {
            "base_metric": metrics_tpu.MeanSquaredError(),
            "num_outputs": 2,
            "remove_nans": False,
        },
        "ClasswiseWrapper": lambda: {"metric": metrics_tpu.MulticlassAccuracy(num_classes=5, average=None)},
        "MinMaxMetric": lambda: {"base_metric": metrics_tpu.BinaryAccuracy()},
        "MetricTracker": lambda: {"metric": metrics_tpu.BinaryAccuracy()},
    }
    return specs


#: fleet-axis ctor specs (core/fleet.py): representative classes — one per
#: state flavor (scalar counts, per-class vectors, float accumulators, a
#: max-reduction state) — re-constructed with a fleet dim so the
#: state-contract rules also sweep a live (fleet_size, *base) registry,
#: including the `_fleet_rows` bookkeeping state it injects
FLEET_VARIANT_SPECS: Tuple[Tuple[str, Dict[str, Any]], ...] = (
    ("BinaryAccuracy", {"fleet_size": 4}),
    ("MulticlassAccuracy", {"num_classes": 5, "average": None, "fleet_size": 4}),
    ("MeanSquaredError", {"fleet_size": 4}),
    ("MinMetric", {"fleet_size": 4}),
)


#: family prefix -> ctor kwargs (matches the contract sweep's FAMILIES)
FAMILY_KWARGS: Tuple[Tuple[str, Dict[str, Any]], ...] = (
    ("Binary", {}),
    ("Multiclass", {"num_classes": 5}),
    ("Multilabel", {"num_labels": 3}),
    ("Retrieval", {}),
)

#: not introspectable here, with reasons (mirrors the sweep's CONSTRUCT_ONLY/SKIPS)
NOT_INTROSPECTED: Dict[str, str] = {
    "Metric": "the ABC itself",
    "CompositionalMetric": "built by operator overloads, not directly",
    "MetricCollection": "container, not a Metric (its members are introspected individually)",
    "BERTScore": "needs a pretrained encoder (no network egress)",
    "InfoLM": "needs a pretrained masked-LM (no network egress)",
    "CLIPScore": "needs pretrained CLIP (no network egress)",
    "LearnedPerceptualImagePatchSimilarity": "needs backbone weights (no network egress)",
    "PerceptualEvaluationSpeechQuality": "delegates to the optional pesq wheel",
    "ShortTimeObjectiveIntelligibility": "optional DSP dependency pipeline",
}


@dataclass
class IntrospectedClass:
    name: str
    cls: type
    instance: Optional[Any]  # None when construction failed/skipped
    skip_reason: str = ""

    @property
    def host_side(self) -> bool:
        """Whether the class declares its update/compute bodies host-side by
        contract (``_host_side_update``, the core/metric.py introspection hook)."""
        return bool(getattr(self.cls, "_host_side_update", False))


def ctor_kwargs_for(name: str) -> Optional[Callable[[], Dict[str, Any]]]:
    specs = _ctor_specs()
    if name in specs:
        return specs[name]
    for prefix, kwargs in FAMILY_KWARGS:
        if name.startswith(prefix):
            return lambda kwargs=kwargs: dict(kwargs)
    return lambda: {}


def iter_metric_classes() -> Iterator[Tuple[str, type]]:
    """Every class exported at the package root, same walk as the sweep."""
    import metrics_tpu

    for name in sorted(set(metrics_tpu.__all__)):
        obj = getattr(metrics_tpu, name, None)
        if inspect.isclass(obj):
            yield name, obj


def introspect_classes() -> Iterator[IntrospectedClass]:
    """Construct one instance per exported metric class (best effort)."""
    from metrics_tpu.core.metric import Metric

    for name, cls in iter_metric_classes():
        if name in NOT_INTROSPECTED:
            yield IntrospectedClass(name, cls, None, NOT_INTROSPECTED[name])
            continue
        thunk = ctor_kwargs_for(name)
        try:
            with warnings.catch_warnings():
                # root-import deprecation shims etc. are not the lint's business
                warnings.simplefilter("ignore")
                instance = cls(**thunk())
        except Exception as err:  # noqa: BLE001 — lint degrades, never dies, on ctor failure
            yield IntrospectedClass(name, cls, None, f"construction failed: {type(err).__name__}: {err}")
            continue
        if not isinstance(instance, Metric):
            yield IntrospectedClass(name, cls, None, "dispatcher returned a non-Metric")
            continue
        yield IntrospectedClass(name, type(instance), instance)


def introspect_fleet_variants() -> Iterator[IntrospectedClass]:
    """Fleet-constructed instances of the ``FLEET_VARIANT_SPECS`` classes,
    named ``Class@fleet`` so reports distinguish them from the plain sweep."""
    import metrics_tpu

    for name, kwargs in FLEET_VARIANT_SPECS:
        cls = getattr(metrics_tpu, name)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                instance = cls(**kwargs)
        except Exception as err:  # noqa: BLE001 — lint degrades, never dies, on ctor failure
            yield IntrospectedClass(f"{name}@fleet", cls, None, f"construction failed: {type(err).__name__}: {err}")
            continue
        yield IntrospectedClass(f"{name}@fleet", type(instance), instance)
