"""tmlint orchestration: files -> jit map -> rules -> baseline -> report."""
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.jitmap import PackageModel, load_package
from metrics_tpu.analysis.trace_rules import run_retrace_rules, run_trace_rules


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # all, waived included
    new_findings: List[Finding] = field(default_factory=list)
    unused_waivers: List[Tuple[str, str, str]] = field(default_factory=list)
    skipped_classes: Dict[str, str] = field(default_factory=dict)
    parse_errors: Dict[str, str] = field(default_factory=dict)
    stats: Dict[str, float] = field(default_factory=dict)

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.new_findings else 0


def _find_repo_root(target: str) -> str:
    """Directory that repo-relative finding paths are anchored to.

    The parent of the ``metrics_tpu`` package dir when the target is (inside)
    it, so paths come out as ``metrics_tpu/ops/...`` and the baseline works
    from any cwd; otherwise the target's own parent.
    """
    absd = os.path.abspath(target)
    d = absd if os.path.isdir(absd) else os.path.dirname(absd)
    while True:
        if os.path.basename(d) == "metrics_tpu" or os.path.exists(os.path.join(d, "metrics_tpu")):
            return d if os.path.basename(d) != "metrics_tpu" else os.path.dirname(d)
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.dirname(absd) if os.path.isfile(absd) else absd
        d = parent


def _introspection_roots(repo_root: str) -> Tuple[Dict[str, Dict[str, str]], Dict[str, str]]:
    """Jit entries from the live Metric registry: every non-host-side class's
    update/compute (the ``Metric._wrap_update`` / ``compute_from`` entries)."""
    import inspect

    from metrics_tpu.analysis.registry import introspect_classes
    from metrics_tpu.core.metric import Metric

    roots: Dict[str, Dict[str, str]] = {}
    skipped: Dict[str, str] = {}
    seen = set()
    for item in introspect_classes():
        if item.instance is None:
            skipped[item.name] = item.skip_reason
            continue
        if item.cls in seen:
            continue
        seen.add(item.cls)
        if item.host_side:
            continue  # declared host-side by contract (_host_side_update hook)
        methods = ("update",) if getattr(item.cls, "_host_side_compute", False) else ("update", "compute")
        for method in methods:
            for base in item.cls.__mro__:
                if base is Metric or method not in base.__dict__:
                    continue
                fn = base.__dict__[method]
                try:
                    path = inspect.getsourcefile(fn)
                except TypeError:
                    continue
                if path is None:
                    continue
                rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
                qual = getattr(fn, "__qualname__", f"{base.__name__}.{method}")
                roots.setdefault(rel, {})[qual] = (
                    f"Metric contract entry ({item.name}.{method} via _wrap_update/compute_from)"
                )
                break
    return roots, skipped


def analyze(
    target: str,
    baseline_path: Optional[str] = None,
    introspect: bool = True,
    repo_root: Optional[str] = None,
) -> Report:
    """Run tmlint over ``target`` (package dir or single file)."""
    t0 = time.perf_counter()
    report = Report()
    repo_root = repo_root or _find_repo_root(target)

    files = load_package(target, repo_root)
    package = PackageModel(files)
    report.parse_errors = dict(package.errors)

    if introspect:
        roots, skipped = _introspection_roots(repo_root)
        report.skipped_classes.update(skipped)
        package.inject_roots(roots)
    package.propagate()

    for module, info, _reason in package.reachable_functions():
        report.findings.extend(run_trace_rules(module, info))
    # retrace hazards live at host-side call sites INTO jit: scan everything
    for module in package.modules.values():
        for info in module.functions.values():
            report.findings.extend(run_retrace_rules(module, info))

    if introspect:
        from metrics_tpu.analysis.contract import run_contract_rules

        contract_findings, _ = run_contract_rules(repo_root)
        # only report classes that live inside the analyzed tree
        analyzed = set(files)
        report.findings.extend(f for f in contract_findings if f.path in analyzed)

    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))

    if baseline_path is None:
        baseline_path = baseline_mod.default_baseline_path(repo_root)
    if baseline_path is not None:
        from metrics_tpu.analysis.findings import LINT_RULES

        # the waiver file is shared with tmsan (the jaxpr tier): an AST-only run
        # must not report TMS-* waivers as stale
        waivers = baseline_mod.scope_waivers(baseline_mod.load_baseline(baseline_path), LINT_RULES)
        report.new_findings, report.unused_waivers = baseline_mod.apply_baseline(
            report.findings, waivers
        )
    else:
        report.new_findings = list(report.findings)

    report.stats = {
        "files": len(files),
        "functions": sum(len(m.functions) for m in package.modules.values()),
        "jit_reachable": len(package.reachable),
        "findings": len(report.findings),
        "waived": len(report.waived),
        "new": len(report.new_findings),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    return report
