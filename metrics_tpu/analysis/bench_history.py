"""Parse the BENCH_r*.json trajectory into backend-normalized series + a gate.

The checked-in ``BENCH_r<NN>.json`` rounds are raw driver captures — a stdout
``tail`` whose last JSON lines carry per-config measurements, later rounds a
machine-readable ``parsed.summary`` block. This module turns that history into
per-``(backend, config, field)`` series and answers the question the perf
trajectory could not answer by machine: *did the newest round regress?*

Backend normalization is the load-bearing rule: r06/r07 were recorded on the
CPU backend while r01–r05 ran on TPU, and absolute throughputs across backends
differ by orders of magnitude — a series only ever compares measurements with
the same backend stamp (legacy rounds without one are ``tpu``, per the
recorded history; ``bench.py`` now stamps every new round itself).

Gate semantics (:func:`find_regressions`): only the round under test is
gated — each of its measurements is compared against the **best** earlier
same-backend value of the same ``(config, field)`` series, and a change
worse than ``threshold`` (default 15%) in the unit's known direction
(``…/s…`` throughputs: higher is better; ``ms``/``s`` latencies: lower is
better) is a regression. Earlier-round dips are history that already shipped;
they surface as non-gating notes in the report so the trajectory stays
readable, but a gate that re-flagged them forever would just be permanently
red. Fields with no inferable direction (counts, parities) are not gated;
the sort-split fields the ROADMAP asks future TPU rounds to record
(``sort_ms``/``post_sort_ms``/``layout_sort_ms``/``scan_ms``) are gated as
latencies alongside each config's primary ``value``.
"""
import json
import os
import re
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

#: >15% worse than the best same-backend round fails the gate (ISSUE 11)
DEFAULT_THRESHOLD = 0.15

#: unstamped legacy rounds (r01–r05) predate the backend stamp and ran on TPU
LEGACY_BACKEND = "tpu"

#: per-config sub-fields gated as ms latencies when a round records them
#: (``tick_p50_ms`` is the ingest tier's deepest coalesced-tick latency — the
#: headline ``ingest_sustained_enqueue`` value gates higher-is-better via its
#: ``Kenq/s`` unit, so both directions of ISSUE 13 are covered)
GATED_SPLIT_FIELDS = ("sort_ms", "post_sort_ms", "layout_sort_ms", "scan_ms",
                      "scan_fused_ms", "tick_p50_ms", "coldstart_prewarmed_ms",
                      "flow_untraced_p50_ms", "flow_traced_p50_ms",
                      "flow_sampled_p50_ms", "restart_to_ready_ms",
                      "serve_round_p50_ms")

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


class Measurement(NamedTuple):
    round_num: int
    value: float
    unit: Optional[str]


class Round(NamedTuple):
    num: int
    backend: str
    ok: bool
    path: str
    #: {config: {field: (value, unit)}}
    measurements: Dict[str, Dict[str, Tuple[float, Optional[str]]]]


class Regression(NamedTuple):
    backend: str
    config: str
    field: str
    unit: Optional[str]
    value: float
    best: float
    best_round: int
    round_num: int
    change_pct: float


def direction_of(unit: Optional[str]) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown (not gated)."""
    if not isinstance(unit, str):
        return 0
    u = unit.strip()
    # latency first: "ms/step" must not match the "/s" throughput test below
    if u in ("ms", "s", "us") or u.startswith(("ms/", "s/", "us/")):
        return -1
    # "/s" as a whole path segment: Gpreds/s/chip, images/s, Mdocs/s/chip, ...
    if re.search(r"/s(/|$)", u):
        return 1
    return 0


def _rows_from_round(data: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-config measurement rows: the summary block when present, else the
    JSON measurement lines recoverable from the stdout tail."""
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("summary"), dict):
        return {
            cfg: row
            for cfg, row in parsed["summary"].items()
            if isinstance(row, dict)
        }
    rows: Dict[str, Dict[str, Any]] = {}
    for line in (data.get("tail") or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if not isinstance(obj, dict) or "metric" not in obj:
            continue
        if obj["metric"] == "summary_all_configs":
            if isinstance(obj.get("summary"), dict):
                rows.update(
                    {c: r for c, r in obj["summary"].items() if isinstance(r, dict)}
                )
        else:
            rows[obj["metric"]] = obj
    return rows


def parse_round(path: str) -> Round:
    """One BENCH_r*.json file -> a :class:`Round` of gateable measurements.

    Errored rounds (``rc != 0``, e.g. r01) parse to an empty measurement set
    — present in the trajectory, excluded from every series. Rows that record
    an ``error`` instead of a value (r06's CPU fid timeout) are skipped the
    same way.
    """
    m = _ROUND_RE.search(os.path.basename(path))
    if m is None:
        raise ValueError(f"not a bench round filename: {path!r}")
    num = int(m.group(1))
    with open(path) as f:
        data = json.load(f)
    backend = data.get("backend")
    parsed = data.get("parsed")
    if backend is None and isinstance(parsed, dict):
        # bench.py now stamps its own env into the summary line (r08+)
        env = parsed.get("env")
        if isinstance(env, dict):
            backend = env.get("backend")
    backend = backend or LEGACY_BACKEND
    ok = data.get("rc", 1) == 0
    measurements: Dict[str, Dict[str, Tuple[float, Optional[str]]]] = {}
    if ok:
        for cfg, row in _rows_from_round(data).items():
            if "error" in row:
                continue
            fields: Dict[str, Tuple[float, Optional[str]]] = {}
            value, unit = row.get("value"), row.get("unit")
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                fields["value"] = (float(value), unit)
            for split in GATED_SPLIT_FIELDS:
                sv = row.get(split)
                if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                    fields[split] = (float(sv), "ms")
            if fields:
                measurements[cfg] = fields
    return Round(num=num, backend=str(backend), ok=ok, path=path, measurements=measurements)


def load_rounds(paths: List[str]) -> List[Round]:
    """Parse and sort a set of round files (duplicate round numbers rejected)."""
    rounds = sorted((parse_round(p) for p in paths), key=lambda r: r.num)
    nums = [r.num for r in rounds]
    if len(set(nums)) != len(nums):
        dupes = sorted({n for n in nums if nums.count(n) > 1})
        raise ValueError(f"duplicate bench round numbers: {dupes}")
    return rounds


def discover(dirpath: str) -> List[str]:
    """All BENCH_r*.json files directly under ``dirpath``, sorted."""
    return sorted(
        os.path.join(dirpath, name)
        for name in os.listdir(dirpath)
        if _ROUND_RE.search(name)
    )


def build_series(
    rounds: List[Round],
) -> Dict[Tuple[str, str, str], List[Measurement]]:
    """``{(backend, config, field): [Measurement, ...]}``, round-ordered."""
    series: Dict[Tuple[str, str, str], List[Measurement]] = {}
    for rnd in rounds:
        for cfg, fields in rnd.measurements.items():
            for field, (value, unit) in fields.items():
                series.setdefault((rnd.backend, cfg, field), []).append(
                    Measurement(round_num=rnd.num, value=value, unit=unit)
                )
    return series


def _relative_loss(value: float, best: float, direction: int) -> float:
    """How much worse ``value`` is than ``best``, as a fraction of ``best``
    (0.0 when equal or better)."""
    if best == 0:
        return 0.0
    if direction > 0:
        return max(0.0, (best - value) / abs(best))
    return max(0.0, (value - best) / abs(best))


def find_regressions(
    series: Dict[Tuple[str, str, str], List[Measurement]],
    round_num: int,
    threshold: float = DEFAULT_THRESHOLD,
) -> List[Regression]:
    """Gate one round: its measurements vs the best earlier same-backend value.

    A series the round under test doesn't appear in, or appears in first
    (a new config, or the first round on a new backend), has nothing to
    compare against and cannot regress.
    """
    out: List[Regression] = []
    for (backend, cfg, field), points in sorted(series.items()):
        current = next((p for p in points if p.round_num == round_num), None)
        if current is None:
            continue
        direction = direction_of(current.unit)
        if direction == 0:
            continue
        earlier = [p.value for p in points if p.round_num < round_num]
        if not earlier:
            continue
        best = max(earlier) if direction > 0 else min(earlier)
        best_round = next(
            p.round_num
            for p in points
            if p.round_num < round_num and p.value == best
        )
        loss = _relative_loss(current.value, best, direction)
        if loss > threshold:
            out.append(
                Regression(
                    backend=backend,
                    config=cfg,
                    field=field,
                    unit=current.unit,
                    value=current.value,
                    best=best,
                    best_round=best_round,
                    round_num=round_num,
                    change_pct=round(loss * 100.0, 2),
                )
            )
    return out


def trajectory_report(
    rounds: List[Round], threshold: float = DEFAULT_THRESHOLD
) -> Dict[str, Any]:
    """Full history view: every series, plus which round (if any) is gated.

    ``historical_dips`` lists >threshold drops at earlier rounds — context
    for a reader, never a gate failure (see module docstring).
    """
    series = build_series(rounds)
    latest = max((r.num for r in rounds), default=None)
    regressions = (
        find_regressions(series, latest, threshold) if latest is not None else []
    )
    dips: List[Dict[str, Any]] = []
    for num in sorted({p.round_num for pts in series.values() for p in pts}):
        if num == latest:
            continue
        for reg in find_regressions(series, num, threshold):
            dips.append(reg._asdict())
    return {
        "rounds": [
            {
                "round": r.num,
                "backend": r.backend,
                "ok": r.ok,
                "configs": sorted(r.measurements),
            }
            for r in rounds
        ],
        "series": {
            f"{backend}/{cfg}/{field}": [
                {"round": p.round_num, "value": p.value, "unit": p.unit}
                for p in points
            ]
            for (backend, cfg, field), points in sorted(series.items())
        },
        "gated_round": latest,
        "threshold": threshold,
        "regressions": [reg._asdict() for reg in regressions],
        "historical_dips": dips,
    }
