from metrics_tpu.nominal.cramers import CramersV
from metrics_tpu.nominal.pearson import PearsonsContingencyCoefficient
from metrics_tpu.nominal.theils_u import TheilsU
from metrics_tpu.nominal.tschuprows import TschuprowsT

__all__ = [
    "CramersV",
    "PearsonsContingencyCoefficient",
    "TheilsU",
    "TschuprowsT",
]
