"""CramersV metric class (reference: nominal/cramers.py:30-120)."""
from typing import Any, Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.nominal.cramers import _cramers_v_compute, _cramers_v_update
from metrics_tpu.functional.nominal.utils import _nominal_input_validation


class CramersV(Metric):
    """Cramer's V statistic of association between two categorical series (reference: nominal/cramers.py:30).

    The class variant requires ``num_classes`` up front so the confusion-matrix state
    has a static shape (the reference infers it per-call in the functional only).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.nominal import CramersV
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> metric = CramersV(num_classes=4)
        >>> 0 <= float(metric(preds, target)) <= 1
        True
    """

    full_state_update: bool = False
    # compute drops all-zero confmat rows/cols (ragged, host-side by design,
    # reference parity); tmlint treats compute as host code, update stays traced
    _host_side_compute = True
    is_differentiable: bool = False
    higher_is_better: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[Union[int, float]] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError("Argument `num_classes` is expected to be a positive integer")
        self.num_classes = num_classes
        self.bias_correction = bias_correction
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes)), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate the contingency table."""
        confmat = _cramers_v_update(preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        """Cramer's V from the accumulated table."""
        return _cramers_v_compute(self.confmat, self.bias_correction)
