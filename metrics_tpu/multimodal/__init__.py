"""Multimodal metrics (reference: src/torchmetrics/multimodal/__init__.py)."""
from metrics_tpu.multimodal.clip_score import CLIPScore

__all__ = ["CLIPScore"]
