"""CLIPScore metric (reference: multimodal/clip_score.py:46-130)."""
from typing import Any, List, Optional, Sequence, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.multimodal.clip_score import (
    _DEFAULT_CLIP,
    ImageEncoder,
    TextEncoder,
    _clip_score_update,
    _default_clip_encoders,
)


class CLIPScore(Metric):
    """Running-mean CLIPScore: ``max(100 * cos(E_I, E_C), 0)`` over all samples.

    Args:
        model_name_or_path: HF CLIP checkpoint for the default torch encoders
            (requires locally cached weights).
        image_encoder / text_encoder: custom embedding callables (both required
            together); see :mod:`metrics_tpu.functional.multimodal.clip_score`.
            For TPU-native forwards, build both with
            :func:`metrics_tpu.models.clip.jax_clip_encoders` (pure-JAX ViT +
            text-transformer port loading HF CLIPModel checkpoints).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(
        self,
        model_name_or_path: str = _DEFAULT_CLIP,
        image_encoder: Optional[ImageEncoder] = None,
        text_encoder: Optional[TextEncoder] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if (image_encoder is None) != (text_encoder is None):
            raise ValueError("`image_encoder` and `text_encoder` must be provided together.")
        self.model_name_or_path = model_name_or_path
        self.image_encoder = image_encoder
        self.text_encoder = text_encoder
        self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.asarray(0), dist_reduce_fx="sum")

    def _encoders(self):
        if self.image_encoder is None:
            # build (and cache) the default encoders once
            self.image_encoder, self.text_encoder = _default_clip_encoders(self.model_name_or_path)
        return self.image_encoder, self.text_encoder

    def update(self, images: Union[Array, List[Array]], text: Union[str, Sequence[str]]) -> None:
        image_encoder, text_encoder = self._encoders()
        score, n_samples = _clip_score_update(images, text, image_encoder, text_encoder)
        self.score = self.score + score.sum(0)
        self.n_samples = self.n_samples + n_samples

    def compute(self) -> Array:
        return jnp.maximum(self.score / self.n_samples, 0.0)
