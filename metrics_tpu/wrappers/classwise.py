"""ClasswiseWrapper (reference: wrappers/classwise.py:26-165): splits a per-class
output tensor into a ``{name_label: scalar}`` dict."""
from typing import Any, Dict, List, Optional

from jax import Array

from metrics_tpu.core.metric import Metric


class ClasswiseWrapper(Metric):
    """Per-class dict output for metrics with ``average=None`` (reference: :26).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.wrappers import ClasswiseWrapper
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> preds = jnp.array([0, 1, 2, 1])
        >>> target = jnp.array([0, 1, 2, 2])
        >>> sorted(metric(preds, target).keys())
        ['multiclassaccuracy_0', 'multiclassaccuracy_1', 'multiclassaccuracy_2']
    """

    full_state_update: Optional[bool] = True

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        if self.metric.fleet_size is not None:
            # fleet inner metric: the compute tree is (fleet_size, num_classes)
            # — enumerate the trailing CLASS axis so each dict value keeps its
            # per-stream leading axis (per-class × per-stream results)
            if self.labels is None:
                return {f"{name}_{i}": x[..., i] for i in range(x.shape[-1])}
            return {f"{name}_{lab}": x[..., i] for i, lab in enumerate(self.labels)}
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def _san_input_specs(self, n: int):
        # tmsan hook (core/metric.py): shapes come from the wrapped metric
        from metrics_tpu.analysis.san.abstract_inputs import inner_spec

        return inner_spec(self.metric, n)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        return self._convert(self.metric(*args, **kwargs))

    def reset(self) -> None:
        self.metric.reset()
