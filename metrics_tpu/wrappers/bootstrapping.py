"""BootStrapper wrapper.

Capability parity with reference ``wrappers/bootstrapping.py`` (_bootstrap_sampler
:30-50, BootStrapper :53-200): N copies of a base metric, each update resamples the
batch with replacement; compute returns mean/std/quantile/raw.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import apply_to_collection


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson", rng: Optional[np.random.Generator] = None) -> Array:
    """Resample indices along dim 0 with replacement (reference: :30-50).

    Host-side RNG (numpy): sampling happens in the eager wrapper, not under jit.
    """
    rng = rng or np.random.default_rng()
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size=size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    if sampling_strategy == "multinomial":
        return jnp.asarray(rng.integers(0, size, size=size))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Bootstrapped confidence intervals for any metric (reference: :53-200).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from metrics_tpu.wrappers import BootStrapper
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> np.random.seed(123)
        >>> base = MulticlassAccuracy(num_classes=5, average="micro")
        >>> bootstrap = BootStrapper(base, num_bootstraps=20)
        >>> rng = np.random.default_rng(0)
        >>> preds = jnp.asarray(rng.integers(0, 5, 100))
        >>> target = jnp.asarray(rng.integers(0, 5, 100))
        >>> bootstrap.update(preds, target)
        >>> output = bootstrap.compute()
        >>> sorted(output.keys())
        ['mean', 'std']
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )

        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        self._rng = np.random.default_rng()

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but recieved {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample inputs along dim 0 per bootstrap copy (reference: :115-135)."""
        array_types = (jnp.ndarray, np.ndarray)
        for idx in range(self.num_bootstraps):
            args_sizes = apply_to_collection(args, array_types, len)
            kwargs_sizes = list(apply_to_collection(kwargs, array_types, len).values()) if kwargs else []
            if len(args_sizes) > 0:
                size = args_sizes[0]
            elif len(kwargs_sizes) > 0:
                size = kwargs_sizes[0]
            else:
                raise ValueError("None of the input contained tensors, so could not determine the sampling size")
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            new_args = apply_to_collection(args, array_types, lambda x: jnp.take(jnp.asarray(x), sample_idx, axis=0))
            new_kwargs = apply_to_collection(
                kwargs, array_types, lambda x: jnp.take(jnp.asarray(x), sample_idx, axis=0)
            )
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """mean/std/quantile/raw over bootstrap computes (reference: :141-157)."""
        computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()
