"""BootStrapper wrapper.

Capability parity with reference ``wrappers/bootstrapping.py`` (_bootstrap_sampler
:30-50, BootStrapper :53-200): N copies of a base metric, each update resamples the
batch with replacement; compute returns mean/std/quantile/raw.

TPU-first pure tier (round 5): instead of the reference's N eager deepcopies fed
in a Python loop, ``init_state``/``local_update``/``compute_from`` carry ONE
stacked ``(num_bootstraps, ...)`` state pytree, resample on device with the jax
PRNG (key carried in the state) and run the base metric's ``local_update`` vmapped
over the bootstrap axis — all N bootstrap replicas cost one fused device program
under jit/shard_map, making bootstrap confidence intervals nearly free on device.

Fleet rebase (round 9): the EAGER tier now rides the same degenerate-fleet shape.
When the base metric is eligible (fixed-shape array states, traceable update, no
child metrics of its own) the wrapper keeps ONE template copy plus registered
``boot_<name>`` states stacked ``(num_bootstraps, *base)``, and each ``update``
is one cached donated launch (``core.fleet.run_step``) vmapping the base
``local_update`` over device-resampled replicas — N dispatches and N state trees
collapse to 1. Ineligible bases (list/cat states, host-side updates, wrapper
bases) keep the reference's N-deepcopy loop.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import apply_to_collection


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson", rng: Optional[np.random.Generator] = None) -> Array:
    """Resample indices along dim 0 with replacement (reference: :30-50).

    Host-side RNG (numpy): sampling happens in the eager wrapper, not under jit.
    """
    rng = rng or np.random.default_rng()
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size=size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    if sampling_strategy == "multinomial":
        return jnp.asarray(rng.integers(0, size, size=size))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Bootstrapped confidence intervals for any metric (reference: :53-200).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from metrics_tpu.wrappers import BootStrapper
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> np.random.seed(123)
        >>> base = MulticlassAccuracy(num_classes=5, average="micro")
        >>> bootstrap = BootStrapper(base, num_bootstraps=20)
        >>> rng = np.random.default_rng(0)
        >>> preds = jnp.asarray(rng.integers(0, 5, 100))
        >>> target = jnp.asarray(rng.integers(0, 5, 100))
        >>> bootstrap.update(preds, target)
        >>> output = bootstrap.compute()
        >>> sorted(output.keys())
        ['mean', 'std']
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        # `seed` is additive over the reference API: it makes BOTH tiers
        # reproducible (numpy rng for eager update, PRNG key for the pure tier)
        self._seed = seed
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )

        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        self._rng = np.random.default_rng(seed)

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but recieved {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy

        self._eager_stacked = self._stackable(base_metric)
        if self._eager_stacked:
            # degenerate fleet: one template + registered (N, *base) states,
            # every eager update is ONE vmapped launch (see module docstring)
            self.metrics = [deepcopy(base_metric)]
            n = num_bootstraps
            for name, default in base_metric._defaults.items():
                stacked = jnp.tile(jnp.asarray(default)[None], (n,) + (1,) * jnp.ndim(default))
                self.add_state(
                    f"boot_{name}",
                    stacked,
                    dist_reduce_fx=base_metric._reductions[name],
                    persistent=base_metric._persistent[name],
                )
        else:
            self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]

    @staticmethod
    def _stackable(base: Metric) -> bool:
        """Can the eager tier carry one stacked state instead of N copies?
        Mirrors the fused-engine eligibility: fixed-shape array states and a
        traceable update on a leaf metric."""
        from metrics_tpu.ckpt.manifest import child_metrics
        from metrics_tpu.core.state import CatBuffer

        if type(base)._host_side_update or not base._defaults:
            return False
        if any(isinstance(v, (list, CatBuffer)) for v in base._defaults.values()):
            return False
        return not child_metrics(base)

    def _san_input_specs(self, n: int):
        # tmsan hook (core/metric.py): shapes come from the wrapped metric
        from metrics_tpu.analysis.san.abstract_inputs import inner_spec

        return inner_spec(self.metrics[0], n) if self.metrics else None

    @staticmethod
    def _batch_size(args: Any, kwargs: Any) -> int:
        array_types = (jnp.ndarray, np.ndarray)
        args_sizes = apply_to_collection(args, array_types, len)
        kwargs_sizes = list(apply_to_collection(kwargs, array_types, len).values()) if kwargs else []
        sizes = list(jax.tree_util.tree_leaves(args_sizes)) + kwargs_sizes
        if not sizes:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        # sizes come from len() over concrete arrays — already host ints
        return sizes[0]

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample inputs along dim 0 per bootstrap replica (reference: :115-135).

        Stacked (degenerate-fleet) path: one cached donated launch vmapping the
        base ``local_update`` over device-resampled replicas. The per-call seed
        still comes from the host ``self._rng`` stream, so seeded wrappers stay
        reproducible and unseeded ones draw fresh subsamples per call.
        """
        array_types = (jnp.ndarray, np.ndarray)
        if self._eager_stacked:
            from metrics_tpu.core import fleet as _fleet
            from metrics_tpu.core import fused as _fused

            base = self.metrics[0]
            size = self._batch_size(args, kwargs)
            seed = int(self._rng.integers(0, 2**63 - 1))
            keys = jax.random.split(jax.random.PRNGKey(seed), self.num_bootstraps)
            state = {name: getattr(self, f"boot_{name}") for name in base._defaults}
            dyn, spec = _fused._split_inputs(args, kwargs)

            def step(st, ks, dl):
                a, kw = _fused._merge_inputs(dl, spec)

                def one(bstate, k):
                    idx = self._device_sample(k, size)
                    new_a = apply_to_collection(a, array_types, lambda x: jnp.take(jnp.asarray(x), idx, axis=0))
                    new_kw = apply_to_collection(kw, array_types, lambda x: jnp.take(jnp.asarray(x), idx, axis=0))
                    return base.local_update(bstate, *new_a, **new_kw)

                return jax.vmap(one)(st, ks)

            new = _fleet.run_step(
                self, "boot.update", step, state, keys, dyn, static_key=_fused._static_key(spec)
            )
            for name, value in new.items():
                setattr(self, f"boot_{name}", value)
            return

        for idx in range(self.num_bootstraps):
            size = self._batch_size(args, kwargs)
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            new_args = apply_to_collection(args, array_types, lambda x: jnp.take(jnp.asarray(x), sample_idx, axis=0))
            new_kwargs = apply_to_collection(
                kwargs, array_types, lambda x: jnp.take(jnp.asarray(x), sample_idx, axis=0)
            )
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """mean/std/quantile/raw over bootstrap computes (reference: :141-157)."""
        if self._eager_stacked:
            base = self.metrics[0]
            state = {name: getattr(self, f"boot_{name}") for name in base._defaults}
            computed_vals = jax.vmap(lambda s: jnp.asarray(base.compute_from(s)))(state)
        else:
            computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()

    # --------------------------------------------------- pure-functional tier

    def init_state(self) -> Dict[str, Any]:
        """One stacked ``(num_bootstraps, ...)`` base-state pytree + the PRNG key."""
        base = self.metrics[0].init_state()
        if any(isinstance(v, list) for v in base.values()):
            raise ValueError(
                "BootStrapper's pure tier needs static-shape base states; construct the"
                " base metric with `cat_capacity` so its cat states become CatBuffers"
            )
        n = self.num_bootstraps
        stacked = jax.tree_util.tree_map(lambda x: jnp.broadcast_to(jnp.asarray(x), (n,) + jnp.shape(x)), base)
        # seed=None draws fresh entropy per init_state (mirroring the eager
        # tier's default_rng()): a fixed fallback key would make "unseeded"
        # wrappers byte-identical across instances and runs, silently
        # correlating their bootstrap CIs
        seed = self._seed if self._seed is not None else int(self._rng.integers(0, 2**63 - 1))
        return {"key": jax.random.PRNGKey(seed), "metrics": stacked}

    def _device_sample(self, key: Array, size: int) -> Array:
        """Resample indices on device with a static output length.

        multinomial == the classic bootstrap (uniform draw with replacement).
        poisson mirrors the reference's variable-length Poisson(1) resampling as
        closely as static shapes allow: per-row counts are realized by
        ``repeat(..., total_repeat_length=size)`` — a draw whose total exceeds
        ``size`` is truncated, and one that falls short is padded with
        uniformly drawn indices (NOT the repeat's default final-row padding,
        which would overweight the last row and make the O(sqrt(size))/size
        boundary correction position-dependent).
        """
        if self.sampling_strategy == "multinomial":
            return jax.random.randint(key, (size,), 0, size)
        # Poisson(1) by inverse CDF over a truncated support (P(K > 16) < 1e-14):
        # jax.random.poisson's rejection while_loop trips shard_map's varying-axis
        # type check, and a branchless searchsorted is also faster for fixed lam=1
        k_cnt, k_pad = jax.random.split(key)
        ks = jnp.arange(17)
        log_pmf = -1.0 - jax.scipy.special.gammaln(ks + 1.0)
        cdf = jnp.cumsum(jnp.exp(log_pmf))
        u = jax.random.uniform(k_cnt, (size,))
        counts = jnp.sum(u[:, None] > cdf[None, :], axis=1)
        idx = jnp.repeat(jnp.arange(size), counts, total_repeat_length=size)
        pad = jax.random.randint(k_pad, (size,), 0, size)
        return jnp.where(jnp.arange(size) < counts.sum(), idx, pad)

    def local_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """All bootstrap replicas in one vmapped program (device-side resampling)."""
        array_types = (jnp.ndarray, np.ndarray)
        sizes = apply_to_collection(args, array_types, len) or tuple(
            apply_to_collection(kwargs, array_types, len).values()
        )
        sizes = jax.tree_util.tree_leaves(sizes)
        if not sizes:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        size = int(sizes[0])
        base = self.metrics[0]
        key, sub = jax.random.split(state["key"])
        keys = jax.random.split(sub, self.num_bootstraps)

        def one(bstate, k):
            idx = self._device_sample(k, size)
            new_args = apply_to_collection(args, array_types, lambda x: jnp.take(jnp.asarray(x), idx, axis=0))
            new_kwargs = apply_to_collection(kwargs, array_types, lambda x: jnp.take(jnp.asarray(x), idx, axis=0))
            return base.local_update(bstate, *new_args, **new_kwargs)

        return {"key": key, "metrics": jax.vmap(one)(state["metrics"], keys)}

    def sync_state(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Dict[str, Any]:
        """Per-replica sync: the base reductions apply elementwise over the stack."""
        base = self.metrics[0]
        if any(kind == "cat" for kind in base._reductions.values()):
            # an all_gather along axis 0 would interleave the bootstrap stack
            # dimension with the mesh axis; no in-tree sum-state metric needs it
            raise NotImplementedError(
                "BootStrapper's pure tier cannot sync cat-reduction base states over a"
                " mesh axis; evaluate per shard and combine computes instead"
            )
        key = state["key"]
        if axis_name is not None:
            # every device ran the same split sequence, so the keys are equal; a
            # pmax no-op gives them the device-invariant type shard_map's
            # out_specs=P() requires (see collective.replicate_gathered)
            from metrics_tpu.parallel import collective

            key = collective.replicate_gathered(key, axis_name)
        return {"key": key, "metrics": base.sync_state(state["metrics"], axis_name)}

    def compute_from(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Dict[str, Array]:
        base = self.metrics[0]
        vals = jax.vmap(lambda s: jnp.asarray(base.compute_from(s, axis_name)))(state["metrics"])
        output_dict: Dict[str, Array] = {}
        if self.mean:
            output_dict["mean"] = vals.mean(axis=0)
        if self.std:
            output_dict["std"] = vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = vals
        return output_dict
