"""MinMaxMetric (reference: wrappers/minmax.py:28-153): tracks running min/max of a
base metric's compute."""
from typing import Any, Dict, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric


class MinMaxMetric(Metric):
    """Track base metric plus its historical min/max (reference: :28).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.wrappers import MinMaxMetric
        >>> from metrics_tpu.classification import BinaryAccuracy
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> _ = metric(jnp.array([1, 0, 0, 1]), jnp.array([1, 1, 0, 1]))
        >>> out = metric.compute()
        >>> sorted(out.keys())
        ['max', 'min', 'raw']
    """

    full_state_update: Optional[bool] = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.add_state("min_val", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("max_val", jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def _san_input_specs(self, n: int):
        # tmsan hook (core/metric.py): shapes come from the wrapped metric
        from metrics_tpu.analysis.san.abstract_inputs import inner_spec

        return inner_spec(self._base_metric, n)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.where(self.max_val < val, jnp.asarray(val, dtype=jnp.float32), self.max_val)
        self.min_val = jnp.where(self.min_val > val, jnp.asarray(val, dtype=jnp.float32), self.min_val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if hasattr(val, "size"):
            return val.size == 1
        return False
