from metrics_tpu.wrappers.bootstrapping import BootStrapper
from metrics_tpu.wrappers.classwise import ClasswiseWrapper
from metrics_tpu.wrappers.minmax import MinMaxMetric
from metrics_tpu.wrappers.multioutput import MultioutputWrapper
from metrics_tpu.wrappers.tracker import MetricTracker

__all__ = ["BootStrapper", "ClasswiseWrapper", "MinMaxMetric", "MultioutputWrapper", "MetricTracker"]
