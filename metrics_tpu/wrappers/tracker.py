"""MetricTracker (reference: wrappers/tracker.py:31-308): tracks a metric (or
collection) over a sequence of steps; exposes best value/step."""
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn


class MetricTracker:
    """List of metric copies over time steps (reference: :31).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from metrics_tpu.wrappers import MetricTracker
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> tracker = MetricTracker(MulticlassAccuracy(num_classes=5, average="micro"))
        >>> rng = np.random.default_rng(42)
        >>> for epoch in range(3):
        ...     tracker.increment()
        ...     for batch in range(5):
        ...         preds = jnp.asarray(rng.integers(0, 5, 100))
        ...         target = jnp.asarray(rng.integers(0, 5, 100))
        ...         _ = tracker.update(preds, target)
        >>> all_results = tracker.compute_all()
        >>> all_results.shape
        (3,)
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a Metric or MetricCollection" f" but got {metric}"
            )
        self._base_metric = metric
        self._metrics: List[Union[Metric, MetricCollection]] = []
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        if isinstance(metric, Metric) and not isinstance(maximize, bool):
            raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        self.maximize = maximize
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of steps tracked so far (reference: :84-87)."""
        return len(self._metrics)

    def increment(self) -> None:
        """Create a new (reset) copy of the base metric for the next step (reference: :89-93)."""
        self._increment_called = True
        metric = deepcopy(self._base_metric)
        metric.reset()
        self._metrics.append(metric)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._metrics[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Any:
        """Compute for all tracked steps (reference: :130-148)."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._metrics]
        try:
            if isinstance(self._base_metric, MetricCollection):
                keys = res[0].keys()
                return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
            return jnp.stack([jnp.asarray(r) for r in res], axis=0)
        except TypeError:  # nested/ragged results
            return res

    def reset(self) -> None:
        """Reset the current step's metric."""
        self._metrics[-1].reset()

    def reset_all(self) -> None:
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[None, float, Tuple[float, int], Dict[str, Optional[float]], Tuple[Dict, Dict]]:
        """Best value (and optionally step) across tracked steps (reference: :184-270)."""
        res = self.compute_all()
        if isinstance(res, list):
            rank_zero_warn(
                "Encounted nested structure. You are probably using a metric collection inside a metric collection,"
                " or a metric wrapper inside a metric collection, which is not supported by `.best_metric()` method."
                " Returning `None` instead."
            )
            return (None, None) if return_step else None

        if isinstance(self._base_metric, Metric):
            fn = jnp.argmax if self.maximize else jnp.argmin
            try:
                idx = int(fn(res))
                value = res[idx]
                if return_step:
                    return float(value), idx
                return float(value)
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric: {error}"
                    " this is probably due to the 'best' not being defined for this metric."
                    " Returning `None` instead.",
                    UserWarning,
                )
                return (None, None) if return_step else None

        maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
        value, idx = {}, {}
        for i, (k, v) in enumerate(res.items()):
            try:
                fn = jnp.argmax if maximize[i] else jnp.argmin
                out = int(fn(v))
                value[k], idx[k] = float(v[out]), out
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric for metric {k}:"
                    f" {error} this is probably due to the 'best' not being defined for this metric."
                    " Returning `None` instead.",
                    UserWarning,
                )
                value[k], idx[k] = None, None

        if return_step:
            return value, idx
        return value

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")
