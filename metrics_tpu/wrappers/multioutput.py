"""MultioutputWrapper (reference: wrappers/multioutput.py:29-192): K copies of a base
metric, one per output dimension, with optional NaN-row removal per output.

TPU-first pure tier (round 5): ``init_state``/``local_update``/``compute_from``
carry one stacked ``(num_outputs, ...)`` base-state pytree and run the base
metric's ``local_update`` vmapped over the output axis — every output column
evaluates in one fused device program under jit/shard_map. ``remove_nans`` is a
data-dependent row filter and stays eager-only (construct with
``remove_nans=False`` for the pure tier)."""
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.data import apply_to_collection


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where any tensor has a NaN (reference: :15-26)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for tensor in tensors:
        permuted = tensor.reshape(len(sentinel), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(permuted), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """Evaluate one metric per output dimension (reference: :29).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.wrappers import MultioutputWrapper
        >>> from metrics_tpu.regression import MeanSquaredError
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> target = jnp.array([[0.1, 0.2], [0.3, 0.4]])
        >>> preds = jnp.array([[0.1, 0.3], [0.5, 0.4]])
        >>> metric(preds, target)
        Array([0.02 , 0.005], dtype=float32)
    """

    is_differentiable = False
    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if self.fleet_size is not None:
            from metrics_tpu.utils.exceptions import MetricsUserError

            raise MetricsUserError(
                "MultioutputWrapper holds its state in per-output child metrics,"
                " so fleet_size on the wrapper registers nothing to route; make"
                " the underlying metric the fleet instead (base_metric with"
                " fleet_size=N, updated with stream_ids)"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple[tuple, dict]]:
        """Slice inputs along output_dim per metric copy (reference: :95-120)."""
        args_kwargs_by_output = []
        array_types = (jnp.ndarray, np.ndarray)
        for i in range(len(self.metrics)):
            def select(x, i=i):
                x = jnp.asarray(x)
                selected = jnp.take(x, jnp.asarray([i]), axis=self.output_dim)
                if self.squeeze_outputs:
                    selected = jnp.squeeze(selected, axis=self.output_dim)
                return selected

            selected_args = apply_to_collection(args, array_types, select)
            selected_kwargs = apply_to_collection(kwargs, array_types, select)
            if self.remove_nans:
                tensors = [a for a in selected_args if isinstance(a, array_types)] + [
                    v for v in selected_kwargs.values() if isinstance(v, array_types)
                ]
                if tensors:
                    if not _is_concrete(*tensors):
                        # row filtering is data-dependent-shape: fail with a
                        # usable message instead of a tracer conversion error
                        raise ValueError(
                            "MultioutputWrapper(remove_nans=True) filters rows by NaN"
                            " content and cannot run under jit/shard_map; use"
                            " remove_nans=False or filter rows on host first."
                        )
                    nan_idxs = np.asarray(_get_nan_indices(*tensors))
                    if nan_idxs.any():
                        selected_args = tuple(np.asarray(a)[~nan_idxs] for a in selected_args)
                        selected_kwargs = {k: np.asarray(v)[~nan_idxs] for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def _san_input_specs(self, n: int):
        # tmsan hook (core/metric.py): the wrapped metric's shapes gain an
        # output axis at output_dim (only the trailing-dim layout is modeled)
        import jax

        from metrics_tpu.analysis.san.abstract_inputs import inner_spec

        if self.output_dim != -1 or not self.metrics:
            return []  # opt out: non-trailing output dims are not modeled
        raw = inner_spec(self.metrics[0], n)
        if raw is None:
            return None
        expanded = []
        for args, kw in raw:
            expanded.append(
                (
                    tuple(
                        jax.ShapeDtypeStruct(tuple(a.shape) + (len(self.metrics),), a.dtype)
                        for a in args
                    ),
                    kw,
                )
            )
        return expanded

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        return jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs)
        ]
        if results[0] is None:
            return None
        return jnp.stack([jnp.asarray(r) for r in results], 0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()

    # --------------------------------------------------- pure-functional tier

    def init_state(self) -> Dict[str, Any]:
        """One stacked ``(num_outputs, ...)`` base-state pytree."""
        base = self.metrics[0].init_state()
        if any(isinstance(v, list) for v in base.values()):
            raise ValueError(
                "MultioutputWrapper's pure tier needs static-shape base states; construct"
                " the base metric with `cat_capacity` so its cat states become CatBuffers"
            )
        k = len(self.metrics)
        return jax.tree_util.tree_map(lambda x: jnp.broadcast_to(jnp.asarray(x), (k,) + jnp.shape(x)), base)

    def local_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """All output columns in one vmapped program."""
        if self.remove_nans:
            raise NotImplementedError(
                "remove_nans drops a data-dependent number of rows and cannot run under"
                " jit; construct MultioutputWrapper(remove_nans=False) for the pure tier"
            )
        array_types = (jnp.ndarray, np.ndarray)
        base = self.metrics[0]

        def one(bstate, i):
            def select(x):
                picked = jnp.take(jnp.asarray(x), i, axis=self.output_dim)  # scalar take drops the axis
                if not self.squeeze_outputs:
                    picked = jnp.expand_dims(picked, self.output_dim)
                return picked

            new_args = apply_to_collection(args, array_types, select)
            new_kwargs = apply_to_collection(kwargs, array_types, select)
            return base.local_update(bstate, *new_args, **new_kwargs)

        return jax.vmap(one)(state, jnp.arange(len(self.metrics)))

    def sync_state(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Dict[str, Any]:
        """Per-output sync: the base reductions apply elementwise over the stack."""
        base = self.metrics[0]
        if any(kind == "cat" for kind in base._reductions.values()):
            raise NotImplementedError(
                "MultioutputWrapper's pure tier cannot sync cat-reduction base states"
                " over a mesh axis; evaluate per shard and combine computes instead"
            )
        return base.sync_state(state, axis_name)

    def compute_from(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Array:
        base = self.metrics[0]
        return jax.vmap(lambda s: jnp.asarray(base.compute_from(s, axis_name)))(state)
