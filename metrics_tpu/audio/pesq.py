"""PerceptualEvaluationSpeechQuality metric (reference: audio/pesq.py:29-140)."""
from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from metrics_tpu.utils.imports import _PESQ_AVAILABLE


class PerceptualEvaluationSpeechQuality(Metric):
    """Mean PESQ MOS-LQO over all seen samples (requires the ``pesq`` package).

    Args:
        fs: sampling rate — 8000 (nb) or 16000 (wb only).
        mode: ``"wb"`` or ``"nb"``.
        n_processes: parallel workers for batched evaluation.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(self, fs: int, mode: str, n_processes: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PerceptualEvaluationSpeechQuality metric requires that `pesq` is installed."
                " Install it with `pip install pesq`."
            )
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes
        self.add_state("sum_pesq", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        pesq_batch = perceptual_evaluation_speech_quality(
            preds, target, self.fs, self.mode, n_processes=self.n_processes
        )
        self.sum_pesq = self.sum_pesq + jnp.sum(pesq_batch)
        self.total = self.total + pesq_batch.size

    def compute(self) -> Array:
        return self.sum_pesq / self.total
