"""SignalDistortionRatio / ScaleInvariantSignalDistortionRatio (reference: audio/sdr.py:29-280)."""
from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio, signal_distortion_ratio


class SignalDistortionRatio(Metric):
    """Mean SDR in dB over all seen samples (optimal-distortion-filter variant).

    Args:
        use_cg_iter: accepted for API parity; the batched Toeplitz solve is used.
        filter_length: length of the allowed distortion filter.
        zero_mean: subtract signal means before computing.
        load_diag: diagonal loading for degenerate references.

    Example:
        >>> import jax
        >>> from metrics_tpu.audio import SignalDistortionRatio
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (8000,))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> sdr = SignalDistortionRatio()
        >>> float(sdr(preds, target)) < 0  # random signals: strongly negative dB
        True
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag
        self.add_state("sum_sdr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sdr_batch = signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )
        self.sum_sdr = self.sum_sdr + jnp.sum(sdr_batch)
        self.total = self.total + sdr_batch.size

    def compute(self) -> Array:
        return self.sum_sdr / self.total


class ScaleInvariantSignalDistortionRatio(Metric):
    """Mean SI-SDR in dB over all seen samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> si_sdr(preds, target)
        Array(18.40..., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_sdr_batch = scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def compute(self) -> Array:
        return self.sum_si_sdr / self.total
