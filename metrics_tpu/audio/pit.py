"""PermutationInvariantTraining metric (reference: audio/pit.py:30-130)."""
from typing import Any, Callable

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.pit import permutation_invariant_training


class PermutationInvariantTraining(Metric):
    """Mean best-permutation metric value for multi-talker separation.

    Args:
        metric_func: pairwise metric ``f(preds[:, i], target[:, j]) -> (batch,)``.
        eval_func: ``"max"`` (higher better) or ``"min"``.
        kwargs: additional args bound to ``metric_func``.

    Example:
        >>> import jax
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.audio import PermutationInvariantTraining
        >>> from metrics_tpu.functional.audio import scale_invariant_signal_distortion_ratio
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (3, 2, 100))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (3, 2, 100))
        >>> pit = PermutationInvariantTraining(scale_invariant_signal_distortion_ratio, 'max')
        >>> bool(jnp.isfinite(pit(preds, target)))
        True
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, metric_func: Callable, eval_func: str = "max", **kwargs: Any) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in (
                "compute_on_cpu",
                "dist_sync_on_step",
                "process_group",
                "sync_axis",
                "dist_sync_fn",
                "distributed_available_fn",
                "sync_on_compute",
                "cat_capacity",
                "fleet_size",
            )
            if k in kwargs
        }
        super().__init__(**base_kwargs)
        if eval_func not in ("max", "min"):
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        self.metric_func = metric_func
        self.eval_func = eval_func
        self.kwargs = kwargs
        self.add_state("sum_pit_metric", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        pit_metric = permutation_invariant_training(preds, target, self.metric_func, self.eval_func, **self.kwargs)[0]
        self.sum_pit_metric = self.sum_pit_metric + jnp.sum(pit_metric)
        self.total = self.total + pit_metric.size

    def compute(self) -> Array:
        return self.sum_pit_metric / self.total
