"""ShortTimeObjectiveIntelligibility metric (reference: audio/stoi.py:29-130)."""
from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility


class ShortTimeObjectiveIntelligibility(Metric):
    """Mean STOI intelligibility score over all seen samples (host-side DSP).

    Args:
        fs: sampling rate in Hz.
        extended: compute extended (language-independent) STOI.

    Example:
        >>> import numpy as np
        >>> from metrics_tpu.audio import ShortTimeObjectiveIntelligibility
        >>> rng = np.random.RandomState(0)
        >>> target = rng.randn(12000)
        >>> preds = target + 0.1 * rng.randn(12000)
        >>> stoi = ShortTimeObjectiveIntelligibility(10000)
        >>> float(stoi(preds, target)) > 0.9
        True
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(fs, int) or fs <= 0:
            raise ValueError(f"Expected argument `fs` to be a positive int, but got {fs}")
        self.fs = fs
        self.extended = extended
        self.add_state("sum_stoi", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        stoi_batch = short_time_objective_intelligibility(preds, target, self.fs, self.extended)
        self.sum_stoi = self.sum_stoi + jnp.sum(stoi_batch)
        self.total = self.total + stoi_batch.size

    def compute(self) -> Array:
        return self.sum_stoi / self.total
