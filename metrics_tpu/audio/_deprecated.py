"""Root-import deprecation shims (reference: audio/_deprecated.py).

v1.0 moved the audio metrics into the subpackage; importing them from the
package root still works through these ``_<Name>`` subclasses but emits the
reference's FutureWarning (utilities/prints.py:59-65). The subpackage path
(``metrics_tpu.audio.<Name>``) stays silent.
"""
from metrics_tpu.audio import PermutationInvariantTraining, ScaleInvariantSignalDistortionRatio, ScaleInvariantSignalNoiseRatio, SignalDistortionRatio, SignalNoiseRatio
from metrics_tpu.utils.prints import _root_class_shim

_PermutationInvariantTraining = _root_class_shim(PermutationInvariantTraining, "PermutationInvariantTraining", "audio", __name__)
_ScaleInvariantSignalDistortionRatio = _root_class_shim(ScaleInvariantSignalDistortionRatio, "ScaleInvariantSignalDistortionRatio", "audio", __name__)
_ScaleInvariantSignalNoiseRatio = _root_class_shim(ScaleInvariantSignalNoiseRatio, "ScaleInvariantSignalNoiseRatio", "audio", __name__)
_SignalDistortionRatio = _root_class_shim(SignalDistortionRatio, "SignalDistortionRatio", "audio", __name__)
_SignalNoiseRatio = _root_class_shim(SignalNoiseRatio, "SignalNoiseRatio", "audio", __name__)

__all__ = ["_PermutationInvariantTraining", "_ScaleInvariantSignalDistortionRatio", "_ScaleInvariantSignalNoiseRatio", "_SignalDistortionRatio", "_SignalNoiseRatio"]
