"""SignalNoiseRatio / ScaleInvariantSignalNoiseRatio (reference: audio/snr.py:27-220)."""
from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.snr import scale_invariant_signal_noise_ratio, signal_noise_ratio


class SignalNoiseRatio(Metric):
    """Mean SNR in dB over all seen samples.

    Args:
        zero_mean: subtract signal means before computing.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.audio import SignalNoiseRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> snr(preds, target)
        Array(16.18..., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean
        self.add_state("sum_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        return self.sum_snr / self.total


class ScaleInvariantSignalNoiseRatio(Metric):
    """Mean SI-SNR in dB over all seen samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.audio import ScaleInvariantSignalNoiseRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> si_snr(preds, target)
        Array(15.09..., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        return self.sum_si_snr / self.total
