"""Audio-domain metrics (reference: src/torchmetrics/audio/__init__.py)."""
from metrics_tpu.audio.pesq import PerceptualEvaluationSpeechQuality
from metrics_tpu.audio.pit import PermutationInvariantTraining
from metrics_tpu.audio.sdr import ScaleInvariantSignalDistortionRatio, SignalDistortionRatio
from metrics_tpu.audio.snr import ScaleInvariantSignalNoiseRatio, SignalNoiseRatio
from metrics_tpu.audio.stoi import ShortTimeObjectiveIntelligibility

__all__ = [
    "PerceptualEvaluationSpeechQuality",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
]
