"""Reference-vs-live histogram drift detection (KL / PSI / total variation)."""
from typing import Any, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.sketch import counts_into_bins
from metrics_tpu.sketches.base import SketchMetric


class HistogramDrift(SketchMetric):
    """Distribution drift between a reference window and the live stream.

    Two fixed-shape bucket histograms over a declared value range: calls with
    ``reference=True`` accumulate the baseline (e.g. the validation window at
    deploy time), default calls accumulate live traffic. ``compute`` reports
    three standard divergences between the two empirical distributions:

    - ``kl``:  KL(live ‖ ref), Jeffreys-smoothed (+0.5 per bin) so empty bins
      cannot produce infinities;
    - ``psi``: population stability index, the symmetrized form
      Σ (p−q)·ln(p/q) on the same smoothed distributions (common alert
      thresholds: 0.1 drifting, 0.25 drifted);
    - ``tv``:  total variation ``0.5·Σ|p−q|`` on the UNsmoothed distributions
      (exact, bounded [0, 1]).

    Binning is linear over ``[low, high)`` with two edge bins catching
    out-of-range mass (±inf included) so drift toward the tails is visible
    rather than dropped; NaNs are ignored. State is ``2·(num_bins+2)`` int32
    counters under ``dist_reduce_fx="sum"`` — psum/:meth:`merge`/ckpt
    re-reduce are all exact histogram addition.

    To slide the live window, snapshot ``compute()`` then call
    :meth:`reset_live` (the reference histogram is kept).

    Args:
        num_bins: interior bin count (plus 2 edge bins).
        low/high: declared value range for the linear binning.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.sketches import HistogramDrift
        >>> hd = HistogramDrift(num_bins=32)
        >>> hd.update(jnp.linspace(0.0, 1.0, 500), reference=True)
        >>> hd.update(jnp.linspace(0.0, 1.0, 500) ** 2)
        >>> out = hd.compute()
        >>> bool(out["tv"] > 0.2)
        True
    """

    higher_is_better: bool = False
    _update_signature_attrs = ("num_bins", "low", "high")

    def __init__(
        self,
        num_bins: int = 64,
        low: float = 0.0,
        high: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_bins, int) or num_bins < 2:
            raise ValueError(f"Argument `num_bins` must be an int >= 2, got {num_bins}")
        if not high > low:
            raise ValueError(f"Argument `high` must exceed `low`, got [{low}, {high})")
        self.num_bins = num_bins
        self.low = float(low)
        self.high = float(high)
        # python-float clamp ceiling, precomputed so the traced bin path does
        # no host conversion on attribute values (tmlint TM-HOSTSYNC)
        self._num_bins_f = float(num_bins)
        self.add_sketch_state("ref_hist", jnp.zeros((num_bins + 2,), jnp.int32), "sum")
        self.add_sketch_state("live_hist", jnp.zeros((num_bins + 2,), jnp.int32), "sum")

    def _bin(self, values: Array) -> Array:
        x = jnp.ravel(jnp.asarray(values)).astype(jnp.float32)
        scale = jnp.float32(self.num_bins / (self.high - self.low))
        # clamp in float space (±inf never reaches the int cast), then shift
        # by 1 so slot 0 / slot num_bins+1 are the under/overflow edge bins
        idx_f = jnp.clip(
            jnp.floor((x - jnp.float32(self.low)) * scale), -1.0, self._num_bins_f
        )
        valid = ~jnp.isnan(x)
        idx = jnp.where(valid, idx_f, -1.0).astype(jnp.int32) + 1
        return counts_into_bins(idx, valid.astype(jnp.int32), self.num_bins + 2)

    def update(self, values: Union[float, Array], reference: bool = False) -> None:
        """Accumulate a batch into the live (default) or reference histogram."""
        hist = self._bin(values)
        if reference:
            self.ref_hist = self.ref_hist + hist
        else:
            self.live_hist = self.live_hist + hist

    def reset_live(self) -> None:
        """Start a fresh live window, keeping the reference histogram."""
        self.live_hist = jnp.zeros_like(self.live_hist)
        self._computed = None

    def compute(self) -> dict:
        """Dict of divergences: ``kl``, ``psi`` (smoothed), ``tv`` (exact)."""
        ref = self.ref_hist.astype(jnp.float32)
        live = self.live_hist.astype(jnp.float32)
        k = jnp.float32(ref.shape[0])
        p = (live + 0.5) / (jnp.sum(live) + 0.5 * k)
        q = (ref + 0.5) / (jnp.sum(ref) + 0.5 * k)
        log_ratio = jnp.log(p) - jnp.log(q)
        p_raw = live / jnp.maximum(jnp.sum(live), 1.0)
        q_raw = ref / jnp.maximum(jnp.sum(ref), 1.0)
        return {
            "kl": jnp.sum(p * log_ratio),
            "psi": jnp.sum((p - q) * log_ratio),
            "tv": 0.5 * jnp.sum(jnp.abs(p_raw - q_raw)),
        }
