"""Mergeable streaming sketch metrics for service telemetry.

Latency percentiles, approximate distinct counts, distribution drift, and
streaming rank-metric bounds as first-class :class:`~metrics_tpu.core.metric.Metric`
subclasses — fixed-shape integer state whose distributed reduction (psum/pmax)
IS the sketch merge. See ``docs/source/pages/sketches.rst`` for the
accuracy/merge/state-size table and when to prefer a sketch over the exact
tier.
"""
from metrics_tpu.sketches.auroc_bound import StreamingAUROCBound
from metrics_tpu.sketches.base import SketchMetric
from metrics_tpu.sketches.distinct import DistinctCount
from metrics_tpu.sketches.drift import HistogramDrift
from metrics_tpu.sketches.quantile import QuantileSketch

__all__ = [
    "DistinctCount",
    "HistogramDrift",
    "QuantileSketch",
    "SketchMetric",
    "StreamingAUROCBound",
]
