"""O(1)-state streaming AUROC/AP with certified error bounds."""
from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import (
    auroc_bounds_from_hists,
    average_precision_bounds_from_hists,
    class_bucket_counts,
    monotone_key_descending,
)
from metrics_tpu.sketches.base import SketchMetric


class StreamingAUROCBound(SketchMetric):
    """Streaming binary AUROC and average precision brackets from two
    fixed-size histograms — no cat buffer, no sort, ever.

    The exact AUROC/AP tier (ops/rank.py, ROADMAP item 3) must materialize
    and sort the full prediction stream; at service scale that is a 2^24-row
    buffer re-sorted per compute and checkpointed in full. This metric lifts
    the same module's one-shot bucket machinery (``class_bucket_counts`` over
    the order-preserving key bijection, ``bucketed_auroc_bounds``'s histogram
    form) into an accumulating Metric: state is one positive and one negative
    histogram over the top ``bits`` key bits — ``2·2^bits`` int32, 32 KB at
    the default ``bits=12`` — and ``compute`` returns CERTIFIED brackets:

    - the exact AUROC lies in ``[auroc_lower, auroc_upper]`` (bracket width =
      same-bucket opposite-class pair mass, the pairs the histogram cannot
      order; exact ties score 1/2 so the midpoint is exact whenever no bucket
      mixes distinct scores, e.g. any ≤ 2^bits-value quantized score domain);
    - the exact AP lies in ``[ap_lower, ap_upper]`` (closed-form best/worst
      within-bucket arrangements via stable ψ-difference sums —
      ``average_precision_bounds_from_hists``).

    ``dist_reduce_fx="sum"``: psum/:meth:`merge`/ckpt N→M re-reduce are exact
    histogram addition, so the brackets computed from merged shards equal the
    single-stream brackets bit-identically.

    Inputs follow the binary convention: ``preds`` float scores, ``target``
    1 for positive, anything else negative. Scores must be NaN-free (the
    rank-engine contract).

    Since round 10 this certificate also backs the tolerance-routed dispatch
    tier: ``BinaryAUROC(tolerance=...)`` / ``binary_auroc_exact(...,
    tolerance=...)`` (and the AP twins, plus ``CollectionSpec(...,
    tolerance=...)`` at the serving layer) accumulate the same two histograms
    and serve the bracket midpoint when the certified width fits the
    tolerance — see classification/precision_recall_curve.py and
    ops/clf_curve.py:_sketch_dispatch. Reach for this class directly when you
    want the bracket itself (both endpoints), a dict of AUROC *and* AP from
    one state, or the sketch-family merge/ckpt surface.

    Args:
        bits: histogram resolution (``2^bits`` buckets over the key space);
            +1 bit halves the expected bracket width for continuous scores.
            Resolution is per-BINADE — the top key bits are sign+exponent, so
            each power-of-two score interval gets ``2^(bits-9)`` buckets.
            Scores concentrated in one binade (e.g. uniform [0.5, 1) mass, or
            saturated sigmoids) see bracket widths around ``2^-(bits-9)``
            rather than ``2^-bits``; spread-spectrum scores (logits spanning
            octaves) get the full resolution. The certificate is unaffected —
            the bracket always contains the exact value, it is just wider
            where the score distribution defeats the bucketing.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.sketches import StreamingAUROCBound
        >>> m = StreamingAUROCBound(bits=12)
        >>> preds = jnp.linspace(0.0, 1.0, 1000)
        >>> m.update(preds, (preds > 0.7).astype(jnp.int32))
        >>> out = m.compute()
        >>> bool(out["auroc_lower"] <= 1.0 <= out["auroc_upper"] + 1e-6)
        True
    """

    higher_is_better: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    _update_signature_attrs = ("bits",)

    def __init__(self, bits: int = 12, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(bits, int) or not 4 <= bits <= 14:
            raise ValueError(f"Argument `bits` must be an int in [4, 14], got {bits}")
        self.bits = bits
        nb = 1 << bits
        self.add_sketch_state("pos_hist", jnp.zeros((nb,), jnp.int32), "sum")
        self.add_sketch_state("neg_hist", jnp.zeros((nb,), jnp.int32), "sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate a batch of (score, binary label) pairs."""
        preds = jnp.ravel(jnp.asarray(preds))
        target = jnp.ravel(jnp.asarray(target))
        keys = monotone_key_descending(preds)
        valid = jnp.ones(keys.shape, bool)
        pos, neg = class_bucket_counts(keys, target == 1, valid, self.bits)
        self.pos_hist = self.pos_hist + pos
        self.neg_hist = self.neg_hist + neg

    def compute(self) -> dict:
        """Certified brackets: ``auroc_lower/auroc_mid/auroc_upper`` and
        ``ap_lower/ap_mid/ap_upper`` (all 0 when either class is absent)."""
        au_lo, au_hi = auroc_bounds_from_hists(self.pos_hist, self.neg_hist)
        ap_lo, ap_hi = average_precision_bounds_from_hists(self.pos_hist, self.neg_hist)
        return {
            "auroc_lower": au_lo,
            "auroc_mid": 0.5 * (au_lo + au_hi),
            "auroc_upper": au_hi,
            "ap_lower": ap_lo,
            "ap_mid": 0.5 * (ap_lo + ap_hi),
            "ap_upper": ap_hi,
        }
