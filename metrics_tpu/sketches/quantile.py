"""DDSketch-style relative-error quantile sketch (Masson et al., VLDB 2019)."""
import math
from typing import Any, Sequence, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.sketch import (
    bucket_midpoints,
    counts_into_bins,
    log_bucket_index,
    quantile_gamma,
)
from metrics_tpu.sketches.base import SketchMetric

#: edge_counts slot layout (see :meth:`QuantileSketch.update`)
_NEG_OVER, _NEG_UNDER, _ZERO, _POS_UNDER, _POS_OVER = range(5)


class QuantileSketch(SketchMetric):
    """Streaming quantiles with a per-value relative-error certificate.

    Log-γ bucketed counts à la DDSketch: magnitudes fall into ``2^bits``
    geometric buckets per sign (bucket ``i`` covers
    ``[min_value·γ^i, min_value·γ^(i+1))`` with ``γ = (1+α)/(1-α)``), plus
    five edge bins (±overflow, ±underflow, exact zeros). Any quantile whose
    rank lands in a regular bucket — or on an exact zero — is certified to
    within relative error ``α = relative_error``; ranks landing in an edge
    bin are still estimated but flagged uncertified.

    State is ``2·2^bits + 5`` int32 counters (16.4 KB at the default
    ``bits=11``), ``dist_reduce_fx="sum"`` throughout — so ``psum`` over a
    mesh axis, :meth:`merge`, and the ckpt N→M re-reduce are all the same
    exact histogram addition; merge-then-compute equals compute-on-concat
    bit-identically at the state level when the shards ran the same update
    program. (Bucket *assignment* is deterministic per compiled executable:
    two different compilations of ``log`` — eager vs jit, or different batch
    shapes — can place a value within 1 ulp of a bucket boundary in the
    adjacent bucket. Both placements satisfy the certificate; the psum/merge
    itself is always exact. Verified: mesh-psum state is bit-identical to
    per-shard same-program ingestion.)

    NaN inputs are excluded from the ranks (``nanquantile`` semantics) and
    tallied in the ``nan_count`` state.

    Args:
        relative_error: certified relative accuracy α of returned quantile
            values (default 1%).
        bits: log2 bucket count per sign; with ``relative_error`` fixes the
            trackable magnitude range ``[min_value, min_value·γ^(2^bits))``.
        min_value: smallest certifiable nonzero magnitude; smaller values
            count as (uncertified) underflow.
        quantiles: the quantile levels ``compute`` reports.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.sketches import QuantileSketch
        >>> sk = QuantileSketch(relative_error=0.01)
        >>> sk.update(jnp.arange(1.0, 1001.0))
        >>> out = sk.compute()
        >>> bool(jnp.abs(out["quantiles"][0] - 500.0) / 500.0 <= 0.01)
        True
        >>> bool(out["certified"].all())
        True
    """

    higher_is_better = None
    _update_signature_attrs = ("relative_error", "bits", "min_value")

    def __init__(
        self,
        relative_error: float = 0.01,
        bits: int = 11,
        min_value: float = 1e-9,
        quantiles: Sequence[float] = (0.5, 0.9, 0.99),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(bits, int) or not 4 <= bits <= 16:
            raise ValueError(f"Argument `bits` must be an int in [4, 16], got {bits}")
        if not min_value > 0.0:
            raise ValueError(f"Argument `min_value` must be positive, got {min_value}")
        qs = tuple(float(q) for q in quantiles)
        if not qs or not all(0.0 <= q <= 1.0 for q in qs):
            raise ValueError(f"Argument `quantiles` must be levels in [0, 1], got {quantiles}")
        self.relative_error = float(relative_error)
        self.bits = bits
        self.min_value = float(min_value)
        self.quantiles = qs
        self._gamma = quantile_gamma(self.relative_error)
        self._log_gamma = math.log(self._gamma)
        nb = 1 << bits
        self.add_sketch_state("pos_buckets", jnp.zeros((nb,), jnp.int32), "sum")
        self.add_sketch_state("neg_buckets", jnp.zeros((nb,), jnp.int32), "sum")
        self.add_sketch_state("edge_counts", jnp.zeros((5,), jnp.int32), "sum")
        self.add_sketch_state("nan_count", jnp.zeros((), jnp.int32), "sum")

    @property
    def max_value(self) -> float:
        """Largest certifiable magnitude, ``min_value · γ^(2^bits)``."""
        return self.min_value * math.exp(self._log_gamma * (1 << self.bits))

    def update(self, values: Union[float, Array]) -> None:
        """Bucket a batch of values (any shape; flattened)."""
        x = jnp.ravel(jnp.asarray(values)).astype(jnp.float32)
        nb = 1 << self.bits
        mag = jnp.abs(x)
        nan = jnp.isnan(x)
        idx = log_bucket_index(mag, self._log_gamma, self.min_value, nb)
        pos = (x > 0) & ~nan
        neg = (x < 0) & ~nan
        in_range = (idx >= 0) & (idx < nb)
        self.pos_buckets = self.pos_buckets + counts_into_bins(
            idx, (pos & in_range).astype(jnp.int32), nb
        )
        self.neg_buckets = self.neg_buckets + counts_into_bins(
            idx, (neg & in_range).astype(jnp.int32), nb
        )
        over, under = idx >= nb, idx < 0
        edges = jnp.stack(
            [
                jnp.sum(neg & over, dtype=jnp.int32),
                jnp.sum(neg & under, dtype=jnp.int32),
                jnp.sum(x == 0, dtype=jnp.int32),
                jnp.sum(pos & under, dtype=jnp.int32),
                jnp.sum(pos & over, dtype=jnp.int32),
            ]
        )
        self.edge_counts = self.edge_counts + edges
        self.nan_count = self.nan_count + jnp.sum(nan, dtype=jnp.int32)

    def compute(self) -> dict:
        """Quantile estimates with their certificate.

        Returns a dict: ``quantiles`` (f32, one per requested level, NaN when
        no values were seen), ``certified`` (bool per level: the rank landed
        in a regular bucket or on an exact zero, so the value is within
        ``relative_error``), ``relative_error`` (the declared α).
        """
        nb = 1 << self.bits
        est = bucket_midpoints(nb, self._log_gamma, self.min_value)
        edge = self.edge_counts
        # merged ascending-value ordering: most-negative first
        counts = jnp.concatenate(
            [
                edge[_NEG_OVER][None],
                jnp.flip(self.neg_buckets),
                edge[_NEG_UNDER][None],
                edge[_ZERO][None],
                edge[_POS_UNDER][None],
                self.pos_buckets,
                edge[_POS_OVER][None],
            ]
        )
        half_min = jnp.float32(0.5 * self.min_value)
        values = jnp.concatenate(
            [
                jnp.float32(-self.max_value)[None],
                -jnp.flip(est),
                -half_min[None],
                jnp.zeros((1,), jnp.float32),
                half_min[None],
                est,
                jnp.float32(self.max_value)[None],
            ]
        )
        certified = jnp.concatenate(
            [
                jnp.zeros((1,), bool),
                jnp.ones((nb,), bool),
                jnp.zeros((1,), bool),
                jnp.ones((1,), bool),  # exact zeros: relative error 0
                jnp.zeros((1,), bool),
                jnp.ones((nb,), bool),
                jnp.zeros((1,), bool),
            ]
        )
        total = jnp.sum(counts)
        q = jnp.asarray(self.quantiles, jnp.float32)
        ranks = jnp.floor(q * jnp.maximum(total.astype(jnp.float32) - 1.0, 0.0))
        slot = jnp.searchsorted(jnp.cumsum(counts).astype(jnp.float32), ranks, side="right")
        slot = jnp.clip(slot, 0, counts.shape[0] - 1)
        nonempty = total > 0
        return {
            "quantiles": jnp.where(nonempty, values[slot], jnp.nan),
            "certified": certified[slot] & nonempty,
            "relative_error": jnp.float32(self.relative_error),
        }
