"""HyperLogLog approximate distinct counting (Flajolet et al., 2007)."""
from typing import Any, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.sketch import hash_u32, hll_estimate, hll_index_rank
from metrics_tpu.sketches.base import SketchMetric


class DistinctCount(SketchMetric):
    """Approximate number of distinct values seen, in ``2^p`` bytes of state.

    HyperLogLog: each value hashes to a u32 (ops/sketch.py's murmur3-finalizer
    bijection); the top ``p`` bits pick one of ``m = 2^p`` u8 registers, which
    keeps a running max of the rank (leading-zero count + 1) of the remaining
    bits. The estimate's standard error is ``1.04/sqrt(m)`` (~1.6% at the
    default ``p=12``), with the standard linear-counting (small-range) and
    32-bit-saturation (large-range) corrections applied in ``compute``.

    ``dist_reduce_fx="max"`` — the elementwise register max IS the HLL merge,
    so ``pmax`` over a mesh axis, :meth:`merge`, and the ckpt N→M ``max``
    re-reduce all commute bit-identically with single-stream ingestion:
    merge-then-compute equals compute-on-concat exactly, in any order.

    Values may be any integer, bool, or float array; floats are hashed by
    their f32 bit pattern (−0.0 folded into +0.0), so bf16/f16 inputs — which
    widen exactly — count the same distinct set as their f32 ingestion.

    Args:
        p: register-count exponent (``m = 2^p`` u8 registers, ``4 <= p <= 16``).
        seed: hash seed; two sketches must share it to be mergeable.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.sketches import DistinctCount
        >>> dc = DistinctCount(p=12)
        >>> dc.update(jnp.arange(5000) % 1000)
        >>> bool(jnp.abs(dc.compute() - 1000.0) / 1000.0 < 0.05)
        True
    """

    higher_is_better = None
    _update_signature_attrs = ("p", "seed")

    def __init__(self, p: int = 12, seed: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or not 4 <= p <= 16:
            raise ValueError(f"Argument `p` must be an int in [4, 16], got {p}")
        self.p = p
        self.seed = int(seed)
        self.add_sketch_state("registers", jnp.zeros((1 << p,), jnp.uint8), "max")

    def update(self, values: Union[int, float, Array]) -> None:
        """Hash a batch of values (any shape; flattened) into the registers."""
        h = hash_u32(jnp.ravel(jnp.asarray(values)), self.seed)
        idx, rank = hll_index_rank(h, self.p)
        self.registers = self.registers.at[idx].max(rank)

    def compute(self) -> Array:
        """Bias-corrected cardinality estimate (f32 scalar; 0 when empty)."""
        return hll_estimate(self.registers)
