"""Shared base for mergeable streaming sketch metrics.

A sketch here is a :class:`~metrics_tpu.core.metric.Metric` whose entire
registered state is a small set of FIXED-SHAPE INTEGER arrays under a
``sum``/``max`` reduction. That single structural invariant buys every
property the rest of the stack contracts on, for free:

- **mesh merge is the collective itself**: ``psum`` (sum states) / ``pmax``
  (max states) over an axis IS the sketch merge — no gather, no host round
  trip, O(state) bytes on the ICI;
- **ckpt-safe**: fixed shapes round-trip bit-identically through the raw-bytes
  serializer, and the N→M topology re-reduce (ckpt/restore.py's sum/max merge
  matrix) is exactly the sketch merge, so host-count changes preserve the
  estimate;
- **fusable**: static-shape integer pytrees chain into the donation-backed
  ``MetricCollection(fused=True)`` engine like any other dense state;
- **bf16/f32-safe** under tmsan's TMS-UPCAST rule trivially — integer state
  cannot be silently promoted by a float cast, and float INPUTS may arrive in
  any width that widens exactly to f32;
- **fleet-ready** (ROADMAP item 1): a leading fleet axis over a fixed-shape
  integer state vmaps without reshaping or re-bucketing.

:meth:`SketchMetric.add_sketch_state` enforces the invariant at registration
time; :meth:`SketchMetric.merge` is the eager pairwise merge (delegating to
``Metric.merge_state``, the core hook that applies each state's registered
reduction algebra) used by multi-stream aggregation and the property tests'
merge-associativity sweeps.
"""
from typing import Any, Dict, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsUserError

#: the reductions whose pairwise merge is the distributed collective
_MERGEABLE_REDUCTIONS = ("sum", "max", "min")


class SketchMetric(Metric):
    """Base class for mergeable streaming sketches (quantiles, distinct
    counts, drift, streaming rank bounds).

    Subclasses register state exclusively through :meth:`add_sketch_state` and
    implement ``update``/``compute`` with pure jnp ops; everything else
    (pure-functional tier, sync, ckpt, fusion) is inherited.
    """

    is_differentiable: bool = False
    higher_is_better = None
    full_state_update: bool = False

    def add_sketch_state(self, name: str, default: Array, dist_reduce_fx: str) -> None:
        """Register a sketch state, enforcing the family invariant: a
        fixed-shape integer array under a mergeable reduction."""
        if dist_reduce_fx not in _MERGEABLE_REDUCTIONS:
            raise MetricsUserError(
                f"Sketch state `{name}` must use a mergeable reduction"
                f" {_MERGEABLE_REDUCTIONS}, got {dist_reduce_fx!r}"
            )
        default = jnp.asarray(default)
        if not jnp.issubdtype(default.dtype, jnp.integer):
            raise MetricsUserError(
                f"Sketch state `{name}` must be an integer array (got {default.dtype}):"
                " integer state is what makes the merge exact and TMS-UPCAST-safe"
            )
        self.add_state(name, default, dist_reduce_fx=dist_reduce_fx)

    def merge(self, other: Union["SketchMetric", Dict[str, Any]]) -> None:
        """Merge another sketch of the same type into this one, in place.

        ``a.merge(b); a.compute()`` equals computing over the concatenated
        input streams — bit-identically for pure count/register states (HLL,
        histograms), within the declared certificate for quantile sketches.
        Associative and commutative, so any merge tree over any shard order
        yields the same state.
        """
        if isinstance(other, Metric) and type(other) is not type(self):
            raise MetricsUserError(
                f"Cannot merge {type(other).__name__} into {type(self).__name__}:"
                " sketch merges are only defined between instances of the same class"
            )
        self.merge_state(other)

    def state_bytes(self) -> int:
        """Total bytes of registered sketch state — the per-stream memory cost
        quoted in the docs table (and the per-save ckpt payload floor)."""
        total = 0
        for name in self._defaults:
            value = getattr(self, name)
            total += int(jnp.asarray(value).size * jnp.asarray(value).dtype.itemsize)
        return total
