"""tmserve: a deployable metrics service front end (``python -m metrics_tpu.serve``).

Sixteen tiers of this repo build the pieces of a metrics *service* — fused
one-launch updates, fleet routing, the :class:`~metrics_tpu.serve.ingest.IngestQueue`
staging ring, excache prewarm, atomic checkpoints, prom/SLO/flow observability,
the tmfault degradation ladder — but a user still had to hand-wire them.
:class:`MetricsServer` is the composition layer: one process object that wires
N named collections, each described declaratively (metric classes + kwargs,
``fleet_size``, checkpoint directory, SLO budget, drift canary), behind a
three-verb request API::

    server = MetricsServer(load_config("serve.json"))
    server.enqueue("quality", preds, target, stream_ids=ids)   # host append
    server.compute("quality")                                   # flush + read
    server.reduce_fleet("quality")                              # cross-stream
    server.drain(); server.stop()

Design points, each load-bearing:

**One ticker, deficit round-robin.** Every collection gets its own bounded
``IngestQueue`` (isolation: one tenant's backlog cannot evict another's rows)
but all queues share ONE tick thread and therefore one tick budget. The
ticker runs classic deficit round-robin: each round every queue accrues
``quantum`` entries of credit and :meth:`IngestQueue.tick` applies at most its
accumulated deficit; credit carries over only while a queue stays backlogged
(reset on empty), so an idle queue cannot bank unbounded credit and a
saturated queue cannot starve its neighbours — every queue drains at least
``quantum`` entries per round regardless of any other queue's depth.

**Adaptive tick interval.** :class:`AdaptiveTickController` tracks the
observed p99 enqueue→applied ingest latency against the configured SLO budget
and adjusts the shared ``tick_interval_s`` multiplicatively — shrink fast
(AIMD-style halving) when latency crosses the high-water fraction of the
budget or backlog accumulates, grow slowly when comfortably under it. The
controller is a pure deterministic object (no clocks, no threads) so its
convergence is unit-testable on a synthetic stepped arrival trace.

**Drift canary.** Each collection may attach a
:class:`~metrics_tpu.sketches.HistogramDrift` watch: the enqueue path samples
1-in-N batches into a small bounded deque (cheap, host-side, drop-oldest), the
control loop absorbs them — the first rows build the reference window, the
rest the live window — and every evaluation compares live vs reference PSI
against the spec's threshold, dispatching the same warn / raise / callable
action ladder the SLO machinery uses. A canary deploy that shifts the input
distribution alerts *from inside the metrics service*, before the aggregate
metric has moved far enough to notice.

**Lifecycle state machine.** ``starting → ready → draining → stopped``.
Startup is *restore → prewarm → ready*: the prom ``/healthz`` endpoint is live
(answering ``503 starting``) before the first checkpoint restore begins, each
collection restores its latest committed step, then replays its warm manifest
through :func:`metrics_tpu.serve.excache.prewarm` so the first request
triggers zero compiles. Shutdown is *drain → ckpt flush + warm-manifest write
→ stop*: admissions are rejected (typed :class:`ServerStateError`), every
queue applies its backlog exactly once, and every collection checkpoints
atomically — ``save_checkpoint`` writes the warm manifest alongside while
recording is on. A rolling restart is therefore one code path, and the
``server.drain`` fault site (fired before anything is flushed) lets the chaos
sweep prove a killed drain never loses a committed row.

Thread model (see ``metrics_tpu/analysis/race``): the ticker thread is named
``tm-serve/ticker`` (role ``tm-serve``); it owns the deficit table, the
adaptive controller, and the drift windows. The request path (role ``user``)
owns admission counters and the drift sample deque (append-only, atomic).
State transitions are plain attribute stores (atomic); the only lock guards
the transition check-and-set itself and is never held across a blocking call.
"""
import atexit
import json
import os
import threading
import time
import warnings
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from metrics_tpu.fault import inject as _fault
from metrics_tpu.obs import flight as _obs_flight
from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _obs
from metrics_tpu.serve import excache as _excache
from metrics_tpu.serve.ingest import IngestQueue
from metrics_tpu.utils.concurrency import thread_role

__all__ = [
    "AdaptiveTickController",
    "CollectionSpec",
    "DriftAlert",
    "DriftAlertError",
    "DriftSpec",
    "MetricsServer",
    "ServerConfig",
    "ServerConfigError",
    "ServerStateError",
    "active_servers",
    "load_config",
]

#: live servers, pulled by ``obs.prom.render`` for the tm_server_* families
_SERVERS: "weakref.WeakSet[MetricsServer]" = weakref.WeakSet()

_STATES = ("starting", "ready", "draining", "stopped")

#: queue keyword arguments a collection spec may override
_QUEUE_KEYS = ("capacity", "backpressure", "block_timeout_s", "max_staleness_s", "max_coalesce")


class ServerConfigError(ValueError):
    """A declarative server config is malformed: unknown metric class,
    duplicate collection name, bad option value. Raised at build time, never
    mid-serve."""


class ServerStateError(RuntimeError):
    """A request arrived in a lifecycle state that cannot honour it (enqueue
    while draining, compute after stop). Typed so a load balancer shim can
    distinguish 'retry elsewhere' from a real failure."""


class DriftAlert(RuntimeWarning):
    """The live input window of a collection drifted past its PSI threshold."""


class DriftAlertError(RuntimeError):
    """``action='raise'`` form of :class:`DriftAlert`."""


# --------------------------------------------------------------------- config


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ServerConfigError(msg)


class DriftSpec:
    """Drift-canary configuration for one collection.

    The watch histograms a deterministic 1-in-``sample_every`` sample of the
    first float array of each enqueued batch: the first
    ``reference_rows`` sampled rows freeze the reference window, subsequent
    rows accumulate into the live window, and once ``min_live_rows`` have
    arrived each control-loop evaluation compares the two (PSI; see
    ``sketches/drift.py`` for the 0.1/0.25 industry thresholds) and slides the
    live window. ``action`` follows the SLO ladder: ``"warn"`` emits
    :class:`DriftAlert`, ``"raise"`` raises :class:`DriftAlertError` (stashed
    by the ticker, re-raised at the next request), a callable receives the
    alert payload dict.
    """

    def __init__(
        self,
        *,
        num_bins: int = 32,
        low: float = 0.0,
        high: float = 1.0,
        max_psi: float = 0.25,
        sample_every: int = 1,
        reference_rows: int = 256,
        min_live_rows: int = 64,
        action: Union[str, Callable[[Dict[str, Any]], None]] = "warn",
    ) -> None:
        _require(int(num_bins) >= 2, f"drift num_bins must be >= 2, got {num_bins}")
        _require(float(high) > float(low), f"drift needs high > low, got [{low}, {high}]")
        _require(float(max_psi) > 0.0, f"drift max_psi must be > 0, got {max_psi}")
        _require(int(sample_every) >= 1, f"drift sample_every must be >= 1, got {sample_every}")
        _require(int(reference_rows) >= 1, "drift reference_rows must be >= 1")
        _require(int(min_live_rows) >= 1, "drift min_live_rows must be >= 1")
        if isinstance(action, str):
            _require(action in ("warn", "raise"), f"drift action must be 'warn', 'raise' or a callable, got {action!r}")
        else:
            _require(callable(action), "drift action must be 'warn', 'raise' or a callable")
        self.num_bins = int(num_bins)
        self.low = float(low)
        self.high = float(high)
        self.max_psi = float(max_psi)
        self.sample_every = int(sample_every)
        self.reference_rows = int(reference_rows)
        self.min_live_rows = int(min_live_rows)
        self.action = action

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DriftSpec":
        _require(isinstance(d, dict), f"drift spec must be a mapping, got {type(d).__name__}")
        return cls(**d)


class CollectionSpec:
    """Declarative description of one served collection.

    ``metrics`` maps result label → ``{"class": <name in metrics_tpu>,
    "kwargs": {...}}`` (a bare string is shorthand for the class name alone).
    A spec-level ``fleet_size`` is injected into every member's kwargs so the
    whole collection shares the fleet axis. A spec-level ``tolerance`` (plus
    optional ``tolerance_bits``) is injected into every *sketch-computable*
    member (AUROC / AveragePrecision with ``thresholds=None``) — those members
    then serve certified-bracket midpoints from O(1) histogram state instead
    of cat-buffer + sort (ops/rank.py sketch tier); other members are left
    untouched, and per-metric kwargs still win. ``queue`` overrides
    IngestQueue knobs (capacity, backpressure, max_coalesce, ...);
    ``ckpt_dir`` enables restore-on-start and checkpoint-on-drain;
    ``slo_p99_ingest_ms`` arms the per-collection latency budget the control
    loop checks; ``drift`` attaches a canary watch.
    """

    def __init__(
        self,
        name: str,
        metrics: Dict[str, Any],
        *,
        fused: bool = True,
        fleet_size: Optional[int] = None,
        tolerance: Optional[float] = None,
        tolerance_bits: Optional[int] = None,
        ckpt_dir: Optional[str] = None,
        queue: Optional[Dict[str, Any]] = None,
        slo_p99_ingest_ms: Optional[float] = None,
        drift: Optional[Union[DriftSpec, Dict[str, Any]]] = None,
    ) -> None:
        _require(bool(name) and isinstance(name, str), f"collection name must be a non-empty string, got {name!r}")
        _require(isinstance(metrics, dict) and bool(metrics), f"collection {name!r} needs a non-empty metrics mapping")
        self.name = name
        self.fused = bool(fused)
        self.fleet_size = None if fleet_size is None else int(fleet_size)
        if self.fleet_size is not None:
            _require(self.fleet_size >= 1, f"collection {name!r}: fleet_size must be >= 1")
        self.tolerance = None if tolerance is None else float(tolerance)
        if self.tolerance is not None:
            _require(self.tolerance >= 0, f"collection {name!r}: tolerance must be >= 0")
        self.tolerance_bits = None if tolerance_bits is None else int(tolerance_bits)
        if self.tolerance_bits is not None:
            _require(
                4 <= self.tolerance_bits <= 14,
                f"collection {name!r}: tolerance_bits must be an int in [4, 14]",
            )
            _require(
                self.tolerance is not None,
                f"collection {name!r}: tolerance_bits without tolerance has no effect",
            )
        self.ckpt_dir = ckpt_dir
        self.queue = dict(queue or {})
        for key in self.queue:
            _require(key in _QUEUE_KEYS, f"collection {name!r}: unknown queue option {key!r}; valid: {_QUEUE_KEYS}")
        self.slo_p99_ingest_ms = None if slo_p99_ingest_ms is None else float(slo_p99_ingest_ms)
        if self.slo_p99_ingest_ms is not None:
            _require(self.slo_p99_ingest_ms > 0, f"collection {name!r}: slo_p99_ingest_ms must be > 0")
        if isinstance(drift, dict):
            drift = DriftSpec.from_dict(drift)
        self.drift = drift
        self.metrics: Dict[str, Tuple[type, Dict[str, Any]]] = {}
        # resolve classes lazily through the root namespace: every public
        # metric is re-exported there, and importing it here (not at module
        # top) avoids the metrics_tpu -> serve -> metrics_tpu cycle
        import metrics_tpu as _mt

        for label, md in metrics.items():
            if isinstance(md, str):
                md = {"class": md}
            _require(isinstance(md, dict), f"collection {name!r}: metric {label!r} spec must be a mapping or class name")
            cls_name = md.get("class")
            klass = getattr(_mt, cls_name, None) if isinstance(cls_name, str) else None
            _require(
                isinstance(klass, type),
                f"collection {name!r}: unknown metric class {cls_name!r} for {label!r}"
                " (must name a class exported from metrics_tpu)",
            )
            kwargs = dict(md.get("kwargs") or {})
            if self.fleet_size is not None:
                kwargs.setdefault("fleet_size", self.fleet_size)
            if (
                self.tolerance is not None
                and getattr(klass, "_sketch_computable", False)
                and kwargs.get("thresholds") is None
            ):
                kwargs.setdefault("tolerance", self.tolerance)
                if self.tolerance_bits is not None:
                    kwargs.setdefault("tolerance_bits", self.tolerance_bits)
            self.metrics[label] = (klass, kwargs)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CollectionSpec":
        _require(isinstance(d, dict), f"collection spec must be a mapping, got {type(d).__name__}")
        d = dict(d)
        name = d.pop("name", None)
        metrics = d.pop("metrics", None)
        return cls(name, metrics, **d)

    def build(self) -> Any:
        """Instantiate the spec as a :class:`MetricCollection` (always a
        collection, even for one member — uniform compute()/ckpt surface)."""
        from metrics_tpu.core.collections import MetricCollection

        try:
            members = {label: klass(**kwargs) for label, (klass, kwargs) in self.metrics.items()}
            return MetricCollection(members, fused=self.fused)
        except ServerConfigError:
            raise
        except Exception as err:
            raise ServerConfigError(f"collection {self.name!r} failed to build: {err}") from err


class ServerConfig:
    """Top-level declarative config: the collections plus the shared ticker,
    checkpoint, prom, and executable-cache knobs. ``from_dict`` accepts the
    JSON shape ``python -m metrics_tpu.serve --config`` loads::

        {"name": "eval",
         "collections": [{"name": "quality",
                          "metrics": {"mse": "MeanSquaredError"},
                          "fleet_size": 4,
                          "ckpt_dir": "/ckpts/quality",
                          "slo_p99_ingest_ms": 50.0,
                          "drift": {"max_psi": 0.25}}],
         "ticker": {"tick_interval_s": 0.005, "adaptive": true, "quantum": 8},
         "prom": {"port": 0},
         "excache": {"persistent_dir": "/cache/xla", "record": true}}
    """

    def __init__(
        self,
        collections: List[Union[CollectionSpec, Dict[str, Any]]],
        *,
        name: str = "metrics-server",
        tick_interval_s: float = 0.005,
        adaptive: bool = True,
        min_tick_interval_s: float = 0.0005,
        max_tick_interval_s: float = 0.25,
        quantum: int = 8,
        control_every_s: float = 0.25,
        retain: Optional[int] = 3,
        prom_port: Optional[int] = None,
        prom_host: str = "127.0.0.1",
        persistent_cache_dir: Optional[str] = None,
        record_manifest: bool = True,
        slo_action: Union[str, Callable[[List[Dict[str, Any]]], None]] = "warn",
    ) -> None:
        _require(bool(collections), "config needs at least one collection")
        self.collections = [c if isinstance(c, CollectionSpec) else CollectionSpec.from_dict(c) for c in collections]
        names = [c.name for c in self.collections]
        _require(len(set(names)) == len(names), f"duplicate collection names in config: {names}")
        _require(float(tick_interval_s) > 0, f"tick_interval_s must be > 0, got {tick_interval_s}")
        _require(0 < float(min_tick_interval_s) <= float(max_tick_interval_s), "need 0 < min_tick_interval_s <= max_tick_interval_s")
        _require(int(quantum) >= 1, f"quantum must be >= 1, got {quantum}")
        _require(float(control_every_s) > 0, f"control_every_s must be > 0, got {control_every_s}")
        if isinstance(slo_action, str):
            _require(slo_action in ("warn", "raise"), f"slo_action must be 'warn', 'raise' or a callable, got {slo_action!r}")
        self.name = str(name)
        self.tick_interval_s = float(tick_interval_s)
        self.adaptive = bool(adaptive)
        self.min_tick_interval_s = float(min_tick_interval_s)
        self.max_tick_interval_s = float(max_tick_interval_s)
        self.quantum = int(quantum)
        self.control_every_s = float(control_every_s)
        self.retain = retain
        self.prom_port = prom_port
        self.prom_host = prom_host
        self.persistent_cache_dir = persistent_cache_dir
        self.record_manifest = bool(record_manifest)
        self.slo_action = slo_action

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServerConfig":
        _require(isinstance(d, dict), f"server config must be a mapping, got {type(d).__name__}")
        d = dict(d)
        collections = d.pop("collections", None)
        _require(isinstance(collections, list), "server config needs a 'collections' list")
        kwargs: Dict[str, Any] = {}
        for key in ("name", "retain", "slo_action"):
            if key in d:
                kwargs[key] = d.pop(key)
        ticker = d.pop("ticker", {})
        _require(isinstance(ticker, dict), "'ticker' must be a mapping")
        for key in ("tick_interval_s", "adaptive", "min_tick_interval_s", "max_tick_interval_s", "quantum", "control_every_s"):
            if key in ticker:
                kwargs[key] = ticker.pop(key)
        _require(not ticker, f"unknown ticker options: {sorted(ticker)}")
        prom = d.pop("prom", {})
        _require(isinstance(prom, dict), "'prom' must be a mapping")
        if "port" in prom:
            kwargs["prom_port"] = prom.pop("port")
        if "host" in prom:
            kwargs["prom_host"] = prom.pop("host")
        _require(not prom, f"unknown prom options: {sorted(prom)}")
        cache = d.pop("excache", {})
        _require(isinstance(cache, dict), "'excache' must be a mapping")
        if "persistent_dir" in cache:
            kwargs["persistent_cache_dir"] = cache.pop("persistent_dir")
        if "record" in cache:
            kwargs["record_manifest"] = cache.pop("record")
        _require(not cache, f"unknown excache options: {sorted(cache)}")
        _require(not d, f"unknown server config keys: {sorted(d)}")
        return cls(collections, **kwargs)


def load_config(source: Union[str, Dict[str, Any], ServerConfig]) -> ServerConfig:
    """Build a :class:`ServerConfig` from a JSON file path, a dict, or an
    already-built config (identity)."""
    if isinstance(source, ServerConfig):
        return source
    if isinstance(source, dict):
        return ServerConfig.from_dict(source)
    _require(isinstance(source, str), f"config source must be a path, dict or ServerConfig, got {type(source).__name__}")
    try:
        with open(source, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except OSError as err:
        raise ServerConfigError(f"cannot read config {source!r}: {err}") from err
    except ValueError as err:
        raise ServerConfigError(f"config {source!r} is not valid JSON: {err}") from err
    return ServerConfig.from_dict(payload)


# ----------------------------------------------------------------- controller


class AdaptiveTickController:
    """Deterministic multiplicative controller for the shared tick interval.

    The tick interval is the dominant term of enqueue→applied latency at low
    load (an entry waits up to one interval before its tick) and pure
    overhead at saturation (ticks fire back-to-back anyway). The controller
    holds the observed p99 ingest latency inside the SLO budget with the
    classic asymmetric rule: **shrink fast** (``interval *= shrink``) whenever
    p99 crosses ``high_water * budget`` or backlog is standing, **grow
    slowly** (``interval *= grow``) only while p99 sits under ``low_water *
    budget`` with an empty backlog, clamped to ``[min_interval, max_interval]``.
    Asymmetry matters: an interval that is too long violates the SLO, one
    that is too short merely burns a few wakeups, so recovery must outpace
    relaxation.

    Pure object — no clocks, no threads, no I/O: ``observe(p99_ms, depth)``
    returns the new interval, which makes convergence on a stepped
    arrival-rate trace a plain unit test.
    """

    def __init__(
        self,
        budget_ms: float,
        *,
        interval_s: float = 0.005,
        min_interval_s: float = 0.0005,
        max_interval_s: float = 0.25,
        high_water: float = 0.7,
        low_water: float = 0.2,
        shrink: float = 0.5,
        grow: float = 1.25,
    ) -> None:
        if not budget_ms > 0:
            raise ValueError(f"budget_ms must be > 0, got {budget_ms}")
        if not 0 < min_interval_s <= max_interval_s:
            raise ValueError("need 0 < min_interval_s <= max_interval_s")
        if not 0 < low_water < high_water <= 1.0:
            raise ValueError("need 0 < low_water < high_water <= 1")
        if not 0 < shrink < 1.0 < grow:
            raise ValueError("need shrink in (0, 1) and grow > 1")
        self.budget_ms = float(budget_ms)
        self.min_interval_s = float(min_interval_s)
        self.max_interval_s = float(max_interval_s)
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.shrink = float(shrink)
        self.grow = float(grow)
        self.interval_s = min(max(float(interval_s), self.min_interval_s), self.max_interval_s)
        self.shrinks = 0
        self.grows = 0
        # observe() normally runs only on the control loop, but it is public
        # (the convergence tests drive it directly from the caller's thread),
        # so the counters and interval get a governing lock rather than a
        # single-writer claim.
        self._lock = threading.Lock()

    @thread_role("tm-serve/ticker")
    def observe(self, p99_ms: Optional[float], depth: int = 0) -> float:
        """Fold one control-window observation; return the new interval."""
        if p99_ms is None:
            return self.interval_s
        with self._lock:
            if p99_ms > self.high_water * self.budget_ms or depth > 0:
                nxt = max(self.interval_s * self.shrink, self.min_interval_s)
                if nxt < self.interval_s:
                    self.shrinks += 1
                self.interval_s = nxt
            elif p99_ms < self.low_water * self.budget_ms:
                nxt = min(self.interval_s * self.grow, self.max_interval_s)
                if nxt > self.interval_s:
                    self.grows += 1
                self.interval_s = nxt
            return self.interval_s


# ---------------------------------------------------------------- drift watch


class _DriftWatch:
    """Runtime state of one collection's drift canary (see :class:`DriftSpec`).

    Split by thread role: :meth:`sample` runs on the request path (role
    ``user``) and only appends to a bounded deque (atomic, drop-oldest);
    :meth:`absorb` and :meth:`evaluate` run on the control loop (role
    ``tm-serve``) and own the histogram and window counters. No lock needed —
    the deque is the only shared structure and deque append/popleft are
    atomic.
    """

    def __init__(self, spec: DriftSpec, collection: str) -> None:
        from metrics_tpu.sketches import HistogramDrift

        self.spec = spec
        self.collection = collection
        self.sketch = HistogramDrift(num_bins=spec.num_bins, low=spec.low, high=spec.high)
        self._pending: "deque[Any]" = deque(maxlen=64)
        self._seen = 0
        self._ref_rows = 0
        self._live_rows = 0
        self.alerts = 0
        self.last: Optional[Dict[str, float]] = None

    def sample(self, args: Tuple, kwargs: Dict) -> None:
        """Request path: keep a host reference to the first float array of a
        1-in-``sample_every`` batch. O(1), never blocks, never dispatches."""
        self._seen += 1
        if (self._seen - 1) % self.spec.sample_every:
            return
        for value in list(args) + list(kwargs.values()):
            if hasattr(value, "dtype") and hasattr(value, "shape"):
                self._pending.append(value)
                return

    def absorb(self) -> None:
        """Control loop: histogram every pending sample — reference window
        first, live window after."""
        while True:
            try:
                value = self._pending.popleft()
            except IndexError:
                return
            rows = int(getattr(value, "size", 1)) or 1
            if self._ref_rows < self.spec.reference_rows:
                self.sketch.update(value, reference=True)
                self._ref_rows += rows
            else:
                self.sketch.update(value)
                self._live_rows += rows

    def evaluate(self) -> Optional[Dict[str, Any]]:
        """Control loop: compare live vs reference once enough live rows have
        accumulated; slide the live window either way. Returns the alert
        payload when PSI crosses the threshold, else None."""
        if self._live_rows < self.spec.min_live_rows or self._ref_rows < self.spec.reference_rows:
            return None
        out = self.sketch.compute()
        self.last = {k: float(v) for k, v in out.items()}
        self.sketch.reset_live()
        self._live_rows = 0
        if self.last["psi"] <= self.spec.max_psi:
            return None
        self.alerts += 1
        return {
            "collection": self.collection,
            "psi": self.last["psi"],
            "kl": self.last["kl"],
            "tv": self.last["tv"],
            "max_psi": self.spec.max_psi,
        }


# ------------------------------------------------------------------ server


class _Collection:
    """Runtime bundle for one served collection: spec + built target + queue
    + canary + restore/commit bookkeeping."""

    __slots__ = ("spec", "target", "queue", "drift", "restored_step", "committed")

    def __init__(self, spec: CollectionSpec, target: Any, queue: IngestQueue) -> None:
        self.spec = spec
        self.target = target
        self.queue = queue
        self.drift = _DriftWatch(spec.drift, spec.name) if spec.drift is not None else None
        self.restored_step: Optional[int] = None
        self.committed: Optional[Dict[str, Any]] = None

    def update_count(self) -> int:
        counts = [int(getattr(m, "_update_count", 0)) for m in self.target._modules.values()]
        return max(counts) if counts else 0


class MetricsServer:
    """The tmserve process object. See the module docstring for the design;
    see ``docs/source/pages/serving.rst`` for the operator view.

    Construction does not start anything; :meth:`start` runs the
    ``restore → prewarm → ready`` sequence and (by default) spawns the shared
    ticker thread. ``ticker=False`` keeps the server in manual-tick mode —
    tests and the chaos sweep drive :meth:`_tick_round` / :meth:`_run_control`
    deterministically. Usable as a context manager: ``__exit__`` drains and
    stops.
    """

    def __init__(
        self,
        config: Union[ServerConfig, Dict[str, Any], str],
        *,
        start: bool = True,
        ticker: bool = True,
        starting_hook: Optional[Callable[["MetricsServer"], None]] = None,
        draining_hook: Optional[Callable[["MetricsServer"], None]] = None,
    ) -> None:
        self.config = load_config(config)
        self.name = self.config.name
        self._state = "starting"
        self._lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticker_enabled = bool(ticker)
        self._starting_hook = starting_hook
        self._draining_hook = draining_hook
        self._error: Optional[BaseException] = None
        self._collections: Dict[str, _Collection] = {}
        self._order: Tuple[str, ...] = tuple(spec.name for spec in self.config.collections)
        self._deficit: Dict[str, float] = {}
        self._drain_report: Optional[Dict[str, Any]] = None
        self._prom_address: Optional[Tuple[str, int]] = None
        self._prom_owned = False
        self._readiness: Optional[Callable[[], Tuple[int, str]]] = None
        self._last_control = 0.0
        self.tick_interval_s = self.config.tick_interval_s
        self.startup_s: Optional[float] = None
        # counters are partitioned by writer role: requests/rejected belong to
        # the request path, rounds/slo_breaches/drift_alerts to the ticker —
        # distinct keys, so no cross-role read-modify-write on any of them.
        # The request path may itself be multi-threaded (N producer threads
        # are all role "user"), so its two counters increment under _req_lock
        # to keep the totals exact; the ticker keys stay lock-free (one thread)
        self.stats: Dict[str, int] = {
            "requests": 0,
            "rejected": 0,
            "rounds": 0,
            "applied_entries": 0,
            "slo_breaches": 0,
            "drift_alerts": 0,
        }
        budgets = [s.slo_p99_ingest_ms for s in self.config.collections if s.slo_p99_ingest_ms is not None]
        self.controller: Optional[AdaptiveTickController] = None
        if self.config.adaptive and budgets:
            self.controller = AdaptiveTickController(
                min(budgets),
                interval_s=self.config.tick_interval_s,
                min_interval_s=self.config.min_tick_interval_s,
                max_interval_s=self.config.max_tick_interval_s,
            )
        if start:
            self.start()

    # ---------------------------------------------------------------- startup

    @property
    def state(self) -> str:
        return self._state

    def start(self) -> "MetricsServer":
        """Run ``restore → prewarm → ready``: bring the health endpoint up
        first (so probes see ``503 starting`` during the expensive part),
        restore every collection's latest committed checkpoint, replay each
        warm manifest, then admit traffic."""
        with self._lock:
            if self._state != "starting" or self._collections:
                raise ServerStateError(f"start() from state {self._state!r}; servers are single-use")
        t0 = time.perf_counter()
        _obs_flight.record("server_state", server=self.name, state="starting")
        if self.config.persistent_cache_dir:
            _excache.enable_persistent_cache(self.config.persistent_cache_dir)
        if self.config.prom_port is not None:
            from metrics_tpu.obs import prom as _prom

            # readiness first: the very first probe must see 503 starting.
            # Bind the method once — clear_readiness compares identity, and
            # every `self._healthz` access builds a fresh bound method.
            self._readiness = self._healthz
            _prom.set_readiness(self._readiness)
            self._prom_address = _prom.start_server(port=self.config.prom_port, host=self.config.prom_host)
            self._prom_owned = True
        if self._starting_hook is not None:
            self._starting_hook(self)
        from metrics_tpu.ckpt import latest_step, restore_checkpoint

        for spec in self.config.collections:
            target = spec.build()
            restored = None
            if spec.ckpt_dir and latest_step(spec.ckpt_dir) is not None:
                restored = restore_checkpoint(target, spec.ckpt_dir)
            queue = IngestQueue(target, name=spec.name, start=False, **spec.queue)
            if spec.ckpt_dir:
                manifest = os.path.join(spec.ckpt_dir, _excache.MANIFEST_NAME)
                if os.path.isfile(manifest):
                    self._prewarm_collection(queue, target, manifest)
            coll = _Collection(spec, target, queue)
            coll.restored_step = restored
            self._collections[spec.name] = coll
            self._deficit[spec.name] = 0.0
        if self.config.record_manifest:
            _excache.enable_recording()
        _SERVERS.add(self)
        if self._ticker_enabled:
            self._thread = threading.Thread(target=self._ticker_loop, name="tm-serve/ticker", daemon=True)
            self._thread.start()
        self.startup_s = time.perf_counter() - t0
        with self._lock:
            self._state = "ready"
        _obs_flight.record("server_state", server=self.name, state="ready", startup_s=self.startup_s)
        return self

    @staticmethod
    def _prewarm_collection(queue: IngestQueue, target: Any, manifest_path: str) -> None:
        """Replay one warm manifest against one collection's two serving
        objects: ingest-chain entries against the queue, fused/fleet/rank
        entries against the collection. The manifest is recorded
        process-wide, so with several collections each one's copy also holds
        the *other* collections' entries — partitioning by the live chain /
        member labels keeps those out of the replay instead of tripping
        prewarm's schema-drift warnings."""
        try:
            payload = _excache.load_manifest(manifest_path)
        except Exception:  # noqa: BLE001 — let prewarm produce its own warning
            _excache.prewarm(queue, manifest_path)
            return
        chain, _eager, _is_coll = queue._plan()
        labels = [label for label, _ in chain]
        members = set(target._modules)
        queue_entries: List[Dict[str, Any]] = []
        target_entries: List[Dict[str, Any]] = []
        for entry in payload.get("entries", []) or []:
            engine = entry.get("engine")
            if engine == "ingest":
                if list(entry.get("chain") or []) == labels:
                    queue_entries.append(entry)
            elif engine == "fused":
                if all(name in members for name, _ in entry.get("groups", [])):
                    target_entries.append(entry)
            else:
                target_entries.append(entry)
        if queue_entries:
            _excache.prewarm(queue, dict(payload, entries=queue_entries))
        if target_entries:
            _excache.prewarm(target, dict(payload, entries=target_entries))

    @thread_role("prom-handler")
    def _healthz(self) -> Tuple[int, str]:
        """Readiness probe body for ``obs.prom``'s ``/healthz`` route:
        ``200 ready`` only while admitting, ``503 <state>`` otherwise.
        Read-only and lock-free — safe from the scrape handler thread."""
        state = self._state
        return (200, "ready\n") if state == "ready" else (503, state + "\n")

    # ------------------------------------------------------------ request API

    def _reraise(self) -> None:
        err, self._error = self._error, None
        if err is not None:
            raise err

    def _coll(self, name: str) -> _Collection:
        coll = self._collections.get(name)
        if coll is None:
            raise ServerConfigError(f"unknown collection {name!r}; serving: {sorted(self._collections)}")
        return coll

    def enqueue(self, name: str, *args: Any, stream_ids: Any = None, **kwargs: Any) -> None:
        """Admit one update batch for collection ``name``. Host append only —
        the shared ticker applies it. Raises :class:`ServerStateError` unless
        the server is ``ready`` (a drained server never half-applies)."""
        self._reraise()
        state = self._state
        if state != "ready":
            with self._req_lock:
                self.stats["rejected"] += 1
            raise ServerStateError(f"server {self.name!r} is {state}; enqueue requires ready")
        coll = self._coll(name)
        if _fault._SCHEDULE is not None:
            _fault.fire("server.request", server=self.name, collection=name)
        t0 = time.monotonic()
        if coll.drift is not None:
            coll.drift.sample(args, kwargs)
        if stream_ids is not None:
            kwargs = dict(kwargs, stream_ids=stream_ids)
        coll.queue.enqueue(*args, **kwargs)
        with self._req_lock:
            self.stats["requests"] += 1
        if _obs._ENABLED:
            _obs.REGISTRY.inc("server", "requests")
        mon = _health._MONITOR
        if mon is not None:
            mon.observe_latency("server.request", name, time.monotonic() - t0)
        self._wake.set()

    def compute(self, name: str, *, stream: Optional[int] = None) -> Any:
        """Flush-before-read compute for collection ``name``; ``stream=i``
        narrows every fleet member to one stream. Allowed while ``ready`` or
        ``draining`` (reads during drain observe the final flushed state)."""
        self._reraise()
        coll = self._coll(name)
        if self._state == "stopped":
            raise ServerStateError(f"server {self.name!r} is stopped")
        if stream is None:
            return coll.queue.compute()
        # MetricCollection.compute() has no stream axis — fan out per member
        coll.queue.flush()
        return {label: m.compute(stream=stream) for label, m in coll.target._modules.items()}

    def reduce_fleet(self, name: str) -> Dict[str, Any]:
        """Cross-stream reduction for every fleet member of collection
        ``name`` (flush first). Returns label → reduced value."""
        self._reraise()
        coll = self._coll(name)
        if self._state == "stopped":
            raise ServerStateError(f"server {self.name!r} is stopped")
        coll.queue.flush()
        out = {
            label: m.reduce_fleet()
            for label, m in coll.target._modules.items()
            if getattr(m, "fleet_size", None) is not None
        }
        if not out:
            raise ServerStateError(f"collection {name!r} has no fleet members to reduce")
        return out

    def status(self) -> Dict[str, Any]:
        """Operator snapshot: lifecycle state, per-collection queue stats and
        restore/commit bookkeeping, ticker and canary posture."""
        collections = {}
        for coll_name, coll in self._collections.items():
            collections[coll_name] = {
                "depth": coll.queue.depth,
                "stats": dict(coll.queue.stats),
                "update_count": coll.update_count(),
                "restored_step": coll.restored_step,
                "committed": coll.committed,
                "deficit": self._deficit.get(coll_name, 0.0),
                "drift": None if coll.drift is None else dict(coll.drift.last or {}, alerts=coll.drift.alerts),
            }
        return {
            "server": self.name,
            "state": self._state,
            "tick_interval_s": self.tick_interval_s,
            "stats": dict(self.stats),
            "prom": self._prom_address,
            "startup_s": self.startup_s,
            "collections": collections,
        }

    # ------------------------------------------------------------- the ticker

    def _ticker_loop(self) -> None:
        """The shared tick thread (role ``tm-serve``): one DRR round per
        wakeup plus the control loop at its own cadence. Errors are stashed
        and re-raised at the next request — the thread itself never dies
        mid-serve."""
        while not self._stop_evt.is_set():
            self._wake.wait(self.tick_interval_s)
            self._wake.clear()
            if self._stop_evt.is_set():
                return
            try:
                self._tick_round()
                now = time.monotonic()
                if now - self._last_control >= self.config.control_every_s:
                    self._last_control = now
                    self._run_control()
            except BaseException as err:  # noqa: BLE001 — stash, surface at host boundary
                if self._error is None:
                    self._error = err

    def _tick_round(self) -> int:
        """One deficit-round-robin pass: every queue accrues ``quantum``
        entries of credit, applies at most its accumulated deficit, and keeps
        the remainder only while backlogged (classic DRR reset-on-empty).
        Returns total entries applied this round."""
        quantum = self.config.quantum
        applied = 0
        for name in self._order:
            coll = self._collections[name]
            credit = self._deficit[name] + quantum
            served = 0
            # tick() caps each call at max_coalesce; loop until the credit or
            # the backlog is spent so a large quantum is honoured in full
            while credit - served >= 1 and coll.queue.depth > 0:
                got = coll.queue.tick(limit=int(credit - served))
                if got == 0:
                    break
                served += got
            applied += served
            self._deficit[name] = 0.0 if coll.queue.depth == 0 else credit - served
        if applied:
            self.stats["rounds"] += 1
            self.stats["applied_entries"] += applied
            if _obs._ENABLED:
                _obs.REGISTRY.inc("server", "rounds")
                _obs.REGISTRY.inc("server", "applied_entries", applied)
        return applied

    def _run_control(self) -> None:
        """The slow loop (role ``tm-serve``): adaptive-interval update,
        per-collection SLO budget checks, drift canary evaluation."""
        mon = _health._MONITOR
        latency: Dict[str, Any] = {}
        if mon is not None:
            latency = mon.report().get("latency_us", {})

        def p99_ms(op: str, coll_name: str) -> Optional[float]:
            row = latency.get(f"{op}/{coll_name}")
            return None if row is None else float(row["p99_us"]) / 1000.0

        if self.controller is not None:
            observed = [p99_ms("ingest", c) for c in self._order]
            observed = [o for o in observed if o is not None]
            depth = max((self._collections[c].queue.depth for c in self._order), default=0)
            if observed:
                self.tick_interval_s = self.controller.observe(max(observed), depth=depth)
        violations: List[Dict[str, Any]] = []
        for name in self._order:
            coll = self._collections[name]
            budget = coll.spec.slo_p99_ingest_ms
            if budget is None:
                continue
            observed = p99_ms("ingest", name)
            if observed is not None and observed > budget:
                violations.append(
                    {"slo": "p99_ingest_latency_ms", "collection": name, "observed": observed, "budget": budget}
                )
        if violations:
            self.stats["slo_breaches"] += len(violations)
            if _obs._ENABLED:
                _obs.REGISTRY.inc("server", "slo_breaches", len(violations))
            _obs_flight.record("server_slo", server=self.name, violations=len(violations))
            self._react_slo(violations)
        for name in self._order:
            coll = self._collections[name]
            if coll.drift is None:
                continue
            coll.drift.absorb()
            alert = coll.drift.evaluate()
            if alert is None:
                continue
            self.stats["drift_alerts"] += 1
            if _obs._ENABLED:
                _obs.REGISTRY.inc("server", "drift_alerts")
            _obs_flight.record("drift_alert", server=self.name, **alert)
            self._react_drift(coll.drift.spec.action, alert)

    def _react_slo(self, violations: List[Dict[str, Any]]) -> None:
        action = self.config.slo_action
        if callable(action):
            action(violations)
            return
        lines = "; ".join(
            f"{v['collection']}: p99 ingest {v['observed']:.2f}ms > budget {v['budget']:.2f}ms" for v in violations
        )
        if action == "raise":
            raise _health.SLOBudgetExceeded(f"server {self.name!r} SLO exceeded — {lines}")
        warnings.warn(f"server {self.name!r} SLO violation — {lines}", _health.SLOViolationWarning, stacklevel=2)

    def _react_drift(self, action: Union[str, Callable], alert: Dict[str, Any]) -> None:
        if callable(action):
            action(alert)
            return
        msg = (
            f"server {self.name!r} collection {alert['collection']!r} input drift:"
            f" PSI {alert['psi']:.4f} > {alert['max_psi']:.4f}"
            f" (kl={alert['kl']:.4f}, tv={alert['tv']:.4f})"
        )
        if action == "raise":
            raise DriftAlertError(msg)
        warnings.warn(msg, DriftAlert, stacklevel=2)

    def _stop_ticker(self) -> None:
        self._stop_evt.set()
        self._wake.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=30.0)
        self._thread = None

    # -------------------------------------------------------------- lifecycle

    def drain(self) -> Dict[str, Any]:
        """``serve → drain``: stop admitting, apply every staged batch exactly
        once, checkpoint every collection (the warm manifest rides along while
        recording is on). Idempotent. The ``server.drain`` fault site fires
        *before* anything is flushed: a killed drain drops only staged-but-
        uncommitted rows — with attribution, never silently — and leaves the
        last committed checkpoint untouched."""
        with self._lock:
            if self._state in ("draining", "stopped"):
                return self._drain_report or {}
            self._state = "draining"
        _obs_flight.record("server_state", server=self.name, state="draining")
        if self._draining_hook is not None:
            self._draining_hook(self)
        try:
            if _fault._SCHEDULE is not None:
                _fault.fire("server.drain", server=self.name, collections=len(self._collections))
        except _fault.InjectedFaultError:
            # salvage path: the drain is dead, but nothing may leak — staged
            # rows are dropped WITH attribution and traced flows are closed
            # as dropped (the chaos sweep's zero-orphaned-flows invariant)
            self._stop_ticker()
            for coll in self._collections.values():
                try:
                    coll.queue.close(drain=False)
                except Exception:  # noqa: BLE001 — salvage must reach every queue
                    pass
            raise
        self._stop_ticker()
        from metrics_tpu.ckpt import save_checkpoint

        report: Dict[str, Any] = {}
        first_error: Optional[BaseException] = None
        for name in self._order:
            coll = self._collections[name]
            try:
                coll.queue.close(drain=True)
                entry: Dict[str, Any] = {
                    "update_count": coll.update_count(),
                    "applied_rows": int(coll.queue.stats["coalesced_rows"]),
                    "dropped": int(coll.queue.stats["dropped"]),
                    "step": None,
                }
                if coll.spec.ckpt_dir:
                    write = save_checkpoint(
                        coll.target, coll.spec.ckpt_dir, blocking=True, retain=self.config.retain
                    )
                    entry["step"] = write.step
                coll.committed = entry
                report[name] = entry
            except Exception as err:  # noqa: BLE001 — drain the rest, re-raise the first
                if first_error is None:
                    first_error = err
                try:
                    coll.queue.close(drain=False)
                except Exception:  # noqa: BLE001
                    pass
        self._drain_report = report
        _obs_flight.record("server_state", server=self.name, state="drained", collections=len(report))
        if first_error is not None:
            raise first_error
        return report

    def stop(self) -> None:
        """Drain (if not already drained) and release everything: ticker,
        queues, readiness registration, prom server ownership."""
        try:
            if self._state not in ("draining", "stopped"):
                self.drain()
        finally:
            self._stop_ticker()
            for coll in self._collections.values():
                try:
                    coll.queue.close(drain=False)
                except Exception:  # noqa: BLE001 — stop() must release everything
                    pass
            if self._prom_owned:
                from metrics_tpu.obs import prom as _prom

                _prom.clear_readiness(self._readiness)
                _prom.stop_server()
                self._prom_owned = False
            with self._lock:
                self._state = "stopped"
            _SERVERS.discard(self)
            _obs_flight.record("server_state", server=self.name, state="stopped")

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.stop()


def active_servers() -> List[MetricsServer]:
    """Live (non-stopped) servers, for the prom exposition's tm_server_*
    families."""
    return [s for s in list(_SERVERS) if s._state != "stopped"]


@thread_role("atexit")
def _stop_all_tickers() -> None:
    """Interpreter-exit backstop: a leaked (never-stopped) server's daemon
    ticker must not be mid-launch while the runtime tears down. Only sets
    events (atomic, handler-safe) — no joins, no locks."""
    for s in list(_SERVERS):
        s._stop_evt.set()
        s._wake.set()


atexit.register(_stop_all_tickers)
