"""metrics_tpu.serve — the serving-path tiers built on top of the core.

Currently one member: the async ingestion tier (:mod:`metrics_tpu.serve.ingest`),
which decouples host batch arrival from device accumulation with a bounded
staging ring and a coalescing tick thread::

    from metrics_tpu.serve import IngestQueue

    q = IngestQueue(metric, capacity=1024, backpressure="block")
    q.enqueue(preds, target, stream_ids=ids)   # host append, no dispatch
    value = q.compute()                        # flush-before-read, exact
    q.close()                                  # clean shutdown drain
"""
from metrics_tpu.serve.ingest import (
    IngestBackpressureError,
    IngestQueue,
    active_queues,
    flush_for,
    max_queue_depth,
)

__all__ = [
    "IngestBackpressureError",
    "IngestQueue",
    "active_queues",
    "flush_for",
    "max_queue_depth",
]
