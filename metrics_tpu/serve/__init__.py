"""metrics_tpu.serve — the serving-path tiers built on top of the core.

Three members today. The async ingestion tier (:mod:`metrics_tpu.serve.ingest`)
decouples host batch arrival from device accumulation with a bounded staging
ring and a coalescing tick thread::

    from metrics_tpu.serve import IngestQueue

    q = IngestQueue(metric, capacity=1024, backpressure="block")
    q.enqueue(preds, target, stream_ids=ids)   # host append, no dispatch
    value = q.compute()                        # flush-before-read, exact
    q.close()                                  # clean shutdown drain

The executable-cache tier (:mod:`metrics_tpu.serve.excache`) makes replica
restarts cold-start-free: JAX's persistent compilation cache under a library
config surface, plus a warm manifest of every engine compile that
``prewarm(target, manifest)`` replays at startup so the first request
triggers zero compiles::

    from metrics_tpu.serve import excache

    excache.enable_persistent_cache("/var/cache/metrics_tpu/xla")
    excache.enable_recording()                 # compiles now land in the manifest
    ...                                        # ckpt writes warm_manifest.json
    excache.prewarm(collection, "ckpts/warm_manifest.json")   # on restart

The serving front end (:mod:`metrics_tpu.serve.server`) composes both — plus
checkpoints, fault sites, and the obs stack — into a deployable process
(``python -m metrics_tpu.serve``): N named collections from a declarative
config, one fair shared ticker, restore→prewarm→ready startup and
drain→ckpt→stop shutdown::

    from metrics_tpu.serve import MetricsServer, load_config

    with MetricsServer(load_config("serve.json")) as server:
        server.enqueue("quality", preds, target, stream_ids=ids)
        value = server.compute("quality")
"""
from metrics_tpu.serve import excache
from metrics_tpu.serve.excache import (
    enable_persistent_cache,
    enable_recording,
    prewarm,
    save_manifest,
)
from metrics_tpu.serve.ingest import (
    IngestBackpressureError,
    IngestQueue,
    active_queues,
    flush_for,
    max_queue_depth,
)
from metrics_tpu.serve.server import (
    CollectionSpec,
    DriftAlert,
    DriftAlertError,
    MetricsServer,
    ServerConfig,
    ServerConfigError,
    ServerStateError,
    load_config,
)

__all__ = [
    "CollectionSpec",
    "DriftAlert",
    "DriftAlertError",
    "IngestBackpressureError",
    "IngestQueue",
    "MetricsServer",
    "ServerConfig",
    "ServerConfigError",
    "ServerStateError",
    "active_queues",
    "excache",
    "enable_persistent_cache",
    "enable_recording",
    "flush_for",
    "load_config",
    "max_queue_depth",
    "prewarm",
    "save_manifest",
]
