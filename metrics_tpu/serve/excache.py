"""Cold-start-free serving: persistent executable cache + warm-manifest prewarm.

Every AOT cache in the serving stack (``core/fused.py``, ``core/fleet.py``,
``serve/ingest.py``, the ``ops/clf_curve.py`` rank kernels) is per-process, so
a fresh replica pays the full retrace+compile bill before its first request —
~20s cold on CPU for the canonical collection (ROADMAP item 4). This module
removes that bill in two layers:

- **Persistent compilation cache** (:func:`enable_persistent_cache`): turns on
  JAX's on-disk compilation cache under a library-owned config surface, with
  the entry-size/compile-time write floors zeroed by default (CPU compiles are
  sub-second and would otherwise silently never be written). A monitoring
  listener splits the accounting into ``excache.disk_hits`` (XLA compile
  served from disk) vs ``excache.compiles`` (true compile), mirrored into the
  obs registry when the obs gate is up.
- **Warm manifest** (:func:`enable_recording` + :func:`prewarm`): every engine
  compile records its stable cache-key digest (``fused.stable_key_digest`` —
  NOT the ``PYTHONHASHSEED``-salted ``hash()``) plus a *reconstructible*
  abstract-input spec (avals + static leaves) into a JSON manifest. The ckpt
  manager writes ``warm_manifest.json`` atomically alongside checkpoints;
  :func:`prewarm` replays each entry through ``.lower().compile()`` at startup
  and seeds the owning engine's in-memory executable cache, so every lowering
  hits the disk cache and the first real request triggers **zero** compiles
  (flight-window provable: ``fused_cache_miss == 0``).

Degradation contract: prewarm never fails startup. Schema drift, a stale
``jax`` version stamp, entries that no longer match the live target, and
injected ``excache.prewarm`` faults all warn (once per site) and skip the
entry — the executable lazily compiles on first use, exactly as without
prewarm, bit-identically.

The recording hooks in the engines gate on
``sys.modules.get("metrics_tpu.serve.excache")`` at *compile* time only (the
cold path), so a process that never imports this module — or never calls
:func:`enable_recording` — pays nothing on the steady-state path.
"""
import json
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core import fused as _fused
from metrics_tpu.fault import inject as _fault
from metrics_tpu.obs import flight as _obs_flight
from metrics_tpu.obs import registry as _obs
from metrics_tpu.utils.exceptions import MetricsUserWarning

__all__ = [
    "MANIFEST_NAME",
    "SCHEMA_VERSION",
    "cache_dir",
    "clear_manifest",
    "clear_stats",
    "disable_persistent_cache",
    "disable_recording",
    "enable_persistent_cache",
    "enable_recording",
    "last_prewarm",
    "load_manifest",
    "manifest_entries",
    "manifest_payload",
    "prewarm",
    "recording",
    "save_manifest",
    "stats",
]

#: manifest file name, written alongside checkpoints by the ckpt manager
MANIFEST_NAME = "warm_manifest.json"

#: bumped on any incompatible change to the entry encoding below
SCHEMA_VERSION = 1

# ----------------------------------------------------------- module state

_LOCK = threading.Lock()

#: the active on-disk cache directory (None == persistent cache off)
_CACHE_DIR: Optional[str] = None

_LISTENER_REGISTERED = False

#: single boolean the engine compile hooks check via ``recording()``
_RECORDING: bool = False

_ENTRIES: List[Dict[str, Any]] = []
_SEEN_DIGESTS: set = set()
#: cheap pre-digest dedup for the per-call rank dispatch hook
_SEEN_RANK: set = set()

#: always-on plain-int accounting (the obs registry mirror is gated)
_STATS: Dict[str, int] = {
    "requests": 0,
    "disk_hits": 0,
    "compiles": 0,
    "prewarmed": 0,
    "manifest_entries": 0,
    "prewarm_failures": 0,
    "unrecordable": 0,
}

#: report dict of the most recent :func:`prewarm` call (``state_report()``
#: surfaces it as the replica's warmup cost)
_LAST_PREWARM: Optional[Dict[str, Any]] = None


class _Unrecordable(Exception):
    """An input leaf that cannot be serialized into the manifest (exotic
    static object); the entry is dropped, never the update."""


# ------------------------------------------------- persistent compile cache


def _on_cache_event(event: str, **kwargs: Any) -> None:
    # jax emits one `compile_requests_use_cache` per cache-eligible compile
    # and one `cache_hits` when the executable came off disk; there is no
    # explicit miss event, so true compiles are maintained as requests - hits.
    if event == "/jax/compilation_cache/compile_requests_use_cache":
        _STATS["requests"] += 1
        _STATS["compiles"] += 1
        if _obs._ENABLED:
            _obs.REGISTRY.inc("excache", "compiles")
    elif event == "/jax/compilation_cache/cache_hits":
        _STATS["disk_hits"] += 1
        _STATS["compiles"] -= 1
        if _obs._ENABLED:
            _obs.REGISTRY.inc("excache", "disk_hits")
            _obs.REGISTRY.inc("excache", "compiles", -1)


def _register_cache_listener() -> None:
    global _LISTENER_REGISTERED
    if _LISTENER_REGISTERED:
        return
    try:
        from jax import monitoring

        monitoring.register_event_listener(_on_cache_event)
        _LISTENER_REGISTERED = True
    except Exception:  # noqa: BLE001 — accounting must never break serving
        pass


def enable_persistent_cache(
    cache_dir_: str,
    *,
    min_entry_size_bytes: int = 0,
    min_compile_time_secs: float = 0.0,
) -> str:
    """Route every XLA compile through JAX's on-disk compilation cache.

    The write floors default to zero: jax's own default
    ``min_compile_time_secs=1.0`` silently skips sub-second compiles — which
    is *every* CPU compile in this library — so a restart would find an empty
    cache and prewarm would degrade to true compiles.
    """
    global _CACHE_DIR
    cache_dir_ = str(cache_dir_)
    os.makedirs(cache_dir_, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir_)
    for name, value in (
        ("jax_persistent_cache_min_compile_time_secs", min_compile_time_secs),
        ("jax_persistent_cache_min_entry_size_bytes", min_entry_size_bytes),
    ):
        try:
            jax.config.update(name, value)
        except Exception:  # noqa: BLE001 — flag absent on this jax version
            pass
    try:
        # the cache object is latched once per process; reset so the new dir
        # takes effect even if a cache was already initialized
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API drift: lazily picked up
        pass
    _register_cache_listener()
    _CACHE_DIR = cache_dir_
    return cache_dir_


def disable_persistent_cache() -> None:
    """Turn the on-disk cache back off (tests / config isolation)."""
    global _CACHE_DIR
    _CACHE_DIR = None
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001
        pass


def cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when disabled."""
    return _CACHE_DIR


def stats() -> Dict[str, int]:
    """Copy of the excache accounting: ``disk_hits`` (XLA compiles served off
    disk), ``compiles`` (true compiles while the cache was enabled),
    ``prewarmed``/``prewarm_failures``, ``manifest_entries``."""
    return dict(_STATS)


def clear_stats() -> None:
    for key in _STATS:
        _STATS[key] = 0


# ------------------------------------------------------- input (de)serializing


def _encode(obj: Any) -> Any:
    """Structural JSON encoding of an ``(args, kwargs)`` pytree: array leaves
    by aval, containers by marker, primitives by python type tag (so json's
    int/float lattice cannot drift the static cache key)."""
    if isinstance(obj, jax.ShapeDtypeStruct) or _is_arraylike(obj):
        return {"t": "aval", "shape": [int(s) for s in obj.shape], "dtype": str(obj.dtype)}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"t": "lit", "py": type(obj).__name__, "v": obj}
    if isinstance(obj, tuple):
        return {"t": "tuple", "v": [_encode(e) for e in obj]}
    if isinstance(obj, list):
        return {"t": "list", "v": [_encode(e) for e in obj]}
    if isinstance(obj, dict):
        if not all(isinstance(k, str) for k in obj):
            raise _Unrecordable("non-string dict key")
        return {"t": "dict", "v": {k: _encode(v) for k, v in sorted(obj.items())}}
    raise _Unrecordable(f"unrecordable static leaf: {type(obj).__name__}")


def _is_arraylike(obj: Any) -> bool:
    from metrics_tpu.utils.data import is_array

    return is_array(obj)


_LIT_TYPES = {"NoneType": lambda v: None, "bool": bool, "int": int, "float": float, "str": str}


def _decode(obj: Any) -> Any:
    """Inverse of :func:`_encode`; array leaves come back as
    :class:`jax.ShapeDtypeStruct` (the prewarm replay is abstract)."""
    if not isinstance(obj, dict) or "t" not in obj:
        raise _Unrecordable(f"malformed manifest node: {obj!r}")
    t = obj["t"]
    if t == "aval":
        return jax.ShapeDtypeStruct(tuple(obj["shape"]), np.dtype(obj["dtype"]))
    if t == "lit":
        py = obj["py"]
        if py not in _LIT_TYPES:
            raise _Unrecordable(f"unknown literal type {py!r}")
        return None if py == "NoneType" else _LIT_TYPES[py](obj["v"])
    if t == "tuple":
        return tuple(_decode(e) for e in obj["v"])
    if t == "list":
        return [_decode(e) for e in obj["v"]]
    if t == "dict":
        return {k: _decode(v) for k, v in obj["v"].items()}
    raise _Unrecordable(f"unknown manifest node type {t!r}")


def _encode_inputs(args: Tuple, kwargs: Dict) -> Any:
    return _encode((tuple(args), dict(kwargs)))


def _decode_inputs(enc: Any) -> Tuple[Tuple, Dict]:
    args, kwargs = _decode(enc)
    return tuple(args), dict(kwargs)


def _sds_tree(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype), tree
    )


# ------------------------------------------------------------- recording


def recording() -> bool:
    """True while warm-manifest recording is on — the one check the engine
    compile hooks make after their ``sys.modules`` probe."""
    return _RECORDING


def enable_recording(clear: bool = False) -> None:
    """Start recording engine compiles into the warm manifest."""
    global _RECORDING
    if clear:
        clear_manifest()
    _RECORDING = True


def disable_recording() -> None:
    global _RECORDING
    _RECORDING = False


def clear_manifest() -> None:
    with _LOCK:
        _ENTRIES.clear()
        _SEEN_DIGESTS.clear()
        _SEEN_RANK.clear()


def manifest_entries() -> List[Dict[str, Any]]:
    with _LOCK:
        return [dict(e) for e in _ENTRIES]


def _add_entry(entry: Dict[str, Any], digest: str) -> None:
    with _LOCK:
        if digest in _SEEN_DIGESTS:
            return
        _SEEN_DIGESTS.add(digest)
        entry["key_digest"] = digest
        _ENTRIES.append(entry)
        # read-modify-write: callers race from every recording thread, so the
        # counter bump belongs inside the same critical section as the entry
        _STATS["manifest_entries"] += 1
    if _obs._ENABLED:
        _obs.REGISTRY.inc("excache", "manifest_entries")


def record_fused_compile(
    *, mode: str, groups: List[Tuple[str, Tuple[str, ...]]], args: Tuple, kwargs: Dict, digest: str
) -> None:
    """Called by ``FusedCollectionUpdate._launch`` on a cache-miss compile."""
    if not _RECORDING:
        return
    try:
        entry = {
            "engine": "fused",
            "mode": mode,
            "groups": [[name, list(members)] for name, members in groups],
            "inputs": _encode_inputs(args, kwargs),
        }
    except _Unrecordable:
        with _LOCK:
            _STATS["unrecordable"] += 1
        return
    _add_entry(entry, digest)


def record_fleet_compile(
    metric: Any, tag: str, args: Tuple, kwargs: Dict, stream_ids: Any, digest: str
) -> None:
    """Called by ``fleet.run_step`` on a cache-miss compile."""
    if not _RECORDING:
        return
    try:
        entry = {
            "engine": "fleet",
            "tag": tag,
            "metric": type(metric).__name__,
            "fleet_size": int(metric.fleet_size),
            "inputs": _encode_inputs(args, kwargs),
            "stream_ids": None if stream_ids is None else _encode(stream_ids),
        }
    except _Unrecordable:
        with _LOCK:
            _STATS["unrecordable"] += 1
        return
    _add_entry(entry, digest)


def record_ingest_compile(
    queue: Any, chain: List[Tuple[str, Any]], scan: bool, entries: List[Any], key: Tuple
) -> None:
    """Called by ``IngestQueue._launch_chain`` on a cache-miss compile. For the
    scan fast path only entry 0's signature is stored (they are uniform by
    construction) plus the coalesced count."""
    if not _RECORDING:
        return
    topo, state_key, sig = key
    digest = _fused.stable_key_digest(
        (tuple(label for label, _ in topo), state_key, sig)
    )
    try:
        recorded = [entries[0]] if scan else entries
        entry = {
            "engine": "ingest",
            "scan": bool(scan),
            "count": len(entries),
            "chain": [label for label, _ in chain],
            "entries": [_encode_inputs(e.args, e.kwargs) for e in recorded],
        }
    except _Unrecordable:
        with _LOCK:
            _STATS["unrecordable"] += 1
        return
    _add_entry(entry, digest)


#: rank ops the prewarm replay knows how to call (schema-drift guard: an
#: unknown op in a manifest is skipped, never getattr'd blindly)
_RANK_REPLAY_OPS = (
    "binary_auroc_exact",
    "binary_average_precision_exact",
    "binary_precision_recall_curve_padded",
    "binary_roc_curve_padded",
    "multiclass_auroc_exact",
    "multiclass_average_precision_exact",
    "multilabel_auroc_exact",
    "multilabel_average_precision_exact",
)

#: sketch-tier histogram units (ops/rank.py, tolerance-routed Metric classes).
#: ``bits`` is the static half of the counts unit's compile key; the bounds
#: units carry it in the histogram shape instead.
_RANK_HIST_REPLAY_OPS = (
    "hist_class_counts",
    "hist_auroc_bounds",
    "hist_ap_bounds",
)


def record_rank_compile(
    op: str,
    tier: Optional[str],
    arrays: Tuple[Any, ...],
    max_fpr: Optional[float] = None,
    bits: Optional[int] = None,
) -> None:
    """Called from the ``ops/clf_curve.py`` dispatch sites and the sketch-tier
    Metric classes (every call while recording, so the dedup check runs
    *before* any encoding work). ``bits`` rides along for sketch entries —
    the bracket kernels' static bit depth is part of their compile key."""
    if not _RECORDING:
        return
    cheap = (op, tier, max_fpr, bits, tuple((tuple(a.shape), str(a.dtype)) for a in arrays))
    with _LOCK:
        if cheap in _SEEN_RANK:
            return
        _SEEN_RANK.add(cheap)
    entry = {
        "engine": "rank",
        "op": op,
        "tier": tier,
        "max_fpr": max_fpr,
        "bits": bits,
        "inputs": [_encode(a) for a in arrays],
    }
    _add_entry(entry, _fused.stable_key_digest(cheap))


# --------------------------------------------------------------- manifest IO


def manifest_payload() -> Dict[str, Any]:
    """The JSON document :func:`save_manifest` writes: schema + jax version
    stamps (prewarm skews on either) and the recorded entries."""
    return {
        "schema": SCHEMA_VERSION,
        "jax_version": jax.__version__,
        "entries": manifest_entries(),
    }


def save_manifest(path: str) -> str:
    """Atomically write the warm manifest (same tmp+fsync+rename discipline as
    the checkpoint commit records). The ckpt manager calls this alongside
    every checkpoint while recording is on."""
    from metrics_tpu.ckpt.manager import _atomic_write_json

    _atomic_write_json(path, manifest_payload())
    return path


def load_manifest(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


# ----------------------------------------------------------------- prewarm


def _warn_skip(reason: str) -> None:
    warnings.warn(
        f"excache.prewarm: {reason} — entry skipped; its executable will"
        " lazily compile on first use instead.",
        MetricsUserWarning,
        stacklevel=3,
    )


def _prewarm_fused(target: Any, entry: Dict[str, Any]) -> bool:
    if getattr(target, "_groups", None) is None or not hasattr(target, "_modules"):
        return False
    groups = [(str(name), tuple(members)) for name, members in entry["groups"]]
    if any(name not in target._modules for name, _ in groups):
        _warn_skip("manifest fused groups do not match the live collection")
        return False
    forward = entry["mode"] == "forward"
    args, kwargs = _decode_inputs(entry["inputs"])
    engine = _fused.engine_for(target)
    dyn, split_spec = _fused._split_inputs(args, kwargs)
    states = {
        name: _sds_tree(target._modules[name].state_pytree()) for name, _ in groups
    }
    topo = tuple((name, members, id(target._modules[name])) for name, members in groups)
    key = (
        entry["mode"],
        topo,
        _fused._aval_key(states),
        _fused._aval_key(dyn),
        _fused._static_key(split_spec),
    )
    if key in engine._cache or key in engine._broken_keys:
        return False
    fresh = (
        {name: _sds_tree(target._modules[name].init_state()) for name, _ in groups}
        if forward
        else None
    )
    compiled = engine._compile(target, groups, states, fresh, dyn, split_spec, forward)
    engine._cache[key] = compiled
    return True


def _prewarm_fleet(target: Any, entry: Dict[str, Any]) -> bool:
    from metrics_tpu.core import fleet as _fleet

    if getattr(target, "fleet_size", None) is None:
        return False
    if (
        type(target).__name__ != entry["metric"]
        or int(target.fleet_size) != entry["fleet_size"]
    ):
        _warn_skip("manifest fleet entry does not match the live metric")
        return False
    args, kwargs = _decode_inputs(entry["inputs"])
    ids = None if entry.get("stream_ids") is None else _decode(entry["stream_ids"])
    # the raw (pre-wrap) bound update, exactly what apply_update closes over
    raw_update = type(target).update.__get__(target)
    dyn, spec = _fused._split_inputs(args, kwargs)
    state = {name: _sds_tree(getattr(target, name)) for name in target._defaults}
    tag = entry["tag"]
    if tag == "fleet.bcast":

        def step(st, dl):
            a, k = _fused._merge_inputs(dl, spec)
            return _fleet.broadcast_new_state(target, raw_update, st, a, k)

        extras: Tuple = (dyn,)
    elif tag == "fleet.route":
        if ids is None:
            _warn_skip("routed fleet entry without stream_ids")
            return False

        def step(st, dl, i_):
            a, k = _fused._merge_inputs(dl, spec)
            return _fleet.routed_new_state(target, raw_update, st, a, k, i_)

        extras = (dyn, ids)
    else:
        _warn_skip(f"unknown fleet tag {tag!r}")
        return False
    donate = getattr(target, "_pure_call_depth", 0) == 0
    key = (
        tag,
        donate,
        _fused._aval_key(state),
        _fused._aval_key(extras),
        _fused._static_key(spec),
    )
    cache = _fleet._cache_for(target)
    if key in cache:
        return False
    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    compiled = jitted.lower(state, *extras).compile()
    cache[key] = compiled
    return True


def _prewarm_ingest(target: Any, entry: Dict[str, Any]) -> bool:
    if not hasattr(target, "_plan") or not hasattr(target, "_cache"):
        return False
    chain, _eager, is_collection = target._plan()
    if not chain:
        return False
    if [label for label, _ in chain] != list(entry["chain"]):
        _warn_skip("manifest ingest chain does not match the live queue target")
        return False
    scan = bool(entry["scan"])
    count = int(entry["count"])
    decoded = [_decode_inputs(e) for e in entry["entries"]]
    if scan:
        decoded = decoded * count
    dyn_lists: List[List[Any]] = []
    specs: List[Tuple[Any, tuple]] = []
    for a, k in decoded:
        dyn, spec = _fused._split_inputs(a, k)
        dyn_lists.append(dyn)
        specs.append(spec)
    states = {label: _sds_tree(m.state_pytree()) for label, m in chain}
    topo = tuple((label, id(m)) for label, m in chain)
    if scan:
        sig: Any = ("scan", count, _fused._aval_key(dyn_lists[0]), _fused._static_key(specs[0]))
    else:
        sig = tuple(
            (_fused._aval_key(dyn), _fused._static_key(spec))
            for dyn, spec in zip(dyn_lists, specs)
        )
    key = (topo, _fused._aval_key(states), sig)
    if key in target._cache or key in target._broken_keys:
        return False
    if scan:
        step = target._build_scan_step(chain, specs[0], is_collection)
    else:
        step = target._build_step(chain, specs, is_collection)
    jitted = jax.jit(step, donate_argnums=(0,))
    # suppress obs during the one-time trace, exactly like the live tick path
    prev = _obs._ENABLED
    _obs._ENABLED = False
    try:
        compiled = jitted.lower(states, dyn_lists).compile()
    finally:
        _obs._ENABLED = prev
    target._cache[key] = compiled
    return True


def _prewarm_rank(entry: Dict[str, Any]) -> bool:
    from metrics_tpu.ops import clf_curve as _clf
    from metrics_tpu.ops import rank as _rank

    op = entry["op"]
    if op in _RANK_HIST_REPLAY_OPS:
        fn = getattr(_rank, op)
    elif op in _RANK_REPLAY_OPS:
        fn = getattr(_clf, op)
    else:
        _warn_skip(f"unknown rank op {op!r}")
        return False
    arrays = [
        jnp.zeros(tuple(a["shape"]), np.dtype(a["dtype"]))
        for a in (dict(e) for e in entry["inputs"])
        if a.get("t") == "aval"
    ]
    if len(arrays) != len(entry["inputs"]):
        raise _Unrecordable("rank entry holds non-aval inputs")
    kwargs: Dict[str, Any] = {}
    if entry.get("max_fpr") is not None:
        kwargs["max_fpr"] = entry["max_fpr"]
    tier = entry.get("tier")
    bits = entry.get("bits")
    if bits is not None:
        if op == "hist_class_counts":
            kwargs["bits"] = int(bits)
        elif op in _RANK_REPLAY_OPS and tier == "sketch":
            # forced sketch replay compiles the bracket kernels at this depth
            kwargs["tolerance_bits"] = int(bits)
    # the rank kernels are ordinary jits: one abstract-shaped call both warms
    # the disk cache and populates the in-process jit dispatch cache, so the
    # first real request neither traces nor compiles
    if tier is not None:
        with _rank.force_tier(tier):
            fn(*arrays, **kwargs)
    else:
        fn(*arrays, **kwargs)
    return True


def _prewarm_entry(target: Any, entry: Dict[str, Any]) -> bool:
    engine = entry.get("engine")
    if engine == "fused":
        return _prewarm_fused(target, entry)
    if engine == "fleet":
        return _prewarm_fleet(target, entry)
    if engine == "ingest":
        return _prewarm_ingest(target, entry)
    if engine == "rank":
        return _prewarm_rank(entry)
    _warn_skip(f"unknown manifest engine {engine!r} (schema drift?)")
    return False


def prewarm(target: Any, manifest: Any) -> Dict[str, Any]:
    """Replay a warm manifest against ``target``, seeding every matching
    engine's in-memory executable cache via ``.lower().compile()``.

    ``target`` is the live object the replica will serve — a fused
    ``MetricCollection``, a fleet ``Metric``, or an ``IngestQueue`` (rank
    entries are module-level and replay regardless of target). Entries that do
    not match the target are skipped silently, so one manifest can be replayed
    once per serving object. ``manifest`` is a path or an already-loaded dict.

    Never raises: every failure mode (unreadable file, schema drift, stale
    jax version, per-entry replay errors, injected ``excache.prewarm``
    faults) warns and degrades to lazy first-use compilation. Returns a
    report dict ``{entries, compiled, skipped, failed, seconds}`` — also
    surfaced by ``state_report()`` and the ``excache_prewarm`` flight event.
    """
    global _LAST_PREWARM
    t0 = time.perf_counter()
    report = {"entries": 0, "compiled": 0, "skipped": 0, "failed": 0, "seconds": 0.0}
    if isinstance(manifest, (str, os.PathLike)):
        try:
            manifest = load_manifest(str(manifest))
        except Exception as err:  # noqa: BLE001 — startup must not fail
            _warn_skip(f"unreadable manifest ({type(err).__name__}: {err})")
            report["seconds"] = time.perf_counter() - t0
            _LAST_PREWARM = report
            return report
    entries = manifest.get("entries") if isinstance(manifest, dict) else None
    if not isinstance(entries, list):
        _warn_skip("manifest has no entry list (schema drift?)")
        entries = []
    elif manifest.get("schema") != SCHEMA_VERSION:
        _warn_skip(
            f"manifest schema {manifest.get('schema')!r} != supported {SCHEMA_VERSION}"
        )
        report["skipped"] = len(entries)
        entries = []
    elif manifest.get("jax_version") != jax.__version__:
        # a different jax version keys different XLA cache entries anyway:
        # replaying would trigger true compiles at startup, not warm reuse
        _warn_skip(
            f"manifest recorded under jax {manifest.get('jax_version')!r}, running"
            f" {jax.__version__!r}"
        )
        report["skipped"] = len(entries)
        entries = []
    for entry in entries:
        report["entries"] += 1
        try:
            if _fault._SCHEDULE is not None:
                _fault.fire(
                    "excache.prewarm",
                    engine=entry.get("engine"),
                    digest=entry.get("key_digest"),
                )
            ok = _prewarm_entry(target, entry)
        except Exception as err:  # noqa: BLE001 — degrade to lazy compile
            report["failed"] += 1
            _STATS["prewarm_failures"] += 1
            _fused._warn_degrade_once(
                "excache.prewarm",
                err,
                "the entry's executable lazily compiles on first use instead.",
            )
            if _obs._ENABLED and _obs_flight._RING is not None:
                _obs_flight.record(
                    "degrade",
                    site="excache.prewarm",
                    engine=entry.get("engine"),
                    error=f"{type(err).__name__}: {str(err).splitlines()[0][:120]}",
                )
            continue
        if ok:
            report["compiled"] += 1
            _STATS["prewarmed"] += 1
            if _obs._ENABLED:
                _obs.REGISTRY.inc("excache", "prewarmed")
        else:
            report["skipped"] += 1
    report["seconds"] = time.perf_counter() - t0
    _LAST_PREWARM = report
    if _obs._ENABLED:
        _obs.REGISTRY.observe_duration("excache", "prewarm_s", report["seconds"])
        if _obs_flight._RING is not None:
            _obs_flight.record("excache_prewarm", **report)
    return report


def last_prewarm() -> Optional[Dict[str, Any]]:
    """Report of the most recent :func:`prewarm` call in this process."""
    return None if _LAST_PREWARM is None else dict(_LAST_PREWARM)
