"""``python -m metrics_tpu.serve`` — run a :class:`MetricsServer` from a JSON
config file.

The process speaks a line protocol on stdout (one JSON object per line, so an
orchestrator — or the subprocess acceptance test — can follow the lifecycle
without scraping logs):

``{"event": "serving", "prom": [host, port], ...}``
    The health endpoint is live; the expensive ``restore → prewarm`` part of
    startup is about to run (``/healthz`` answers ``503 starting``).
``{"event": "ready", "restored": {...}, "first_request_compiles": 0, ...}``
    Startup finished: per-collection restored steps and update counts, the
    prewarm report, and — when ``--probe`` ran — how many true XLA compiles
    the deterministic first request cost (the cold-start-free acceptance
    number: exactly 0 after a restart with a warm manifest).
``{"event": "draining", ...}`` / ``{"event": "stopped", ...}``
    Shutdown: the final line carries the committed per-collection bookkeeping
    (update counts, checkpoint steps), queue statistics, and throughput.

SIGTERM/SIGINT request a graceful drain: the handler only sets an event
(async-signal-safe); the main thread runs ``drain → ckpt flush +
warm-manifest write → stop``. ``--wait-stdin`` gates the ``starting → ready``
and ``draining → stopped`` transitions on reading one newline from stdin, so
a parent process can observe each ``/healthz`` phase deterministically.
``--drive`` generates deterministic synthetic traffic (seeded, fixed batch
shape) — the smoke mode the kill-and-restart acceptance test and
``bench.py --serve`` build on.
"""
import argparse
import json
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from metrics_tpu.obs import registry as _obs
from metrics_tpu.serve import excache as _excache
from metrics_tpu.serve.server import MetricsServer, load_config

#: the graceful-shutdown request flag; the signal handler ONLY sets it
#: (Event.set is async-signal-safe and atomic — see analysis/race TMR-HANDLER)
_STOPPING = threading.Event()


def _on_signal(signum: int, frame: Any) -> None:
    _STOPPING.set()


def _emit(event: str, **kv: Any) -> None:
    print(json.dumps({"event": event, **kv}, sort_keys=True, default=str), flush=True)


def _batch(rng: np.random.RandomState, rows: int, fleet_size: Optional[int]) -> Dict[str, Any]:
    """One deterministic synthetic update batch: a (preds, target) pair in
    [0, 1] with a constant shape, so steady-state traffic re-uses one
    executable signature per coalesce depth."""
    preds = rng.random_sample(rows).astype(np.float32)
    target = rng.random_sample(rows).astype(np.float32)
    out: Dict[str, Any] = {"args": (preds, target)}
    if fleet_size is not None:
        out["stream_ids"] = rng.randint(0, fleet_size, size=rows).astype(np.int32)
    return out


def _jsonable(value: Any) -> Any:
    if hasattr(value, "tolist"):
        return np.asarray(value).tolist()
    return value


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m metrics_tpu.serve",
        description="Run a MetricsServer from a declarative JSON config.",
    )
    parser.add_argument("--config", required=True, help="path to the JSON server config")
    parser.add_argument("--drive", action="store_true", help="generate deterministic synthetic traffic")
    parser.add_argument("--duration", type=float, default=0.0,
                        help="drive for this many seconds (0 = until SIGTERM)")
    parser.add_argument("--rows", type=int, default=64, help="rows per synthetic batch")
    parser.add_argument("--seed", type=int, default=0, help="seed for the synthetic traffic")
    parser.add_argument("--probe", dest="probe", action="store_true", default=True,
                        help="send one deterministic first request per collection after ready (default)")
    parser.add_argument("--no-probe", dest="probe", action="store_false")
    parser.add_argument("--wait-stdin", action="store_true",
                        help="gate starting->ready and draining->stopped on one stdin line each")
    args = parser.parse_args(argv)

    config = load_config(args.config)
    _obs.enable()
    from metrics_tpu.obs import health as _health_mod

    _health_mod.enable()
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    def _gate() -> None:
        if args.wait_stdin:
            sys.stdin.readline()

    def _on_starting(server: MetricsServer) -> None:
        _emit("serving", server=server.name, prom=server._prom_address,
              collections=list(server._order))
        _gate()

    def _on_draining(server: MetricsServer) -> None:
        _emit("draining", server=server.name)
        _gate()

    server = MetricsServer(
        config, start=False, starting_hook=_on_starting, draining_hook=_on_draining
    )
    enqueued: Dict[str, int] = {}
    t_start = time.monotonic()
    try:
        server.start()
        restored = {n: server._collections[n].restored_step for n in server._order}
        restored_counts = {n: server._collections[n].update_count() for n in server._order}
        first_request_compiles = None
        if args.probe:
            before = _excache.stats().get("compiles", 0)
            rng = np.random.RandomState(args.seed)
            for name in server._order:
                spec = server._collections[name].spec
                batch = _batch(rng, args.rows, spec.fleet_size)
                server.enqueue(name, *batch["args"], stream_ids=batch.get("stream_ids"))
                server.compute(name)
            first_request_compiles = _excache.stats().get("compiles", 0) - before
        _emit(
            "ready",
            server=server.name,
            restored=restored,
            restored_update_counts=restored_counts,
            first_request_compiles=first_request_compiles,
            prewarm=_excache.last_prewarm(),
            startup_s=server.startup_s,
        )
        if args.drive:
            rng = np.random.RandomState(args.seed + 1)
            deadline = t_start + args.duration if args.duration > 0 else None
            while not _STOPPING.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                for name in server._order:
                    spec = server._collections[name].spec
                    batch = _batch(rng, args.rows, spec.fleet_size)
                    server.enqueue(name, *batch["args"], stream_ids=batch.get("stream_ids"))
                    enqueued[name] = enqueued.get(name, 0) + 1
        else:
            while not _STOPPING.is_set():
                _STOPPING.wait(0.1)
        elapsed = time.monotonic() - t_start
        report = server.drain()
        queue_stats = {n: dict(server._collections[n].queue.stats) for n in server._order}
        results = {n: {k: _jsonable(v) for k, v in server.compute(n).items()} for n in server._order}
        snapshot = _obs.snapshot()
        total = sum(enqueued.values())
        _emit(
            "stopped",
            server=server.name,
            committed=report,
            enqueued=enqueued,
            enqueues_per_s=round(total / elapsed, 2) if elapsed > 0 else None,
            queue_stats=queue_stats,
            launches_eq_ticks={
                n: queue_stats[n]["launches"] == queue_stats[n]["ticks"] for n in server._order
            },
            dispatches=snapshot.get("ingest", {}).get("dispatches", 0),
            excache=_excache.stats(),
            results=results,
            elapsed_s=round(elapsed, 3),
        )
        return 0
    finally:
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
