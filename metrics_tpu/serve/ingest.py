"""Async ingestion tier: a host-side staging ring + coalesced one-launch ticks.

The synchronous serving path pays one host dispatch per ``update()`` call —
measured host-bound at ~0.5 ms/call on CPU even with the fused and fleet tiers
already at one launch per step. At traffic scale that per-call floor *is* the
throughput ceiling. This module removes it by decoupling arrival from
accumulation:

- :meth:`IngestQueue.enqueue` appends the batch (args + kwargs, including
  ``stream_ids``) to a bounded host-side ring (:class:`obs.ring.Ring` — the
  same ring discipline as the flight recorder) and returns immediately. No
  device work, no jit cache lookup, no dispatch.
- A background tick thread drains everything pending and applies it as **one
  compiled launch per tick**: the pending batches are chained through the
  target's pure ``local_update`` transitions inside a single donated
  executable, in enqueue order. Chaining — never row concatenation — is what
  makes the result **bit-equal** to applying the same batches synchronously:
  each batch keeps its own shapes and reduction order, only the host dispatch
  is amortized. (Concatenating rows re-associates the float reductions and is
  *not* bitwise stable; this module never does it.)

Correctness contract:

- **Bit-equal**: after ``flush()``, the target's state is bitwise identical to
  the state produced by calling ``target.update`` synchronously with the same
  batches in the same order.
- **Bounded backpressure**: a full ring either blocks the producer
  (``backpressure="block"``), evicts the oldest pending batch
  (``"drop_oldest"``, counted in ``stats["dropped"]``), or raises
  :class:`IngestBackpressureError` (``"raise"``).
- **Staleness bound on reads**: :meth:`IngestQueue.compute` flushes pending
  batches before reading (exact), unless ``max_staleness_s`` allows returning
  the last ticked state. Reading the target directly requires an explicit
  ``flush()`` first — same rule the checkpoint writer follows
  (``ckpt.save_checkpoint`` flushes any active queue for the object being
  saved, so checkpoints never miss enqueued rows).
- **Clean shutdown**: ``close(drain=True)`` (and the context-manager exit)
  stops the tick thread and applies everything still pending.
- **Graceful degradation**: a failed tick — including an injected
  ``ingest.tick`` fault — falls back to applying the pending batches
  synchronously through the public ``update`` path. No rows are lost; the
  demotion is counted (``stats["degrades"]``, obs ``ingest.degrades``) and
  recorded as a ``degrade`` flight event.

Donation interaction: the chained launch donates the gathered state tree, so
it reuses the fused engine's snapshot-before-donate machinery
(``_secure_ckpt_snapshots`` materializes in-flight async-checkpoint snapshot
entries) and its donation guard (default-aliased and duplicated buffers are
copied before the donating call).

Eligibility mirrors the fused engine: a target (or compute-group leader)
whose update cannot be chained — host-side update, list ('cat') state without
``cat_capacity``, ``nan_policy`` quarantine, wrapper metrics, ...
(``fused.fusion_fallback_reason``) — is still served by the queue, but its
pending batches are applied eagerly inside the tick (one dispatch per batch,
full synchronous semantics preserved). ``--ingest`` in ``bench.py`` measures
the coalesced path; ``docs/source/pages/ingestion.rst`` documents when *not*
to put a queue in front of a metric.
"""
import itertools
import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.core.fused import (
    FusedCollectionUpdate,
    _aval_key,
    _merge_inputs,
    _split_inputs,
    _static_key,
    _warn_degrade_once,
    fusion_fallback_reason,
)
from metrics_tpu.fault import inject as _fault
from metrics_tpu.obs import flight as _obs_flight
from metrics_tpu.obs import flow as _obs_flow
from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _obs
from metrics_tpu.obs.ring import Ring
from metrics_tpu.utils.concurrency import locked_by

__all__ = [
    "IngestBackpressureError",
    "IngestQueue",
    "active_queues",
    "flush_for",
    "max_queue_depth",
]

#: every live, unclosed queue — consulted by ``ckpt.save_checkpoint``
#: (flush-before-save) and ``obs.prom.render`` (tm_ingest_* gauges). Weak so
#: a dropped queue never outlives its last strong reference.
_ACTIVE: "weakref.WeakSet[IngestQueue]" = weakref.WeakSet()

_NAME_SEQ = itertools.count()

_BACKPRESSURE_POLICIES = ("block", "drop_oldest", "raise")


class IngestBackpressureError(RuntimeError):
    """The staging ring is full and the policy refuses the batch: raised
    immediately under ``backpressure="raise"``, or after ``block_timeout_s``
    under ``backpressure="block"``."""


class _DonatedStateLost(RuntimeError):
    """A chained launch failed AFTER consuming its donated inputs: the live
    state cannot be re-pointed and a synchronous retry would double-apply.
    Never degraded; stashed and re-raised at the next host-call boundary."""

    def __init__(self, queue: str, cause: BaseException) -> None:
        super().__init__(
            f"IngestQueue {queue!r}: coalesced launch failed after donation"
            f" consumed the state buffers ({type(cause).__name__}: {cause});"
            " the accumulated state is unrecoverable — reset the target"
        )
        self.__cause__ = cause


class _Entry:
    """One enqueued batch: inputs verbatim plus arrival bookkeeping.

    ``flow`` is the tmflow record minted at admission (``obs/flow.py``) —
    ``None`` whenever tracing is off or the flow was sampled out."""

    __slots__ = ("args", "kwargs", "rows", "t_enq", "flow")

    def __init__(self, args: Tuple, kwargs: Dict, rows: int, t_enq: float) -> None:
        self.args = args
        self.kwargs = kwargs
        self.rows = rows
        self.t_enq = t_enq
        self.flow = None


def _count_rows(args: Tuple, kwargs: Dict) -> int:
    """Leading dim of the first array-ish input — the coalesced_rows unit."""
    for value in itertools.chain(args, kwargs.values()):
        shape = getattr(value, "shape", None)
        if shape:
            return int(shape[0])
    return 1


class IngestQueue:
    """Bounded async staging for a ``Metric`` or ``MetricCollection``.

    Args:
        target: the metric or collection every enqueued batch is applied to.
            The queue never copies it — reads of ``target`` stay live, which
            is why direct reads require :meth:`flush` first.
        capacity: staging-ring size (pending batches, not rows).
        tick_interval_s: how long the background thread sleeps between drain
            attempts; an enqueue also wakes it immediately.
        backpressure: ``"block"`` | ``"drop_oldest"`` | ``"raise"`` — what a
            full ring does to the producer (see module docstring).
        block_timeout_s: upper bound on a blocked producer's wait before
            :class:`IngestBackpressureError`.
        max_staleness_s: when set, :meth:`compute` may serve the last ticked
            state instead of flushing, as long as the newest applied tick is
            at most this old. ``None`` (default) = always flush-before-read.
        max_coalesce: most batches chained into one launch; a deeper backlog
            drains in successive launches. Bounds both the chained program
            length and the compile-cache variety.
        name: label used in obs counters, flight events, health latency keys
            and ``tm_ingest_*`` Prometheus gauges.
        start: start the background tick thread (``False`` = manual ticking
            via :meth:`flush`, the deterministic mode tests and the chaos
            sweep use).
    """

    def __init__(
        self,
        target: Any,
        *,
        capacity: int = 1024,
        tick_interval_s: float = 0.005,
        backpressure: str = "block",
        block_timeout_s: float = 30.0,
        max_staleness_s: Optional[float] = None,
        max_coalesce: int = 128,
        name: Optional[str] = None,
        start: bool = True,
    ) -> None:
        if backpressure not in _BACKPRESSURE_POLICIES:
            raise ValueError(
                f"backpressure must be one of {_BACKPRESSURE_POLICIES}, got {backpressure!r}"
            )
        if max_coalesce < 1:
            raise ValueError(f"max_coalesce must be >= 1, got {max_coalesce}")
        self.target = target
        self.name = name or f"{type(target).__name__}-{next(_NAME_SEQ)}"
        self.backpressure = backpressure
        self.block_timeout_s = float(block_timeout_s)
        self.max_staleness_s = max_staleness_s
        self.max_coalesce = int(max_coalesce)
        self.tick_interval_s = float(tick_interval_s)

        self._ring = Ring(capacity)
        # producer-side lock/condvar: admission checks and the block policy
        self._admit = threading.Condition(threading.Lock())
        # one tick at a time: background thread, flush(), and close() serialize
        self._tick_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._closed = False
        #: first unrecoverable apply error; re-raised at the next host boundary
        self._error: Optional[BaseException] = None

        # chained-launch executable cache: signature key -> compiled step
        self._cache: Dict[Tuple, Any] = {}
        self._broken_keys: set = set()

        self.stats: Dict[str, int] = {
            "enqueued": 0,
            "ticks": 0,
            "launches": 0,
            "coalesced_rows": 0,
            "dropped": 0,
            "degrades": 0,
            "eager_entries": 0,
            "max_depth": 0,
        }
        self._last_apply_t = time.monotonic()

        self._thread: Optional[threading.Thread] = None
        _ACTIVE.add(self)
        if start:
            self._thread = threading.Thread(
                target=self._loop, name=f"tm-ingest/{self.name}", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------- producer

    @property
    def depth(self) -> int:
        """Batches currently staged (pending, not yet applied)."""
        return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.capacity

    def enqueue(self, *args: Any, **kwargs: Any) -> None:
        """Stage one batch; returns without touching the device.

        Accepts exactly what ``target.update`` accepts (``stream_ids=`` rides
        along for fleet metrics). Admission is the only place backpressure
        acts; see the class docstring for the three policies.
        """
        if self._closed:
            raise RuntimeError(f"IngestQueue {self.name!r} is closed")
        self._reraise()
        if _fault._SCHEDULE is not None:
            _fault.fire("ingest.enqueue", queue=self.name, depth=len(self._ring))
        # **kwargs already materialized a fresh dict for this call — no copy
        entry = _Entry(args, kwargs, _count_rows(args, kwargs), time.monotonic())
        if _obs._ENABLED and _obs_flow._TRACER is not None:
            entry.flow = _obs_flow._TRACER.mint(
                self.name,
                id(self.target),
                rows=entry.rows,
                streams=_obs_flow.host_stream_ids(kwargs.get("stream_ids")),
            )
        with self._admit:
            if self._ring.full:
                if self.backpressure == "raise":
                    raise IngestBackpressureError(
                        f"IngestQueue {self.name!r} is full"
                        f" ({self._ring.capacity} pending batches) and"
                        " backpressure='raise'; flush(), widen capacity, or"
                        " pick 'block'/'drop_oldest'"
                    )
                if self.backpressure == "drop_oldest":
                    evicted = self._ring.pop_oldest()
                    if evicted is not None:
                        self.stats["dropped"] += 1
                        self._note_dropped(evicted, site="backpressure")
                else:  # block
                    deadline = time.monotonic() + self.block_timeout_s
                    while self._ring.full:
                        self._wake.set()
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or not self._admit.wait(remaining):
                            raise IngestBackpressureError(
                                f"IngestQueue {self.name!r}: producer blocked"
                                f" > {self.block_timeout_s}s on a full ring"
                                " (is the tick thread running?)"
                            )
                        self._reraise()
            self._ring.append(entry)
            self.stats["enqueued"] += 1
            depth = len(self._ring)
            if depth > self.stats["max_depth"]:
                self.stats["max_depth"] = depth
        if _obs._ENABLED:
            _obs.REGISTRY.inc("ingest", "enqueued")
        if self._thread is not None:  # nobody waits on _wake in manual mode
            self._wake.set()

    # ------------------------------------------------------------- reading

    def flush(self) -> None:
        """Apply everything pending; on return the target state is exact."""
        with self._tick_lock:
            self._run_ticks()
        self._reraise()

    def tick(self, limit: Optional[int] = None) -> int:
        """One bounded drain-and-apply; returns the number of entries applied.

        The hand-off point for an *external* ticker: ``flush()`` drains to
        empty, which is the wrong primitive when one thread shares its tick
        budget across several queues (a saturated queue would monopolize the
        round). ``tick(limit=n)`` applies at most ``min(n, max_coalesce)``
        staged batches as one coalesced launch and returns, so a deficit
        round-robin scheduler (``serve.server.MetricsServer``) can hold every
        queue to its per-round quantum. Error semantics match the background
        tick exactly: apply failures degrade or stash, never raise here —
        the stashed error surfaces at the next host-call boundary.
        """
        budget = self.max_coalesce if limit is None else min(int(limit), self.max_coalesce)
        if budget < 1:
            return 0
        with self._tick_lock:
            with self._admit:
                entries = self._ring.drain(limit=budget)
                if entries:
                    self._admit.notify_all()
            if not entries:
                return 0
            try:
                self._apply(entries)
            except BaseException as err:  # noqa: BLE001 — same stash as _run_ticks
                if self._error is None:
                    self._error = err
        return len(entries)

    def compute(self, **kwargs: Any) -> Any:
        """Staleness-bounded read of ``target.compute()``.

        Default (``max_staleness_s=None``): flush-before-read — pending
        batches are applied first and the value is exact. With a staleness
        budget, pending batches are left staged when the last applied tick is
        fresh enough, and the *last ticked state* is read instead.
        """
        self._reraise()
        if len(self._ring):
            stale_ok = (
                self.max_staleness_s is not None
                and (time.monotonic() - self._last_apply_t) <= self.max_staleness_s
            )
            if not stale_ok:
                self.flush()
        if _obs._ENABLED and _obs_flow._TRACER is not None:
            # readback stage: the compute() host transfer, stamped onto the
            # completed-but-unread flows this read serves
            t0 = time.perf_counter()
            value = self.target.compute(**kwargs)
            trc = _obs_flow._TRACER
            if trc is not None:
                trc.note_readback(self.name, time.perf_counter() - t0)
            return value
        return self.target.compute(**kwargs)

    # ------------------------------------------------------------ lifecycle

    def close(self, drain: bool = True) -> None:
        """Stop the tick thread; ``drain=True`` applies everything pending,
        ``drain=False`` discards it (counted in ``stats['dropped']``)."""
        if self._closed:
            return
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, self.block_timeout_s))
            self._thread = None
        with self._tick_lock:
            if drain:
                self._run_ticks()
            else:
                discarded = self._ring.drain()
                if discarded:
                    self.stats["dropped"] += len(discarded)
                    for e in discarded:
                        self._note_dropped(e, site="close")
        self._closed = True
        _ACTIVE.discard(self)
        self._reraise()

    def __enter__(self) -> "IngestQueue":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close(drain=True)

    def _reraise(self) -> None:
        err = self._error
        if err is not None:
            self._error = None
            raise err

    def _note_dropped(self, e: _Entry, site: str) -> None:
        """Attribute one evicted batch (drop_oldest backpressure or a
        drain=False close). A dropped batch previously vanished from the
        health sketch entirely — enqueue→applied latency is only measured at
        tick time — so drops get their own ``flow_dropped`` flight event and
        an ``ingest.dropped_latency`` observation, and the batch's flow (when
        traced) closes as dropped instead of orphaning."""
        waited_s = time.monotonic() - e.t_enq
        if _obs._ENABLED:
            _obs.REGISTRY.inc("ingest", "dropped")
            if _obs_flight._RING is not None:
                _obs_flight.record(
                    "flow_dropped",
                    queue=self.name,
                    site=site,
                    rows=e.rows,
                    waited_us=round(waited_s * 1e6, 1),
                    flow_id=None if e.flow is None else e.flow.flow_id,
                )
        mon = _health._MONITOR
        if mon is not None:
            mon.observe_latency("ingest.dropped_latency", self.name, waited_s)
        if e.flow is not None:
            trc = _obs_flow._TRACER
            if trc is not None:
                trc.close_dropped(e.flow)

    # ------------------------------------------------------------- ticking

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.tick_interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            if not len(self._ring):
                continue
            with self._tick_lock:
                self._run_ticks()

    @locked_by("IngestQueue._tick_lock")
    def _run_ticks(self) -> None:
        """Drain-and-apply until the ring is empty (caller holds _tick_lock).

        Never raises: apply failures degrade to the synchronous path, and an
        unrecoverable error is stashed for the next host-call boundary
        (``enqueue``/``flush``/``compute``/``close``) — a background thread
        has nowhere useful to raise.
        """
        while True:
            with self._admit:
                entries = self._ring.drain(limit=self.max_coalesce)
                if entries:
                    self._admit.notify_all()
            if not entries:
                return
            try:
                self._apply(entries)
            except BaseException as err:  # noqa: BLE001 — see docstring
                if self._error is None:
                    self._error = err
                return

    def _apply(self, entries: List[_Entry]) -> None:
        """One tick: chain the drained batches into one donated launch."""
        launches_before = self.stats["launches"]
        trc = _obs_flow._TRACER if _obs._ENABLED else None
        if trc is not None:
            flows = [e.flow for e in entries if e.flow is not None]
            if flows:
                trc.stamp_drain(flows)
        if _fault._SCHEDULE is not None:
            try:
                _fault.fire("ingest.tick", queue=self.name, entries=len(entries))
            except _fault.InjectedFaultError as err:
                self._degrade(entries, err)
                self._finish_tick(entries, launched=0)
                return
        try:
            launched = self._apply_coalesced(entries)
        except _DonatedStateLost:
            # the state is gone; degrading would double-apply — propagate,
            # but close the traced flows first (an unrecoverable tick must
            # not leave orphaned spans behind)
            if trc is not None:
                for e in entries:
                    if e.flow is not None and not e.flow.closed:
                        trc.close_degraded(e.flow)
            raise
        except Exception as err:  # noqa: BLE001 — eager is always correct
            # anything else (trace/compile/shape failures) degrades cleanly:
            # the donation guard kept the pre-launch buffers intact
            self._degrade(entries, err)
            launched = self.stats["launches"] - launches_before
        self._finish_tick(entries, launched=launched)

    def _finish_tick(self, entries: List[_Entry], launched: int) -> None:
        now = time.monotonic()
        rows = sum(e.rows for e in entries)
        self.stats["ticks"] += 1
        self.stats["coalesced_rows"] += rows
        self._last_apply_t = now
        if _obs._ENABLED:
            _obs.REGISTRY.inc("ingest", "ticks")
            _obs.REGISTRY.inc("ingest", "coalesced_rows", rows)
            if _obs_flight._RING is not None:
                _obs_flight.record(
                    "ingest_tick",
                    queue=self.name,
                    entries=len(entries),
                    rows=rows,
                    launches=launched,
                )
        mon = _health._MONITOR
        if mon is not None:
            for e in entries:
                mon.observe_latency("ingest", self.name, now - e.t_enq)
        if _obs._ENABLED:
            trc = _obs_flow._TRACER
            if trc is not None:
                # anything the tick neither launched nor explicitly closed
                # (e.g. an eager-only plan) ends here — no orphaned flows
                leftovers = [
                    e.flow
                    for e in entries
                    if e.flow is not None and not e.flow.dispatched and not e.flow.closed
                ]
                if leftovers:
                    trc.close_now(leftovers)

    # ----------------------------------------------------- degradation path

    def _degrade(self, entries: List[_Entry], err: Exception) -> None:
        """Apply the pending batches synchronously — no rows lost."""
        self.stats["degrades"] += 1
        if _obs._ENABLED:
            _obs.REGISTRY.inc("ingest", "degrades")
            if _obs_flight._RING is not None:
                _obs_flight.record(
                    "degrade",
                    site="ingest.tick",
                    queue=self.name,
                    entries=len(entries),
                    error=type(err).__name__,
                )
        _warn_degrade_once(
            "ingest.tick",
            err,
            "the pending batches were applied synchronously (no rows lost).",
        )
        trc = _obs_flow._TRACER if _obs._ENABLED else None
        for e in entries:
            # push the originating flow as the ambient context so the fused
            # engine attributes the synchronous re-apply to it instead of
            # minting a second flow for the same batch
            if trc is not None and e.flow is not None:
                _obs_flow._push(e.flow)
            try:
                self.target.update(*e.args, **e.kwargs)
            except BaseException as apply_err:  # noqa: BLE001 — keep draining
                # a rejected batch (quarantine, user error) is the same outcome
                # the synchronous caller would have seen; stash the first one
                # and keep the later batches flowing
                if self._error is None:
                    self._error = apply_err
            finally:
                if trc is not None and e.flow is not None:
                    _obs_flow._pop()
                    if not e.flow.closed:
                        trc.close_degraded(e.flow)

    # ------------------------------------------------------- coalesced path

    def _plan(self) -> Tuple[List[Tuple[str, Any]], List[Tuple[str, Any]], bool]:
        """Resolve the target into (chainable leaders, eager leaders).

        Returns ``(chain, eager, is_collection)`` where each element is a
        ``(label, metric)`` pair. For a bare ``Metric`` the label is the
        metric itself under one key; for a ``MetricCollection`` one leader
        per compute group (members re-alias the leader state afterwards,
        exactly like the fused engine).
        """
        groups = getattr(self.target, "_groups", None)
        if groups is None:
            reason = fusion_fallback_reason(self.target, (self.target,))
            if reason is None:
                return [("__target__", self.target)], [], False
            return [], [("__target__", self.target)], False
        self.target._split_diverged_members()
        chain: List[Tuple[str, Any]] = []
        eager: List[Tuple[str, Any]] = []
        for cg in self.target._groups.values():
            names = list(cg)
            leader = self.target._modules[names[0]]
            members = [self.target._modules[n] for n in names]
            if fusion_fallback_reason(leader, members) is None:
                chain.append((names[0], leader))
            else:
                eager.append((names[0], leader))
        return chain, eager, True

    def _apply_coalesced(self, entries: List[_Entry]) -> int:
        """Apply one drained chunk; returns the number of chained launches.

        Chainable leaders advance through ONE compiled, donated launch that
        threads every batch (in enqueue order) through their pure
        ``local_update`` transitions. Non-chainable leaders fall back to one
        eager update per batch — synchronous semantics, still inside the tick.
        """
        chain, eager, is_collection = self._plan()
        launched = 0
        if chain:
            self._launch_chain(chain, entries, filter_kwargs=is_collection)
            launched = 1
        trc = _obs_flow._TRACER if _obs._ENABLED else None
        for _label, leader in eager:
            self.stats["eager_entries"] += len(entries)
            for e in entries:
                if trc is not None and e.flow is not None:
                    _obs_flow._push(e.flow)
                try:
                    if is_collection:
                        leader.update(*e.args, **leader._filter_kwargs(**e.kwargs))
                    else:
                        leader.update(*e.args, **e.kwargs)
                finally:
                    if trc is not None and e.flow is not None:
                        _obs_flow._pop()
        if is_collection:
            self.target._state_is_copy = False
            self.target._compute_groups_create_state_ref()
        return launched

    def _build_step(
        self,
        chain: List[Tuple[str, Any]],
        specs: List[Tuple[Any, tuple]],
        filter_kwargs: bool,
    ) -> Callable:
        def step(states: Dict[str, Any], dyn_lists: List[List[Any]]) -> Dict[str, Any]:
            states = dict(states)
            for dyn, spec in zip(dyn_lists, specs):
                a, k = _merge_inputs(dyn, spec)
                for label, m in chain:
                    kw = m._filter_kwargs(**k) if filter_kwargs else k
                    with jax.named_scope(f"tm.ingest/{type(m).__name__}"):
                        states[label] = m.local_update(states[label], *a, **kw)
            return states

        return step

    def _build_scan_step(
        self,
        chain: List[Tuple[str, Any]],
        spec0: Tuple[Any, tuple],
        filter_kwargs: bool,
    ) -> Callable:
        """Uniform-signature variant: stack the per-entry leaves inside the
        trace and ``lax.scan`` one update-transition body over them. Trace and
        compile cost is O(1) in the number of coalesced entries (the unrolled
        step is O(n)), and the scan body executes the exact per-batch update
        program in enqueue order, so the bit-equality contract is unchanged.
        """

        def body(states: Dict[str, Any], dyn: Tuple) -> Tuple[Dict[str, Any], None]:
            a, k = _merge_inputs(list(dyn), spec0)
            states = dict(states)
            for label, m in chain:
                kw = m._filter_kwargs(**k) if filter_kwargs else k
                with jax.named_scope(f"tm.ingest/{type(m).__name__}"):
                    states[label] = m.local_update(states[label], *a, **kw)
            return states, None

        def step(states: Dict[str, Any], dyn_lists: List[List[Any]]) -> Dict[str, Any]:
            # stacking happens inside the launch: the tick stays ONE dispatch
            stacked = tuple(
                jnp.stack([dyn[i] for dyn in dyn_lists])
                for i in range(len(dyn_lists[0]))
            )
            states, _ = jax.lax.scan(body, states, stacked)
            return states

        return step

    @staticmethod
    def _uniform_signature(
        dyn_lists: List[List[Any]], specs: List[Tuple[Any, tuple]]
    ) -> bool:
        """True when every entry shares entry 0's structure, shapes, and
        dtypes — the steady-state serving shape, and the scan fast path's
        precondition (stacking requires congruent leaves)."""
        dyn0, spec0 = dyn_lists[0], specs[0]
        shapes0 = [(l.shape, l.dtype) for l in dyn0]
        try:
            for dyn, spec in zip(dyn_lists[1:], specs[1:]):
                if len(dyn) != len(dyn0) or spec != spec0:
                    return False
                for leaf, (shape, dtype) in zip(dyn, shapes0):
                    if leaf.shape != shape or leaf.dtype != dtype:
                        return False
        except Exception:  # noqa: BLE001 — exotic static __eq__: take the slow path
            return False
        return True

    def _launch_chain(
        self, chain: List[Tuple[str, Any]], entries: List[_Entry], filter_kwargs: bool
    ) -> None:
        trc = _obs_flow._TRACER if _obs._ENABLED else None
        flows = (
            [e.flow for e in entries if e.flow is not None] if trc is not None else []
        )
        if flows:
            trc.stamp_launch(flows)
        # split each batch into traced leaves + static spec (jit cache-key
        # semantics, same split the fused engine and retrace detector use)
        dyn_lists: List[List[Any]] = []
        specs: List[Tuple[Any, tuple]] = []
        for e in entries:
            dyn, spec = _split_inputs(e.args, e.kwargs)
            dyn_lists.append(dyn)
            specs.append(spec)
        scan = len(entries) > 1 and self._uniform_signature(dyn_lists, specs)

        # gather live leader states, shielding registered defaults from the
        # donation (same _protected_ids discipline as the fused engine)
        states: Dict[str, Any] = {}
        for label, m in chain:
            protected = FusedCollectionUpdate._protected_ids(m)

            def shield(leaf: Any, _protected: set = protected) -> Any:
                return leaf.copy() if id(leaf) in _protected else leaf

            states[label] = jax.tree_util.tree_map(shield, m.state_pytree())

        topo = tuple((label, id(m)) for label, m in chain)
        if scan:
            # uniform entries: entry 0's signature + the count keys them all
            sig = ("scan", len(entries), _aval_key(dyn_lists[0]), _static_key(specs[0]))
        else:
            sig = tuple(
                (_aval_key(dyn), _static_key(spec)) for dyn, spec in zip(dyn_lists, specs)
            )
        key = (topo, _aval_key(states), sig)
        if key in self._broken_keys:
            raise RuntimeError(
                f"ingest chain signature previously failed for {self.name!r}"
            )

        compiled = self._cache.get(key)
        if compiled is None:
            if scan:
                step = self._build_scan_step(chain, specs[0], filter_kwargs)
            else:
                step = self._build_step(chain, specs, filter_kwargs)
            jitted = jax.jit(step, donate_argnums=(0,))
            # suppress obs during the one-time trace: the wrapped update
            # closures fire counters per TRACE, not per launch
            prev = _obs._ENABLED
            _obs._ENABLED = False
            t_compile = time.perf_counter()
            try:
                compiled = jitted.lower(states, dyn_lists).compile()
            except Exception:
                self._broken_keys.add(key)
                raise
            finally:
                _obs._ENABLED = prev
            if flows:
                trc.add_compile(flows, (time.perf_counter() - t_compile) * 1e6)
            self._cache[key] = compiled
            # warm-manifest recording: the tick compile is the cold path, so
            # the sys.modules probe costs the steady-state tick nothing
            _excache = sys.modules.get("metrics_tpu.serve.excache")
            if _excache is not None and _excache.recording():
                _excache.record_ingest_compile(self, chain, scan, entries, key)

        donate_trees = [states]
        FusedCollectionUpdate._secure_ckpt_snapshots(donate_trees)
        FusedCollectionUpdate._donation_guard(donate_trees)
        (states,) = donate_trees

        try:
            new_states = compiled(states, dyn_lists)
        except Exception as err:
            if any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in jax.tree_util.tree_leaves(states)
            ):
                raise _DonatedStateLost(self.name, err) from err
            self._broken_keys.add(key)
            # live state untouched (the gathered tree held the donation-guard
            # copies); the caller degrades to the synchronous path
            for label, m in chain:
                m._load_state(states[label])
            raise

        if flows:
            # hand the flows to the completion watcher: it stamps device time
            # off `block_until_ready` on the freshly returned state buffers
            trc.dispatch(flows, jax.tree_util.tree_leaves(new_states))

        self.stats["launches"] += 1
        n = len(entries)
        for label, m in chain:
            m._load_state(new_states[label])
            m._update_count += n
            m._computed = None
            if _obs._ENABLED:
                _obs.REGISTRY.inc(type(m).__name__, "updates", n)
        if _obs._ENABLED:
            _obs.REGISTRY.inc("ingest", "launches")
            _obs.REGISTRY.inc("ingest", "dispatches")


# --------------------------------------------------------------- module API


def active_queues() -> List[IngestQueue]:
    """Every live, unclosed queue (weakly tracked)."""
    return [q for q in list(_ACTIVE) if not q._closed]


def flush_for(target: Any) -> int:
    """Flush every active queue attached to ``target``; returns the count.

    ``ckpt.save_checkpoint`` calls this (lazily, only when this module is
    already imported) before snapshotting, so a checkpoint of a queue-fronted
    metric never misses enqueued rows.
    """
    n = 0
    for q in active_queues():
        if q.target is target:
            q.flush()
            n += 1
    return n


def max_queue_depth() -> int:
    """Deepest staging backlog across active queues (the SLO input)."""
    return max((q.depth for q in active_queues()), default=0)
