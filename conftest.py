"""Root pytest config: pin the CPU backend for all test/doctest runs.

The environment forces ``JAX_PLATFORMS=axon`` (a single tunneled TPU); tests and
doctests must not compete for it. The env var cannot override the plugin — the config
call can. Real-TPU execution happens only via bench.py / __graft_entry__.py.
"""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

collect_ignore = ["reference"]
