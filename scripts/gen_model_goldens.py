#!/usr/bin/env python
"""Generate committed golden fixtures for the Inception / BERT / CLIP ports.

Published weights for these models cannot be committed or fetched here (no
network egress; the reference auto-downloads Inception/BERT/CLIP at runtime and
vendors only the LPIPS heads, which already have a real-weight golden). These
fixtures therefore pin the next-strongest chain, with zero skips and no heavy
deps at test time:

- torch-equivalence is proven by the differential tests
  (tests/unittests/image/test_inception_model.py, text/test_bert_jax_port.py,
  multimodal/test_clip_jax_port.py: torch/HF model -> state_dict -> our
  converter -> forward must match), and
- these goldens freeze that verified converter+forward behavior against
  committed outputs, so any later regression (resize change, layernorm eps,
  head transpose...) fails without torch/transformers installed.

Inception: weights are regenerated at test time from the numpy-seeded
``random_inception_params`` (23M params — too large to commit); only input
hashes and output slices are stored. BERT/CLIP: the tiny seeded HF state dicts
(~100-300 KB) ARE committed in the npz alongside the outputs, so the test
exercises the real ``params_from_state_dict`` converters on genuine HF-layout
state dicts.

Run from the repo root (needs transformers + torch once, to generate):

    python scripts/gen_model_goldens.py [out_dir]
"""
import os
import sys

import numpy as np


def gen_inception(out_dir):
    import jax.numpy as jnp

    from metrics_tpu.models.inception import inception_features, random_inception_params

    params = random_inception_params(0)
    rng = np.random.RandomState(7)
    img_299 = rng.randint(0, 256, (2, 3, 299, 299)).astype(np.uint8)
    img_odd = rng.randint(0, 256, (2, 3, 67, 45)).astype(np.uint8)  # matmul-resize path
    out = {}
    for tag, img in (("i299", img_299), ("iodd", img_odd)):
        for feat in (64, 192, 768, 2048, "logits_unbiased"):
            f = np.asarray(inception_features(params, jnp.asarray(img), feat))
            out[f"{tag}_{feat}"] = f[:, :16].astype(np.float32)  # slice: small commit
    np.savez(os.path.join(out_dir, "inception_golden.npz"), **out)
    print("wrote inception_golden.npz")


def gen_bert(out_dir):
    import torch
    import transformers

    import jax.numpy as jnp

    from metrics_tpu.models.bert import bert_forward, bert_position_ids, params_from_state_dict

    torch.manual_seed(0)
    config = transformers.BertConfig(
        vocab_size=99, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=128, max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    model = transformers.BertModel(config).eval()
    state = {k: v.numpy() for k, v in model.state_dict().items()}

    rng = np.random.RandomState(0)
    ids = rng.randint(3, 99, (3, 12)).astype(np.int32)
    mask = np.ones((3, 12), np.int32)
    mask[0, 8:] = 0
    ids[mask == 0] = 1
    params = params_from_state_dict(state)
    pos_ids = bert_position_ids(mask, "bert")
    hidden = np.asarray(
        bert_forward(
            params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos_ids),
            num_heads=4, eps=config.layer_norm_eps,
        )
    )
    # verify against the HF torch forward before freezing
    with torch.no_grad():
        want = model(torch.from_numpy(ids.astype(np.int64)), torch.from_numpy(mask.astype(np.int64)))[0].numpy()
    assert np.allclose(hidden, want, atol=2e-4), np.abs(hidden - want).max()
    np.savez(
        os.path.join(out_dir, "bert_golden.npz"),
        ids=ids, mask=mask, pos_ids=pos_ids, hidden=hidden.astype(np.float32),
        **{f"state::{k}": v for k, v in state.items()},
    )
    print("wrote bert_golden.npz (hf-verified)")


def gen_clip(out_dir):
    import torch
    import transformers

    import jax.numpy as jnp

    from metrics_tpu.models.clip import (
        clip_image_features,
        clip_text_features,
        params_from_state_dict,
        preprocess,
    )

    torch.manual_seed(0)
    config = transformers.CLIPConfig(
        text_config={"vocab_size": 99, "hidden_size": 32, "num_hidden_layers": 2,
                     "num_attention_heads": 4, "intermediate_size": 128,
                     "max_position_embeddings": 16, "eos_token_id": 98, "bos_token_id": 97,
                     "pad_token_id": 0},
        vision_config={"hidden_size": 32, "num_hidden_layers": 2, "num_attention_heads": 4,
                       "intermediate_size": 128, "image_size": 32, "patch_size": 8},
        projection_dim=16,
    )
    model = transformers.CLIPModel(config).eval()
    state = {k: v.numpy() for k, v in model.state_dict().items()}
    params = params_from_state_dict(state)

    rng = np.random.RandomState(0)
    ids = rng.randint(1, 97, (2, 9)).astype(np.int32)
    ids[:, -1] = 98  # eos
    mask = np.ones((2, 9), np.int32)
    imgs = rng.randint(0, 256, (2, 3, 32, 32)).astype(np.uint8)
    pixel = preprocess(jnp.asarray(imgs), size=32)
    txt = np.asarray(clip_text_features(params, jnp.asarray(ids), jnp.asarray(mask), num_heads=4, eos_token_id=98))
    img = np.asarray(clip_image_features(params, pixel, num_heads=4))
    with torch.no_grad():
        want_t = model.get_text_features(torch.from_numpy(ids.astype(np.int64)),
                                         torch.from_numpy(mask.astype(np.int64))).numpy()
        want_i = model.get_image_features(pixel_values=torch.from_numpy(np.asarray(pixel))).numpy()
    assert np.allclose(txt, want_t, atol=2e-4), np.abs(txt - want_t).max()
    assert np.allclose(img, want_i, atol=2e-4), np.abs(img - want_i).max()
    np.savez(
        os.path.join(out_dir, "clip_golden.npz"),
        ids=ids, mask=mask, imgs=imgs,
        text_features=txt.astype(np.float32), image_features=img.astype(np.float32),
        pixel_values=np.asarray(pixel, np.float32),
        **{f"state::{k}": v for k, v in state.items()},
    )
    print("wrote clip_golden.npz (hf-verified text+image towers)")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "tests/fixtures"
    os.makedirs(out_dir, exist_ok=True)
    gen_inception(out_dir)
    gen_bert(out_dir)
    gen_clip(out_dir)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
