#!/usr/bin/env python
"""bench_gate: fail CI when the newest bench round regresses the trajectory.

Parses the checked-in ``BENCH_r*.json`` rounds into backend-normalized
per-(config, field) series (``metrics_tpu.analysis.bench_history``) and gates
the newest round against the best earlier same-backend measurement of each
series. Exit 1 on any >threshold regression, 0 otherwise.

Usage::

    python scripts/bench_gate.py                  # gate ./BENCH_r*.json
    python scripts/bench_gate.py --dir path/      # gate another trajectory
    python scripts/bench_gate.py --round 7        # gate a specific round
    python scripts/bench_gate.py --threshold 0.2  # loosen the bar
    python scripts/bench_gate.py --json           # machine-readable report

Stdlib-only on the CLI side so the gate runs before (and regardless of) any
accelerator runtime coming up.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from metrics_tpu.analysis import bench_history as bh  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_gate.py",
        description="Gate the newest BENCH_r*.json round against the best"
        " earlier same-backend measurement of every (config, field) series.",
    )
    parser.add_argument(
        "--dir",
        default=".",
        help="directory holding BENCH_r*.json rounds (default: cwd)",
    )
    parser.add_argument(
        "--round",
        type=int,
        default=None,
        help="round number to gate (default: the newest round present)",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=bh.DEFAULT_THRESHOLD,
        help="relative regression bar (default: %(default)s = 15%%)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the full trajectory report as JSON"
    )
    args = parser.parse_args(argv)

    paths = bh.discover(args.dir)
    if not paths:
        print(f"bench_gate: no BENCH_r*.json rounds under {args.dir!r}", file=sys.stderr)
        return 2
    rounds = bh.load_rounds(paths)
    series = bh.build_series(rounds)
    gated = args.round if args.round is not None else max(r.num for r in rounds)
    if gated not in {r.num for r in rounds}:
        print(f"bench_gate: round {gated} not found in trajectory", file=sys.stderr)
        return 2
    regressions = bh.find_regressions(series, gated, threshold=args.threshold)

    if args.json:
        report = bh.trajectory_report(rounds, threshold=args.threshold)
        report["gated_round"] = gated
        report["regressions"] = [r._asdict() for r in regressions]
        print(json.dumps(report, indent=2))
    else:
        print(
            f"bench_gate: {len(rounds)} rounds, {len(series)} series,"
            f" gating r{gated:02d} at {args.threshold:.0%}"
        )
        for (backend, cfg, field), points in sorted(series.items()):
            vals = " -> ".join(f"r{p.round_num:02d}:{p.value:g}" for p in points)
            unit = points[-1].unit or "?"
            print(f"  [{backend}] {cfg}/{field} ({unit}): {vals}")
        for reg in regressions:
            print(
                f"REGRESSION [{reg.backend}] {reg.config}/{reg.field}:"
                f" r{reg.round_num:02d}={reg.value:g} is {reg.change_pct:.1f}% worse"
                f" than best r{reg.best_round:02d}={reg.best:g} ({reg.unit})"
            )
        if not regressions:
            print(f"OK: r{gated:02d} does not regress any same-backend series")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
