#!/usr/bin/env python
"""Regenerate tests/fixtures/lpips_golden.npz.

The fixture pins the full LPIPS pipeline (JAX backbone forward + unit-normalize +
lin-head weighting + spatial mean) against scores computed with the REAL vendored
LPIPS linear-head weights from the reference checkout
(``src/torchmetrics/functional/image/lpips_models/*.pth``). The backbone and the
input images are deterministic ``np.random.RandomState`` draws (bit-stable across
numpy versions), so only the tiny score vectors need committing.

Run from the repo root with the reference mounted:

    python scripts/gen_golden_fixtures.py [reference_lpips_dir] [out_npz]
"""
import os
import sys

import numpy as np


def random_backbone_state(net_type, rng):
    """Deterministic correctly-shaped backbone (same layout as torchvision's)."""
    shapes = {
        "alex": {
            "features.0": (64, 3, 11, 11),
            "features.3": (192, 64, 5, 5),
            "features.6": (384, 192, 3, 3),
            "features.8": (256, 384, 3, 3),
            "features.10": (256, 256, 3, 3),
        },
        "vgg": {
            f"features.{k}": s
            for k, s in zip(
                [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28],
                [(64, 3, 3, 3), (64, 64, 3, 3), (128, 64, 3, 3), (128, 128, 3, 3), (256, 128, 3, 3),
                 (256, 256, 3, 3), (256, 256, 3, 3), (512, 256, 3, 3), (512, 512, 3, 3), (512, 512, 3, 3),
                 (512, 512, 3, 3), (512, 512, 3, 3), (512, 512, 3, 3)],
            )
        },
    }[net_type]
    state = {}
    for prefix, shape in shapes.items():
        state[f"{prefix}.weight"] = (rng.randn(*shape) * 0.1).astype(np.float32)
        state[f"{prefix}.bias"] = (rng.randn(shape[0]) * 0.1).astype(np.float32)
    return state


def compute_scores(lpips_dir: str, net_type: str):
    import jax.numpy as jnp

    from metrics_tpu.models._io import load_checkpoint_state
    from metrics_tpu.models.lpips import (
        alex_params_from_state_dict,
        linear_weights_from_state_dict,
        lpips_forward,
        vgg_params_from_state_dict,
    )

    rng = np.random.RandomState(1234)
    state = random_backbone_state(net_type, rng)
    img1 = (2 * rng.rand(2, 3, 40, 40) - 1).astype(np.float32)
    img2 = (2 * rng.rand(2, 3, 40, 40) - 1).astype(np.float32)
    lins_state = load_checkpoint_state(os.path.join(lpips_dir, f"{net_type}.pth"))
    lins = linear_weights_from_state_dict(lins_state, net_type)
    converter = {"alex": alex_params_from_state_dict, "vgg": vgg_params_from_state_dict}[net_type]
    scores = lpips_forward(
        converter(state), [jnp.asarray(w) for w in lins], jnp.asarray(img1), jnp.asarray(img2), net_type, False
    )
    return np.asarray(scores)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    lpips_dir = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/src/torchmetrics/functional/image/lpips_models"
    out = sys.argv[2] if len(sys.argv) > 2 else "tests/fixtures/lpips_golden.npz"
    os.makedirs(os.path.dirname(out), exist_ok=True)
    np.savez(out, alex=compute_scores(lpips_dir, "alex"), vgg=compute_scores(lpips_dir, "vgg"))
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
