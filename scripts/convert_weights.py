#!/usr/bin/env python
"""Convert published metric-model checkpoints to the ``.npz`` format this
framework loads natively (no torch needed at metric runtime).

The reference downloads or vendors these weights directly as torch checkpoints
(InceptionV3: torch-fidelity, ``image/fid.py:52-157``; LPIPS linear heads:
vendored ``functional/image/lpips_models/*.pth``; CLIP/BERT: HF hub,
``multimodal/clip_score.py:46`` / ``text/bert.py:55``). This environment has no
network egress, so conversion is a user-run step:

    python scripts/convert_weights.py inception pt_inception-2015-12-05.pth inception.npz
    python scripts/convert_weights.py lpips lpips_models/alex.pth alex_lins.npz
    python scripts/convert_weights.py state-dict <any .pth or HF pytorch_model.bin> out.npz

Then point the loaders at the outputs:

    METRICS_TPU_INCEPTION_WEIGHTS=inception.npz      # FID / KID / InceptionScore
    METRICS_TPU_LPIPS_LINEAR_WEIGHTS=alex_lins.npz   # LPIPS lin heads
    METRICS_TPU_LPIPS_ALEX_WEIGHTS=<backbone.npz>    # LPIPS backbone

Verification story (tests/unittests/image/test_golden_weights.py):
- a committed golden fixture pins the full LPIPS pipeline against scores
  generated with the reference's vendored lin heads;
- when METRICS_TPU_INCEPTION_WEIGHTS points at real torch-fidelity weights and
  torch is importable, a differential test checks our features against the
  reference extractor on the same inputs (skip-if-absent).
"""
import argparse
import sys


def convert_inception(src: str, dst: str) -> None:
    from metrics_tpu.models.inception import convert_torch_fidelity_checkpoint

    convert_torch_fidelity_checkpoint(src, dst)


def convert_lpips(src: str, dst: str) -> None:
    """Extract LPIPS linear-head weights (lpips ``.pth`` layout) to npz."""
    import numpy as np

    from metrics_tpu.models._io import load_checkpoint_state

    state = load_checkpoint_state(src)
    np.savez(dst, **state)


def convert_state_dict(src: str, dst: str) -> None:
    """Generic torch state-dict (incl. HF ``pytorch_model.bin``) -> flat npz."""
    import numpy as np

    from metrics_tpu.models._io import load_checkpoint_state

    state = load_checkpoint_state(src)
    np.savez(dst, **state)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("kind", choices=("inception", "lpips", "state-dict"))
    parser.add_argument("src", help="source checkpoint (.pth / .bin)")
    parser.add_argument("dst", help="output .npz path")
    args = parser.parse_args(argv)
    {"inception": convert_inception, "lpips": convert_lpips, "state-dict": convert_state_dict}[args.kind](
        args.src, args.dst
    )
    print(f"wrote {args.dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
