"""Experiment: where does the float-logits accuracy path lose its throughput?

The README path (probs (N, C=5) f32 -> argmax -> eq -> sum) measured 7.9 Gpreds/s
vs 126-182 for the int8-label path.  Read traffic is 4*C+1 = 21 B/pred, so the
HBM roofline (819 GB/s, v5e) is ~39 Gpreds/s.  Hypotheses:

H1 (layout): (N, 5) f32 with minor dim 5 is stored in padded (8,128) tiles ->
    up to 25.6x read amplification.  Witness: on-device buffer size; a pure
    sum() over the array vs over a flat (5N,) array.
H2 (argmax lowering): variadic reduce (value,index) lowers worse than a chain
    of elementwise max/select.  Witness: argmax vs max-only vs manual unrolled
    compare chain.
H3 (stream shape): like the int8 kernel, more independent streams in one
    fusion raises the issue rate -> zip4 on the sample axis.

Run on the real chip: python experiments/logits_exp.py [--n 26] [--reps 5]
"""
import argparse
import statistics
import time

import jax
import jax.numpy as jnp

C = 5


def device_size(x):
    try:
        return x._arrays[0].on_device_size_in_bytes()
    except Exception:
        return -1


def make_bufs(n, key, transposed=False, flat=False, int8=False):
    bufs = []
    for _ in range(2):
        k1, k2, key = jax.random.split(key, 3)
        if int8:
            probs = jax.random.randint(k1, (n,), 0, C, dtype=jnp.int8)
        elif transposed:
            probs = jax.random.uniform(k1, (C, n), jnp.float32)
        elif flat:
            probs = jax.random.uniform(k1, (n * C,), jnp.float32)
        else:
            probs = jax.random.uniform(k1, (n, C), jnp.float32)
        target = jax.random.randint(k2, (n,), 0, C, dtype=jnp.int32).astype(jnp.int8)
        bufs.append((probs, target))
    return bufs, key


def timed_passes(update, init, bufs, steps, n):
    state = update(init, *bufs[0])
    jax.device_get(state)  # compile
    t0 = time.perf_counter()
    state = init
    for i in range(steps):
        state = update(state, *bufs[i % 2])
    jax.device_get(state)
    dt = time.perf_counter() - t0
    return steps * n / dt


# ------------------------------------------------------------------ variants

def v_baseline(s, p, t):
    return s + jnp.sum(p.argmax(axis=1).astype(jnp.int8) == t, dtype=jnp.int32)


def v_max_only(s, p, t):
    # not accuracy; isolates reduce cost without index tracking
    return s + jnp.sum(p.max(axis=1) > t.astype(jnp.float32), dtype=jnp.int32)


def v_sum_only(s, p, t):
    # pure f32 read-bound witness over the whole (N,C) buffer
    return s + jnp.sum(p, dtype=jnp.float32).astype(jnp.int32)


def v_unrolled(s, p, t):
    # manual first-occurrence argmax as a compare/select chain over C columns
    best = p[:, 0]
    idx = jnp.zeros(p.shape[0], jnp.int8)
    for c in range(1, C):
        col = p[:, c]
        better = col > best
        best = jnp.where(better, col, best)
        idx = jnp.where(better, jnp.int8(c), idx)
    return s + jnp.sum(idx == t, dtype=jnp.int32)


def v_rowmax_at_target(s, p, t):
    # "is target's prob the row max" -- differs from argmax only on exact ties
    rowmax = p.max(axis=1)
    tv = jnp.take_along_axis(p, t.astype(jnp.int32)[:, None], axis=1)[:, 0]
    return s + jnp.sum(tv >= rowmax, dtype=jnp.int32)


def v_onehot_dot(s, p, t):
    # value-at-target via elementwise one-hot multiply + minor-dim sum
    oh = jax.nn.one_hot(t.astype(jnp.int32), C, dtype=p.dtype)
    tv = (p * oh).sum(axis=1)
    rowmax = p.max(axis=1)
    return s + jnp.sum(tv >= rowmax, dtype=jnp.int32)


def v_transposed(s, p, t):
    # p is (C, N): argmax along axis 0 = chain of elementwise ops on (N,) rows
    best = p[0]
    idx = jnp.zeros(p.shape[1], jnp.int8)
    for c in range(1, C):
        better = p[c] > best
        best = jnp.where(better, p[c], best)
        idx = jnp.where(better, jnp.int8(c), idx)
    return s + jnp.sum(idx == t, dtype=jnp.int32)


def v_transpose_then(s, p, t):
    # user gives (N, C); pay one explicit transpose then run the fast form
    return v_transposed(s, p.T, t)


def v_transposed_argmax(s, p, t):
    # p is (C, N): let XLA lower argmax over the MAJOR dim (sublane reduction)
    return s + jnp.sum(p.argmax(axis=0).astype(jnp.int8) == t, dtype=jnp.int32)


def v_flat_strided(s, p, t):
    # p is flat (N*C,) row-major; column c = p[c::C] strided slice
    n = t.shape[0]
    cols = [p[c::C] for c in range(C)]
    best = cols[0]
    idx = jnp.zeros(n, jnp.int8)
    for c in range(1, C):
        better = cols[c] > best
        best = jnp.where(better, cols[c], best)
        idx = jnp.where(better, jnp.int8(c), idx)
    return s + jnp.sum(idx == t, dtype=jnp.int32)


def v_flat_reshaped(s, p, t):
    # p flat (N*C,) -> reshape to (N, C) inside the kernel, then baseline
    n = t.shape[0]
    return v_baseline(s, p.reshape(n, C), t)


def v_int8_calib(s, p, t):
    # harness calibration: the int8-label streaming kernel (bench headline path)
    return s + jnp.sum(p == t, dtype=jnp.int32)


def _zip_argmax(s, p, t, ways):
    # zip the sample axis into `ways` independent streams whose int8 correct-masks
    # are summed elementwise inside ONE fusion (the streaming.py zip4 trick)
    n = t.shape[0]
    q = n // ways
    acc = None
    for i in range(ways):
        pi = p[i * q:(i + 1) * q]
        ti = t[i * q:(i + 1) * q]
        eq = (pi.argmax(axis=1).astype(jnp.int8) == ti).astype(jnp.int8)
        acc = eq if acc is None else acc + eq
    return s + jnp.sum(acc, dtype=jnp.int32)


def v_zip2_argmax(s, p, t):
    return _zip_argmax(s, p, t, 2)


def v_zip4_argmax(s, p, t):
    return _zip_argmax(s, p, t, 4)


def v_zip8_argmax(s, p, t):
    return _zip_argmax(s, p, t, 8)


def v_argmax_i8idx(s, p, t):
    # lax.argmax with a narrow index dtype: if the index array is materialized,
    # i8 cuts its HBM round-trip 4x vs s32
    idx = jax.lax.argmax(p, 1, jnp.int8)
    return s + jnp.sum(idx == t, dtype=jnp.int32)


def v_reduce_flag(s, p, t):
    # ONE variadic reduce carrying (value, is_target) -- never produces an index.
    # Combiner keeps the lexicographically-first max (argmax tie semantics).
    nloc = t.shape[0]
    flags = (jax.lax.broadcasted_iota(jnp.int8, (nloc, C), 1) == t[:, None]).astype(jnp.int8)

    def comb(a, b):
        av, af = a
        bv, bf = b
        keep = av >= bv  # left operand is the earlier index: ties keep left
        return jnp.where(keep, av, bv), jnp.where(keep, af, bf)

    _, win = jax.lax.reduce((p, flags), (jnp.float32(-jnp.inf), jnp.int8(0)), comb, (1,))
    return s + jnp.sum(win, dtype=jnp.int32)


def v_reduce_idx8(s, p, t):
    # commutation-safe total-order reduce carrying an i8 index lane (the
    # reduce_flag combiner mis-ties on TPU: lax.reduce may swap operands)
    nloc = t.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int8, (nloc, C), 1)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        a_nan = jnp.isnan(av)
        b_nan = jnp.isnan(bv)
        a_gt = (av > bv) | (a_nan & ~b_nan)
        a_eq = (av == bv) | (a_nan & b_nan)
        keep = a_gt | (a_eq & (ai < bi))
        return jnp.where(keep, av, bv), jnp.where(keep, ai, bi)

    _, win = jax.lax.reduce((p, iota), (jnp.float32(-jnp.inf), jnp.int8(127)), comb, (1,))
    return s + jnp.sum(win == t, dtype=jnp.int32)


def v_twopass_minidx(s, p, t):
    # rowmax (f32 max-reduce) then first index where p == rowmax (i8 min-reduce):
    # both reduces commutative => exact ties/NaN on any backend; XLA may keep the
    # row tile in registers across both passes (one HBM read)
    rowmax = p.max(axis=1)
    eqn = (p == rowmax[:, None]) | jnp.isnan(p)
    iota = jax.lax.broadcasted_iota(jnp.int8, p.shape, 1)
    first = jnp.min(jnp.where(eqn, iota, jnp.int8(127)), axis=1)
    return s + jnp.sum(first == t, dtype=jnp.int32)


def v_packed_u32(s, p, t):
    # Monotone u32 key with the column index packed in the low 3 bits:
    # one plain max-reduce replaces the variadic argmax. Exact ties resolve to
    # the smallest column (= argmax first-occurrence); values differing only in
    # the low 3 mantissa bits (~2^-21 rel) can mis-rank -- measure-only variant.
    u = jax.lax.bitcast_convert_type(p, jnp.uint32)
    u = jnp.where(u >> 31 == 0, u | jnp.uint32(0x80000000), ~u)
    col = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 1)
    key = (u & jnp.uint32(0xFFFFFFF8)) | (jnp.uint32(7) - col)
    best = key.max(axis=1)
    win = (best & 7) == (jnp.uint32(7) - t.astype(jnp.uint32))
    return s + jnp.sum(win, dtype=jnp.int32)


def v_packed_u64(s, p, t):
    # INVALID under default (x64-disabled) JAX: astype(uint64) silently degrades
    # to uint32, so `u << 3` drops the key's top 3 bits — the measured 10.5
    # Gpreds/s row is a truncated-u32 reduce, not a u64 one, and mis-ranks
    # cross-magnitude values (ties verified wrong in-session). Kept only as a
    # record of the rejected direction; a real u64 key needs two u32 lanes.
    # Original intent: monotone u32 key from f32 (order-preserving bijection,
    # NaN maximal), widened to u64 with the reversed column index in the low 3
    # bits; one commutative u64 max-reduce == first-occurrence argmax.
    u = jax.lax.bitcast_convert_type(p, jnp.uint32)
    u = jnp.where(u >> 31 == 0, u | jnp.uint32(0x80000000), ~u)
    col = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 1)
    key = (u.astype(jnp.uint64) << 3) | (jnp.uint32(7) - col).astype(jnp.uint64)
    best = key.max(axis=1)
    win = (best & 7).astype(jnp.int8) == (jnp.int8(7) - t)
    return s + jnp.sum(win, dtype=jnp.int32)


def v_masked3_NC(s, p, t):
    # exact argmax==target via 3 masked commutative max-reduces in one fusion:
    # argmax(p)==t  <=>  p[t] > max(p[:t])  and  p[t] >= max(p[t+1:])
    iota = jax.lax.broadcasted_iota(jnp.int8, p.shape, 1)
    tt = t[:, None]
    ninf = jnp.float32(-jnp.inf)
    pv = jnp.max(jnp.where(iota == tt, p, ninf), axis=1)
    mlt = jnp.max(jnp.where(iota < tt, p, ninf), axis=1)
    mgt = jnp.max(jnp.where(iota > tt, p, ninf), axis=1)
    ok = (pv > mlt) & (pv >= mgt)
    return s + jnp.sum(ok, dtype=jnp.int32)


def v_bf16_argmax(s, p, t):
    # convert-on-load to bf16 before the argmax reduce (precision-lossy witness:
    # does halving vreg width double the reduce issue rate?)
    idx = p.astype(jnp.bfloat16).argmax(axis=1).astype(jnp.int8)
    return s + jnp.sum(idx == t, dtype=jnp.int32)


def v_flat_sum(s, p, t):
    # pure f32 read-bound witness on a FLAT (5N,) array (no 2-D layout in play)
    return s + jnp.sum(p, dtype=jnp.float32).astype(jnp.int32)


def v_flat_zipsum(s, p, t):
    # 4 independent f32 streams summed elementwise inside one fusion: does the
    # zip trick raise the f32 issue rate the way it does for int8?
    n = p.shape[0]
    q = n // 4
    acc = p[:q]
    for i in range(1, 4):
        acc = acc + p[i * q:(i + 1) * q]
    return s + jnp.sum(acc, dtype=jnp.float32).astype(jnp.int32)


VARIANTS = {
    "int8_calib": (v_int8_calib, {"int8": True}),
    "flat_sum_f32": (v_flat_sum, {"flat": True}),
    "flat_zipsum_f32": (v_flat_zipsum, {"flat": True}),
    "baseline_argmax_NC": (v_baseline, {}),
    "max_only_NC": (v_max_only, {}),
    "sum_only_NC": (v_sum_only, {}),
    "unrolled_cols_NC": (v_unrolled, {}),
    "onehot_dot_NC": (v_onehot_dot, {}),
    "transposed_CN": (v_transposed, {"transposed": True}),
    "transposed_argmax_CN": (v_transposed_argmax, {"transposed": True}),
    "transpose_then_CN": (v_transpose_then, {}),
    "zip2_argmax_NC": (v_zip2_argmax, {}),
    "zip4_argmax_NC": (v_zip4_argmax, {}),
    "zip8_argmax_NC": (v_zip8_argmax, {}),
    "argmax_i8idx_NC": (v_argmax_i8idx, {}),
    "reduce_flag_NC": (v_reduce_flag, {}),
    "reduce_idx8_NC": (v_reduce_idx8, {}),
    "twopass_minidx_NC": (v_twopass_minidx, {}),
    "packed_u32_NC": (v_packed_u32, {}),
    "packed_u64_NC": (v_packed_u64, {}),
    "masked3_NC": (v_masked3_NC, {}),
    "bf16_argmax_NC": (v_bf16_argmax, {}),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=26, help="log2 samples per dispatch")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--reps", type=int, default=5, help="interleaved trials per variant")
    ap.add_argument("--only", type=str, default=None)
    args = ap.parse_args()
    n = 1 << args.n

    key = jax.random.PRNGKey(0)
    cache = {}

    def get_bufs(kw):
        k = tuple(sorted(kw.items()))
        nonlocal key
        if k not in cache:
            cache[k], key = make_bufs(n, key, **kw)
        return cache[k]

    only = args.only.split(",") if args.only else None
    names = [k for k in VARIANTS if only is None or any(o in k for o in only)]
    # report layouts once
    b, _ = make_bufs(1 << 20, jax.random.PRNGKey(1))
    print(f"(2^20,5) f32 logical {b[0][0].nbytes} on-device {device_size(b[0][0])}")

    fns = {}
    for name in names:
        fn, kw = VARIANTS[name]
        fns[name] = (jax.jit(fn), get_bufs(kw))

    results = {name: [] for name in names}
    dead = set()
    for _ in range(args.reps):
        for name in names:  # interleaved: each rep visits every variant
            if name in dead:
                continue
            fn, bufs = fns[name]
            try:
                eps = timed_passes(fn, jnp.int32(0), bufs, args.steps, n)
            except Exception as e:
                print(f"  {name}: FAILED {type(e).__name__}: {str(e)[:120]}")
                dead.add(name)
                continue
            results[name].append(eps)
    print(f"n=2^{args.n} steps={args.steps} reps={args.reps}  (p50 / max, Gpreds/s)")
    for name in names:
        r = results[name]
        if not r:
            continue
        p50 = statistics.median(r)
        print(f"  {name:24s} {p50 / 1e9:8.2f} / {max(r) / 1e9:8.2f}   ({21 * p50 / 1e9:.0f} GB/s eff-read)")


if __name__ == "__main__":
    main()
