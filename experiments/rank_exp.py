"""Experiment grid for the bucketed rank engine (ops/rank.py) — sort widths,
histogram tiers, and the retrieval radix-partition evaluation.

Run: python experiments/rank_exp.py [--n 24] [--section all|sort|hist|partition]

== RECORDED VERDICT: radix partition-by-query for the retrieval layout ==

REJECTED; the adopted change is sort-operand slimming (ops/segment.py r6).

The layout pass needs rows grouped by query and ranked by score inside each
query. The partition alternative (compute per-row destinations from a query
histogram + prefix sum, then materialize the permutation) was evaluated
against the measured cost model and the ``partition`` section below, which
times its mandatory ingredients:

- A materializing partition IS a permutation apply: one computed-destination
  gather (or scatter) per pass. Measured on the v5e (round 5, ops/segment.py
  notes): ~90 ms per 16M-row gather — MORE than the entire 4.2M-row 3-payload
  sort (45 ms) and ~70% of the full 2^24-row sort (~125 ms). Multi-pass radix
  (needed because query ids span up to 2^24 values) multiplies that cost.
- The gather-free alternative (per-row destination via scans, then positional
  relabeling) still has to MOVE the payload columns — which is exactly the
  data reorganization ``lax.sort`` already performs in its bitonic network,
  with no computed-index traffic at all.
- What partitioning would save is the sort's ranking work WITHIN queries — but
  scores must be ranked within queries anyway; the sort does both in one op.

The measurable lever was operand bytes, not the network: the r3 layout carried
(indexes, -preds, indexes, preds, target) = 20 B/row where the sorted key
columns come out of ``lax.sort`` anyway; the r6 form carries (indexes, -preds,
target) = 12 B/row and recovers ndcg's ideal layout by negating its own sort
key (8 vs 12 B/row). ADOPTED — bit-identical outputs, 40% fewer bytes through
the ~300-pass network. bench.py's retrieval line now records the measured
layout_sort_ms/scan_ms split each round so the win is visible in BENCH_r06+.

== Sort-width grid (``--section sort``) ==

Times the exact-AUROC sort candidates at equal N: the (f32, i32) oracle, the
(u32, i32) integer-comparator variant, the shipped (u32, u8) reduced-payload
tier, a key-only u32 sort (the no-label floor), and the curve path's
(u8 flag + 3 f32) front-pack vs argsort + 3 gathers. On the tunneled TPU the
bitonic cost model predicts ~bytes-proportional scaling (5/8 for the shipped
tier); this grid is the ground truth for that prediction.

== Histogram tier grid (``--section hist``) ==

bincount tiers (compare / tiled-Pallas / MXU pair-split / scatter) across
num_bins in {64..16384}: records the compare-vs-Pallas crossover that decides
whether PALLAS_MAX_BINS (raised 64 -> 256 in r6 via output-block bin tiling)
should rise further, and the pair-split-vs-scatter margin at 2^12-2^14 bins
that the rank engine's bucket histograms (ops/rank.py:bucket_counts) ride.
"""
import argparse
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out):
    # block_until_ready does not round-trip on the tunneled backend; a scalar
    # device_get is the only trustworthy sync (in-order queue drains first)
    leaf = jax.tree.leaves(out)[0]
    jax.device_get(leaf.ravel()[0] if leaf.ndim else leaf)


def timeit(fn, *args, reps=5):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(4):
            out = fn(*args)
        _sync(out)
        ts.append((time.perf_counter() - t0) / 4)
    return statistics.median(ts)


def _report(name, dt, n):
    print(f"  {name:28s} {dt * 1e3:8.1f} ms   {n / dt / 1e6:8.2f} Melem/s")


def section_sort(n):
    from metrics_tpu.ops import rank

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    target = jnp.asarray((rng.rand(n) > 0.7).astype(np.int32))
    valid = jnp.ones((n,), bool)

    f_oracle = jax.jit(lambda p, t: jax.lax.sort((-p, t), num_keys=1))
    f_u32_i32 = jax.jit(lambda p, t: jax.lax.sort((rank.monotone_key_descending(p), t), num_keys=1))
    f_u32_u8 = jax.jit(
        lambda p, t: jax.lax.sort((rank.monotone_key_descending(p), t.astype(jnp.uint8)), num_keys=1)
    )
    f_keyonly = jax.jit(lambda p: jax.lax.sort((rank.monotone_key_descending(p),), num_keys=1))
    f_full_oracle = jax.jit(lambda p, t, v: rank_counts_oracle(p, t, v))
    f_full_rank = jax.jit(lambda p, t, v: rank.rank_run_end_counts(p, t, v))

    def rank_counts_oracle(p, t, v):
        from metrics_tpu.ops.clf_curve import _run_end_counts

        return _run_end_counts(p, t, v, tier="sort")

    for name, fn, a in (
        ("sort_f32key_i32lab (oracle)", f_oracle, (preds, target)),
        ("sort_u32key_i32lab", f_u32_i32, (preds, target)),
        ("sort_u32key_u8lab (shipped)", f_u32_u8, (preds, target)),
        ("sort_u32key_only (floor)", f_keyonly, (preds,)),
        ("run_end_counts oracle", f_full_oracle, (preds, target, valid)),
        ("run_end_counts rank tier", f_full_rank, (preds, target, valid)),
    ):
        _report(name, timeit(fn, *a), n)

    # curve compaction: argsort + 3 gathers vs one stable payload sort
    mask = jnp.asarray(rng.rand(n) > 0.5)
    cols = tuple(jnp.asarray(rng.rand(n).astype(np.float32)) for _ in range(3))

    def compact_gather(m, a, b, c):
        order = jnp.argsort(~m, stable=True)
        return jnp.take(a, order), jnp.take(b, order), jnp.take(c, order)

    _report("front_pack argsort+3gather", timeit(jax.jit(compact_gather), mask, *cols), n)
    _report("front_pack payload sort", timeit(jax.jit(rank.stable_front_pack), mask, *cols), n)


def section_hist(n):
    from metrics_tpu.ops import histogram as H
    from metrics_tpu.ops import rank

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    keys = rank.monotone_key_descending(preds)
    on_tpu = jax.default_backend() == "tpu"

    for bins in (64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384):
        bits = bins.bit_length() - 1
        x = (keys >> jnp.uint32(32 - bits)).astype(jnp.int32)
        if bins <= H.COMPARE_MAX_BINS:
            _report(f"compare       bins={bins}", timeit(jax.jit(
                lambda v, b=bins: H._compare_bincount(v, None, b)), x), n)
        if on_tpu and bins <= 2048:  # tiled kernel: VMEM-unbounded, work O(bins*N)
            _report(f"pallas_tiled  bins={bins}", timeit(jax.jit(
                lambda v, b=bins: H._pallas_bincount(v, None, b)), x), n)
        if bins > 2048:
            _report(f"pairsplit_mxu bins={bins}", timeit(jax.jit(
                lambda v, b=bins: H._pairsplit_bincount(v, None, b)), x), n)
            _report(f"scatter       bins={bins}", timeit(jax.jit(
                lambda v, b=bins: jnp.zeros((b,), jnp.int32).at[v].add(1, mode="drop")), x), n)

    # the histogram-only AUROC bounds pass vs the exact sort kernel
    target = jnp.asarray((rng.rand(n) > 0.7).astype(np.int32))
    _report("bucketed_auroc_bounds b=12", timeit(jax.jit(
        lambda p, t: rank.bucketed_auroc_bounds(p, t, bits=12)), preds, target), n)


def section_partition(n):
    """Radix partition ingredients vs the one-sort layout (verdict: rejected)."""
    rng = np.random.RandomState(0)
    idx = jnp.asarray(np.sort(rng.randint(0, n // 64, n)).astype(np.int32))
    scores = jnp.asarray(rng.rand(n).astype(np.float32))
    rel = jnp.asarray((rng.rand(n) > 0.7).astype(np.int32))

    f_sort3 = jax.jit(lambda i, s, t: jax.lax.sort((i, -s, t), num_keys=2, is_stable=True))
    f_sort5 = jax.jit(lambda i, s, t: jax.lax.sort((i, -s, i, s, t), num_keys=2, is_stable=True))
    # the partition's mandatory permutation-apply: 3 computed-index gathers
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    f_gather3 = jax.jit(lambda p, i, s, t: (jnp.take(i, p), jnp.take(s, p), jnp.take(t, p)))
    # destination computation alone (histogram + prefix + rank-in-bucket scans)
    def dests(i):
        new_seg = jnp.concatenate([jnp.ones(1, bool), i[1:] != i[:-1]])
        pos = jnp.arange(i.shape[0])
        start = jax.lax.cummax(jnp.where(new_seg, pos, 0))
        return pos - start

    f_dests = jax.jit(dests)

    _report("layout sort 3-op (adopted)", timeit(f_sort3, idx, scores, rel), n)
    _report("layout sort 5-op (r3 form)", timeit(f_sort5, idx, scores, rel), n)
    _report("partition: 3 perm-gathers", timeit(f_gather3, perm, idx, scores, rel), n)
    _report("partition: dest scans only", timeit(f_dests, idx), n)
    print("  -> verdict (module docstring): partition REJECTED — the permutation")
    print("     apply alone costs more than the whole slimmed sort; adopted the")
    print("     20->12 B/row operand slimming instead.")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=22)
    ap.add_argument("--section", choices=("all", "sort", "hist", "partition"), default="all")
    args = ap.parse_args()
    n = 1 << args.n
    for name, fn in (("sort", section_sort), ("hist", section_hist), ("partition", section_partition)):
        if args.section in ("all", name):
            print(f"== {name} (n=2^{args.n}, backend={jax.default_backend()}) ==")
            fn(n)


if __name__ == "__main__":
    main()
