"""Measure ndcg/r_precision on the unified scan path at bench scale (2^24 rows)."""
import sys, os, time, statistics
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
import numpy as np
from metrics_tpu.retrieval import RetrievalNormalizedDCG, RetrievalRPrecision, RetrievalMAP

n_docs = 1 << 24
rng = np.random.RandomState(0)
idx = jnp.asarray(np.sort(rng.randint(0, n_docs // 64, n_docs)).astype(np.int32))
scores = jnp.asarray(rng.rand(n_docs).astype(np.float32))
rel = jnp.asarray((rng.rand(n_docs) > 0.7).astype(np.int32))

for cls in (RetrievalNormalizedDCG, RetrievalRPrecision, RetrievalMAP):
    m = cls(cat_capacity=n_docs, validate_args=False)
    update = jax.jit(m.local_update)
    state = update(m.init_state(), scores, rel, idx)
    v = float(m.compute_from(state))
    rates = []
    for _ in range(4):
        t0 = time.perf_counter()
        state = update(m.init_state(), scores, rel, idx)
        v = float(m.compute_from(state))
        rates.append(n_docs / (time.perf_counter() - t0))
    print(f"{cls.__name__}: {statistics.median(rates)/1e6:.1f} Mdocs/s  value={v:.4f}")
