"""Shape-stability check: pow2-pad the consolidated staging, print bucket dims,
and time repeated evaluation of distinct datasets with IDENTICAL shapes."""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
from metrics_tpu.detection import MeanAveragePrecision
from metrics_tpu.functional.detection import _mean_ap_device as D


def _pow2(n):
    return 1 << max(0, (n - 1).bit_length())


def consolidate_pow2(preds, target):
    B = len(preds)
    md = _pow2(max(max(p[0].shape[0] for p in preds), 1))
    mg = _pow2(max(max(t[0].shape[0] for t in target), 1))
    pb = np.zeros((B, md, 4), np.float32)
    ps = np.full((B, md), -np.inf, np.float32)
    pl = np.full((B, md), -1, np.int32)
    tb = np.zeros((B, mg, 4), np.float32)
    tl = np.full((B, mg), -1, np.int32)
    for i, ((db, dsc, dl), (gb, gl)) in enumerate(zip(preds, target)):
        n = db.shape[0]
        pb[i, :n], ps[i, :n], pl[i, :n] = db, dsc, dl
        n = gb.shape[0]
        tb[i, :n], tl[i, :n] = gb, gl
    return ({"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.asarray(pl)},
            {"boxes": jnp.asarray(tb), "labels": jnp.asarray(tl)})


def main(n_images=1000):
    datasets = [bench._coco_like_dataset(n_images, seed) for seed in range(4)]
    for p, t in datasets:
        dl = np.concatenate([x[2] for x in p])
        counts = [np.bincount(x[2], minlength=5).max() if len(x[2]) else 0 for x in p]
        gcounts = [np.bincount(x[1], minlength=5).max() if len(x[1]) else 0 for x in t]
        print("max per-(img,cls) det count:", max(counts), " gt:", max(gcounts),
              " n big det>16:", sum(1 for c in counts if c > 16),
              " n big gt>16:", sum(1 for c in gcounts if c > 16))
    device_data = [consolidate_pow2(p, t) for p, t in datasets]
    jax.device_get(device_data[-1][0]["boxes"])
    for p, t in device_data:
        print("shapes:", p["boxes"].shape, t["boxes"].shape)

    metric = MeanAveragePrecision()
    t0 = time.perf_counter()
    metric.update(*device_data[0])
    out = metric.compute()
    print(f"warm-up (compile): {time.perf_counter()-t0:6.1f} s, map={float(out['map']):.4f}")

    for preds, target in device_data[1:] + device_data[1:2]:
        metric.reset()
        t0 = time.perf_counter()
        metric.update(preds, target)
        out = metric.compute()
        mv = float(jax.device_get(out["map"]))
        dt = time.perf_counter() - t0
        print(f"cycle {dt*1e3:7.1f} ms -> {n_images/dt:7.1f} img/s   map={mv:.4f}")

    print("consolidated_tables compiles:", D.consolidated_tables._cache_size())


if __name__ == "__main__":
    main()
