"""Experiment: close PR 3's open 256..2048 Pallas-vs-compare bincount crossover.

Both tiers do O(num_bins * N) compare work (ops/histogram.py module docstring),
so the open question since the round-6 bin-tiled output block raised
``PALLAS_MAX_BINS`` to 256 was purely empirical: does the Pallas kernel's edge
(+6% over the fused-XLA compare form, measured at 25 bins on v5e) survive the
bin-tile revisits the 256..2048 range needs (up to 32 output columns per input
block), or does the grid overhead flip the winner back to the compare tier?

Grid: num_bins in {64, 256, 512, 1024, 2048} x N in {2^18, 2^21, 2^24}, both
tiers jitted, weighted and unweighted. On TPU this times the real kernels; on
CPU the Pallas kernel only runs in interpret mode (not representative), so the
CPU run reports the compare tier's scaling plus bit-parity of the two tiers,
and the structural observations below carry the verdict until a TPU round.

Run: JAX_PLATFORMS=cpu python experiments/histogram_crossover.py   (parity + scaling)
     python experiments/histogram_crossover.py                      (TPU: full timing)

Round-10 verdict (recorded in ops/histogram.py):

- Compare-tier scaling on CPU is linear in num_bins across 256..2048 (measured
  here: within noise of the bins/256 ratio), confirming neither tier has a
  super-linear term the other lacks — the crossover cannot re-flip with bins.
- The Pallas kernel's per-element work is IDENTICAL at every bin tile (same
  compare-reduce, same (8, 4096) input block streamed once per 64-bin column);
  the only added cost at 2048 bins is 32x grid-step bookkeeping on a revisited
  VMEM-resident input block, which is amortized over 2^15-element blocks at
  N >= PALLAS_MIN_SIZE (grid-step overhead «1% of the block's compare work).
- Bit-parity between the tiers holds across the grid (checked here in
  interpret mode, weighted and unweighted).

=> PALLAS_MAX_BINS raised 256 -> 2048: the Pallas tier now covers the full
compare-tier range on TPU, and the 256..2048 band no longer silently prefers
the fused-XLA form. Directional until a TPU re-run of this grid pins the
measured ratio (CPU cannot time the kernel); the dispatch still requires
``_on_tpu`` + ``_provably_unsharded`` + ``N >= PALLAS_MIN_SIZE``, so nothing
changes off-TPU.
"""
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops.histogram import _compare_bincount, _pallas_bincount

BINS_GRID = (64, 256, 512, 1024, 2048)
#: full grid on TPU; the CPU compare tier is ~2 orders slower, so the parity +
#: scaling run caps N to keep the grid under a few minutes
N_GRID_TPU = (1 << 18, 1 << 21, 1 << 24)
N_GRID_CPU = (1 << 16, 1 << 18)


def timed(fn, *args, reps=5):
    fn(*args).block_until_ready()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main():
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    n_grid = N_GRID_TPU if on_tpu else N_GRID_CPU
    print(f"backend={jax.default_backend()}  (Pallas timings {'REAL' if on_tpu else 'SKIPPED: interpret-only'})")
    print(f"{'bins':>6} {'N':>10} {'compare_ms':>11} {'pallas_ms':>10} {'ratio':>7}  parity")

    for n in n_grid:
        x_np = rng.integers(0, BINS_GRID[-1], size=n).astype(np.int32)
        w_np = rng.integers(0, 3, size=n).astype(np.int32)
        x = jnp.asarray(x_np)
        w = jnp.asarray(w_np)
        for bins in BINS_GRID:
            compare_j = jax.jit(lambda a, b=bins: _compare_bincount(a, None, b))
            t_cmp = timed(compare_j, x) * 1e3
            if on_tpu:
                pallas_j = jax.jit(lambda a, b=bins: _pallas_bincount(a, None, b))
                t_pal = timed(pallas_j, x) * 1e3
                ratio = f"{t_cmp / t_pal:7.2f}"
                pal_ms = f"{t_pal:10.3f}"
                parity_ref = pallas_j(x)
            else:
                pal_ms, ratio = f"{'--':>10}", f"{'--':>7}"
                # interpret mode is too slow to run at full N; parity on a slice
                xs, ws = x[: 1 << 16], w[: 1 << 16]
                parity_ref = _pallas_bincount(xs, None, bins, interpret=True)
                assert jnp.array_equal(parity_ref, _compare_bincount(xs, None, bins))
                pw = _pallas_bincount(xs, ws, bins, interpret=True)
                assert jnp.array_equal(pw, _compare_bincount(xs, ws, bins))
            print(f"{bins:>6} {n:>10} {t_cmp:>11.3f} {pal_ms} {ratio}  ok")

    # compare-tier scaling check: ms(bins)/ms(256) vs bins/256 at the largest N
    x = jnp.asarray(rng.integers(0, BINS_GRID[-1], size=n_grid[-1]).astype(np.int32))
    base = timed(jax.jit(lambda a: _compare_bincount(a, None, 256)), x)
    for bins in (512, 1024, 2048):
        t = timed(jax.jit(lambda a, b=bins: _compare_bincount(a, None, b)), x)
        print(f"compare scaling: bins={bins:>5} measured x{t / base:5.2f} vs linear x{bins / 256:.2f}")


if __name__ == "__main__":
    main()
