"""Experiment: what bounds RetrievalMAP compute (5.97 Mdocs/s r03)?

Pieces: one 2-key lexsort (indexes, -preds) + ~8 segment reductions + cumsum.
Run: python experiments/retrieval_exp.py [--n 22]
"""
import argparse
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np


# moved here from ops/segment.py when the library dropped its last scatter path
# (r5): only this experiment grid still exercises the segment-reduction layout
def _segment_layout(indexes: Array, preds: Array, target: Array):
    """Sort rows by (query, -score); return per-row segment ids and rank info.

    Returns: (seg_id, rank, sorted_preds, sorted_target, n_seg_upper, seg_count,
    seg_index) where rank is the 1-based position of the row inside its query's
    score-ordered list, seg_count[s] is the number of docs of segment s (0 for unused
    slots), and seg_index[s] is the original query id of segment s (negative values
    mark padding rows whose segment must not count as a real query).
    """
    n = indexes.shape[0]
    # one variadic sort carrying the columns as payloads: measured 6.8x faster
    # than argsort + three 4M-row gathers on TPU (see module docstring)
    _, _, s_idx, s_preds, s_target = jax.lax.sort(
        (indexes, -preds, indexes, preds, target), num_keys=2, is_stable=True
    )

    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    seg_id = jnp.cumsum(new_seg) - 1  # dense 0..n_q-1

    pos = jnp.arange(n)
    # broadcast each segment's start row to its members via one scan (no gather)
    seg_start_row = jax.lax.cummax(jnp.where(new_seg, pos, 0))
    rank = pos - seg_start_row + 1  # 1-based within query

    seg_count = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg_id, num_segments=n, indices_are_sorted=True)
    # first (== any) original index of each segment: negative marks padding rows
    # (cat-buffer fill / pow2 pad), whose segment must not count as a real query
    seg_index = jax.ops.segment_min(s_idx, seg_id, num_segments=n, indices_are_sorted=True)
    return seg_id, rank, s_preds, s_target, n, seg_count, seg_index



def _sync(out):
    # block_until_ready does not round-trip on the tunneled backend; a scalar
    # device_get is the only trustworthy sync (in-order queue drains first)
    leaf = jax.tree.leaves(out)[0]
    jax.device_get(leaf.ravel()[0] if leaf.ndim else leaf)


def timeit(fn, *args, reps=5):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(4):
            out = fn(*args)
        _sync(out)
        ts.append((time.perf_counter() - t0) / 4)
    return statistics.median(ts)


def layout_v2(i, s, t):
    n = i.shape[0]
    _, _, s_idx, s_preds, s_target = jax.lax.sort(
        (i, -s, i, s, t), num_keys=2, is_stable=True
    )
    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    seg_id = jnp.cumsum(new_seg) - 1
    pos = jnp.arange(n)
    seg_start_row = jax.lax.cummax(jnp.where(new_seg, pos, 0))  # no gather
    rank = pos - seg_start_row + 1
    seg_count = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), seg_id, num_segments=n, indices_are_sorted=True
    )
    seg_index = jax.ops.segment_min(s_idx, seg_id, num_segments=n, indices_are_sorted=True)
    return seg_id, rank, s_preds, s_target, n, seg_count, seg_index

def ap_v2(i, s, t):
    n = i.shape[0]
    seg_id, rank, s_preds, s_target, n_seg, seg_count, seg_index = layout_v2(i, s, t)
    valid = (seg_count > 0) & (seg_index >= 0)
    binary_t = (s_target > 0).astype(jnp.float32)
    new_seg = rank == 1
    # within-segment cumsum of NON-NEGATIVE values: base via cummax, no gather
    g = jnp.cumsum(binary_t)
    base = jax.lax.cummax(jnp.where(new_seg, g - binary_t, 0.0))
    cumrel = g - base
    contrib = binary_t * cumrel / rank
    seg_sum = lambda v: jax.ops.segment_sum(v, seg_id, num_segments=n_seg, indices_are_sorted=True)
    n_pos = seg_sum(binary_t)
    scores = jnp.where(n_pos > 0, seg_sum(contrib) / jnp.maximum(n_pos, 1.0), 0.0)
    return scores, n_pos, valid


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=22)
    args = ap.parse_args()
    n = 1 << args.n
    rng = np.random.RandomState(0)
    idx = jnp.asarray(np.sort(rng.randint(0, n // 64, n)).astype(np.int32))
    scores = jnp.asarray(rng.rand(n).astype(np.float32))
    rel = jnp.asarray((rng.rand(n) > 0.7).astype(np.int32))

    f_sort1 = jax.jit(lambda s: jnp.sort(s))
    f_argsort1 = jax.jit(lambda s: jnp.argsort(s))
    f_lex2 = jax.jit(lambda i, s: jnp.lexsort((-s, i)))
    f_lex_gather = jax.jit(lambda i, s, t: tuple(x[jnp.lexsort((-s, i))] for x in (i, s, t)))

    def lex_payload(i, s, t):
        # single variadic sort carrying payloads instead of argsort+gathers
        neg = -s
        _, _, si, ss, st = jax.lax.sort((i, neg, i, s, t), num_keys=2, is_stable=True)
        return si, ss, st

    f_lex_payload = jax.jit(lex_payload)

    def seg_ops(i, s, t):
        return _segment_layout(i, s, t)

    f_layout = jax.jit(seg_ops)

    from metrics_tpu.ops.segment import grouped_retrieval_scores
    f_map = jax.jit(lambda i, s, t: grouped_retrieval_scores(i, s, t, "average_precision"))

    f_layout2 = jax.jit(layout_v2)
    f_ap2 = jax.jit(ap_v2)

    for name, fn, a in (
        ("sort_f32", f_sort1, (scores,)),
        ("argsort_f32", f_argsort1, (scores,)),
        ("lexsort2_idx", f_lex2, (idx, scores)),
        ("lexsort2+3gathers", f_lex_gather, (idx, scores, rel)),
        ("sort_payload5", f_lex_payload, (idx, scores, rel)),
        ("segment_layout", f_layout, (idx, scores, rel)),
        ("grouped_AP_full", f_map, (idx, scores, rel)),
        ("layout_v2", f_layout2, (idx, scores, rel)),
        ("AP_v2", f_ap2, (idx, scores, rel)),
    ):
        dt = timeit(fn, *a)
        print(f"  {name:20s} {dt * 1e3:8.1f} ms   {n / dt / 1e6:8.2f} Mdocs/s")


if __name__ == "__main__":
    main()
