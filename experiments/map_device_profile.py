"""End-to-end timing of the fully-device consolidated mAP path on the real TPU."""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
from experiments.map_profile2 import consolidate
from metrics_tpu.detection import MeanAveragePrecision


def main(n_images=1000):
    datasets = [bench._coco_like_dataset(n_images, seed) for seed in range(4)]
    device_data = [consolidate(p, t) for p, t in datasets]
    jax.device_get(device_data[-1][0]["boxes"])

    metric = MeanAveragePrecision()
    t0 = time.perf_counter()
    metric.update(*device_data[0])
    out = metric.compute()
    print(f"warm-up (compile): {time.perf_counter()-t0:6.1f} s, map={float(out['map']):.4f}")

    for preds, target in device_data[1:]:
        metric.reset()
        t0 = time.perf_counter()
        metric.update(preds, target)
        out = metric.compute()
        mv = float(jax.device_get(out["map"]))
        dt = time.perf_counter() - t0
        print(f"cycle {dt*1e3:7.1f} ms -> {n_images/dt:7.1f} img/s   map={mv:.4f}")

    from metrics_tpu.functional.detection import _mean_ap_device as D
    print("consolidated_tables compiles:", D.consolidated_tables._cache_size())


if __name__ == "__main__":
    main()
