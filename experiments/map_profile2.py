"""Profile the mAP cycle with CONSOLIDATED inputs on the real TPU: where does the
time go once per-image buffers are gone? Splits _calculate into group-build,
group-pack, kernel+fetch, and PR accumulation."""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
from metrics_tpu.detection import MeanAveragePrecision
from metrics_tpu.functional.detection import _mean_ap_kernel as _K


def consolidate(preds, target):
    B = len(preds)
    md = max(p[0].shape[0] for p in preds) or 1
    mg = max(t[0].shape[0] for t in target) or 1
    pb = np.zeros((B, md, 4), np.float32)
    ps = np.full((B, md), -np.inf, np.float32)
    pl = np.full((B, md), -1, np.int32)
    tb = np.zeros((B, mg, 4), np.float32)
    tl = np.full((B, mg), -1, np.int32)
    for i, ((db, dsc, dl), (gb, gl)) in enumerate(zip(preds, target)):
        n = db.shape[0]
        pb[i, :n], ps[i, :n], pl[i, :n] = db, dsc, dl
        n = gb.shape[0]
        tb[i, :n], tl[i, :n] = gb, gl
    return ({"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.asarray(pl)},
            {"boxes": jnp.asarray(tb), "labels": jnp.asarray(tl)})


def main(n_images=1000):
    datasets = [bench._coco_like_dataset(n_images, seed) for seed in range(3)]
    device_data = [consolidate(p, t) for p, t in datasets]
    jax.device_get(device_data[-1][0]["boxes"])

    metric = MeanAveragePrecision()
    metric.update(*device_data[0])
    jax.device_get(metric.compute()["map"])  # warm-up

    for preds, target in device_data[1:]:
        metric.reset()
        t0 = time.perf_counter()
        metric.update(preds, target)
        t_update = time.perf_counter() - t0

        t0 = time.perf_counter()
        host = metric._fetch_host_states()
        t_fetch = time.perf_counter() - t0

        t0 = time.perf_counter()
        classes = metric._get_classes(host=host)
        t_classes = time.perf_counter() - t0

        t0 = time.perf_counter()
        groups = metric._build_groups(classes, host=host)
        t_groups = time.perf_counter() - t0

        # pack + kernel + fetch (reproduce _calculate's middle)
        t0 = time.perf_counter()
        precisions, recalls = metric._calculate(classes, host=host)
        t_calc = time.perf_counter() - t0

        t0 = time.perf_counter()
        metric._summarize_results(precisions, recalls)
        t_sum = time.perf_counter() - t0

        total = t_update + t_fetch + t_classes + t_calc + t_sum
        print(
            f"update {t_update*1e3:6.1f} | fetch {t_fetch*1e3:6.1f} | classes {t_classes*1e3:6.1f} | "
            f"build_groups {t_groups*1e3:6.1f} (n={len(groups)}, inside calc) | "
            f"calculate {t_calc*1e3:7.1f} | summarize {t_sum*1e3:5.1f} | "
            f"total {total*1e3:7.1f} ms -> {n_images/total:6.1f} img/s"
        )
    print("match_groups compile count:", _K._match_groups._cache_size())


if __name__ == "__main__" and "--breakdown" not in sys.argv:
    main()


def breakdown(n_images=1000):
    """Copy of _calculate's body with timers around each stage."""
    datasets = [bench._coco_like_dataset(n_images, seed) for seed in range(3)]
    device_data = [consolidate(p, t) for p, t in datasets]
    jax.device_get(device_data[-1][0]["boxes"])

    metric = MeanAveragePrecision()
    metric.update(*device_data[0])
    jax.device_get(metric.compute()["map"])  # warm-up

    for preds, target in device_data[1:]:
        metric.reset()
        metric.update(preds, target)
        host = metric._fetch_host_states()
        classes = metric._get_classes(host=host)

        num_t = len(metric.iou_thresholds)
        t0 = time.perf_counter()
        groups = metric._build_groups(classes, host=host)
        t_groups = time.perf_counter() - t0

        ng = len(groups)
        pad_n = _K._pow2(ng)
        area_ranges = np.asarray(list(metric.bbox_area_ranges.values()), np.float32)
        group_cls = np.zeros(ng, np.int64)

        t0 = time.perf_counter()
        pad_d = _K._pow2(max(1, max(g[1].shape[0] for g in groups)))
        pad_g = _K._pow2(max(1, max(g[3].shape[0] for g in groups)))
        det_scores = np.full((pad_n, pad_d), -np.inf, np.float32)
        det_valid = np.zeros((pad_n, pad_d), bool)
        gt_valid = np.zeros((pad_n, pad_g), bool)
        det_boxes = np.zeros((pad_n, pad_d, 4), np.float32)
        gt_boxes = np.zeros((pad_n, pad_g, 4), np.float32)
        for i, (k_idx, db, ds, gb) in enumerate(groups):
            group_cls[i] = k_idx
            det_boxes[i, : db.shape[0]] = db
            det_scores[i, : ds.shape[0]] = ds
            det_valid[i, : db.shape[0]] = True
            gt_boxes[i, : gb.shape[0]] = gb
            gt_valid[i, : gb.shape[0]] = True
        t_pack = time.perf_counter() - t0

        t0 = time.perf_counter()
        dev_args = [jnp.asarray(x) for x in (det_boxes, det_valid, gt_boxes, gt_valid)]
        jax.device_get(dev_args[0][0, 0])  # force upload
        t_h2d = time.perf_counter() - t0

        t0 = time.perf_counter()
        out = _K._match_groups(*dev_args, jnp.asarray(metric.iou_thresholds, jnp.float32), jnp.asarray(area_ranges))
        out[0].block_until_ready() if hasattr(out[0], "block_until_ready") else None
        jax.device_get(out[2][0, 0])
        t_kernel = time.perf_counter() - t0

        t0 = time.perf_counter()
        det_matched, det_ignored, npig_ga = jax.device_get(out)
        t_d2h = time.perf_counter() - t0
        nbytes = det_matched.nbytes + det_ignored.nbytes + npig_ga.nbytes

        det_matched = det_matched[:ng]
        det_ignored = det_ignored[:ng]
        npig_ga = npig_ga[:ng]

        t0 = time.perf_counter()
        num_r = len(metric.rec_thresholds)
        num_k = len(classes)
        num_a = len(metric.bbox_area_ranges)
        num_m = len(metric.max_detection_thresholds)
        precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
        recall = -np.ones((num_t, num_k, num_a, num_m))
        rec_thresholds = np.asarray(metric.rec_thresholds)
        _EPS = float(np.finfo(np.float64).eps)
        for k_idx in range(num_k):
            sel = np.nonzero(group_cls == k_idx)[0]
            if sel.size == 0:
                continue
            for a_idx in range(num_a):
                npig = int(npig_ga[sel, a_idx].sum())
                if npig == 0:
                    continue
                for m_idx, max_det in enumerate(metric.max_detection_thresholds):
                    cap = min(max_det, det_scores.shape[1])
                    scores_flat = det_scores[sel, :cap].reshape(-1)
                    matched = det_matched[sel, a_idx, :, :cap].transpose(1, 0, 2).reshape(num_t, -1)
                    ignored = det_ignored[sel, a_idx, :, :cap].transpose(1, 0, 2).reshape(num_t, -1)
                    order = np.argsort(-scores_flat, kind="stable")
                    matched = matched[:, order]
                    ignored = ignored[:, order]
                    tps = np.cumsum(matched & ~ignored, axis=1, dtype=np.float64)
                    fps = np.cumsum(~matched & ~ignored, axis=1, dtype=np.float64)
                    nd = tps.shape[1]
                    rc = tps / npig
                    pr = tps / (fps + tps + _EPS)
                    recall[:, k_idx, a_idx, m_idx] = rc[:, -1] if nd else 0.0
                    pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
                    for t_idx in range(num_t):
                        inds = np.searchsorted(rc[t_idx], rec_thresholds, side="left")
                        num_inds = int(inds.argmax()) if inds.max() >= nd else num_r
                        prec = np.zeros(num_r)
                        prec[:num_inds] = pr[t_idx][inds[:num_inds]]
                        precision[t_idx, :, k_idx, a_idx, m_idx] = prec
        t_pr = time.perf_counter() - t0
        print(
            f"groups {t_groups*1e3:6.1f} | pack {t_pack*1e3:6.1f} | h2d {t_h2d*1e3:6.1f} | "
            f"kernel {t_kernel*1e3:7.1f} | d2h {t_d2h*1e3:6.1f} ({nbytes/1e6:.0f} MB) | hostPR {t_pr*1e3:7.1f}"
        )


if __name__ == "__main__" and "--breakdown" in sys.argv:
    breakdown()
