"""Probe: is the fused tuple-carry segmented multi-scan viable at stream scale?

Three questions behind ops/segment.py's round-10 fusion, answered empirically:

1. **Compile time.** Round 5 rejected ``lax.associative_scan`` at 2^24 rows for
   the per-element FLOAT scan variants (minutes-long compiles on the tunneled
   v5e backend). The integer tuple-carry form is a different program: one scan
   over a ``(flags, lane0, lane1, ...)`` carry with a branchless segmented
   monoid. Measured here: ~5 s at 2^24 rows / 3 lanes on current jaxlib (and
   ~0.7 s even at test-suite shapes) — acceptable for a warm serving process
   (paid once per shape through the persistent compile cache), which is why
   the dispatcher reserves this tier for min/max lanes over real segment
   flags and routes sum-only / statically-global requests to native
   cumsum/cummax scans that compile in milliseconds.
2. **Run time vs unfused.** k statistics in one pass vs k cumsum passes: the
   fused carry reads the flag column once and keeps the lanes in the same
   scan network, so wall time scales well below k× a single scan.
3. **Pallas crossover.** On TPU the block-streaming kernel (flag-aware
   Hillis-Steele in-register, open-segment carry in scratch) takes over at
   ``SEGSCAN_PALLAS_MIN_SIZE``; on CPU it only runs in interpret mode, so this
   probe times it on a small slice purely as a parity check.

Run: JAX_PLATFORMS=cpu python experiments/segment_fused_probe.py   (1+2, parity)
     python experiments/segment_fused_probe.py                      (TPU: adds 3)
"""
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops.segment import force_scan_impl, segment_multi_scan

N_GRID_TPU = (1 << 21, 1 << 24)
N_GRID_CPU = (1 << 18, 1 << 21)
LANES = 3


def timed(fn, *args, reps=5):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
        times.append(time.perf_counter() - t0)
    return compile_s, statistics.median(times)


def main():
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    print(f"backend={jax.default_backend()}")
    print(f"{'n':>10} {'lanes':>5} {'compile_s':>9} {'fused_ms':>9} {'unfused_ms':>10} {'speedup':>7}")

    for n in N_GRID_TPU if on_tpu else N_GRID_CPU:
        vals = tuple(jnp.asarray(rng.integers(0, 7, n).astype(np.int32)) for _ in range(LANES))
        flags = jnp.asarray(rng.random(n) < 0.01)
        ops = ("sum", "sum", "min")

        with force_scan_impl("assoc"):
            fused = jax.jit(lambda *a: segment_multi_scan(a[:-1], a[-1], ops=ops))
            c_fused, t_fused = timed(fused, *vals, flags)
            unfused = jax.jit(
                lambda *a: tuple(
                    segment_multi_scan((v,), a[-1], ops=(o,))[0] for v, o in zip(a[:-1], ops)
                )
            )
            c_unf, t_unf = timed(unfused, *vals, flags)
        print(
            f"{n:>10} {LANES:>5} {c_fused:>9.2f} {t_fused * 1e3:>9.2f}"
            f" {t_unf * 1e3:>10.2f} {t_unf / t_fused:>7.2f}"
        )

        # parity across tiers (interpret mode on CPU: small slice only)
        sl = slice(0, 1 << 16)
        with force_scan_impl("pallas_interpret" if not on_tpu else "pallas"):
            pal = segment_multi_scan(tuple(v[sl] for v in vals), flags[sl], ops=ops)
        with force_scan_impl("assoc"):
            ref = segment_multi_scan(tuple(v[sl] for v in vals), flags[sl], ops=ops)
        for p, r in zip(pal, ref):
            assert jnp.array_equal(p, r)
        print(f"{'':>10} parity assoc == {'pallas' if on_tpu else 'pallas_interpret'}: ok")


if __name__ == "__main__":
    main()
