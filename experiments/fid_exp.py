"""Experiment: what bounds the FID Inception forward (1389 img/s r03 ~= 4% MFU)?

Grid: batch size x compute dtype x resize-included, deep dispatch queue.
Run: python experiments/fid_exp.py
"""
import statistics
import time

import jax
import jax.numpy as jnp

from metrics_tpu.models.inception import inception_features, random_inception_params, _tf1_bilinear_resize


def timed(fn, x, steps, reps=3):
    out = fn(x)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = None
        for _ in range(steps):
            o = fn(x)
        jax.device_get(jax.tree.leaves(o)[0].ravel()[0])
        rates.append(steps * x.shape[0] / (time.perf_counter() - t0))
    return statistics.median(rates)


def main():
    params = random_inception_params(0)

    def fwd_f32(x):
        return inception_features(params, x, 2048).sum(0)

    def fwd_bf16(x):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a, params)
        # keep bn math f32; cast activations bf16 after resize/normalize
        return inception_features(params, x, 2048, compute_dtype=jnp.bfloat16).sum(0)

    def resize_only(x):
        return _tf1_bilinear_resize(x.astype(jnp.float32), 299, 299).sum()

    key = jax.random.PRNGKey(0)
    for batch in (32, 128):
        x = jax.random.randint(key, (batch, 3, 299, 299), 0, 256, dtype=jnp.uint8)
        steps = max(4, 1024 // batch)
        r_f32 = timed(jax.jit(fwd_f32), x, steps)
        r_res = timed(jax.jit(resize_only), x, steps)
        print(f"batch {batch:4d}: f32 {r_f32:8.0f} img/s   resize-only {r_res:8.0f} img/s")
        try:
            r_bf16 = timed(jax.jit(fwd_bf16), x, steps)
            print(f"             bf16 {r_bf16:8.0f} img/s")
        except TypeError as e:
            print("             bf16 path needs compute_dtype support:", e)


if __name__ == "__main__":
    main()
