"""Scaling probe for the mAP matching kernel: how does runtime scale with the
scan length (pad_d), group count, and gt width? Decides whether group-size
bucketing (short scans for the common case) is worth the routing complexity."""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from metrics_tpu.functional.detection._mean_ap_kernel import _match_groups

A = np.asarray([[0.0, 1e10], [0, 1024], [1024, 9216], [9216, 1e10]], np.float32)
T = np.linspace(0.5, 0.95, 10).astype(np.float32)


def timed_match(pad_n, pad_d, pad_g, reps=3):
    rng = np.random.RandomState(0)
    db = rng.rand(pad_n, pad_d, 4).astype(np.float32) * 100
    db[..., 2:] += db[..., :2]
    gb = rng.rand(pad_n, pad_g, 4).astype(np.float32) * 100
    gb[..., 2:] += gb[..., :2]
    dv = rng.rand(pad_n, pad_d) < 0.5
    gv = rng.rand(pad_n, pad_g) < 0.5
    args = [jnp.asarray(x) for x in (db, dv, gb, gv, T, A)]
    jax.device_get(_match_groups(*args)[2][0, 0])  # compile + settle
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = _match_groups(*args)
        jax.device_get(out[2][0, 0])
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    jax.device_get(jnp.zeros(8) + 1)
    for pad_n, pad_d, pad_g in (
        (8192, 128, 64),
        (8192, 64, 64),
        (8192, 32, 64),
        (8192, 16, 64),
        (8192, 16, 16),
        (8192, 128, 16),
        (2048, 128, 64),
        (512, 128, 64),
    ):
        dt = timed_match(pad_n, pad_d, pad_g)
        print(f"N={pad_n:5d} D={pad_d:4d} G={pad_g:3d}: {dt*1e3:8.1f} ms")


if __name__ == "__main__":
    main()
