"""Measure D2H packing strategies for mAP's per-image ragged state lists.

The round-4 bench showed the mAP cycle is transfer-bound: ~3 s of the ~1.8 s/1000-img
cycle is the batched device_get of ~5000 per-image buffers (~0.6 ms/buffer floor on
the tunneled backend). Candidate fixes move the packing onto the device so compute
fetches a handful of large buffers instead:

A. status quo: device_get of all per-image arrays in one pytree call
B. one eager jnp.concatenate over all arrays per state (compile keyed on the FULL
   ragged shape tuple -> recompiles every dataset)
C. chunked concat: eager concat in fixed-size operand chunks, then concat of chunks
D. exact-shape grouping: group arrays by identical shape, jnp.stack each group
   (compile keyed on (group_size, shape); pad group count to pow2 with a dummy)
E. host roundtrip per array (np.asarray) — known-bad floor check at small n

Run on the real TPU (axon tunnel) — the numbers only mean anything there.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def make_dataset(n_images: int, seed: int):
    rng = np.random.RandomState(seed)
    arrays = []
    for _ in range(n_images):
        n = int(np.clip(rng.poisson(12), 1, 90))
        arrays.append(jnp.asarray(rng.rand(n, 4).astype(np.float32)))
    return arrays


def sync(x):
    jax.device_get(x)


def timeit(fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    dt = time.perf_counter() - t0
    return out, dt


def strat_a(arrays):
    return jax.device_get(arrays)


def strat_b(arrays):
    big = jnp.concatenate(arrays, axis=0)
    return jax.device_get(big)


def strat_c(arrays, chunk=64):
    chunks = [jnp.concatenate(arrays[i : i + chunk], axis=0) for i in range(0, len(arrays), chunk)]
    big = jnp.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]
    return jax.device_get(big)


def strat_d(arrays):
    groups = {}
    for i, a in enumerate(arrays):
        groups.setdefault(a.shape, []).append((i, a))
    out = {}
    for shape, items in groups.items():
        stack = jnp.stack([a for _, a in items])
        out[shape] = (jax.device_get(stack), [i for i, _ in items])
    return out


def main():
    n = 1000
    # warm the backend
    sync(jnp.zeros(8) + 1)

    for trial_seed in (0, 1, 2):
        arrays = make_dataset(n, trial_seed)
        sync(arrays[-1])  # settle H2D queue
        print(f"--- dataset seed={trial_seed}, {n} ragged arrays ---")
        _, dt = timeit(strat_a, arrays)
        print(f"A pytree device_get of {n} buffers:   {dt*1e3:8.1f} ms  ({dt/n*1e3:.3f} ms/buf)")
        _, dt = timeit(strat_b, arrays)
        print(f"B single {n}-operand eager concat:    {dt*1e3:8.1f} ms")
        _, dt = timeit(strat_b, arrays)
        print(f"B   (second call, compile cached):    {dt*1e3:8.1f} ms")
        _, dt = timeit(strat_c, arrays)
        print(f"C chunked concat (64-op chunks):      {dt*1e3:8.1f} ms")
        _, dt = timeit(strat_c, arrays)
        print(f"C   (second call):                    {dt*1e3:8.1f} ms")
        _, dt = timeit(strat_d, arrays)
        print(f"D exact-shape group + stack:          {dt*1e3:8.1f} ms")
        _, dt = timeit(strat_d, arrays)
        print(f"D   (second call):                    {dt*1e3:8.1f} ms")

    small = make_dataset(64, 9)
    _, dt = timeit(lambda: [np.asarray(a) for a in small])
    print(f"E per-array np.asarray (64 arrays):   {dt*1e3:8.1f} ms  ({dt/64*1e3:.3f} ms/buf)")


if __name__ == "__main__":
    main()


# ---------------------------------------------------------------- experiment 2
# Compile-stable packing: pad each image to pow2-bucketed rows (jitted per
# (in_rows, bucket) pair -> cached forever), then a fixed-chunk concat tree over
# uniform shapes with operand-count padded by a dummy (cache keys independent of
# the dataset's raggedness). Measures steady-state dispatch + transfer cost.

def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def make_image_triples(n_images, seed):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_images):
        nd = int(np.clip(rng.poisson(12), 1, 90))
        out.append((
            jnp.asarray(rng.rand(nd, 4).astype(np.float32)),
            jnp.asarray(rng.rand(nd).astype(np.float32)),
            jnp.asarray(rng.randint(0, 5, nd), jnp.int32),
        ))
    return out


from functools import partial


@partial(jax.jit, static_argnames=("bucket",))
def pad_triple(b, s, l, bucket):
    pad = bucket - b.shape[0]
    return (
        jnp.pad(b, ((0, pad), (0, 0))),
        jnp.pad(s, ((0, pad),), constant_values=-np.inf),
        jnp.pad(l, ((0, pad),), constant_values=-1),
    )


CHUNK_OPS = 64


def chunk_tree_concat(stacks):
    """Concat a list of uniformly-shaped arrays with dataset-independent compile keys."""
    while len(stacks) > 1:
        if len(stacks) % CHUNK_OPS:
            dummy = jnp.zeros_like(stacks[0])
            stacks = stacks + [dummy] * (CHUNK_OPS - len(stacks) % CHUNK_OPS) if len(stacks) > CHUNK_OPS else stacks + [dummy] * (_pow2(len(stacks)) - len(stacks))
        step = min(CHUNK_OPS, len(stacks))
        stacks = [jnp.concatenate(stacks[i:i + step], axis=0) for i in range(0, len(stacks), step)]
    return stacks[0]


def strat_pad_bucket(triples):
    buckets = {}
    for b, s, l in triples:
        bucket = _pow2(b.shape[0])
        pb, ps, pl = pad_triple(b, s, l, bucket)
        buckets.setdefault(bucket, []).append((pb, ps, pl))
    outs = {}
    for bucket, items in buckets.items():
        bb = chunk_tree_concat([x[0] for x in items])
        ss = chunk_tree_concat([x[1] for x in items])
        ll = chunk_tree_concat([x[2] for x in items])
        outs[bucket] = jax.device_get((bb, ss, ll))
    return outs


def exp2():
    n = 1000
    sync(jnp.zeros(8) + 1)
    # dispatch-overhead probe: tiny jitted op, queued without sync
    x = jnp.ones((64, 4))
    f = jax.jit(lambda a: a + 1)
    sync(f(x))
    t0 = time.perf_counter()
    ys = [f(x) for _ in range(2000)]
    sync(ys[-1])
    print(f"2000 tiny jitted dispatches (queued): {(time.perf_counter()-t0)*1e3:8.1f} ms")

    for seed in (0, 1, 2):
        triples = make_image_triples(n, seed)
        sync(triples[-1][0])
        t0 = time.perf_counter()
        out = strat_pad_bucket(triples)
        dt = time.perf_counter() - t0
        nb = sum(3 for _ in out)
        print(f"seed={seed} pad+bucket+chunktree ({len(out)} buckets, {nb} fetches): {dt*1e3:8.1f} ms")


if __name__ == "__main__" and __import__("sys").argv[-1] == "exp2":
    exp2()
