"""Break down the mAP bench cycle: update dispatches, state fetch, group build,
matching kernel, host PR accumulation. Run on the real TPU tunnel."""
import time

import jax
import jax.numpy as jnp
import numpy as np

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
from metrics_tpu.detection import MeanAveragePrecision


def main(n_images=1000):
    datasets = [bench._coco_like_dataset(n_images, seed) for seed in range(2)]

    def to_jnp(preds, target):
        ps = [
            {"boxes": jnp.asarray(b), "scores": jnp.asarray(s), "labels": jnp.asarray(l.astype(np.int32))}
            for b, s, l in preds
        ]
        ts = [{"boxes": jnp.asarray(b), "labels": jnp.asarray(l.astype(np.int32))} for b, l in target]
        return ps, ts

    device_data = [to_jnp(p, t) for p, t in datasets]
    jax.device_get(device_data[-1][0][-1]["boxes"])

    metric = MeanAveragePrecision()
    metric.update(*device_data[0])
    jax.device_get(metric.compute()["map"])  # warm-up

    for preds, target in device_data[1:]:
        metric.reset()
        t0 = time.perf_counter()
        metric.update(preds, target)
        t_update = time.perf_counter() - t0

        t0 = time.perf_counter()
        host = metric._fetch_host_states()
        t_fetch = time.perf_counter() - t0

        t0 = time.perf_counter()
        classes = metric._get_classes(host=host)
        groups = metric._build_groups(classes, host=host)
        t_groups = time.perf_counter() - t0

        t0 = time.perf_counter()
        precisions, recalls = metric._calculate(classes, host=host)
        t_calc = time.perf_counter() - t0

        t0 = time.perf_counter()
        metric._summarize_results(precisions, recalls)
        t_sum = time.perf_counter() - t0

        total = t_update + t_fetch + t_calc + t_sum
        print(
            f"update {t_update*1e3:7.1f} ms | fetch {t_fetch*1e3:7.1f} ms | "
            f"build_groups {t_groups*1e3:7.1f} ms (x2 inside calc) | "
            f"calculate(groups+kernel+PR) {t_calc*1e3:7.1f} ms | summarize {t_sum*1e3:6.1f} ms | "
            f"total-ish {total*1e3:7.1f} ms -> {n_images/total:6.1f} img/s"
        )


if __name__ == "__main__":
    main()
