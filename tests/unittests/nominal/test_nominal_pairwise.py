"""Differential tests for nominal association metrics (vs scipy/pandas-free references)
and pairwise distance functionals (vs sklearn).

References: tests/unittests/nominal/test_{cramers,pearson,theils_u,tschuprows}.py and
tests/unittests/pairwise/test_pairwise_distance.py in the reference repo (which use the
`dython` library and sklearn.metrics.pairwise respectively).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats.contingency import association
from sklearn.metrics.pairwise import (
    cosine_similarity,
    euclidean_distances,
    linear_kernel,
    manhattan_distances,
)

from metrics_tpu.functional.nominal import (
    cramers_v,
    cramers_v_matrix,
    pearsons_contingency_coefficient,
    theils_u,
    tschuprows_t,
)
from metrics_tpu.functional.pairwise import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
    pairwise_minkowski_distance,
)
from metrics_tpu.nominal import CramersV, PearsonsContingencyCoefficient, TheilsU, TschuprowsT

_rng = np.random.default_rng(42)
_NUM_CLASSES = 4


def _confmat(preds, target, n):
    cm = np.zeros((n, n), dtype=np.int64)
    np.add.at(cm, (target, preds), 1)
    return cm


def _sp_association(preds, target, method):
    # scipy "cramer"/"tschuprow"/"pearson" operate on the contingency table with
    # empty rows/cols dropped, no bias correction
    cm = _confmat(preds, target, _NUM_CLASSES)
    cm = cm[cm.sum(1) > 0][:, cm.sum(0) > 0]
    return association(cm, method=method, correction=False)


class TestNominal:
    def setup_method(self):
        self.preds = _rng.integers(0, _NUM_CLASSES, 200)
        self.target = (self.preds + _rng.integers(0, 2, 200)) % _NUM_CLASSES

    def test_cramers_no_bias_correction(self):
        val = cramers_v(jnp.array(self.preds), jnp.array(self.target), bias_correction=False)
        ref = _sp_association(self.preds, self.target, "cramer")
        np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_tschuprows_no_bias_correction(self):
        val = tschuprows_t(jnp.array(self.preds), jnp.array(self.target), bias_correction=False)
        ref = _sp_association(self.preds, self.target, "tschuprow")
        np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_pearson(self):
        val = pearsons_contingency_coefficient(jnp.array(self.preds), jnp.array(self.target))
        ref = _sp_association(self.preds, self.target, "pearson")
        np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_theils_u_properties(self):
        # U(x|x) == 1; independence ~ 0; asymmetric in general
        x = jnp.array(self.preds)
        assert np.isclose(float(theils_u(x, x)), 1.0, atol=1e-6)
        indep = jnp.array(_rng.integers(0, _NUM_CLASSES, 5000))
        other = jnp.array(_rng.integers(0, _NUM_CLASSES, 5000))
        assert float(theils_u(indep, other)) < 0.01

    def test_theils_u_manual(self):
        # entropy-based hand computation
        preds, target = self.preds, self.target
        cm = _confmat(preds, target, _NUM_CLASSES).astype(float)
        n = cm.sum()
        p_xy = cm / n
        p_y = cm.sum(1) / n  # rows (= target axis in our confmat[target, preds])
        with np.errstate(divide="ignore", invalid="ignore"):
            s_xy = np.nansum(p_xy * np.log(p_y[:, None] / p_xy))
        p_x = cm.sum(0) / n
        s_x = -np.nansum(p_x * np.log(p_x))
        ref = (s_x - s_xy) / s_x
        val = theils_u(jnp.array(preds), jnp.array(target))
        np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_classes_accumulate(self):
        m = CramersV(num_classes=_NUM_CLASSES, bias_correction=False)
        half = len(self.preds) // 2
        m.update(jnp.array(self.preds[:half]), jnp.array(self.target[:half]))
        m.update(jnp.array(self.preds[half:]), jnp.array(self.target[half:]))
        ref = _sp_association(self.preds, self.target, "cramer")
        np.testing.assert_allclose(float(m.compute()), ref, atol=1e-6)

        for cls, fn in [
            (PearsonsContingencyCoefficient, pearsons_contingency_coefficient),
            (TheilsU, theils_u),
        ]:
            m = cls(num_classes=_NUM_CLASSES)
            m.update(jnp.array(self.preds), jnp.array(self.target))
            np.testing.assert_allclose(
                float(m.compute()), float(fn(jnp.array(self.preds), jnp.array(self.target))), atol=1e-6
            )
        m = TschuprowsT(num_classes=_NUM_CLASSES, bias_correction=False)
        m.update(jnp.array(self.preds), jnp.array(self.target))
        np.testing.assert_allclose(
            float(m.compute()),
            float(tschuprows_t(jnp.array(self.preds), jnp.array(self.target), bias_correction=False)),
            atol=1e-6,
        )

    def test_matrix_symmetry(self):
        matrix = jnp.array(_rng.integers(0, _NUM_CLASSES, (100, 4)))
        out = cramers_v_matrix(matrix, bias_correction=False)
        out = np.asarray(out)
        np.testing.assert_allclose(out, out.T, atol=1e-6)
        np.testing.assert_allclose(np.diag(out), 1.0)


class TestPairwise:
    def setup_method(self):
        self.x = _rng.normal(size=(10, 5)).astype(np.float32)
        self.y = _rng.normal(size=(8, 5)).astype(np.float32)

    @pytest.mark.parametrize(
        ("ours", "ref"),
        [
            (pairwise_cosine_similarity, cosine_similarity),
            (pairwise_euclidean_distance, euclidean_distances),
            (pairwise_linear_similarity, linear_kernel),
            (pairwise_manhattan_distance, manhattan_distances),
        ],
    )
    def test_vs_sklearn(self, ours, ref):
        np.testing.assert_allclose(
            np.asarray(ours(jnp.array(self.x), jnp.array(self.y))), ref(self.x, self.y), atol=1e-5
        )
        # x-only form zeroes the diagonal
        got = np.asarray(ours(jnp.array(self.x)))
        expected = ref(self.x)
        np.fill_diagonal(expected, 0)
        np.testing.assert_allclose(got, expected, atol=1e-5)

    def test_minkowski(self):
        from scipy.spatial.distance import cdist

        for p in (1, 2, 3.5):
            got = np.asarray(pairwise_minkowski_distance(jnp.array(self.x), jnp.array(self.y), exponent=p))
            expected = cdist(self.x, self.y, metric="minkowski", p=p)
            np.testing.assert_allclose(got, expected, atol=1e-4)

    def test_reductions(self):
        full = np.asarray(pairwise_euclidean_distance(jnp.array(self.x), jnp.array(self.y)))
        np.testing.assert_allclose(
            np.asarray(pairwise_euclidean_distance(jnp.array(self.x), jnp.array(self.y), reduction="mean")),
            full.mean(-1),
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(pairwise_euclidean_distance(jnp.array(self.x), jnp.array(self.y), reduction="sum")),
            full.sum(-1),
            atol=1e-5,
        )
