"""8-device sharded equivalence for nominal metrics (VERDICT r2 item 3)."""
import numpy as np

from tests.helpers.testers import MetricTester

from metrics_tpu.nominal import CramersV

_rng = np.random.RandomState(31)
NUM_BATCHES, BATCH, C = 4, 64, 4
PREDS = _rng.randint(0, C, (NUM_BATCHES, BATCH)).astype(np.int32)
TARGET = ((PREDS + (_rng.rand(NUM_BATCHES, BATCH) < 0.3)) % C).astype(np.int32)


def _ref_cramers(preds, target, correction=True):
    """Bias-corrected Cramer's V from the contingency table (reference
    functional/nominal/cramers.py)."""
    preds, target = preds.reshape(-1), target.reshape(-1)
    table = np.zeros((C, C))
    for p, t in zip(preds, target):
        table[p, t] += 1
    n = table.sum()
    row, col = table.sum(1), table.sum(0)
    expected = np.outer(row, col) / n
    with np.errstate(divide="ignore", invalid="ignore"):
        chi2 = np.nansum(np.where(expected > 0, (table - expected) ** 2 / expected, 0.0))
    phi2 = chi2 / n
    r, k = (row > 0).sum(), (col > 0).sum()
    if correction:
        phi2 = max(0.0, phi2 - (k - 1) * (r - 1) / (n - 1))
        r = r - (r - 1) ** 2 / (n - 1)
        k = k - (k - 1) ** 2 / (n - 1)
    return float(np.sqrt(phi2 / min(k - 1, r - 1)))


class TestShardedNominal(MetricTester):
    atol = 1e-5

    def test_cramers_sharded(self):
        self.run_class_metric_test(
            PREDS,
            TARGET,
            CramersV,
            _ref_cramers,
            metric_args={"num_classes": C},
            check_batch=False,  # per-batch bias correction differs from all-data
            sharded=True,
        )
