"""Seeded fuzz parity: random configurations vs the actual reference library.

Complements the fixed cartesian grid (test_classification_parity_grid.py) with
SHAPE and data diversity — odd lengths, tiny batches, extra dims, degenerate
class distributions, logits vs probs vs hard labels — across randomly drawn
argument combinations. Every case is reproducible from its seed.
"""
import numpy as np
import pytest

import metrics_tpu.functional.classification as F

from .conftest import assert_close

N_CASES = 60


def _draw_case(seed):
    rng = np.random.RandomState(seed)
    task = rng.choice(["binary", "multiclass", "multilabel"])
    n = int(rng.choice([1, 2, 7, 33, 100, 257]))
    kwargs = {}
    if task == "binary":
        name = rng.choice(["binary_accuracy", "binary_f1_score", "binary_stat_scores", "binary_precision"])
        preds = rng.rand(n).astype(np.float32) if rng.rand() < 0.5 else rng.randn(n).astype(np.float32) * 2
        target = rng.randint(0, 2, n)
        if rng.rand() < 0.3:
            kwargs["threshold"] = float(rng.choice([0.25, 0.5, 0.75]))
    elif task == "multiclass":
        nc = int(rng.choice([2, 3, 5, 11]))
        name = rng.choice(
            ["multiclass_accuracy", "multiclass_f1_score", "multiclass_stat_scores", "multiclass_recall"]
        )
        kwargs["num_classes"] = nc
        kwargs["average"] = str(rng.choice(["micro", "macro", "weighted", "none"]))
        if rng.rand() < 0.5:
            preds = rng.rand(n, nc).astype(np.float32)
            preds = preds / preds.sum(-1, keepdims=True)
        else:
            preds = rng.randint(0, nc, n)
        target = rng.randint(0, nc, n)
        if rng.rand() < 0.3:  # skewed targets: some classes absent
            target = np.minimum(target, 1)
        if rng.rand() < 0.3:
            kwargs["ignore_index"] = int(rng.choice([0, -1]))
            target = target.copy()
            target[:: max(2, n // 5)] = kwargs["ignore_index"]
        if rng.rand() < 0.3 and preds.ndim == 2 and nc > 2:
            kwargs["top_k"] = 2
    else:
        nl = int(rng.choice([2, 3, 6]))
        name = rng.choice(["multilabel_accuracy", "multilabel_f1_score", "multilabel_stat_scores"])
        kwargs["num_labels"] = nl
        kwargs["average"] = str(rng.choice(["micro", "macro", "weighted", "none"]))
        preds = rng.rand(n, nl).astype(np.float32)
        target = rng.randint(0, 2, (n, nl))
    return name, preds, target, kwargs




def _compare(ref_fn, our_fn, args_np, kwargs, atol, text=False):
    """Shared comparison protocol for every fuzz driver: run the reference on
    torch tensors (or raw strings) and ours on jnp arrays, assert closeness."""
    import jax.numpy as jnp
    import torch

    if text:
        theirs = ref_fn(*args_np, **kwargs)
        ours = our_fn(*args_np, **kwargs)
    else:
        theirs = ref_fn(*[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs)
        ours = our_fn(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)

@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_case(ref, seed):
    name, preds, target, kwargs = _draw_case(seed)
    _compare(getattr(ref.functional.classification, name), getattr(F, name), (preds, target), kwargs, 1e-5)


# ------------------------------------------------------- regression domain

def _draw_regression_case(seed):
    rng = np.random.RandomState(1000 + seed)
    name = rng.choice(
        [
            "mean_squared_error", "mean_absolute_error", "explained_variance",
            "r2_score", "cosine_similarity", "pearson_corrcoef", "spearman_corrcoef",
            "mean_absolute_percentage_error", "symmetric_mean_absolute_percentage_error",
            "mean_squared_log_error", "log_cosh_error", "kendall_rank_corrcoef",
        ]
    )
    n = int(rng.choice([2, 5, 33, 100]))
    kwargs = {}
    if name == "cosine_similarity":
        preds = rng.randn(n, 8).astype(np.float32)
        target = rng.randn(n, 8).astype(np.float32)
    elif name in ("mean_absolute_percentage_error", "symmetric_mean_absolute_percentage_error",
                  "mean_squared_log_error"):
        preds = np.abs(rng.randn(n)).astype(np.float32) + 0.5
        target = np.abs(rng.randn(n)).astype(np.float32) + 0.5
    else:
        preds = rng.randn(n).astype(np.float32)
        target = (preds + rng.randn(n) * rng.choice([0.1, 1.0, 5.0])).astype(np.float32)
    return name, preds, target, kwargs


@pytest.mark.parametrize("seed", range(40))
def test_fuzz_regression_case(ref, seed):
    import metrics_tpu.functional.regression as R

    name, preds, target, kwargs = _draw_regression_case(seed)
    ref_fn = getattr(ref.functional.regression, name, None) or getattr(ref.functional, name)
    _compare(ref_fn, getattr(R, name), (preds, target), kwargs, 1e-4)


# ----------------------------------------------------------- text domain

_WORDS = [
    "the", "a", "cat", "dog", "sat", "ran", "on", "under", "mat", "tree",
    "fast", "slow", "red", "blue", "big", "jumped", "house", "bird", "saw", "ate",
]


def _rand_sentence(rng, lo=1, hi=12):
    return " ".join(rng.choice(_WORDS, rng.randint(lo, hi)))


def _draw_text_case(seed):
    rng = np.random.RandomState(2000 + seed)
    name = rng.choice(
        ["word_error_rate", "char_error_rate", "match_error_rate",
         "word_information_lost", "word_information_preserved", "bleu_score", "chrf_score"]
    )
    n = int(rng.choice([1, 3, 8]))
    preds = [_rand_sentence(rng) for _ in range(n)]
    if rng.rand() < 0.3:  # some predictions identical to targets
        target = list(preds)
    else:
        target = [_rand_sentence(rng) for _ in range(n)]
    if name == "bleu_score":
        # reference signature: (preds, target) with target as list-of-references
        return name, preds, [[t] for t in target], {"n_gram": int(rng.choice([1, 2, 3]))}
    return name, preds, target, {}


@pytest.mark.parametrize("seed", range(40))
def test_fuzz_text_case(ref, seed):
    import metrics_tpu.functional.text as T

    name, preds, target, kwargs = _draw_text_case(seed)
    _compare(getattr(ref.functional.text, name), getattr(T, name), (preds, target), kwargs, 1e-5, text=True)


# ------------------------------------------------------ retrieval domain

def _draw_retrieval_case(seed):
    rng = np.random.RandomState(3000 + seed)
    name = rng.choice(
        ["retrieval_average_precision", "retrieval_reciprocal_rank", "retrieval_normalized_dcg",
         "retrieval_precision", "retrieval_recall", "retrieval_hit_rate", "retrieval_fall_out",
         "retrieval_r_precision"]
    )
    n = int(rng.choice([1, 4, 17, 50]))
    preds = rng.rand(n).astype(np.float32)
    target = (rng.rand(n) > rng.choice([0.3, 0.7])).astype(np.int64)
    if not target.any():
        target[rng.randint(n)] = 1  # ensure a positive (reference errors otherwise vary)
    kwargs = {}
    if name in ("retrieval_precision", "retrieval_recall", "retrieval_hit_rate"):
        kwargs["top_k"] = int(rng.choice([1, 3, 10]))
    return name, preds, target, kwargs


@pytest.mark.parametrize("seed", range(30))
def test_fuzz_retrieval_case(ref, seed):
    import metrics_tpu.functional.retrieval as RT

    name, preds, target, kwargs = _draw_retrieval_case(seed)
    _compare(getattr(ref.functional.retrieval, name), getattr(RT, name), (preds, target), kwargs, 1e-5)


# --------------------------------------------------------- audio domain

def _draw_audio_case(seed):
    rng = np.random.RandomState(4000 + seed)
    name = rng.choice(
        ["signal_noise_ratio", "scale_invariant_signal_noise_ratio",
         "scale_invariant_signal_distortion_ratio", "signal_distortion_ratio"]
    )
    b = int(rng.choice([1, 2, 4]))
    # SDR needs length > its default filter taps; branch before generating
    t = 1000 if name == "signal_distortion_ratio" else int(rng.choice([64, 256, 1000]))
    noise = 0.1 if name == "signal_distortion_ratio" else float(rng.choice([0.05, 0.5]))
    scale = 1.0 if name == "signal_distortion_ratio" else float(rng.choice([0.5, 1.0]))
    preds = rng.randn(b, t).astype(np.float32)
    target = (preds * scale + rng.randn(b, t) * noise).astype(np.float32)
    kwargs = {}
    if name == "signal_noise_ratio":
        kwargs["zero_mean"] = bool(rng.rand() < 0.5)
    return name, preds, target, kwargs


@pytest.mark.parametrize("seed", range(24))
def test_fuzz_audio_case(ref, seed):
    import metrics_tpu.functional.audio as A

    name, preds, target, kwargs = _draw_audio_case(seed)
    atol = 1e-2 if name == "signal_distortion_ratio" else 1e-4  # toeplitz solve f32
    _compare(getattr(ref.functional.audio, name), getattr(A, name), (preds, target), kwargs, atol)


# --------------------------------------------------------- image domain

def _draw_image_case(seed):
    rng = np.random.RandomState(5000 + seed)
    name = rng.choice(
        ["peak_signal_noise_ratio", "structural_similarity_index_measure",
         "universal_image_quality_index", "total_variation", "spectral_angle_mapper",
         "error_relative_global_dimensionless_synthesis"]
    )
    b = int(rng.choice([1, 2]))
    hw = int(rng.choice([16, 33]))
    preds = rng.rand(b, 3, hw, hw).astype(np.float32)
    target = np.clip(preds + rng.randn(b, 3, hw, hw) * rng.choice([0.02, 0.2]), 0, 1).astype(np.float32)
    kwargs = {}
    if name == "peak_signal_noise_ratio":
        kwargs["data_range"] = 1.0
    if name == "structural_similarity_index_measure":
        kwargs["data_range"] = 1.0
        if rng.rand() < 0.3:
            kwargs["gaussian_kernel"] = False
            kwargs["kernel_size"] = 5
    if name == "error_relative_global_dimensionless_synthesis":
        preds = preds + 0.1
        target = target + 0.1
    return name, preds, target, kwargs


@pytest.mark.parametrize("seed", range(24))
def test_fuzz_image_case(ref, seed):
    import metrics_tpu.functional.image as I

    name, preds, target, kwargs = _draw_image_case(seed)
    ref_fn = getattr(ref.functional.image, name, None) or getattr(ref.functional, name)
    args = (preds,) if name == "total_variation" else (preds, target)
    _compare(ref_fn, getattr(I, name), args, kwargs, 1e-4)


# --------------------------------------------------------- nominal domain

def _draw_nominal_case(seed):
    rng = np.random.RandomState(6000 + seed)
    name = rng.choice(["cramers_v", "pearsons_contingency_coefficient", "theils_u", "tschuprows_t"])
    n = int(rng.choice([20, 100, 400]))
    c = int(rng.choice([2, 3, 5]))
    preds = rng.randint(0, c, n).astype(np.float32)  # float labels: the reference's documented input style
    noise = rng.rand(n) < rng.choice([0.1, 0.5])
    target = np.where(noise, rng.randint(0, c, n), preds).astype(np.float32)
    kwargs = {}
    # The REFERENCE's Yates bias correction (df==1, i.e. an effective 2x2 table
    # after it drops empty rows/cols) crashes on its own Long confmat for EVERY
    # input dtype (in-place float add, functional/nominal/utils.py:55) — a
    # reference bug our build doesn't share (see test_cramers_v_yates_2x2_vs_scipy).
    # Exclude exactly the effective-2x2 case so the fuzz compares only where the
    # reference can answer; distinct-value counts give the post-drop table shape.
    effective_2x2 = len(np.unique(preds)) <= 2 and len(np.unique(target)) <= 2
    if name in ("cramers_v", "tschuprows_t") and (effective_2x2 or rng.rand() < 0.5):
        kwargs["bias_correction"] = False
    return name, preds, target, kwargs


@pytest.mark.parametrize("seed", range(24))
def test_fuzz_nominal_case(ref, seed):
    import metrics_tpu.functional.nominal as NM

    name, preds, target, kwargs = _draw_nominal_case(seed)
    _compare(getattr(ref.functional.nominal, name), getattr(NM, name), (preds, target), kwargs, 1e-5)


def test_cramers_v_yates_2x2_vs_scipy():
    """2x2 + bias_correction: the reference crashes here (Long confmat, see
    _draw_nominal_case) — pin OUR Yates path against a scipy-derived oracle."""
    scipy_stats = pytest.importorskip("scipy.stats")
    import jax.numpy as jnp

    import metrics_tpu.functional.nominal as NM

    rng = np.random.RandomState(0)
    p = rng.randint(0, 2, 200)
    t = np.where(rng.rand(200) < 0.3, rng.randint(0, 2, 200), p)
    table = np.zeros((2, 2))
    for a, b in zip(p, t):
        table[a, b] += 1
    chi2 = scipy_stats.chi2_contingency(table, correction=True)[0]
    n = table.sum()
    phi2c = max(0.0, chi2 / n - 1.0 / (n - 1))
    rc = kc = 2 - 1.0 / (n - 1)
    expected = np.sqrt(phi2c / (min(kc, rc) - 1))
    ours = float(
        NM.cramers_v(jnp.asarray(p.astype(np.float32)), jnp.asarray(t.astype(np.float32)), bias_correction=True)
    )
    assert ours == pytest.approx(expected, abs=1e-6)
