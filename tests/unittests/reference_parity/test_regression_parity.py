"""Differential tests: regression + pairwise functionals vs the actual reference."""
import numpy as np
import pytest

import metrics_tpu.functional.regression as F

from .conftest import assert_close

N = 200
NO = 3  # outputs for multioutput sweeps

rng = np.random.RandomState(11)
P1 = rng.randn(N).astype(np.float32)
T1 = (P1 + 0.5 * rng.randn(N)).astype(np.float32)
P2 = rng.randn(N, NO).astype(np.float32)
T2 = (P2 + 0.5 * rng.randn(N, NO)).astype(np.float32)
POS_P = np.abs(P1) + 0.1
POS_T = np.abs(T1) + 0.1
PROB_P = rng.dirichlet(np.ones(5), N).astype(np.float32)
PROB_T = rng.dirichlet(np.ones(5), N).astype(np.float32)


def _run(ref, name, args_np, kwargs, atol=1e-5):
    import jax.numpy as jnp
    import torch

    theirs = getattr(ref.functional.regression, name)(*[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs)
    ours = getattr(F, name)(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)


SWEEP_1D = [
    ("mean_squared_error", {}),
    ("mean_squared_error", {"squared": False}),
    ("mean_absolute_error", {}),
    ("mean_absolute_percentage_error", {}),
    ("symmetric_mean_absolute_percentage_error", {}),
    ("weighted_mean_absolute_percentage_error", {}),
    ("log_cosh_error", {}),
    ("minkowski_distance", {"p": 3.0}),
    ("cosine_similarity", {"reduction": "mean"}),
    ("explained_variance", {}),
    ("explained_variance", {"multioutput": "variance_weighted"}),
    ("r2_score", {}),
    ("r2_score", {"adjusted": 5}),
    ("pearson_corrcoef", {}),
    ("spearman_corrcoef", {}),
    ("kendall_rank_corrcoef", {}),
    ("kendall_rank_corrcoef", {"variant": "a"}),
    ("concordance_corrcoef", {}),
    ("tweedie_deviance_score", {"power": 0.0}),
]


@pytest.mark.parametrize(("name", "kwargs"), SWEEP_1D)
def test_regression_1d(ref, name, kwargs):
    if name == "cosine_similarity":
        _run(ref, name, (P2, T2), kwargs)
        return
    _run(ref, name, (P1, T1), kwargs)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("mean_squared_error", {}),
        ("mean_absolute_error", {}),
        ("r2_score", {"multioutput": "raw_values"}),
        ("r2_score", {"multioutput": "uniform_average"}),
        ("explained_variance", {"multioutput": "raw_values"}),
        ("pearson_corrcoef", {}),
        ("spearman_corrcoef", {}),
        ("concordance_corrcoef", {}),
    ],
)
def test_regression_multioutput(ref, name, kwargs):
    _run(ref, name, (P2, T2), kwargs)


def test_msle_tweedie_positive(ref):
    _run(ref, "mean_squared_log_error", (POS_P, POS_T), {})
    _run(ref, "tweedie_deviance_score", (POS_P, POS_T), {"power": 1.5})
    _run(ref, "tweedie_deviance_score", (POS_P, POS_T), {"power": 2.0})
    _run(ref, "tweedie_deviance_score", (POS_P, POS_T), {"power": 3.0})


@pytest.mark.parametrize("log_prob", [True, False])
def test_kl_divergence(ref, log_prob):
    import jax.numpy as jnp
    import torch

    p = np.log(PROB_P) if log_prob else PROB_P
    q = np.log(PROB_T) if log_prob else PROB_T
    theirs = ref.functional.regression.kl_divergence(
        torch.from_numpy(p), torch.from_numpy(q), log_prob=log_prob
    )
    ours = F.kl_divergence(jnp.asarray(p), jnp.asarray(q), log_prob=log_prob)
    assert_close(ours, theirs, atol=1e-5)


# ------------------------------------------------------------------- pairwise


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("pairwise_cosine_similarity", {}),
        ("pairwise_cosine_similarity", {"zero_diagonal": True}),
        ("pairwise_euclidean_distance", {}),
        ("pairwise_euclidean_distance", {"reduction": "mean"}),
        ("pairwise_linear_similarity", {}),
        ("pairwise_manhattan_distance", {}),
        ("pairwise_minkowski_distance", {"exponent": 3}),
    ],
)
def test_pairwise(ref, name, kwargs):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.pairwise as FP

    x = rng.randn(20, 8).astype(np.float32)
    y = rng.randn(16, 8).astype(np.float32)
    theirs = getattr(ref.functional.pairwise, name)(torch.from_numpy(x), torch.from_numpy(y), **kwargs)
    ours = getattr(FP, name)(jnp.asarray(x), jnp.asarray(y), **kwargs)
    assert_close(ours, theirs, atol=1e-4)


# ----------------------------------------------------------------- aggregation


def test_aggregation_classes(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    vals = rng.randn(4, 16).astype(np.float32)
    weights = np.abs(rng.randn(4, 16)).astype(np.float32)
    for name in ("MeanMetric", "SumMetric", "MaxMetric", "MinMetric"):
        theirs_m = getattr(ref, name)()
        ours_m = getattr(M, name)()
        for i in range(4):
            if name == "MeanMetric":
                theirs_m.update(torch.from_numpy(vals[i]), torch.from_numpy(weights[i]))
                ours_m.update(jnp.asarray(vals[i]), jnp.asarray(weights[i]))
            else:
                theirs_m.update(torch.from_numpy(vals[i]))
                ours_m.update(jnp.asarray(vals[i]))
        assert_close(ours_m.compute(), theirs_m.compute(), atol=1e-6)
