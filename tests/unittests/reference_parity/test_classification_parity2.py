"""Differential tests vs the reference: the classification surface not covered by
the first sweep — fixed-operating-point multiclass/multilabel variants, multilabel
curves, hinge, dice, fairness rates, and the remaining dispatchers."""
import numpy as np
import pytest

import metrics_tpu.functional.classification as F

from .conftest import assert_close

N = 128
NC = 5
NL = 4

rng = np.random.RandomState(41)
BIN_PROBS = rng.rand(N).astype(np.float32)
BIN_TARGET = rng.randint(0, 2, N)
MC_LOGITS = rng.randn(N, NC).astype(np.float32)
MC_PROBS = np.exp(MC_LOGITS) / np.exp(MC_LOGITS).sum(-1, keepdims=True)
MC_TARGET = rng.randint(0, NC, N)
ML_PROBS = rng.rand(N, NL).astype(np.float32)
ML_TARGET = rng.randint(0, 2, (N, NL))
GROUPS = rng.randint(0, 2, N)


def _run(ref, name, args_np, kwargs, atol=1e-5):
    import jax.numpy as jnp
    import torch

    theirs = getattr(ref.functional.classification, name)(
        *[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs
    )
    ours = getattr(F, name)(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("multiclass_recall_at_fixed_precision", {"min_precision": 0.4}),
        ("multiclass_recall_at_fixed_precision", {"min_precision": 0.4, "thresholds": 50}),
        ("multiclass_precision_at_fixed_recall", {"min_recall": 0.5}),
        ("multiclass_specificity_at_sensitivity", {"min_sensitivity": 0.5}),
        ("multiclass_hinge_loss", {}),
        ("multiclass_hinge_loss", {"multiclass_mode": "one-vs-all"}),
    ],
)
def test_multiclass_extra(ref, name, kwargs):
    args = (MC_PROBS, MC_TARGET)
    if "hinge" in name:
        args = (MC_LOGITS, MC_TARGET)
    _run(ref, name, args, {"num_classes": NC, **kwargs})


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("multilabel_recall_at_fixed_precision", {"min_precision": 0.4}),
        ("multilabel_precision_at_fixed_recall", {"min_recall": 0.5}),
        ("multilabel_specificity_at_sensitivity", {"min_sensitivity": 0.5}),
    ],
)
def test_multilabel_fixed_point(ref, name, kwargs):
    _run(ref, name, (ML_PROBS, ML_TARGET), {"num_labels": NL, **kwargs})


@pytest.mark.parametrize("thresholds", [None, 20])
def test_multilabel_curves(ref, thresholds):
    import jax.numpy as jnp
    import torch

    for name in ("multilabel_precision_recall_curve", "multilabel_roc"):
        theirs = getattr(ref.functional.classification, name)(
            torch.from_numpy(ML_PROBS), torch.from_numpy(ML_TARGET), num_labels=NL, thresholds=thresholds
        )
        ours = getattr(F, name)(jnp.asarray(ML_PROBS), jnp.asarray(ML_TARGET), num_labels=NL, thresholds=thresholds)
        for o, t in zip(ours, theirs):
            assert_close(o, t, atol=1e-6)


@pytest.mark.parametrize("thresholds", [None, 20])
def test_multiclass_precision_recall_curve(ref, thresholds):
    import jax.numpy as jnp
    import torch

    theirs = ref.functional.classification.multiclass_precision_recall_curve(
        torch.from_numpy(MC_PROBS), torch.from_numpy(MC_TARGET), num_classes=NC, thresholds=thresholds
    )
    ours = F.multiclass_precision_recall_curve(
        jnp.asarray(MC_PROBS), jnp.asarray(MC_TARGET), num_classes=NC, thresholds=thresholds
    )
    for o, t in zip(ours, theirs):
        assert_close(o, t, atol=1e-6)


def test_dice(ref):
    import jax.numpy as jnp
    import torch

    preds = rng.randint(0, 2, N)
    theirs = ref.functional.classification.dice(torch.from_numpy(preds), torch.from_numpy(BIN_TARGET))
    ours = F.dice(jnp.asarray(preds), jnp.asarray(BIN_TARGET))
    assert_close(ours, theirs, atol=1e-6)


def test_binary_groups_stat_rates(ref):
    _run(ref, "binary_groups_stat_rates", (BIN_PROBS, BIN_TARGET, GROUPS), {"num_groups": 2})


def test_binary_fairness(ref):
    import jax.numpy as jnp
    import torch

    for task in ("demographic_parity", "equal_opportunity", "all"):
        theirs = ref.functional.classification.binary_fairness(
            torch.from_numpy(BIN_PROBS), torch.from_numpy(BIN_TARGET), torch.from_numpy(GROUPS), task=task
        )
        ours = F.binary_fairness(
            jnp.asarray(BIN_PROBS), jnp.asarray(BIN_TARGET), jnp.asarray(GROUPS), task=task
        )
        assert_close(ours, theirs, atol=1e-6)


@pytest.mark.parametrize(
    ("name", "task_kwargs", "which"),
    [
        ("precision", {"task": "multiclass", "num_classes": NC, "average": "macro"}, "mc"),
        ("recall", {"task": "multilabel", "num_labels": NL, "average": "micro"}, "ml"),
        ("specificity", {"task": "binary"}, "bin"),
        ("fbeta_score", {"task": "binary", "beta": 0.5}, "bin"),
        ("hamming_distance", {"task": "multiclass", "num_classes": NC, "average": "macro"}, "mc"),
        ("jaccard_index", {"task": "multilabel", "num_labels": NL}, "ml"),
        ("matthews_corrcoef", {"task": "binary"}, "bin"),
        ("cohen_kappa", {"task": "multiclass", "num_classes": NC}, "mc"),
        ("confusion_matrix", {"task": "binary"}, "bin"),
        ("stat_scores", {"task": "multiclass", "num_classes": NC, "average": "macro"}, "mc"),
        ("average_precision", {"task": "multiclass", "num_classes": NC, "average": "macro"}, "mc"),
        ("calibration_error", {"task": "binary", "n_bins": 10}, "bin"),
        ("exact_match", {"task": "multilabel", "num_labels": NL}, "ml"),
        ("hinge_loss", {"task": "binary"}, "bin"),
    ],
)
def test_remaining_dispatchers(ref, name, task_kwargs, which):
    import jax.numpy as jnp
    import torch

    a = {"bin": (BIN_PROBS, BIN_TARGET), "mc": (MC_PROBS, MC_TARGET), "ml": (ML_PROBS, ML_TARGET)}[which]
    theirs = getattr(ref.functional, name)(*[torch.from_numpy(np.asarray(x)) for x in a], **task_kwargs)
    ours = getattr(__import__("metrics_tpu.functional", fromlist=[name]), name)(
        *[jnp.asarray(x) for x in a], **task_kwargs
    )
    assert_close(ours, theirs, atol=1e-5)
