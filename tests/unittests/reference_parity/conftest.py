"""Fixtures for differential tests against the actual reference library.

The reference (torchmetrics v1.0.0rc0, torch CPU) is imported straight from
``/root/reference/src`` through the ``lightning_utilities`` shim vendored at
``tests/helpers/refshim``. Every test in this tier feeds identical numpy inputs to
the reference and to ``metrics_tpu`` and asserts the outputs match — the strongest
parity evidence available short of running both on the same accelerator.
"""
import numpy as np
import pytest

from tests.helpers.reference import import_reference


@pytest.fixture(scope="session")
def ref():
    tm = import_reference()
    if tm is None:
        pytest.skip("reference tree not available")
    return tm


@pytest.fixture(scope="session")
def torch():
    import torch as _torch

    return _torch


def assert_close(ours, theirs, atol=1e-6, rtol=1e-5):
    """Compare a metrics_tpu result against a torch reference result."""
    import torch as _torch

    if isinstance(theirs, dict):
        assert set(map(str, ours.keys())) >= set(map(str, theirs.keys())), (
            f"missing keys: {set(map(str, theirs)) - set(map(str, ours))}"
        )
        for k in theirs:
            assert_close(ours[k], theirs[k], atol=atol, rtol=rtol)
        return
    if isinstance(theirs, (list, tuple)):
        assert len(ours) == len(theirs)
        for o, t in zip(ours, theirs):
            assert_close(o, t, atol=atol, rtol=rtol)
        return
    if isinstance(theirs, _torch.Tensor):
        theirs = theirs.detach().cpu().numpy()
    np.testing.assert_allclose(
        np.asarray(ours, dtype=np.float64), np.asarray(theirs, dtype=np.float64), atol=atol, rtol=rtol
    )
