"""Differential tests: nominal functionals, stateful class accumulation, and wrappers
vs the actual reference library."""
import numpy as np
import pytest

from .conftest import assert_close

rng = np.random.RandomState(31)
N = 150
CAT_A = rng.randint(0, 4, N)
CAT_B = (CAT_A + rng.randint(0, 2, N)) % 4  # correlated
MATRIX = rng.randint(0, 3, (N, 5))


# --------------------------------------------------------------------- nominal


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("cramers_v", {}),
        ("cramers_v", {"bias_correction": False}),
        ("pearsons_contingency_coefficient", {}),
        ("theils_u", {}),
        ("tschuprows_t", {}),
        ("tschuprows_t", {"bias_correction": False}),
    ],
)
def test_nominal(ref, name, kwargs):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.nominal as FN

    theirs = getattr(ref.functional.nominal, name)(torch.from_numpy(CAT_A), torch.from_numpy(CAT_B), **kwargs)
    ours = getattr(FN, name)(jnp.asarray(CAT_A), jnp.asarray(CAT_B), **kwargs)
    assert_close(ours, theirs, atol=1e-5)


@pytest.mark.parametrize(
    "name",
    ["cramers_v_matrix", "pearsons_contingency_coefficient_matrix", "theils_u_matrix", "tschuprows_t_matrix"],
)
def test_nominal_matrix(ref, name):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.nominal as FN

    theirs = getattr(ref.functional.nominal, name)(torch.from_numpy(MATRIX))
    ours = getattr(FN, name)(jnp.asarray(MATRIX))
    assert_close(ours, theirs, atol=1e-5)


# ------------------------------------------------- stateful class accumulation

NC = 5
BATCHES = 4
B = 48
MC_PROBS = rng.dirichlet(np.ones(NC), (BATCHES, B)).astype(np.float32)
MC_TARGET = rng.randint(0, NC, (BATCHES, B))
REG_P = rng.randn(BATCHES, B).astype(np.float32)
REG_T = (REG_P + 0.4 * rng.randn(BATCHES, B)).astype(np.float32)


def _accumulate(ref_cls, our_cls, preds, target, kwargs, atol=1e-5):
    import jax.numpy as jnp
    import torch

    theirs_m = ref_cls(**kwargs)
    ours_m = our_cls(**kwargs)
    for i in range(len(preds)):
        theirs_m.update(torch.from_numpy(preds[i]), torch.from_numpy(target[i]))
        ours_m.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    assert_close(ours_m.compute(), theirs_m.compute(), atol=atol)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("MulticlassAccuracy", {"num_classes": NC, "average": "macro"}),
        ("MulticlassAccuracy", {"num_classes": NC, "average": "weighted"}),
        ("MulticlassPrecision", {"num_classes": NC, "average": "macro"}),
        ("MulticlassF1Score", {"num_classes": NC, "average": "none"}),
        ("MulticlassAUROC", {"num_classes": NC, "average": "macro", "thresholds": None}),
        ("MulticlassAUROC", {"num_classes": NC, "average": "macro", "thresholds": 50}),
        ("MulticlassAveragePrecision", {"num_classes": NC, "average": "macro", "thresholds": None}),
        ("MulticlassCohenKappa", {"num_classes": NC}),
        ("MulticlassMatthewsCorrCoef", {"num_classes": NC}),
        ("MulticlassConfusionMatrix", {"num_classes": NC}),
        ("MulticlassCalibrationError", {"num_classes": NC, "n_bins": 10}),
    ],
)
def test_stateful_classification(ref, name, kwargs):
    import metrics_tpu.classification as C

    _accumulate(getattr(ref.classification, name), getattr(C, name), MC_PROBS, MC_TARGET, kwargs)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("MeanSquaredError", {}),
        ("MeanAbsoluteError", {}),
        ("PearsonCorrCoef", {}),
        ("SpearmanCorrCoef", {}),
        ("KendallRankCorrCoef", {}),
        ("ConcordanceCorrCoef", {}),
        ("R2Score", {}),
        ("ExplainedVariance", {}),
        ("CosineSimilarity", {}),
        ("LogCoshError", {}),
    ],
)
def test_stateful_regression(ref, name, kwargs):
    import metrics_tpu.regression as R

    _accumulate(getattr(ref.regression, name), getattr(R, name), REG_P, REG_T, kwargs)


# -------------------------------------------------------------------- wrappers


def test_minmax_wrapper(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    theirs_m = ref.MinMaxMetric(ref.regression.MeanSquaredError())
    ours_m = M.MinMaxMetric(M.regression.MeanSquaredError())
    for i in range(BATCHES):
        theirs_m.update(torch.from_numpy(REG_P[i]), torch.from_numpy(REG_T[i]))
        ours_m.update(jnp.asarray(REG_P[i]), jnp.asarray(REG_T[i]))
    theirs = theirs_m.compute()
    ours = ours_m.compute()
    for k in ("raw", "min", "max"):
        assert_close(ours[k], theirs[k], atol=1e-6)


def test_classwise_wrapper(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    theirs_m = ref.ClasswiseWrapper(ref.classification.MulticlassAccuracy(num_classes=NC, average=None))
    ours_m = M.ClasswiseWrapper(M.classification.MulticlassAccuracy(num_classes=NC, average=None))
    for i in range(BATCHES):
        theirs_m.update(torch.from_numpy(MC_PROBS[i]), torch.from_numpy(MC_TARGET[i]))
        ours_m.update(jnp.asarray(MC_PROBS[i]), jnp.asarray(MC_TARGET[i]))
    theirs = theirs_m.compute()
    ours = ours_m.compute()
    assert set(ours) == set(theirs)
    for k in theirs:
        assert_close(ours[k], theirs[k], atol=1e-6)


def test_multioutput_wrapper(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    p = rng.randn(BATCHES, B, 3).astype(np.float32)
    t = (p + 0.3 * rng.randn(BATCHES, B, 3)).astype(np.float32)
    theirs_m = ref.MultioutputWrapper(ref.regression.MeanSquaredError(), num_outputs=3)
    ours_m = M.MultioutputWrapper(M.regression.MeanSquaredError(), num_outputs=3)
    for i in range(BATCHES):
        theirs_m.update(torch.from_numpy(p[i]), torch.from_numpy(t[i]))
        ours_m.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    assert_close(ours_m.compute(), theirs_m.compute(), atol=1e-6)


def test_tracker(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    theirs_m = ref.MetricTracker(ref.regression.MeanSquaredError(), maximize=False)
    ours_m = M.MetricTracker(M.regression.MeanSquaredError(), maximize=False)
    for i in range(BATCHES):
        theirs_m.increment()
        ours_m.increment()
        theirs_m.update(torch.from_numpy(REG_P[i]), torch.from_numpy(REG_T[i]))
        ours_m.update(jnp.asarray(REG_P[i]), jnp.asarray(REG_T[i]))
    assert_close(ours_m.compute_all(), theirs_m.compute_all(), atol=1e-6)
    t_best, t_step = theirs_m.best_metric(return_step=True)
    o_best, o_step = ours_m.best_metric(return_step=True)
    assert o_step == t_step
    assert_close(o_best, t_best, atol=1e-6)


def test_metric_collection(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    theirs_m = ref.MetricCollection(
        {
            "acc": ref.classification.MulticlassAccuracy(num_classes=NC, average="micro"),
            "f1": ref.classification.MulticlassF1Score(num_classes=NC, average="macro"),
            "kappa": ref.classification.MulticlassCohenKappa(num_classes=NC),
        }
    )
    ours_m = M.MetricCollection(
        {
            "acc": M.classification.MulticlassAccuracy(num_classes=NC, average="micro"),
            "f1": M.classification.MulticlassF1Score(num_classes=NC, average="macro"),
            "kappa": M.classification.MulticlassCohenKappa(num_classes=NC),
        }
    )
    for i in range(BATCHES):
        theirs_m.update(torch.from_numpy(MC_PROBS[i]), torch.from_numpy(MC_TARGET[i]))
        ours_m.update(jnp.asarray(MC_PROBS[i]), jnp.asarray(MC_TARGET[i]))
    theirs = theirs_m.compute()
    ours = ours_m.compute()
    assert set(ours) == set(theirs)
    for k in theirs:
        assert_close(ours[k], theirs[k], atol=1e-5)


def test_composition_arithmetic(ref, torch):
    import jax.numpy as jnp

    import metrics_tpu as M

    t_a = ref.regression.MeanSquaredError()
    t_b = ref.regression.MeanAbsoluteError()
    t_c = t_a + 2 * t_b
    o_a = M.regression.MeanSquaredError()
    o_b = M.regression.MeanAbsoluteError()
    o_c = o_a + 2 * o_b
    for i in range(BATCHES):
        t_c.update(torch.from_numpy(REG_P[i]), torch.from_numpy(REG_T[i]))
        o_c.update(jnp.asarray(REG_P[i]), jnp.asarray(REG_T[i]))
    assert_close(o_c.compute(), t_c.compute(), atol=1e-6)
