"""Enumerated parameter grids vs the live reference — the MetricTester-style
cartesian coverage for the domains whose parity tiers were sampled, not
enumerated (VERDICT r4 weak #7): regression (multioutput x shapes x kwargs),
aggregation (nan_strategy x inputs), audio (SDR/SNR config grid x lengths), and
text (sacrebleu tokenizer x lowercase, ROUGE variants x accumulate, TER/EED/CHRF
flag grids). Mirrors what the reference's MetricTester enumerates
(/root/reference/tests/unittests/helpers/testers.py:319-443) as oracle-parity
parametrizations over identical inputs.
"""
import itertools
import zlib

import numpy as np
import pytest

from .conftest import assert_close

rng = np.random.RandomState(1234)


# ------------------------------------------------------------------ regression

N = 64
SHAPES = {
    "1d": (N,),
    "multioutput": (N, 3),
    "single_col": (N, 1),
    "tiny": (4,),
}
REG_FNS = [
    ("mean_squared_error", {}),
    ("mean_squared_error", {"squared": False}),
    ("mean_absolute_error", {}),
    ("mean_absolute_percentage_error", {}),
    ("symmetric_mean_absolute_percentage_error", {}),
    ("weighted_mean_absolute_percentage_error", {}),
    ("log_cosh_error", {}),
    ("explained_variance", {"multioutput": "raw_values"}),
    ("explained_variance", {"multioutput": "uniform_average"}),
    ("explained_variance", {"multioutput": "variance_weighted"}),
    ("r2_score", {"multioutput": "raw_values"}),
    ("r2_score", {"multioutput": "uniform_average"}),
    ("r2_score", {"multioutput": "variance_weighted"}),
    ("pearson_corrcoef", {}),
    ("spearman_corrcoef", {}),
    ("concordance_corrcoef", {}),
    ("kendall_rank_corrcoef", {"variant": "b"}),
    ("kendall_rank_corrcoef", {"variant": "a"}),
    ("kendall_rank_corrcoef", {"variant": "c"}),
    ("cosine_similarity", {"reduction": "mean"}),
    ("cosine_similarity", {"reduction": "sum"}),
    ("cosine_similarity", {"reduction": "none"}),
    ("minkowski_distance", {"p": 1.0}),
    ("minkowski_distance", {"p": 2.0}),
    ("minkowski_distance", {"p": 4.5}),
]
REG_GRID = [
    (name, kwargs, shape_key)
    for (name, kwargs), shape_key in itertools.product(REG_FNS, SHAPES)
    # cosine/minkowski need >= 2 feature dims or vector rows; kendall on (N, 1)
    # IndexErrors in the reference itself (kendall.py:54 deprecated .T path), so
    # there is no behavior to be parity with; keep the valid cartesian subset
    if not (name in ("cosine_similarity", "minkowski_distance") and shape_key in ("1d", "tiny"))
    and not (name == "kendall_rank_corrcoef" and shape_key == "single_col")
]


@pytest.mark.parametrize(("name", "kwargs", "shape_key"), REG_GRID,
                         ids=[f"{n}-{'-'.join(f'{k}={v}' for k, v in kw.items()) or 'default'}-{s}" for n, kw, s in REG_GRID])
def test_regression_grid(ref, name, kwargs, shape_key):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.regression as F

    shape = SHAPES[shape_key]
    r = np.random.RandomState(zlib.crc32(str(((name, shape_key))).encode()))
    preds = r.randn(*shape).astype(np.float32)
    target = (preds + 0.5 * r.randn(*shape)).astype(np.float32)

    theirs = getattr(ref.functional.regression, name)(torch.from_numpy(preds), torch.from_numpy(target), **kwargs)
    ours = getattr(F, name)(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    assert_close(ours, theirs, atol=2e-5, rtol=1e-4)


# ----------------------------------------------------------------- aggregation

AGG_GRID = list(itertools.product(
    ("MeanMetric", "SumMetric", "MaxMetric", "MinMetric", "CatMetric"),
    ("warn", "ignore", 0.0, 5.5),
    ("clean", "some_nan", "all_nan_batch"),
))


@pytest.mark.parametrize(("cls_name", "nan_strategy", "data_kind"), AGG_GRID,
                         ids=[f"{c}-{s}-{d}" for c, s, d in AGG_GRID])
def test_aggregation_nan_grid(ref, cls_name, nan_strategy, data_kind):
    import warnings

    import jax.numpy as jnp
    import torch

    import metrics_tpu as M

    r = np.random.RandomState(zlib.crc32(str(((cls_name, str(nan_strategy), data_kind))).encode()))
    batches = [r.randn(16).astype(np.float32) for _ in range(3)]
    if data_kind == "some_nan":
        for b in batches:
            b[r.randint(0, 16, 3)] = np.nan
    elif data_kind == "all_nan_batch":
        batches[1][:] = np.nan

    theirs_m = getattr(ref, cls_name)(nan_strategy=nan_strategy)
    ours_m = getattr(M, cls_name)(nan_strategy=nan_strategy)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for b in batches:
            theirs_m.update(torch.from_numpy(b))
            ours_m.update(jnp.asarray(b))
    assert_close(ours_m.compute(), theirs_m.compute(), atol=1e-5, rtol=1e-5, )


def test_aggregation_error_strategy_raises(ref):
    import jax.numpy as jnp
    import torch

    import metrics_tpu as M

    bad = np.asarray([1.0, np.nan], np.float32)
    theirs = ref.MeanMetric(nan_strategy="error")
    ours = M.MeanMetric(nan_strategy="error")
    with pytest.raises(RuntimeError):
        theirs.update(torch.from_numpy(bad))
    with pytest.raises(RuntimeError):
        ours.update(jnp.asarray(bad))


# ----------------------------------------------------------------------- audio

SDR_GRID = list(itertools.product(
    (None, 10),            # use_cg_iter
    (False, True),         # zero_mean
    (512, 128),            # filter_length
    (False, True),         # load_diag
    ("short", "long"),     # input length
))


@pytest.mark.parametrize(("use_cg_iter", "zero_mean", "filter_length", "load_diag", "length"), SDR_GRID,
                         ids=[f"cg={c}-zm={z}-fl={f}-ld={d}-{l}" for c, z, f, d, l in SDR_GRID])
def test_sdr_grid(ref, use_cg_iter, zero_mean, filter_length, load_diag, length):
    import jax.numpy as jnp
    import torch

    from metrics_tpu.functional.audio import signal_distortion_ratio

    n = 3000 if length == "short" else 16000
    r = np.random.RandomState(zlib.crc32(str(((use_cg_iter, zero_mean, filter_length, load_diag, length))).encode()))
    target = r.randn(2, n).astype(np.float32)
    preds = (target + 0.1 * r.randn(2, n)).astype(np.float32)
    kwargs = dict(
        use_cg_iter=use_cg_iter,
        zero_mean=zero_mean,
        filter_length=filter_length,
        load_diag=1e-4 if load_diag else None,
    )
    theirs = ref.functional.audio.signal_distortion_ratio(torch.from_numpy(preds), torch.from_numpy(target), **kwargs)
    ours = signal_distortion_ratio(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    # the toeplitz solve chain is long; both sides are f32 so allow small drift
    assert_close(ours, theirs, atol=2e-2, rtol=1e-3)


SNR_GRID = list(itertools.product(("snr", "si_sdr", "si_snr"), (False, True)))


@pytest.mark.parametrize(("which", "zero_mean"), SNR_GRID, ids=[f"{w}-zm={z}" for w, z in SNR_GRID])
def test_snr_family_grid(ref, which, zero_mean):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.audio as FA

    r = np.random.RandomState(zlib.crc32(str(((which, zero_mean))).encode()))
    target = r.randn(4, 2000).astype(np.float32)
    preds = (target + 0.3 * r.randn(4, 2000)).astype(np.float32)
    names = {
        "snr": "signal_noise_ratio",
        "si_sdr": "scale_invariant_signal_distortion_ratio",
        "si_snr": "scale_invariant_signal_noise_ratio",
    }
    name = names[which]
    kwargs = {"zero_mean": zero_mean} if which != "si_snr" else {}
    if which == "si_snr" and zero_mean:
        pytest.skip("si_snr has no zero_mean argument")
    theirs = getattr(ref.functional.audio, name)(torch.from_numpy(preds), torch.from_numpy(target), **kwargs)
    ours = getattr(FA, name)(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    assert_close(ours, theirs, atol=1e-4, rtol=1e-4)


# ------------------------------------------------------------------------ text

_PREDS = [
    "the cat is on the mat",
    "a quick brown fox jumps over the lazy dog",
    "hello there General Kenobi, you are a bold one",
    "numbers like 1,234.5 and punct-uation; are hard!",
]
_REFS = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["the quick brown fox jumped over the lazy dog"],
    ["hello there general kenobi you are bold"],
    ["numbers like 1,234.5 and punctuation are hard"],
]

SACRE_GRID = list(itertools.product(("13a", "char", "none", "intl"), (False, True), (2, 4)))


@pytest.mark.parametrize(("tokenize", "lowercase", "n_gram"), SACRE_GRID,
                         ids=[f"{t}-lc={l}-n={n}" for t, l, n in SACRE_GRID])
def test_sacrebleu_grid(ref, tokenize, lowercase, n_gram):
    import jax.numpy as jnp  # noqa: F401

    import metrics_tpu.functional.text as FT

    try:
        theirs = ref.functional.text.sacre_bleu_score(
            _PREDS, _REFS, tokenize=tokenize, lowercase=lowercase, n_gram=n_gram
        )
    except (ModuleNotFoundError, ValueError) as e:
        pytest.skip(f"reference cannot run this tokenizer here: {e}")
    ours = FT.sacre_bleu_score(_PREDS, _REFS, tokenize=tokenize, lowercase=lowercase, n_gram=n_gram)
    assert_close(ours, theirs, atol=1e-5)


BLEU_GRID = list(itertools.product((1, 2, 3, 4), (False, True)))


@pytest.mark.parametrize(("n_gram", "smooth"), BLEU_GRID, ids=[f"n={n}-smooth={s}" for n, s in BLEU_GRID])
def test_bleu_grid(ref, n_gram, smooth):
    import metrics_tpu.functional.text as FT

    theirs = ref.functional.text.bleu_score(_PREDS, _REFS, n_gram=n_gram, smooth=smooth)
    ours = FT.bleu_score(_PREDS, _REFS, n_gram=n_gram, smooth=smooth)
    assert_close(ours, theirs, atol=1e-5)


ROUGE_GRID = list(itertools.product(
    (("rouge1",), ("rouge2",), ("rougeL",), ("rougeLsum",), ("rouge1", "rouge2", "rougeL")),
    ("best", "avg"),
    (False, True),  # use_stemmer
))


@pytest.mark.parametrize(("keys", "accumulate", "use_stemmer"), ROUGE_GRID,
                         ids=[f"{'-'.join(k)}-{a}-stem={s}" for k, a, s in ROUGE_GRID])
def test_rouge_grid(ref, keys, accumulate, use_stemmer):
    import metrics_tpu.functional.text as FT

    try:
        theirs = ref.functional.text.rouge_score(
            _PREDS, _REFS, rouge_keys=keys, accumulate=accumulate, use_stemmer=use_stemmer
        )
    except (ModuleNotFoundError, ValueError, LookupError, OSError) as e:
        # rougeLsum needs nltk punkt data, unavailable without egress; the
        # in-repo rougeLsum is pinned by tests/unittests/text/test_text.py
        pytest.skip(f"reference rouge unavailable in this config: {e}")
    ours = FT.rouge_score(_PREDS, _REFS, rouge_keys=keys, accumulate=accumulate, use_stemmer=use_stemmer)
    assert_close(ours, theirs, atol=1e-5)


TER_GRID = list(itertools.product((False, True), (False, True), (False, True)))


@pytest.mark.parametrize(("normalize", "no_punctuation", "lowercase"), TER_GRID,
                         ids=[f"norm={n}-nopunct={p}-lc={l}" for n, p, l in TER_GRID])
def test_ter_grid(ref, normalize, no_punctuation, lowercase):
    import metrics_tpu.functional.text as FT

    kwargs = dict(normalize=normalize, no_punctuation=no_punctuation, lowercase=lowercase)
    theirs = ref.functional.text.translation_edit_rate(_PREDS, _REFS, **kwargs)
    ours = FT.translation_edit_rate(_PREDS, _REFS, **kwargs)
    assert_close(ours, theirs, atol=1e-5)


CHRF_GRID = [
    (6, 0, 2.0, False),
    (6, 2, 2.0, False),
    (4, 0, 1.0, False),
    (6, 0, 2.0, True),   # lowercase
    (6, 2, 3.0, True),
]


@pytest.mark.parametrize(("n_char_order", "n_word_order", "beta", "lowercase"), CHRF_GRID,
                         ids=[f"c={c}-w={w}-b={b}-lc={l}" for c, w, b, l in CHRF_GRID])
def test_chrf_grid(ref, n_char_order, n_word_order, beta, lowercase):
    import metrics_tpu.functional.text as FT

    kwargs = dict(n_char_order=n_char_order, n_word_order=n_word_order, beta=beta, lowercase=lowercase)
    theirs = ref.functional.text.chrf_score(_PREDS, _REFS, **kwargs)
    ours = FT.chrf_score(_PREDS, _REFS, **kwargs)
    assert_close(ours, theirs, atol=1e-5)


EED_GRID = [
    {},
    {"alpha": 1.0},
    {"rho": 0.5},
    {"deletion": 1.0, "insertion": 0.5},
    {"language": "en"},
]


@pytest.mark.parametrize("kwargs", EED_GRID, ids=[str(sorted(k)) or "default" for k in EED_GRID])
def test_eed_grid(ref, kwargs):
    import metrics_tpu.functional.text as FT

    theirs = ref.functional.text.extended_edit_distance(_PREDS, [r[0] for r in _REFS], **kwargs)
    ours = FT.extended_edit_distance(_PREDS, [r[0] for r in _REFS], **kwargs)
    assert_close(ours, theirs, atol=1e-5)


EDIT_FNS = ("char_error_rate", "word_error_rate", "match_error_rate", "word_information_lost",
            "word_information_preserved")


@pytest.mark.parametrize("name", EDIT_FNS, ids=EDIT_FNS)
@pytest.mark.parametrize("case", ["plain", "empty_pred", "unicode"])
def test_edit_distance_grid(ref, name, case):
    import metrics_tpu.functional.text as FT

    preds = {
        "plain": ["this is the prediction", "there is an other sample"],
        "empty_pred": ["", "there is an other sample"],
        "unicode": ["café naïve résumé", "日本語 テスト"],
    }[case]
    target = {
        "plain": ["this is the reference", "there is another one"],
        "empty_pred": ["this is the reference", "there is another one"],
        "unicode": ["cafe naive resume", "日本語 テスト です"],
    }[case]
    theirs = getattr(ref.functional.text, name)(preds, target)
    ours = getattr(FT, name)(preds, target)
    assert_close(ours, theirs, atol=1e-6)
