"""Differential tests: retrieval + image functionals vs the actual reference."""
import numpy as np
import pytest

from .conftest import assert_close

rng = np.random.RandomState(23)

NQ = 12
NDOC = 180
IDX = np.sort(rng.randint(0, NQ, NDOC)).astype(np.int64)
SCORES = rng.rand(NDOC).astype(np.float32)
REL = (rng.rand(NDOC) > 0.6).astype(np.int64)
REL_GRADED = rng.randint(0, 4, NDOC).astype(np.int64)


# ------------------------------------------------------------------- retrieval


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("retrieval_average_precision", {}),
        ("retrieval_average_precision", {"top_k": 5}),
        ("retrieval_reciprocal_rank", {}),
        ("retrieval_precision", {"top_k": 5}),
        ("retrieval_precision", {"top_k": 5, "adaptive_k": True}),
        ("retrieval_recall", {"top_k": 5}),
        ("retrieval_hit_rate", {"top_k": 5}),
        ("retrieval_fall_out", {"top_k": 5}),
        ("retrieval_r_precision", {}),
        ("retrieval_normalized_dcg", {}),
        ("retrieval_normalized_dcg", {"top_k": 5}),
    ],
)
def test_retrieval_functional_per_query(ref, name, kwargs):
    """Functionals operate on a single query's documents."""
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.retrieval as FR

    rel = REL_GRADED if name == "retrieval_normalized_dcg" else REL
    for q in range(4):
        m = IDX == q
        p, t = SCORES[m], rel[m]
        if t.sum() == 0 and name != "retrieval_fall_out":
            continue
        theirs = getattr(ref.functional.retrieval, name)(torch.from_numpy(p), torch.from_numpy(t), **kwargs)
        ours = getattr(FR, name)(jnp.asarray(p), jnp.asarray(t), **kwargs)
        assert_close(ours, theirs, atol=1e-6)


@pytest.mark.parametrize(
    ("cls_name", "kwargs"),
    [
        ("RetrievalMAP", {}),
        ("RetrievalMRR", {}),
        ("RetrievalPrecision", {"top_k": 5}),
        ("RetrievalRecall", {"top_k": 5}),
        ("RetrievalHitRate", {"top_k": 5}),
        ("RetrievalFallOut", {"top_k": 5}),
        ("RetrievalRPrecision", {}),
        ("RetrievalNormalizedDCG", {}),
        ("RetrievalPrecisionRecallCurve", {"max_k": 10}),
    ],
)
def test_retrieval_class(ref, cls_name, kwargs):
    """Stateful retrieval metrics: multi-batch accumulate, grouped compute."""
    import jax.numpy as jnp
    import torch

    import metrics_tpu.retrieval as R

    rel = REL_GRADED if cls_name == "RetrievalNormalizedDCG" else REL
    theirs_m = getattr(ref.retrieval, cls_name)(**kwargs)
    ours_m = getattr(R, cls_name)(**kwargs)
    for lo in range(0, NDOC, 60):
        sl = slice(lo, lo + 60)
        theirs_m.update(torch.from_numpy(SCORES[sl]), torch.from_numpy(rel[sl]), indexes=torch.from_numpy(IDX[sl]))
        ours_m.update(jnp.asarray(SCORES[sl]), jnp.asarray(rel[sl]), indexes=jnp.asarray(IDX[sl]))
    theirs = theirs_m.compute()
    ours = ours_m.compute()
    if cls_name == "RetrievalPrecisionRecallCurve":
        for o, t in zip(ours, theirs):
            assert_close(o, t, atol=1e-6)
    else:
        assert_close(ours, theirs, atol=1e-6)


@pytest.mark.parametrize("empty_target_action", ["neg", "pos", "skip"])
def test_retrieval_empty_target_action(ref, empty_target_action):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.retrieval as R

    idx = np.array([0, 0, 0, 1, 1, 1, 2, 2], np.int64)
    scores = rng.rand(8).astype(np.float32)
    rel = np.array([1, 0, 1, 0, 0, 0, 1, 0], np.int64)  # query 1 has no positives
    theirs_m = ref.retrieval.RetrievalMAP(empty_target_action=empty_target_action)
    ours_m = R.RetrievalMAP(empty_target_action=empty_target_action)
    theirs_m.update(torch.from_numpy(scores), torch.from_numpy(rel), indexes=torch.from_numpy(idx))
    ours_m.update(jnp.asarray(scores), jnp.asarray(rel), indexes=jnp.asarray(idx))
    assert_close(ours_m.compute(), theirs_m.compute(), atol=1e-6)


# ----------------------------------------------------------------------- image

B, C, H, W = 3, 3, 48, 48
IMG_P = rng.rand(B, C, H, W).astype(np.float32)
IMG_T = rng.rand(B, C, H, W).astype(np.float32)


def _run_img(ref, name, args_np, kwargs, atol=1e-4):
    import jax.numpy as jnp
    import torch

    theirs = getattr(ref.functional.image, name)(*[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs)
    import metrics_tpu.functional.image as FI

    ours = getattr(FI, name)(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("peak_signal_noise_ratio", {"data_range": 1.0}),
        ("peak_signal_noise_ratio", {"data_range": 1.0, "dim": (1, 2, 3)}),
        ("structural_similarity_index_measure", {"data_range": 1.0}),
        ("structural_similarity_index_measure", {"data_range": 1.0, "gaussian_kernel": False, "kernel_size": 7}),
        ("structural_similarity_index_measure", {"data_range": 1.0, "sigma": 2.0}),
        ("universal_image_quality_index", {}),
        ("spectral_angle_mapper", {}),
        ("error_relative_global_dimensionless_synthesis", {}),
        ("relative_average_spectral_error", {}),
        ("root_mean_squared_error_using_sliding_window", {}),
        ("spectral_distortion_index", {}),
    ],
)
def test_image_functional(ref, name, kwargs):
    _run_img(ref, name, (IMG_P, IMG_T), kwargs)


@pytest.mark.parametrize("reduction", ["sum", "mean", "none"])
def test_total_variation(ref, reduction):
    _run_img(ref, "total_variation", (IMG_P,), {"reduction": reduction})


def test_psnrb(ref):
    gray_p = rng.rand(B, 1, H, W).astype(np.float32)
    gray_t = rng.rand(B, 1, H, W).astype(np.float32)
    _run_img(ref, "peak_signal_noise_ratio_with_blocked_effect", (gray_p, gray_t), {})
    _run_img(ref, "peak_signal_noise_ratio_with_blocked_effect", (gray_p, gray_t), {"block_size": 4})


def test_multiscale_ssim(ref):
    p = rng.rand(2, 3, 192, 192).astype(np.float32)
    t = rng.rand(2, 3, 192, 192).astype(np.float32)
    _run_img(ref, "multiscale_structural_similarity_index_measure", (p, t), {"data_range": 1.0}, atol=1e-4)


def test_image_gradients(ref):
    import jax.numpy as jnp
    import torch

    import metrics_tpu.functional.image as FI

    img = rng.rand(2, 3, 16, 16).astype(np.float32)
    ty, tx = ref.functional.image.image_gradients(torch.from_numpy(img))
    oy, ox = FI.image_gradients(jnp.asarray(img))
    assert_close(oy, ty, atol=1e-6)
    assert_close(ox, tx, atol=1e-6)


@pytest.mark.parametrize("reduction", ["elementwise_mean", "sum", "none"])
def test_ssim_reductions(ref, reduction):
    _run_img(ref, "structural_similarity_index_measure", (IMG_P, IMG_T), {"data_range": 1.0, "reduction": reduction})
