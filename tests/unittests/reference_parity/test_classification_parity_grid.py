"""Cartesian parity grids vs the actual reference library (VERDICT r2 item 9).

Full ``average x top_k x ignore_index x multidim_average`` sweeps over the two
shared classification cores (stat_scores family, curve family) — the axes where
silent divergence hides. The older parity files sample these axes; this file
crosses them.
"""
import numpy as np
import pytest

import metrics_tpu.functional.classification as F

from .conftest import assert_close

N = 96
NC = 5
NL = 3
B, E = 16, 6

rng = np.random.RandomState(21)
MC_LOGITS = rng.randn(N, NC).astype(np.float32)
MC_PROBS = np.exp(MC_LOGITS) / np.exp(MC_LOGITS).sum(-1, keepdims=True)
MC_TARGET = rng.randint(0, NC, N)
MD_PROBS = rng.rand(B, NC, E).astype(np.float32)
MD_PROBS = MD_PROBS / MD_PROBS.sum(1, keepdims=True)
MD_TARGET = rng.randint(0, NC, (B, E))
BIN_PROBS2D = rng.rand(B, E).astype(np.float32)
BIN_TARGET2D = rng.randint(0, 2, (B, E))
CURVE_PROBS = rng.rand(N).astype(np.float32)
CURVE_TARGET = rng.randint(0, 2, N)
ML_PROBS = rng.rand(N, NL).astype(np.float32)
ML_TARGET = rng.randint(0, 2, (N, NL))


def _run(ref, name, args_np, kwargs, atol=1e-5):
    import jax.numpy as jnp
    import torch

    ref_fn = getattr(ref.functional.classification, name)
    our_fn = getattr(F, name)
    theirs = ref_fn(*[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs)
    ours = our_fn(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)


# ------------------------------------------- multiclass stat-scores core grid

STAT_FAMILY = [
    "multiclass_stat_scores",
    "multiclass_accuracy",
    "multiclass_precision",
    "multiclass_recall",
    "multiclass_f1_score",
    "multiclass_specificity",
    "multiclass_hamming_distance",
]


@pytest.mark.parametrize("name", STAT_FAMILY)
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("ignore_index", [None, 1, -1], ids=["noignore", "ign1", "ign-1"])
def test_multiclass_stat_grid(ref, name, average, top_k, ignore_index):
    target = MC_TARGET.copy()
    if ignore_index is not None:
        target[::7] = ignore_index
    _run(
        ref,
        name,
        (MC_PROBS, target),
        {"num_classes": NC, "average": average, "top_k": top_k, "ignore_index": ignore_index},
    )


@pytest.mark.parametrize("name", ["multiclass_stat_scores", "multiclass_accuracy", "multiclass_f1_score"])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("ignore_index", [None, 1], ids=["noignore", "ign1"])
def test_multiclass_samplewise_grid(ref, name, average, ignore_index):
    target = MD_TARGET.copy()
    if ignore_index is not None:
        target[:, ::3] = ignore_index
    _run(
        ref,
        name,
        (MD_PROBS, target),
        {"num_classes": NC, "average": average, "multidim_average": "samplewise", "ignore_index": ignore_index},
    )


# ------------------------------------------------- binary multidim grid

BIN_FAMILY = ["binary_stat_scores", "binary_accuracy", "binary_f1_score", "binary_precision", "binary_recall"]


@pytest.mark.parametrize("name", BIN_FAMILY)
@pytest.mark.parametrize("multidim_average", ["global", "samplewise"])
@pytest.mark.parametrize("ignore_index", [None, -1], ids=["noignore", "ign-1"])
def test_binary_multidim_grid(ref, name, multidim_average, ignore_index):
    target = BIN_TARGET2D.copy()
    if ignore_index is not None:
        target[:, ::3] = ignore_index  # sparse masked positions, labels stay mixed
    _run(
        ref,
        name,
        (BIN_PROBS2D, target),
        {"multidim_average": multidim_average, "ignore_index": ignore_index},
    )


# ---------------------------------------------- multilabel stat grid

ML_FAMILY = ["multilabel_stat_scores", "multilabel_accuracy", "multilabel_f1_score", "multilabel_specificity"]


@pytest.mark.parametrize("name", ML_FAMILY)
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("ignore_index", [None, 0], ids=["noignore", "ign0"])
def test_multilabel_stat_grid(ref, name, average, ignore_index):
    _run(
        ref,
        name,
        (ML_PROBS, ML_TARGET),
        {"num_labels": NL, "average": average, "ignore_index": ignore_index},
    )


# --------------------------------------------------- curve-family grid

@pytest.mark.parametrize("name", ["binary_auroc", "binary_average_precision"])
@pytest.mark.parametrize("thresholds", [None, 20], ids=["exact", "binned"])
@pytest.mark.parametrize("ignore_index", [None, 0], ids=["noignore", "ign0"])
def test_binary_curve_grid(ref, name, thresholds, ignore_index):
    _run(ref, name, (CURVE_PROBS, CURVE_TARGET), {"thresholds": thresholds, "ignore_index": ignore_index}, atol=1e-6)


@pytest.mark.parametrize("name", ["multiclass_auroc", "multiclass_average_precision"])
@pytest.mark.parametrize("average", ["macro", "weighted", "none"])
@pytest.mark.parametrize("thresholds", [None, 20], ids=["exact", "binned"])
@pytest.mark.parametrize("ignore_index", [None, 2], ids=["noignore", "ign2"])
def test_multiclass_curve_grid(ref, name, average, thresholds, ignore_index):
    target = MC_TARGET.copy()
    if ignore_index is not None:
        target[::5] = ignore_index
    _run(
        ref,
        name,
        (MC_PROBS, target),
        {"num_classes": NC, "average": average, "thresholds": thresholds, "ignore_index": ignore_index},
        atol=1e-5,
    )


@pytest.mark.parametrize("name", ["multilabel_auroc", "multilabel_average_precision"])
@pytest.mark.parametrize("average", ["macro", "micro", "weighted", "none"])
@pytest.mark.parametrize("thresholds", [None, 20], ids=["exact", "binned"])
def test_multilabel_curve_grid(ref, name, average, thresholds):
    _run(
        ref,
        name,
        (ML_PROBS, ML_TARGET),
        {"num_labels": NL, "average": average, "thresholds": thresholds},
        atol=1e-5,
    )


@pytest.mark.parametrize("task", ["roc", "precision_recall_curve"])
@pytest.mark.parametrize("thresholds", [None, 20], ids=["exact", "binned"])
@pytest.mark.parametrize("ignore_index", [None, 0], ids=["noignore", "ign0"])
def test_binary_curve_outputs_grid(ref, task, thresholds, ignore_index):
    import jax.numpy as jnp
    import torch

    preds, target = CURVE_PROBS, CURVE_TARGET
    ref_fn = getattr(ref.functional.classification, f"binary_{task}")
    our_fn = getattr(F, f"binary_{task}")
    theirs = ref_fn(
        torch.from_numpy(preds), torch.from_numpy(target), thresholds=thresholds, ignore_index=ignore_index
    )
    ours = our_fn(jnp.asarray(preds), jnp.asarray(target), thresholds=thresholds, ignore_index=ignore_index)
    for o, t in zip(ours, theirs):
        assert_close(o, t, atol=1e-6)
