"""Differential tests: classification functionals vs the actual reference library.

Identical numpy inputs go to ``torchmetrics.functional.classification`` (torch CPU)
and ``metrics_tpu.functional.classification``; outputs must agree. Sweeps cover the
argument axes where silent divergence hides: ``average``, ``top_k``,
``ignore_index``, ``multidim_average``, logits-vs-probs, and binned thresholds.
"""
import numpy as np
import pytest

import metrics_tpu.functional.classification as F

from .conftest import assert_close

N = 128
NC = 5
NL = 4

rng = np.random.RandomState(7)
BIN_PROBS = rng.rand(N).astype(np.float32)
BIN_LOGITS = rng.randn(N).astype(np.float32) * 3
BIN_TARGET = rng.randint(0, 2, N)
MC_LOGITS = rng.randn(N, NC).astype(np.float32)
MC_PROBS = np.exp(MC_LOGITS) / np.exp(MC_LOGITS).sum(-1, keepdims=True)
MC_TARGET = rng.randint(0, NC, N)
MC_PREDS_INT = rng.randint(0, NC, N)
ML_PROBS = rng.rand(N, NL).astype(np.float32)
ML_TARGET = rng.randint(0, 2, (N, NL))
MD_PROBS = rng.rand(32, NC, 6).astype(np.float32)
MD_PROBS = MD_PROBS / MD_PROBS.sum(1, keepdims=True)
MD_TARGET = rng.randint(0, NC, (32, 6))


def _run(ref, name, args_np, kwargs, atol=1e-6):
    import jax.numpy as jnp
    import torch

    ref_fn = getattr(ref.functional.classification, name)
    our_fn = getattr(F, name)
    theirs = ref_fn(*[torch.from_numpy(np.asarray(a)) for a in args_np], **kwargs)
    ours = our_fn(*[jnp.asarray(a) for a in args_np], **kwargs)
    assert_close(ours, theirs, atol=atol)


# ---------------------------------------------------------------- binary family

BINARY_SWEEP = [
    ("binary_accuracy", {}),
    ("binary_accuracy", {"threshold": 0.3}),
    ("binary_accuracy", {"ignore_index": 0}),
    ("binary_accuracy", {"multidim_average": "global"}),
    ("binary_precision", {}),
    ("binary_recall", {}),
    ("binary_specificity", {}),
    ("binary_f1_score", {}),
    ("binary_fbeta_score", {"beta": 0.5}),
    ("binary_jaccard_index", {}),
    ("binary_cohen_kappa", {}),
    ("binary_matthews_corrcoef", {}),
    ("binary_hamming_distance", {}),
    ("binary_auroc", {"thresholds": None}),
    ("binary_auroc", {"thresholds": 50}),
    ("binary_average_precision", {"thresholds": None}),
    ("binary_average_precision", {"thresholds": 50}),
    ("binary_calibration_error", {"n_bins": 10, "norm": "l1"}),
    ("binary_calibration_error", {"n_bins": 15, "norm": "max"}),
    ("binary_calibration_error", {"n_bins": 15, "norm": "l2"}),
    ("binary_hinge_loss", {}),
    ("binary_hinge_loss", {"squared": False}),
    ("binary_stat_scores", {}),
    ("binary_confusion_matrix", {}),
    ("binary_confusion_matrix", {"normalize": "true"}),
]


@pytest.mark.parametrize(("name", "kwargs"), BINARY_SWEEP)
@pytest.mark.parametrize("probs", [True, False], ids=["probs", "logits"])
def test_binary(ref, name, kwargs, probs):
    preds = BIN_PROBS if probs else BIN_LOGITS
    if name == "binary_hinge_loss" and probs:
        pytest.skip("hinge operates on raw scores only")
    _run(ref, name, (preds, BIN_TARGET), kwargs, atol=1e-5)


# ------------------------------------------------------------- multiclass family

MULTICLASS_SWEEP = [
    ("multiclass_accuracy", {"average": "micro"}),
    ("multiclass_accuracy", {"average": "macro"}),
    ("multiclass_accuracy", {"average": "weighted"}),
    ("multiclass_accuracy", {"average": "none"}),
    ("multiclass_accuracy", {"average": "macro", "top_k": 2}),
    ("multiclass_accuracy", {"average": "micro", "ignore_index": 1}),
    ("multiclass_precision", {"average": "macro"}),
    ("multiclass_precision", {"average": "weighted", "top_k": 2}),
    ("multiclass_recall", {"average": "macro"}),
    ("multiclass_recall", {"average": "none"}),
    ("multiclass_specificity", {"average": "macro"}),
    ("multiclass_f1_score", {"average": "macro"}),
    ("multiclass_f1_score", {"average": "micro", "ignore_index": 2}),
    ("multiclass_fbeta_score", {"beta": 2.0, "average": "weighted"}),
    ("multiclass_jaccard_index", {"average": "macro"}),
    ("multiclass_cohen_kappa", {}),
    ("multiclass_cohen_kappa", {"weights": "linear"}),
    ("multiclass_cohen_kappa", {"weights": "quadratic"}),
    ("multiclass_matthews_corrcoef", {}),
    ("multiclass_hamming_distance", {"average": "macro"}),
    ("multiclass_auroc", {"average": "macro", "thresholds": None}),
    ("multiclass_auroc", {"average": "weighted", "thresholds": 50}),
    ("multiclass_average_precision", {"average": "macro", "thresholds": None}),
    ("multiclass_average_precision", {"average": "weighted", "thresholds": 50}),
    ("multiclass_calibration_error", {"n_bins": 10, "norm": "l1"}),
    ("multiclass_confusion_matrix", {}),
    ("multiclass_confusion_matrix", {"normalize": "all"}),
    ("multiclass_stat_scores", {"average": "macro"}),
    ("multiclass_stat_scores", {"average": "micro", "top_k": 2}),
    ("multiclass_exact_match", {"multidim_average": "global"}),
]


@pytest.mark.parametrize(("name", "kwargs"), MULTICLASS_SWEEP)
def test_multiclass(ref, name, kwargs):
    args = {"num_classes": NC, **kwargs}
    if name == "multiclass_exact_match":
        _run(ref, name, (MD_PROBS, MD_TARGET), args, atol=1e-5)
        return
    _run(ref, name, (MC_PROBS, MC_TARGET), args, atol=1e-5)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("multiclass_accuracy", {"average": "micro"}),
        ("multiclass_accuracy", {"average": "macro"}),
        ("multiclass_f1_score", {"average": "macro"}),
        ("multiclass_jaccard_index", {"average": "macro"}),
        ("multiclass_confusion_matrix", {}),
    ],
)
def test_multiclass_int_preds(ref, name, kwargs):
    """Hard label predictions (int) path."""
    _run(ref, name, (MC_PREDS_INT, MC_TARGET), {"num_classes": NC, **kwargs}, atol=1e-6)


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("multiclass_accuracy", {"average": "macro", "multidim_average": "samplewise"}),
        ("multiclass_accuracy", {"average": "micro", "multidim_average": "samplewise"}),
        ("multiclass_stat_scores", {"average": "macro", "multidim_average": "samplewise"}),
        ("multiclass_exact_match", {"multidim_average": "samplewise"}),
    ],
)
def test_multidim_samplewise(ref, name, kwargs):
    _run(ref, name, (MD_PROBS, MD_TARGET), {"num_classes": NC, **kwargs}, atol=1e-5)


# ------------------------------------------------------------- multilabel family

MULTILABEL_SWEEP = [
    ("multilabel_accuracy", {"average": "micro"}),
    ("multilabel_accuracy", {"average": "macro"}),
    ("multilabel_accuracy", {"average": "none"}),
    ("multilabel_accuracy", {"average": "macro", "ignore_index": 0}),
    ("multilabel_precision", {"average": "macro"}),
    ("multilabel_recall", {"average": "weighted"}),
    ("multilabel_specificity", {"average": "macro"}),
    ("multilabel_f1_score", {"average": "macro"}),
    ("multilabel_fbeta_score", {"beta": 0.5, "average": "micro"}),
    ("multilabel_jaccard_index", {"average": "macro"}),
    ("multilabel_matthews_corrcoef", {}),
    ("multilabel_hamming_distance", {"average": "macro"}),
    ("multilabel_auroc", {"average": "macro", "thresholds": None}),
    ("multilabel_auroc", {"average": "micro", "thresholds": 50}),
    ("multilabel_average_precision", {"average": "macro", "thresholds": None}),
    ("multilabel_confusion_matrix", {}),
    ("multilabel_stat_scores", {"average": "macro"}),
    ("multilabel_exact_match", {}),
    ("multilabel_ranking_average_precision", {}),
    ("multilabel_coverage_error", {}),
    ("multilabel_ranking_loss", {}),
]


@pytest.mark.parametrize(("name", "kwargs"), MULTILABEL_SWEEP)
def test_multilabel(ref, name, kwargs):
    args = {"num_labels": NL, **kwargs}
    _run(ref, name, (ML_PROBS, ML_TARGET), args, atol=1e-5)


# ----------------------------------------------------------------- curve outputs


@pytest.mark.parametrize("thresholds", [None, 20])
def test_binary_precision_recall_curve(ref, thresholds):
    import jax.numpy as jnp
    import torch

    theirs = ref.functional.classification.binary_precision_recall_curve(
        torch.from_numpy(BIN_PROBS), torch.from_numpy(BIN_TARGET), thresholds=thresholds
    )
    ours = F.binary_precision_recall_curve(jnp.asarray(BIN_PROBS), jnp.asarray(BIN_TARGET), thresholds=thresholds)
    for o, t in zip(ours, theirs):
        assert_close(o, t, atol=1e-6)


@pytest.mark.parametrize("thresholds", [None, 20])
def test_binary_roc(ref, thresholds):
    import jax.numpy as jnp
    import torch

    theirs = ref.functional.classification.binary_roc(
        torch.from_numpy(BIN_PROBS), torch.from_numpy(BIN_TARGET), thresholds=thresholds
    )
    ours = F.binary_roc(jnp.asarray(BIN_PROBS), jnp.asarray(BIN_TARGET), thresholds=thresholds)
    for o, t in zip(ours, theirs):
        assert_close(o, t, atol=1e-6)


@pytest.mark.parametrize("thresholds", [None, 20])
def test_multiclass_roc(ref, thresholds):
    import jax.numpy as jnp
    import torch

    theirs = ref.functional.classification.multiclass_roc(
        torch.from_numpy(MC_PROBS), torch.from_numpy(MC_TARGET), num_classes=NC, thresholds=thresholds
    )
    ours = F.multiclass_roc(jnp.asarray(MC_PROBS), jnp.asarray(MC_TARGET), num_classes=NC, thresholds=thresholds)
    for o, t in zip(ours, theirs):
        assert_close(o, t, atol=1e-6)


# ------------------------------------------------------- fixed-operating-point


@pytest.mark.parametrize(
    ("name", "kwargs"),
    [
        ("binary_recall_at_fixed_precision", {"min_precision": 0.5}),
        ("binary_recall_at_fixed_precision", {"min_precision": 0.5, "thresholds": 100}),
        ("binary_precision_at_fixed_recall", {"min_recall": 0.5}),
        ("binary_specificity_at_sensitivity", {"min_sensitivity": 0.5}),
    ],
)
def test_binary_fixed_point(ref, name, kwargs):
    _run(ref, name, (BIN_PROBS, BIN_TARGET), kwargs, atol=1e-6)


# ------------------------------------------------------------------- dispatchers


@pytest.mark.parametrize(
    ("name", "task_kwargs"),
    [
        ("accuracy", {"task": "binary"}),
        ("accuracy", {"task": "multiclass", "num_classes": NC, "average": "macro"}),
        ("f1_score", {"task": "multilabel", "num_labels": NL, "average": "micro"}),
        ("auroc", {"task": "binary"}),
    ],
)
def test_dispatchers(ref, name, task_kwargs):
    import jax.numpy as jnp
    import torch

    if task_kwargs["task"] == "binary":
        a = (BIN_PROBS, BIN_TARGET)
    elif task_kwargs["task"] == "multiclass":
        a = (MC_PROBS, MC_TARGET)
    else:
        a = (ML_PROBS, ML_TARGET)
    theirs = getattr(ref.functional, name)(*[torch.from_numpy(np.asarray(x)) for x in a], **task_kwargs)
    ours = getattr(__import__("metrics_tpu.functional", fromlist=[name]), name)(
        *[jnp.asarray(x) for x in a], **task_kwargs
    )
    assert_close(ours, theirs, atol=1e-5)


# --------------------------------------------------------------------- fairness


def test_group_fairness(ref):
    import jax.numpy as jnp
    import torch

    groups = rng.randint(0, 2, N)
    theirs = ref.functional.classification.demographic_parity(
        torch.from_numpy(BIN_PROBS), torch.from_numpy(groups)
    )
    ours = F.demographic_parity(jnp.asarray(BIN_PROBS), jnp.asarray(groups))
    assert_close(ours, theirs, atol=1e-6)

    theirs = ref.functional.classification.equal_opportunity(
        torch.from_numpy(BIN_PROBS), torch.from_numpy(BIN_TARGET), torch.from_numpy(groups)
    )
    ours = F.equal_opportunity(jnp.asarray(BIN_PROBS), jnp.asarray(BIN_TARGET), jnp.asarray(groups))
    assert_close(ours, theirs, atol=1e-6)
