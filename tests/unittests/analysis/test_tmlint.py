"""tmlint (metrics_tpu/analysis/): per-rule fixtures and the repo-wide guard.

Every shipped rule has one known-bad snippet (asserting the exact rule ID and
line) and one known-clean snippet (asserting silence — the clean twin encodes
the jit-boundary/guard model the rule must respect). The repo-wide test runs
the analyzer over the whole package against the checked-in baseline: a new
finding anywhere in metrics_tpu/ fails CI here.
"""
import importlib.util
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

import metrics_tpu
from metrics_tpu.analysis import BASELINE_FILENAME, RULES, analyze, explain
from metrics_tpu.analysis.contract import class_findings
from metrics_tpu.analysis.registry import IntrospectedClass

pytestmark = pytest.mark.lint

REPO_ROOT = pathlib.Path(metrics_tpu.__file__).resolve().parent.parent


def _lint_snippet(tmp_path, source, introspect=False):
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    report = analyze(str(path), introspect=introspect, repo_root=str(tmp_path))
    return report.new_findings


def _rules_and_lines(findings):
    return sorted((f.rule, f.line) for f in findings)


# --------------------------------------------------------------- TM-HOSTSYNC


def test_hostsync_bad(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def kernel(x):
            total = x.sum().item()
            arr = np.asarray(x)
            return jnp.asarray(total) + arr.sum()
        """,
    )
    assert ("TM-HOSTSYNC", 8) in _rules_and_lines(findings)  # .item()
    assert ("TM-HOSTSYNC", 9) in _rules_and_lines(findings)  # np.asarray
    assert all(f.rule == "TM-HOSTSYNC" for f in findings)


def test_hostsync_clean_guarded_and_static(tmp_path):
    """Concreteness guards and shape-derived statics must not be flagged."""
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        import numpy as np
        from metrics_tpu.utils.checks import _is_concrete

        @jax.jit
        def kernel(x):
            n = x.shape[0]
            m = int(n) * 2                      # static shape arithmetic
            pad = np.zeros(3, np.float32)       # static-arg numpy constant
            if _is_concrete(x):
                host = float(x.sum())           # eager-only side of the guard
                return jnp.asarray(host + m)
            return x.sum() + m + pad.sum()
        """,
    )
    assert findings == []


def test_hostsync_bad_bare_imports(tmp_path):
    """Rule-gap regression (found by tmsan's crosscheck tier): bare-name
    from-imports of numpy compute calls and aliased jax.device_get."""
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        from numpy import asarray, array
        from jax import device_get as dget

        @jax.jit
        def kernel(x):
            a = asarray(x)
            b = array(x)
            c = dget(x)
            return a.sum() + b.sum() + c.sum()
        """,
    )
    assert ("TM-HOSTSYNC", 8) in _rules_and_lines(findings)  # bare asarray
    assert ("TM-HOSTSYNC", 9) in _rules_and_lines(findings)  # bare array
    assert ("TM-HOSTSYNC", 10) in _rules_and_lines(findings)  # aliased device_get
    assert all(f.rule == "TM-HOSTSYNC" for f in findings)


def test_hostsync_clean_bare_imports_static(tmp_path):
    """Bare numpy imports on static values (shape math, dtype objects) stay clean."""
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        from numpy import asarray, prod, float32

        @jax.jit
        def kernel(x):
            n = prod(x.shape)
            pad = asarray([0.0, 1.0], float32)
            return x.sum() + int(n) + pad.sum()
        """,
    )
    assert findings == []


# --------------------------------------------------------------- TM-PYBRANCH


def test_pybranch_bad(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            if jnp.any(x > 0):
                return x.sum()
            return -x.sum()
        """,
    )
    assert _rules_and_lines(findings) == [("TM-PYBRANCH", 7)]


def test_pybranch_clean_static_tests(tmp_path):
    """Dtype checks and guarded data branches are not python branching bugs."""
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from metrics_tpu.utils.checks import _is_concrete

        @jax.jit
        def kernel(x, flag: bool):
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                x = x * 2
            if flag:
                x = x + 1
            if _is_concrete(x) and bool(jnp.any(x > 100)):
                raise ValueError("overflow")
            return x.sum()
        """,
    )
    assert findings == []


# --------------------------------------------------------------- TM-DYNSHAPE


def test_dynshape_bad(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            u = jnp.unique(x)
            pos = x[x > 0]
            return u.sum() + pos.sum()
        """,
    )
    assert ("TM-DYNSHAPE", 7) in _rules_and_lines(findings)  # unique without size=
    assert ("TM-DYNSHAPE", 8) in _rules_and_lines(findings)  # boolean mask
    assert all(f.rule == "TM-DYNSHAPE" for f in findings)


def test_dynshape_clean_with_size(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            u = jnp.unique(x, size=16, fill_value=0)
            pos = jnp.where(x > 0, x, 0.0)
            return u.sum() + pos.sum()
        """,
    )
    assert findings == []


# ---------------------------------------------------------------- TM-RETRACE


def test_retrace_bad(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def _kernel(x, scale):
            return x * scale

        _kernel_j = jax.jit(_kernel)

        def apply(x, scale: float):
            return _kernel_j(x, scale)

        def rebuild_every_call(x):
            return jax.jit(lambda v: v * 2)(x)
        """,
    )
    assert ("TM-RETRACE", 11) in _rules_and_lines(findings)  # scalar into jit
    assert ("TM-RETRACE", 14) in _rules_and_lines(findings)  # jit built per call
    assert all(f.rule == "TM-RETRACE" for f in findings)


def test_retrace_clean_static_argnames_and_asarray(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def _kernel(x, scale, mode):
            return x * scale if mode == "a" else x + scale

        _kernel_j = jax.jit(_kernel, static_argnames=("mode",))

        def apply(x, scale: float, mode: str):
            return _kernel_j(x, jnp.asarray(scale), mode=mode)
        """,
    )
    assert findings == []


# ---------------------------------------------------- state-contract fixtures


def _load_module(tmp_path, name, source):
    path = tmp_path / f"{name}.py"
    path.write_text("import jax.numpy as jnp\nfrom metrics_tpu.core.metric import Metric\n" + textwrap.dedent(source))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _contract(tmp_path, mod, cls_name, ctor_kwargs=None):
    cls = getattr(mod, cls_name)
    instance = cls(**(ctor_kwargs or {}))
    item = IntrospectedClass(cls_name, cls, instance)
    return class_findings(item, repo_root=str(tmp_path))




def test_state_unreg_bad(tmp_path):
    mod = _load_module(
        tmp_path,
        "unreg_bad",
        """
        class BadUnreg(Metric):
            full_state_update = False
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
            def update(self, x) -> None:
                self.total = self.total + x.sum()
                self.last_batch_mean = x.mean()
            def compute(self):
                return self.total
        """,
    )
    findings = _contract(tmp_path, mod, "BadUnreg")
    (f,) = [f for f in findings if f.rule == "TM-STATE-UNREG"]
    assert f.symbol.endswith(".last_batch_mean")
    # anchored to the offending assignment line in the source file
    line = pathlib.Path(tmp_path / "unreg_bad.py").read_text().split("\n")[f.line - 1]
    assert "last_batch_mean" in line


def test_state_unreg_clean_conditional_registration(tmp_path):
    """Attrs registered in ANY branch (curve-metric pattern) are not findings."""
    mod = _load_module(
        tmp_path,
        "unreg_clean",
        """
        class CleanConditional(Metric):
            full_state_update = False
            def __init__(self, binned=False, **kw):
                super().__init__(**kw)
                if binned:
                    self.add_state("confmat", jnp.zeros((2, 2)), dist_reduce_fx="sum")
                else:
                    self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
                self.binned = binned
            def update(self, x) -> None:
                if self.binned:
                    self.confmat = self.confmat + 1
                else:
                    self.total = self.total + x.sum()
            def compute(self):
                return self.total if not self.binned else self.confmat
        """,
    )
    assert [f for f in _contract(tmp_path, mod, "CleanConditional") if f.rule == "TM-STATE-UNREG"] == []


def test_reduce_mismatch_bad(tmp_path):
    mod = _load_module(
        tmp_path,
        "reduce_bad",
        """
        def _weird(stack):
            return stack[0]

        class BadReduce(Metric):
            full_state_update = False
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("dense_cat", jnp.zeros(3), dist_reduce_fx="cat")
                self.add_state("int_mean", jnp.asarray(0), dist_reduce_fx="mean")
                self.add_state("custom", jnp.asarray(0.0), dist_reduce_fx=_weird)
            def update(self, x) -> None:
                self.int_mean = self.int_mean + 1
            def compute(self):
                return self.int_mean
        """,
    )
    findings = [f for f in _contract(tmp_path, mod, "BadReduce") if f.rule == "TM-REDUCE-MISMATCH"]
    symbols = {f.symbol for f in findings}
    assert symbols == {"BadReduce.dense_cat", "BadReduce.int_mean", "BadReduce.custom"}
    cls_line = [
        i + 1
        for i, l in enumerate(pathlib.Path(tmp_path / "reduce_bad.py").read_text().split("\n"))
        if l.startswith("class BadReduce")
    ][0]
    assert all(f.line == cls_line for f in findings)


def test_reduce_mismatch_clean(tmp_path):
    mod = _load_module(
        tmp_path,
        "reduce_clean",
        """
        class CleanReduce(Metric):
            full_state_update = False
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
                self.add_state("avg", jnp.asarray(0.0), dist_reduce_fx="mean")
                self.add_state("rows", [], dist_reduce_fx="cat")
                self.add_state("stacked", jnp.asarray(0.0), dist_reduce_fx=None)
            def update(self, x) -> None:
                self.total = self.total + x.sum()
            def compute(self):
                return self.total
        """,
    )
    assert [f for f in _contract(tmp_path, mod, "CleanReduce") if f.rule == "TM-REDUCE-MISMATCH"] == []


def test_persist_bad(tmp_path):
    mod = _load_module(
        tmp_path,
        "persist_bad",
        """
        class BadPersist(Metric):
            full_state_update = False
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
                self.running_window = jnp.zeros(8)
            def update(self, x) -> None:
                self.total = self.total + x.sum()
            def compute(self):
                return self.total
        """,
    )
    findings = [f for f in _contract(tmp_path, mod, "BadPersist") if f.rule == "TM-PERSIST"]
    assert [f.symbol for f in findings] == ["BadPersist.running_window"]


def test_persist_clean_declared_exemptions(tmp_path):
    """Ctor knobs (_update_signature_attrs) and declared exemptions are fine."""
    mod = _load_module(
        tmp_path,
        "persist_clean",
        """
        class CleanPersist(Metric):
            full_state_update = False
            _update_signature_attrs = ("thresholds",)
            _ckpt_exempt_attrs = ("scratch",)
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
                self.thresholds = jnp.linspace(0, 1, 5)
                self.scratch = jnp.zeros(4)
            def update(self, x) -> None:
                self.total = self.total + x.sum()
            def compute(self):
                return self.total
        """,
    )
    assert [f for f in _contract(tmp_path, mod, "CleanPersist") if f.rule == "TM-PERSIST"] == []


# ------------------------------------------------------------ repo-wide guard


def test_tmlint_no_new_findings():
    """The whole package must be clean against the checked-in baseline."""
    report = analyze(str(REPO_ROOT / "metrics_tpu"), baseline_path=str(REPO_ROOT / BASELINE_FILENAME))
    assert report.parse_errors == {}
    msgs = "\n".join(f.format() for f in report.new_findings)
    assert not report.new_findings, f"new tmlint findings:\n{msgs}"
    # stale waivers rot silently; fail so the baseline shrinks as fixes land
    assert not report.unused_waivers, f"stale baseline waivers: {report.unused_waivers}"


def test_every_rule_documented_and_cross_linked():
    from metrics_tpu.analysis.findings import (
        LINT_RULES, OWN_RULES, RACE_RULES, SAN_RULES, SHARD_RULES,
    )

    assert set(LINT_RULES) == {
        "TM-HOSTSYNC", "TM-PYBRANCH", "TM-DYNSHAPE", "TM-RETRACE",
        "TM-STATE-UNREG", "TM-REDUCE-MISMATCH", "TM-PERSIST",
    }
    assert set(SAN_RULES) == {
        "TMS-CALLBACK", "TMS-F64", "TMS-UPCAST", "TMS-BIGCONST",
        "TMS-COLLECTIVE", "TMS-DYNSHAPE", "TMS-LINTGAP", "TMS-STALE-WAIVER",
        "TMS-BUDGET",
    }
    assert set(RACE_RULES) == {
        "TMR-UNLOCKED", "TMR-ORDER", "TMR-HOLD-HOST", "TMR-HANDLER", "TMR-LEAK",
    }
    assert set(OWN_RULES) == {
        "TMO-DONATE-ALIAS", "TMO-USE-AFTER-DONATE", "TMO-DOUBLE-DONATE",
        "TMO-SNAPSHOT-GAP", "TMO-KEY-GAP", "TMO-ENGINE-DRIFT",
    }
    assert set(SHARD_RULES) == {
        "TMH-AXIS-UNBOUND", "TMH-SPEC-ALGEBRA", "TMH-REPLICA-DIVERGE",
        "TMH-DONATE-RESHARD", "TMH-KEY-SHARD", "TMH-MESH-DRIFT",
    }
    assert set(RULES) == (
        set(LINT_RULES) | set(SAN_RULES) | set(RACE_RULES) | set(OWN_RULES)
        | set(SHARD_RULES)
    )
    # the five tiers partition RULES: every waiver has exactly one staleness home
    tiers = [
        set(LINT_RULES), set(SAN_RULES), set(RACE_RULES), set(OWN_RULES),
        set(SHARD_RULES),
    ]
    for i, a in enumerate(tiers):
        for b in tiers[i + 1:]:
            assert not a & b
    for rule_id, rule in RULES.items():
        text = explain(rule_id)
        assert rule_id in text and rule.runtime_signal in text
    # the retrace rule must name the obs counters it mirrors (obs/recompile.py)
    assert "retrace_signatures" in RULES["TM-RETRACE"].counter


def test_registry_covers_contract_sweep_classes():
    """The analyzer's ctor registry must construct what the sweep tests: every
    exported metric class is introspected or carries an explicit skip reason."""
    from metrics_tpu.analysis.registry import introspect_classes

    results = {item.name: item for item in introspect_classes()}
    unexplained = [
        name for name, item in results.items() if item.instance is None and not item.skip_reason
    ]
    assert not unexplained
    constructed = [n for n, item in results.items() if item.instance is not None]
    assert len(constructed) > 100, f"only {len(constructed)} classes constructible"
    failures = {
        n: item.skip_reason
        for n, item in results.items()
        if item.instance is None and item.skip_reason.startswith("construction failed")
    }
    assert not failures, f"registry ctor specs out of sync with exports: {failures}"


# --------------------------------------------------------------------- CLI


@pytest.mark.smoke
def test_cli_seeded_violation_and_clean_exit(tmp_path):
    """Acceptance: clean tree exits 0; a seeded `.item()` in a jitted kernel
    exits non-zero and names the rule."""
    pkg = tmp_path / "toypkg"
    pkg.mkdir()
    clean = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return x.sum()
        """
    )
    (pkg / "mod.py").write_text(clean)
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO_ROOT)}

    def run():
        return subprocess.run(
            [sys.executable, "-m", "metrics_tpu.analysis", str(pkg), "--no-introspect"],
            capture_output=True, text=True, timeout=120, env=env, cwd=str(tmp_path),
        )

    result = run()
    assert result.returncode == 0, result.stdout + result.stderr

    (pkg / "mod.py").write_text(clean.replace("return x.sum()", "return x.sum().item()"))
    result = run()
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TM-HOSTSYNC" in result.stdout


@pytest.mark.smoke
def test_cli_explain_and_json(tmp_path):
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO_ROOT)}
    result = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--explain", "TM-HOSTSYNC"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert result.returncode == 0
    assert "TM-HOSTSYNC" in result.stdout and "obs" in result.stdout

    pkg = tmp_path / "p"
    pkg.mkdir()
    (pkg / "m.py").write_text("import jax\n@jax.jit\ndef k(x):\n    return float(x)\n")
    result = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", str(pkg), "--no-introspect", "--json"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert result.returncode == 1
    payload = json.loads(result.stdout)
    assert payload["new"] and payload["new"][0]["rule"] == "TM-HOSTSYNC"
