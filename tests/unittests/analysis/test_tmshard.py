"""tmshard unit tier: per-rule seeded fixtures (each with a clean twin
encoding the repo's guard idiom), the mesh-awareness drift matrix, the
checked-in ROADMAP-item-1/4 shard-plan worksheet, the five-tier waiver
scoping, the repo-wide no-new-findings guard, and end-to-end CLI exit-code
regressions.

Pure static analysis — nothing here executes the analyzed code except the
worksheet in-sync test, which pays the registry introspection cost the same
way ``--shard --write-plan`` does; it rides the ``lint`` CI step next to the
other tiers and also carries the ``shard`` marker for the dedicated CI step.
"""
import pathlib
import subprocess
import sys
import textwrap

import pytest

import metrics_tpu
from metrics_tpu.analysis import BASELINE_FILENAME
from metrics_tpu.analysis.baseline import load_baseline, scope_waivers
from metrics_tpu.analysis.findings import SHARD_RULES
from metrics_tpu.analysis.shard import plan, run_shard, spec_rules
from metrics_tpu.analysis.shard.axis_model import build_model

pytestmark = [pytest.mark.lint, pytest.mark.shard]

REPO_ROOT = pathlib.Path(metrics_tpu.__file__).resolve().parent.parent


def _shard_snippet(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    report = run_shard(str(path), repo_root=str(tmp_path))
    assert report.parse_errors == {}
    # fixture runs never see the repo engine anchors: no matrix, no drift
    assert report.mesh_matrix == {}
    return report.new_findings


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------- TMH-AXIS-UNBOUND


def test_axis_unbound_bad(tmp_path):
    """A literal collective axis in a function no shard_map/pmap context
    reaches: the trace fails at best, silently degenerates at worst."""
    findings = _shard_snippet(
        tmp_path,
        """
        import jax

        def merge(x):
            return jax.lax.psum(x, "fleet")

        def launch(x):
            run = jax.jit(merge)
            return run(x)
        """,
    )
    assert _rules(findings) == ["TMH-AXIS-UNBOUND"]
    (f,) = findings
    assert f.symbol == "merge"
    assert "no shard_map/pmap reaches this function" in f.message


def test_axis_unbound_shard_map_clean_twin(tmp_path):
    """Same reduce under a shard_map whose mesh binds the axis -> clean."""
    findings = _shard_snippet(
        tmp_path,
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((8,), ("fleet",))

        @partial(shard_map, mesh=MESH, in_specs=(P(),), out_specs=P())
        def merge(x):
            return jax.lax.psum(x, "fleet")

        def launch(x):
            return merge(x)
        """,
    )
    assert findings == []


def test_axis_unbound_must_analysis_intersects_callers(tmp_path):
    """A helper reached from two mapped contexts is bound only to the
    *intersection* of their axes — the axis one caller lacks is flagged."""
    findings = _shard_snippet(
        tmp_path,
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        FLEET = jax.make_mesh((8,), ("fleet",))
        DATA = jax.make_mesh((8,), ("data",))

        def helper(x):
            return jax.lax.psum(x, "fleet")

        @partial(shard_map, mesh=FLEET, in_specs=(P(),), out_specs=P())
        def fleet_body(x):
            return helper(x)

        @partial(shard_map, mesh=DATA, in_specs=(P(),), out_specs=P())
        def data_body(x):
            return helper(x)
        """,
    )
    assert _rules(findings) == ["TMH-AXIS-UNBOUND"]
    (f,) = findings
    assert f.symbol == "helper"


# --------------------------------------------------------- TMH-SPEC-ALGEBRA


def test_spec_algebra_partitioned_psum_bad(tmp_path):
    """The double-count shape: psum over an axis the in-spec *partitions* —
    each shard holds distinct rows, so the reduce mixes them."""
    findings = _shard_snippet(
        tmp_path,
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((8,), ("data",))

        @partial(shard_map, mesh=MESH, in_specs=(P("data"),), out_specs=P())
        def sync(state):
            return jax.lax.psum(state, "data")
        """,
    )
    assert _rules(findings) == ["TMH-SPEC-ALGEBRA"]
    (f,) = findings
    assert f.symbol == "sync"
    assert "double-counts" in f.message


def test_spec_algebra_local_reduce_clean_twin(tmp_path):
    """The guard idiom: fold the local block first, then sync the folded
    scalar — the reduced operand is no longer the partitioned parameter."""
    findings = _shard_snippet(
        tmp_path,
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((8,), ("data",))

        @partial(shard_map, mesh=MESH, in_specs=(P("data"),), out_specs=P())
        def sync(state):
            return jax.lax.psum(state.sum(axis=0), "data")
        """,
    )
    assert findings == []


def test_spec_algebra_replicated_operand_clean(tmp_path):
    """psum over a *replicated* (P()) operand is the evaluate_sharded idiom
    and must not be flagged."""
    findings = _shard_snippet(
        tmp_path,
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((8,), ("data",))

        @partial(shard_map, mesh=MESH, in_specs=(P(),), out_specs=P())
        def sync(state):
            return jax.lax.psum(state, "data")
        """,
    )
    assert findings == []


# ------------------------------------------------------ TMH-REPLICA-DIVERGE


def test_replica_diverge_bad(tmp_path):
    """A host read traced under a map bakes a different value into each
    replica (a), and feeding it to a collective combines them (b)."""
    findings = _shard_snippet(
        tmp_path,
        """
        import time
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((4,), ("fleet",))

        @partial(shard_map, mesh=MESH, in_specs=(P(),), out_specs=P())
        def merge(x):
            seed = time.time()
            return jax.lax.pmax(x + seed, "fleet")
        """,
    )
    assert _rules(findings) == ["TMH-REPLICA-DIVERGE"]
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2
    assert any("executes inside a mapped trace" in m for m in msgs)
    assert any("replica-divergent host read" in m for m in msgs)


def test_replica_diverge_hoisted_clean_twin(tmp_path):
    """The guard idiom: the host read runs in the eager launcher and enters
    the mapped body as data."""
    findings = _shard_snippet(
        tmp_path,
        """
        import time
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((4,), ("fleet",))

        @partial(shard_map, mesh=MESH, in_specs=(P(), P()), out_specs=P())
        def merge(x, seed):
            return jax.lax.pmax(x + seed, "fleet")

        def launch(x):
            seed = time.time()
            return merge(x, seed)
        """,
    )
    assert findings == []


# ------------------------------------------------------ TMH-DONATE-RESHARD


def test_donate_reshard_bad(tmp_path):
    """Donating a P('data')-placed buffer into a launch whose in-spec is
    replicated: XLA inserts a resharding copy, the donation frees nothing."""
    findings = _shard_snippet(
        tmp_path,
        """
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        def step(s):
            return s + 1

        def launch(mesh, x):
            x = jax.device_put(x, NamedSharding(mesh, P("data")))
            run = jax.jit(
                step,
                donate_argnums=(0,),
                in_shardings=(NamedSharding(mesh, P(None)),),
            )
            return run(x)
        """,
    )
    assert _rules(findings) == ["TMH-DONATE-RESHARD"]
    (f,) = findings
    assert f.symbol == "launch"
    assert "the donation frees nothing" in f.message


def test_donate_reshard_matching_spec_clean_twin(tmp_path):
    findings = _shard_snippet(
        tmp_path,
        """
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        def step(s):
            return s + 1

        def launch(mesh, x):
            x = jax.device_put(x, NamedSharding(mesh, P("data")))
            run = jax.jit(
                step,
                donate_argnums=(0,),
                in_shardings=(NamedSharding(mesh, P("data")),),
            )
            return run(x)
        """,
    )
    assert findings == []


# ---------------------------------------------------------- TMH-KEY-SHARD


_KEYED_ENGINE = """
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    class Engine:
        def __init__(self):
            self._cache = {}

        def launch(self, tag, x, mesh):
            x = jax.device_put(x, NamedSharding(mesh, P("data")))
            key = (tag, x.shape)
            compiled = self._cache.get(key)
            if compiled is None:
                compiled = jax.jit(lambda s: s + 1)
                self._cache[key] = compiled
            return compiled(x)
    """


def test_key_shard_bad(tmp_path):
    """A cached launch consuming a placed array whose cache key has no
    sharding/mesh facet: a placement change replays a stale executable."""
    findings = _shard_snippet(tmp_path, _KEYED_ENGINE)
    assert _rules(findings) == ["TMH-KEY-SHARD"]
    (f,) = findings
    assert f.symbol == "Engine.launch.sharding"
    assert "no sharding/mesh facet" in f.message


def test_key_shard_facet_clean_twin(tmp_path):
    """The guard idiom (the fused._aval_key fix): fold the placement spec
    into the key tuple."""
    findings = _shard_snippet(
        tmp_path,
        _KEYED_ENGINE.replace(
            "key = (tag, x.shape)", "key = (tag, x.shape, str(x.sharding))"
        ),
    )
    assert findings == []


# --------------------------------------------------------- TMH-MESH-DRIFT


_SHARDED_ENGINE = textwrap.dedent(
    """
    import jax

    _CACHE = {}

    def step(s):
        return s + 1

    def launch(tag, state):
        key = (tag, state.shape, str(state.sharding))
        compiled = _CACHE.get(key)
        if compiled is None:
            compiled = jax.jit(step)
            _CACHE[key] = compiled
        return compiled(state)
    """
)

_UNSHARDED_ENGINE = _SHARDED_ENGINE.replace(
    "key = (tag, state.shape, str(state.sharding))", "key = (tag, state.shape)"
)


def _mini_fleet(third_engine_src):
    model = build_model(
        {
            "eng_a.py": ("eng_a", _SHARDED_ENGINE),
            "eng_b.py": ("eng_b", _SHARDED_ENGINE),
            "eng_c.py": ("eng_c", third_engine_src),
        }
    )
    engines = {
        "a": ("eng_a.py", "launch"),
        "b": ("eng_b.py", "launch"),
        "c": ("eng_c.py", "launch"),
    }
    return spec_rules.extract_mesh_contract(model, engines=engines)


def test_mesh_drift_fires_on_everyone_but_you():
    """A component two peers implement and one engine lacks is drift; the
    components nobody implements are just not part of the contract."""
    matrix = _mini_fleet(_UNSHARDED_ENGINE)
    findings = spec_rules.drift_findings(matrix)
    assert _rules(findings) == ["TMH-MESH-DRIFT"]
    assert sorted(f.symbol for f in findings) == [
        "c.placed_io", "c.sharded_key_facet",
    ]
    assert all(f.path == "eng_c.py" for f in findings)
    assert matrix["a"]["components"]["sharded_key_facet"] == "launch"


def test_mesh_drift_uniform_fleet_clean():
    matrix = _mini_fleet(_SHARDED_ENGINE)
    assert spec_rules.drift_findings(matrix) == []


# --------------------------------------------- repo-wide guard + worksheet


@pytest.fixture(scope="module")
def repo_report():
    return run_shard(
        str(REPO_ROOT / "metrics_tpu"),
        baseline_path=str(REPO_ROOT / BASELINE_FILENAME),
    )


def test_tmshard_no_new_findings(repo_report):
    """The whole package must be sharding-clean against the checked-in
    baseline, with every waiver carrying a reason and none stale."""
    assert repo_report.parse_errors == {}
    msgs = "\n".join(f.format() for f in repo_report.new_findings)
    assert not repo_report.new_findings, f"new tmshard findings:\n{msgs}"
    assert not repo_report.unused_waivers, (
        f"stale baseline waivers: {repo_report.unused_waivers}"
    )
    for f in repo_report.waived:
        assert f.waive_reason, f"waiver without a reason covers {f.key()}"
    # the ISSUE's cold-wall budget is 60s on CPU; the AST sweep is ~15x under
    assert repo_report.stats["seconds"] < 60


def test_repo_mesh_matrix(repo_report):
    """The matrix must see all five engines; the three keyed-cache engines
    share the _aval_key sharding facet, and only the two triaged gaps
    (rank/mesh sharded_key_facet — jax.jit keys on shardings natively)
    survive as waived drift."""
    assert set(repo_report.mesh_matrix) == {
        "fused", "fleet", "ingest", "rank", "mesh",
    }
    for engine in ("fused", "fleet", "ingest"):
        comp = repo_report.mesh_matrix[engine]["components"]
        assert comp["placed_io"], f"{engine} lost placed_io"
        assert comp["sharded_key_facet"], f"{engine} lost sharded_key_facet"
        assert repo_report.mesh_matrix[engine]["has_cache"]
    mesh = repo_report.mesh_matrix["mesh"]["components"]
    for component in ("axis_binding", "collective_sync", "spec_plumbing", "placed_io"):
        assert mesh[component], f"mesh program lost {component}"
    waived = {f.symbol for f in repo_report.waived if f.rule == "TMH-MESH-DRIFT"}
    assert waived == {"rank.sharded_key_facet", "mesh.sharded_key_facet"}


def test_repo_collective_axes_all_parameterized(repo_report):
    """The package idiom the dataflow rules rest on: every repo collective
    takes its axis as a parameter (or from a mapped context), never a free
    literal — so the five dataflow rules run clean without any waiver."""
    dataflow = [f for f in repo_report.findings if f.rule != "TMH-MESH-DRIFT"]
    assert dataflow == []
    assert repo_report.stats["collectives"] > 0
    assert repo_report.stats["mapped_bodies"] >= 1  # evaluate_sharded.run


def test_plan_worksheet_in_sync(repo_report):
    """`tmshard_state_plan.json` is the checked-in ROADMAP-item-1/4
    worksheet; it must match a fresh extraction (regenerate with
    --shard --write-plan) and cover the whole constructible registry."""
    checked_in = plan.load_worksheet(str(REPO_ROOT / plan.PLAN_FILENAME))
    fresh = __import__("json").loads(
        __import__("json").dumps(repo_report.plan_worksheet())
    )
    assert checked_in == fresh
    assert len(checked_in["classes"]) > 100
    # every class got a verdict for every registered state, with a reason
    for name, entry in checked_in["classes"].items():
        for state, facts in entry["states"].items():
            assert set(facts["verdicts"]) == set(plan._AXIS_LEGEND), (name, state)
            for verdict in facts["verdicts"].values():
                assert verdict["reason"], (name, state)
            assert facts["plan"]


def test_state_verdicts_algebra():
    """The pure verdict function matches the fleet eligibility gate."""
    v = plan.state_verdicts("sum", "vector", host_side=False)
    assert v["psum_safe"]["ok"] and v["fleet_partitionable"]["ok"]
    v = plan.state_verdicts("mean", "scalar", host_side=False)
    assert v["psum_safe"]["ok"] and not v["fleet_partitionable"]["ok"]
    v = plan.state_verdicts("cat", "cat_list", host_side=False)
    assert v["cat_shard_only"]["ok"] and not v["psum_safe"]["ok"]
    v = plan.state_verdicts("sum", "vector", host_side=True)
    assert not v["fleet_partitionable"]["ok"]
    v = plan.state_verdicts("none", "scalar", host_side=False)
    assert v["replicate_only"]["ok"]


def test_waiver_scoping_partitions_staleness():
    """The shared baseline is scoped per tier: the tmshard view holds
    exactly the TMH-* waivers and nothing from the other four tiers."""
    waivers = load_baseline(str(REPO_ROOT / BASELINE_FILENAME))
    scoped = scope_waivers(waivers, SHARD_RULES)
    assert scoped, "repo baseline lost its TMH waivers"
    assert all(rule.startswith("TMH-") for rule, _p, _s in scoped)
    dropped = set(waivers) - set(scoped)
    assert all(not rule.startswith("TMH-") for rule, _p, _s in dropped)


def test_shard_obs_counters(tmp_path):
    """A seeded run increments the shard.* counters when obs is enabled."""
    import metrics_tpu.obs as obs

    path = tmp_path / "mod.py"
    path.write_text(
        textwrap.dedent(
            """
            import jax

            def merge(x):
                return jax.lax.psum(x, "fleet")
            """
        )
    )
    with obs.observe() as reg:
        before = reg.get("shard", "axis_unbound")
        report = run_shard(str(path), repo_root=str(tmp_path))
        assert _rules(report.new_findings) == ["TMH-AXIS-UNBOUND"]
        assert reg.get("shard", "axis_unbound") == before + 1


# ------------------------------------------------------------ CLI end-to-end


_CLI_ENV = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO_ROOT)}


def _run_cli(pkg, tmp_path):
    return subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--shard", str(pkg)],
        capture_output=True, text=True, timeout=120, env=_CLI_ENV, cwd=str(tmp_path),
    )


@pytest.mark.smoke
def test_cli_partitioned_psum_regression(tmp_path):
    """Acceptance regression: the seeded partitioned-psum double-count must
    fail the build end-to-end (exit 1, rule named); the local-reduce twin
    passes."""
    pkg = tmp_path / "toypkg"
    pkg.mkdir()
    bad = textwrap.dedent(
        """
        from functools import partial

        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH = jax.make_mesh((8,), ("data",))

        @partial(shard_map, mesh=MESH, in_specs=(P("data"),), out_specs=P())
        def sync(state):
            return jax.lax.psum(state, "data")
        """
    )
    (pkg / "mod.py").write_text(bad)
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TMH-SPEC-ALGEBRA" in result.stdout

    (pkg / "mod.py").write_text(
        bad.replace("psum(state, ", "psum(state.sum(axis=0), ")
    )
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 0, result.stdout + result.stderr
