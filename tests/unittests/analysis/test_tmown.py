"""tmown unit tier: per-rule seeded fixtures (each with a clean twin — the
TMO-DONATE-ALIAS pair reproduces the PR 16 restore-aliasing incident), the
engine-contract drift matrix, the checked-in ROADMAP-item-5 worksheet, the
five-tier waiver scoping, the repo-wide no-new-findings guard, and end-to-end
CLI exit-code regressions.

Pure static analysis — nothing here executes the analyzed code; it rides the
``lint`` CI step next to tmlint/tmsan/tmrace and also carries the ``own``
marker for the dedicated CI step.
"""
import pathlib
import subprocess
import sys
import textwrap

import pytest

import metrics_tpu
from metrics_tpu.analysis import BASELINE_FILENAME
from metrics_tpu.analysis.own import run_own
from metrics_tpu.analysis.own import engine_contract
from metrics_tpu.analysis.own.buffer_model import build_model

pytestmark = [pytest.mark.lint, pytest.mark.own]

REPO_ROOT = pathlib.Path(metrics_tpu.__file__).resolve().parent.parent


def _own_snippet(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    report = run_own(str(path), repo_root=str(tmp_path))
    assert report.parse_errors == {}
    # fixture runs never see the repo engine anchors: no contract, no drift
    assert report.contract == {}
    return report.new_findings


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------- TMO-DONATE-ALIAS


def test_donate_alias_bad_pr16_twin(tmp_path):
    """The PR 16 heap-corruption shape: jnp.asarray over an np.frombuffer
    payload view zero-copy aliases host memory, then flows into a donated
    position of a compiled step -> TMO-DONATE-ALIAS."""
    findings = _own_snippet(
        tmp_path,
        """
        import numpy as np
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def restore(payload):
            view = np.frombuffer(payload, dtype="float32")
            state = jnp.asarray(view)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            out = jitted(state)
            return out
        """,
    )
    assert _rules(findings) == ["TMO-DONATE-ALIAS"]
    (f,) = findings
    assert f.symbol == "restore"
    assert "aliases host memory" in f.message


def test_donate_alias_clean_twin_owned_copy(tmp_path):
    """Same flow through the ckpt.restore._owned() fix — jnp.array(...,
    copy=True) materializes an owned device buffer -> clean."""
    findings = _own_snippet(
        tmp_path,
        """
        import numpy as np
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def restore(payload):
            view = np.frombuffer(payload, dtype="float32")
            state = jnp.array(view, copy=True)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            out = jitted(state)
            return out
        """,
    )
    assert findings == []


def test_donate_alias_host_numpy_bad(tmp_path):
    """Donating host-allocated numpy memory directly (zero-copy on the CPU
    backend) is the same class of bug, phrased differently."""
    findings = _own_snippet(
        tmp_path,
        """
        import numpy as np
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state

        def launch(n):
            state = np.zeros(n, dtype="float32")
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            return jitted(state)
        """,
    )
    assert _rules(findings) == ["TMO-DONATE-ALIAS"]
    assert "host-allocated numpy memory" in findings[0].message


# ----------------------------------------------------- TMO-USE-AFTER-DONATE


def test_use_after_donate_bad(tmp_path):
    """Reading a donated name before re-pointing it: the buffer is dead."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def launch(n):
            state = jnp.zeros(n)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            out = jitted(state)
            norm = out - state
            return norm
        """,
    )
    assert _rules(findings) == ["TMO-USE-AFTER-DONATE"]
    (f,) = findings
    assert f.symbol == "launch"
    assert "`state` was donated" in f.message


def test_use_after_donate_repoint_clean_twin(tmp_path):
    """Reassigning the name to the exec result re-points it -> clean."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def launch(n):
            state = jnp.zeros(n)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            state = jitted(state)
            norm = state.sum()
            return norm
        """,
    )
    assert findings == []


def test_use_after_donate_is_deleted_handler_exempt(tmp_path):
    """The sanctioned recovery idiom — an except handler probing
    ``.is_deleted()`` before reloading — reads a maybe-dead buffer on
    purpose and must not be flagged (the fused/ingest recovery path)."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def launch(n):
            state = jnp.zeros(n)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            try:
                out = jitted(state)
            except RuntimeError:
                if state.is_deleted():
                    out = jnp.zeros(n)
                else:
                    raise
            return out
        """,
    )
    assert findings == []


# ------------------------------------------------------- TMO-DOUBLE-DONATE


def test_double_donate_bad(tmp_path):
    """One buffer reaching two donated positions of one call with no dedup
    guard: XLA frees it twice."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        def step2(a, b):
            return a + b, b

        def launch(x):
            jitted = jax.jit(step2, donate_argnums=(0, 1))
            secure_pending_snapshots([x])
            out, aux = jitted(x, x)
            return out
        """,
    )
    assert _rules(findings) == ["TMO-DOUBLE-DONATE"]
    (f,) = findings
    assert f.symbol == "launch"
    assert "positions 0 and 1" in f.message


def test_double_donate_distinct_args_clean_twin(tmp_path):
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        def step2(a, b):
            return a + b, b

        def launch(x, y):
            jitted = jax.jit(step2, donate_argnums=(0, 1))
            secure_pending_snapshots([x, y])
            out, aux = jitted(x, y)
            return out
        """,
    )
    assert findings == []


def test_double_donate_guard_clean_twin(tmp_path):
    """A dominating _donation_guard call (the fused dedup) sanctions the
    duplicate — the guard replaces dupes with copies at runtime."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        def _donation_guard(buffers):
            return buffers

        def step2(a, b):
            return a + b, b

        def launch(x):
            jitted = jax.jit(step2, donate_argnums=(0, 1))
            secure_pending_snapshots([x])
            _donation_guard([x, x])
            out, aux = jitted(x, x)
            return out
        """,
    )
    assert findings == []


# -------------------------------------------------------- TMO-SNAPSHOT-GAP


def test_snapshot_gap_bad(tmp_path):
    """A donating exec with no dominating snapshot shield: a pending async
    ckpt may still reference the about-to-be-freed buffers."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def step(state):
            return state + 1

        def launch(n):
            state = jnp.zeros(n)
            jitted = jax.jit(step, donate_argnums=(0,))
            out = jitted(state)
            return out
        """,
    )
    assert _rules(findings) == ["TMO-SNAPSHOT-GAP"]
    (f,) = findings
    assert f.symbol == "launch"
    assert "secure_pending_snapshots" in f.message


def test_snapshot_gap_shield_clean_twin(tmp_path):
    findings = _own_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def launch(n):
            state = jnp.zeros(n)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            out = jitted(state)
            return out
        """,
    )
    assert findings == []


def test_snapshot_gap_fleet_shield_assignment_with_starred_args(tmp_path):
    """Regression for the fleet false positive: the shield runs in a branch
    (not dominating), the donated value is the *result* of _shield_donation,
    and the exec passes trailing *extras — a Starred after the donated
    position must not disable the donated-argument mapping."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def _shield_donation(metric, state):
            return state

        def step(state, *extras):
            return state

        def launch(metric, state, extras, donate):
            jitted = jax.jit(step, donate_argnums=(0,))
            if donate:
                state = _shield_donation(metric, state)
            out = jitted(state, *extras)
            return out
        """,
    )
    assert findings == []


# ------------------------------------------------------------- TMO-KEY-GAP


def test_key_gap_bad(tmp_path):
    """The executable-cache key omits a runtime argument of the compiled
    call (`dyn`) and a local the traced step closes over (`scale`): a cache
    hit replays an executable specialized on stale values."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        class Engine:
            def __init__(self):
                self._cache = {}

            def launch(self, tag, state, dyn, scale):
                def step(s, d):
                    return s * scale + d

                key = (tag, state.shape)
                compiled = self._cache.get(key)
                if compiled is None:
                    compiled = jax.jit(step, donate_argnums=(0,))
                    self._cache[key] = compiled
                secure_pending_snapshots([state])
                out = compiled(state, dyn)
                return out
        """,
    )
    assert _rules(findings) == ["TMO-KEY-GAP"]
    assert sorted(f.symbol for f in findings) == [
        "Engine.launch.dyn", "Engine.launch.scale",
    ]
    by_symbol = {f.symbol: f.message for f in findings}
    assert "runtime argument of the compiled call" in by_symbol["Engine.launch.dyn"]
    assert "closed over by the traced step" in by_symbol["Engine.launch.scale"]


def test_key_gap_clean_twin(tmp_path):
    """Same engine with both inputs folded into the key -> clean."""
    findings = _own_snippet(
        tmp_path,
        """
        import jax

        def secure_pending_snapshots(buffers):
            return buffers

        class Engine:
            def __init__(self):
                self._cache = {}

            def launch(self, tag, state, dyn, scale):
                def step(s, d):
                    return s * scale + d

                key = (tag, state.shape, dyn.shape, scale)
                compiled = self._cache.get(key)
                if compiled is None:
                    compiled = jax.jit(step, donate_argnums=(0,))
                    self._cache[key] = compiled
                secure_pending_snapshots([state])
                out = compiled(state, dyn)
                return out
        """,
    )
    assert findings == []


# -------------------------------------------------------- TMO-ENGINE-DRIFT


_FULL_ENGINE = textwrap.dedent(
    """
    import jax

    _CACHE = {}

    def secure_pending_snapshots(buffers):
        return buffers

    def step(s):
        return s + 1

    def launch(tag, state):
        key = (tag, state.shape)
        compiled = _CACHE.get(key)
        if compiled is None:
            compiled = jax.jit(step, donate_argnums=(0,))
            _CACHE[key] = compiled
        secure_pending_snapshots([state])
        return compiled(state)
    """
)

_NO_SNAPSHOT_ENGINE = textwrap.dedent(
    """
    import jax

    _CACHE = {}

    def step(s):
        return s + 1

    def launch(tag, state):
        key = (tag, state.shape)
        compiled = _CACHE.get(key)
        if compiled is None:
            compiled = jax.jit(step, donate_argnums=(0,))
            _CACHE[key] = compiled
        return compiled(state)
    """
)


def _mini_fleet(third_engine_src):
    model = build_model(
        {
            "eng_a.py": ("eng_a", _FULL_ENGINE),
            "eng_b.py": ("eng_b", _FULL_ENGINE),
            "eng_c.py": ("eng_c", third_engine_src),
        }
    )
    engines = {
        "a": ("eng_a.py", "launch"),
        "b": ("eng_b.py", "launch"),
        "c": ("eng_c.py", "launch"),
    }
    return engine_contract.extract_contract(model, engines=engines)


def test_engine_drift_fires_on_everyone_but_you():
    """A component two peers implement and one engine lacks is drift; the
    components nobody implements are just not part of the contract."""
    matrix = _mini_fleet(_NO_SNAPSHOT_ENGINE)
    findings = engine_contract.drift_findings(matrix)
    assert _rules(findings) == ["TMO-ENGINE-DRIFT"]
    (f,) = findings
    assert f.symbol == "c.snapshot_before_donate"
    assert f.path == "eng_c.py"
    assert "implemented by a, b" in f.message
    # the worksheet payload carries the same divergence
    payload = engine_contract.worksheet(matrix, findings)
    assert [d["symbol"] for d in payload["divergences"]] == ["c.snapshot_before_donate"]
    assert payload["engines"]["a"]["components"]["executable_cache"] == "launch"
    assert payload["engines"]["a"]["key_fields"] == ["tag", "state.shape"]


def test_engine_drift_uniform_fleet_clean():
    matrix = _mini_fleet(_FULL_ENGINE)
    assert engine_contract.drift_findings(matrix) == []


# --------------------------------------------- repo-wide guard + worksheet


@pytest.fixture(scope="module")
def repo_report():
    return run_own(
        str(REPO_ROOT / "metrics_tpu"),
        baseline_path=str(REPO_ROOT / BASELINE_FILENAME),
    )


def test_tmown_no_new_findings(repo_report):
    """The whole package must be ownership-clean against the checked-in
    baseline, with every waiver carrying a reason and none stale."""
    assert repo_report.parse_errors == {}
    msgs = "\n".join(f.format() for f in repo_report.new_findings)
    assert not repo_report.new_findings, f"new tmown findings:\n{msgs}"
    assert not repo_report.unused_waivers, (
        f"stale baseline waivers: {repo_report.unused_waivers}"
    )
    for f in repo_report.waived:
        assert f.waive_reason, f"waiver without a reason covers {f.key()}"
    # the ISSUE's cold-wall budget is 60s on CPU; the AST sweep is ~20x under
    assert repo_report.stats["seconds"] < 60


def test_repo_engine_contract(repo_report):
    """The model must see all four launch engines, with the shared contract
    fully present on fused/fleet/ingest (their divergence set is empty)."""
    assert set(repo_report.contract) == {"fused", "fleet", "ingest", "rank"}
    for engine in ("fused", "fleet", "ingest"):
        components = repo_report.contract[engine]["components"]
        missing = [c for c, ev in components.items() if not ev]
        assert not missing, f"{engine} lost contract components: {missing}"
        # every stateful engine keys its executable cache on something real
        assert repo_report.contract[engine]["key_fields"]


def test_drift_worksheet_in_sync(repo_report):
    """`tmown_engine_drift.json` is the checked-in ROADMAP-item-5 worksheet;
    it must match a fresh extraction (regenerate with --own --write-drift)."""
    checked_in = engine_contract.load_worksheet(
        str(REPO_ROOT / engine_contract.DRIFT_FILENAME)
    )
    assert checked_in == repo_report.drift_worksheet()
    # every rank divergence the worksheet records is triaged in the baseline
    recorded = {d["symbol"] for d in checked_in["divergences"]}
    waived = {f.symbol for f in repo_report.waived if f.rule == "TMO-ENGINE-DRIFT"}
    assert recorded == waived


def test_own_scope_excludes_shard_waivers(repo_report):
    """The tmown staleness check must never see the TMH-* (tmshard) waivers
    that share the baseline file: the repo baseline holds both, and every
    waiver tmown applied is strictly TMO-*."""
    from metrics_tpu.analysis.baseline import load_baseline, scope_waivers
    from metrics_tpu.analysis.findings import OWN_RULES, SHARD_RULES

    waivers = load_baseline(str(REPO_ROOT / BASELINE_FILENAME))
    own_scope = scope_waivers(waivers, OWN_RULES)
    shard_scope = scope_waivers(waivers, SHARD_RULES)
    assert own_scope and shard_scope
    assert not set(own_scope) & set(shard_scope)
    assert all(f.rule.startswith("TMO-") for f in repo_report.waived)


def test_own_obs_counters(tmp_path):
    """A seeded run increments the own.* counters when obs is enabled."""
    import metrics_tpu.obs as obs

    path = tmp_path / "mod.py"
    path.write_text(
        textwrap.dedent(
            """
            import numpy as np
            import jax

            def secure_pending_snapshots(buffers):
                return buffers

            def step(state):
                return state

            def launch(payload):
                state = np.frombuffer(payload, dtype="float32")
                jitted = jax.jit(step, donate_argnums=(0,))
                secure_pending_snapshots([state])
                return jitted(state)
            """
        )
    )
    with obs.observe() as reg:
        before = reg.get("own", "donate_alias")
        report = run_own(str(path), repo_root=str(tmp_path))
        assert _rules(report.new_findings) == ["TMO-DONATE-ALIAS"]
        assert reg.get("own", "donate_alias") == before + 1


# ------------------------------------------------------------ CLI end-to-end


_CLI_ENV = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO_ROOT)}


def _run_cli(pkg, tmp_path):
    return subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--own", str(pkg)],
        capture_output=True, text=True, timeout=120, env=_CLI_ENV, cwd=str(tmp_path),
    )


@pytest.mark.smoke
def test_cli_donate_alias_regression(tmp_path):
    """Acceptance regression: the seeded PR 16 aliasing shape must fail the
    build end-to-end (exit 1, rule named); the owned-copy twin passes."""
    pkg = tmp_path / "toypkg"
    pkg.mkdir()
    bad = textwrap.dedent(
        """
        import numpy as np
        import jax
        import jax.numpy as jnp

        def secure_pending_snapshots(buffers):
            return buffers

        def step(state):
            return state + 1

        def restore(payload):
            view = np.frombuffer(payload, dtype="float32")
            state = jnp.asarray(view)
            jitted = jax.jit(step, donate_argnums=(0,))
            secure_pending_snapshots([state])
            return jitted(state)
        """
    )
    (pkg / "mod.py").write_text(bad)
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TMO-DONATE-ALIAS" in result.stdout

    (pkg / "mod.py").write_text(
        bad.replace("jnp.asarray(view)", "jnp.array(view, copy=True)")
    )
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 0, result.stdout + result.stderr
