"""tmsan (metrics_tpu/analysis/san/): per-rule fixtures and the repo-wide gate.

Every TMS rule has a seeded-violation fixture asserting the exact rule ID,
driven through the same machinery the analyzer uses (abstract trace ->
collect_graph_facts -> findings). The repo-wide tier runs the full two-tier
analyzer once (shared module fixture) and asserts: no new findings against the
checked-in baseline, >100 registered metric classes traced, every TM-HOSTSYNC
waiver corroborated by jaxpr evidence, and a perturbed cost budget fails the
gate CI-style.
"""
import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu
from metrics_tpu.analysis.findings import SAN_RULES
from metrics_tpu.analysis.san import costs as costs_mod
from metrics_tpu.analysis.san.crosscheck import corroborate_waivers, lintgap_findings
from metrics_tpu.analysis.san.jaxpr_rules import (
    TraceAnchor,
    collect_graph_facts,
    findings_from_facts,
    upcast_findings,
)
from metrics_tpu.analysis.san.runner import _trace, run_san

pytestmark = [pytest.mark.lint, pytest.mark.san]

REPO_ROOT = pathlib.Path(metrics_tpu.__file__).resolve().parent.parent
_ANCHOR = TraceAnchor(path="metrics_tpu/fake.py", line=1, symbol="Fake.update")


def _sds(*shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _facts(fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    return collect_graph_facts(closed, str(REPO_ROOT))


def _rules(fn, *args, case="canon"):
    return sorted({f.rule for f in findings_from_facts(_facts(fn, *args), _ANCHOR, case)})


# ------------------------------------------------------------- per-rule seeds


def test_callback_rule_fires_on_pure_callback():
    def bad(x):
        return jax.pure_callback(lambda a: np.asarray(a) * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    assert _rules(bad, _sds(8)) == ["TMS-CALLBACK"]


def test_callback_rule_fires_on_debug_callback():
    def bad(x):
        jax.debug.callback(lambda a: None, x)
        return x * 2

    assert "TMS-CALLBACK" in _rules(bad, _sds(8))


def test_callback_rule_silent_on_pure_graph():
    assert _rules(lambda x: jnp.sum(x * 2), _sds(8)) == []


def test_f64_rule_fires_under_x64():
    from jax.experimental import enable_x64

    def bad(x):
        return x.astype(jnp.float64).sum()

    with enable_x64():
        rules = _rules(bad, _sds(8))
    assert "TMS-F64" in rules


@pytest.mark.filterwarnings("ignore:Explicitly requested dtype")
def test_f64_rule_silent_on_default_config():
    # with x64 disabled a f64 request is truncated at the boundary: the traced
    # graph itself is f32-pure and must not be flagged
    assert _rules(lambda x: x.astype("float64").sum(), _sds(8)) == []


def test_upcast_rule_compares_state_dtypes():
    ins = {"total": _sds(dtype=jnp.bfloat16), "count": _sds(dtype=jnp.int32)}
    outs = {"total": _sds(dtype=jnp.float32), "count": _sds(dtype=jnp.int32)}
    found = upcast_findings(ins, outs, _ANCHOR, "canon:bf16")
    assert [f.rule for f in found] == ["TMS-UPCAST"]
    assert "total" in found[0].message
    # dtype-preserving update: no finding
    assert upcast_findings(ins, dict(ins), _ANCHOR, "canon:bf16") == []


def test_bigconst_rule_fires_on_baked_table():
    table = jnp.asarray(np.arange(64 * 1024, dtype=np.float32))  # 256 KiB

    def bad(x):
        return x.sum() + table[:8].sum()

    assert "TMS-BIGCONST" in _rules(bad, _sds(8))


def test_bigconst_rule_silent_on_small_consts():
    small = jnp.asarray(np.arange(16, dtype=np.float32))
    assert _rules(lambda x: x.sum() + small.sum(), _sds(8)) == []


def test_collective_rule_fires_on_named_axis_psum():
    def bad(x):
        return jax.vmap(lambda v: jax.lax.psum(v, "b"), axis_name="b")(x)

    assert "TMS-COLLECTIVE" in _rules(bad, _sds(8))


def test_dynshape_classified_trace_failure():
    def bad(x):
        if (x > 0).any():  # TracerBoolConversionError under tracing
            return x.sum()
        return -x.sum()

    outcome = _trace(bad, (_sds(8),), str(REPO_ROOT))
    assert outcome.error is not None and outcome.facts is None
    assert type(outcome.error).__name__ == "TracerBoolConversionError"


def test_unclassified_trace_failure_is_a_skip_not_a_finding():
    def weird(x):
        raise RuntimeError("unrelated breakage")

    outcome = _trace(weird, (_sds(8),), str(REPO_ROOT))
    assert outcome.error is None and outcome.skip.startswith("trace failed: RuntimeError")


# -------------------------------------------------------------- crosscheck


def test_lintgap_fires_without_covering_hostsync_finding():
    callbacks = [("pure_callback", "metrics_tpu/some/mod.py", 42, "helper")]
    found = lintgap_findings(callbacks, lint_findings=[])
    assert [f.rule for f in found] == ["TMS-LINTGAP"]


def test_lintgap_silent_when_hostsync_covers_it():
    from metrics_tpu.analysis.findings import Finding

    covering = Finding(
        rule="TM-HOSTSYNC", path="metrics_tpu/some/mod.py", line=41, col=0,
        symbol="helper", message="", waived=True,
    )
    callbacks = [("pure_callback", "metrics_tpu/some/mod.py", 42, "helper")]
    assert lintgap_findings(callbacks, [covering]) == []


def test_stale_waiver_vs_corroborated():
    from metrics_tpu.analysis.findings import Finding

    key = ("TM-HOSTSYNC", "metrics_tpu/some/mod.py", "helper")
    waivers = {key: "claims host-only"}
    finding = Finding(rule="TM-HOSTSYNC", path=key[1], line=10, col=0, symbol="helper", message="")
    # waived line absent from every traced graph -> corroborated
    stale, status = corroborate_waivers(waivers, [finding], footprint=set(), callbacks=[])
    assert stale == [] and "corroborated-by-absence" in status[":".join(key)]
    # waived line participates in a traced graph -> stale
    stale, status = corroborate_waivers(waivers, [finding], footprint={(key[1], 10)}, callbacks=[])
    assert [f.rule for f in stale] == ["TMS-STALE-WAIVER"]
    assert "STALE" in status[":".join(key)]


# ------------------------------------------------------------- cost budget


def test_budget_breach_and_missing_entry():
    current = {"M.update[canon]": {"flops": 200.0, "bytes_accessed": 100.0, "peak_bytes": 10.0}}
    budget = {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "entries": {"M.update[canon]": {"flops": 100.0, "bytes_accessed": 100.0, "peak_bytes": 10.0}},
    }
    findings, _ = costs_mod.compare_costs(current, budget, anchors={})
    assert [f.rule for f in findings] == ["TMS-BUDGET"] and "flops" in findings[0].message

    findings, _ = costs_mod.compare_costs({"New.update[canon]": current["M.update[canon]"]}, budget, anchors={})
    assert [f.rule for f in findings] == ["TMS-BUDGET"] and "no budget recorded" in findings[0].message


def test_budget_within_tolerance_is_clean():
    current = {"M.update[canon]": {"flops": 110.0, "bytes_accessed": 100.0, "peak_bytes": 10.0}}
    budget = {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "entries": {"M.update[canon]": {"flops": 100.0, "bytes_accessed": 100.0, "peak_bytes": 10.0}},
    }
    findings, notes = costs_mod.compare_costs(current, budget, anchors={})
    assert findings == []


def test_budget_version_skew_degrades_to_warning():
    current = {"M.update[canon]": {"flops": 200.0, "bytes_accessed": 100.0, "peak_bytes": 10.0}}
    budget = {"jax": "0.0.1", "backend": "tpu", "entries": {"M.update[canon]": {"flops": 100.0}}}
    findings, notes = costs_mod.compare_costs(current, budget, anchors={})
    assert findings == [] and any("version-skew" in n for n in notes)


# --------------------------------------------------------- repo-wide gate


@pytest.fixture(scope="module")
def repo_report():
    """One full two-tier run with the cost tier, obs enabled (san.* counters)."""
    from metrics_tpu import obs

    obs.enable(clear=True)
    try:
        report = run_san(str(REPO_ROOT / "metrics_tpu"))
    finally:
        snap = obs.snapshot()
        obs.disable()
    return report, snap


def test_repo_wide_no_new_findings(repo_report):
    report, _ = repo_report
    msgs = "\n".join(f.format() for f in report.new_findings + (report.lint.new_findings if report.lint else []))
    assert not report.new_findings and not (report.lint and report.lint.new_findings), f"new findings:\n{msgs}"
    # stale waivers rot silently, in either tier's scope
    unused = set(report.unused_waivers) | set(report.lint.unused_waivers if report.lint else [])
    assert not unused, f"stale baseline waivers: {sorted(unused)}"


def test_registry_coverage_over_100_traced_classes(repo_report):
    report, _ = repo_report
    metric_classes = [k for k in report.traced if not k.startswith("ops.")]
    assert len(metric_classes) > 100, f"only {len(metric_classes)} metric classes traced"
    assert any(k.startswith("ops.") for k in report.traced), "ops/ entrypoints missing from the sweep"
    # every skip must carry an explicit reason
    assert all(reason for reason in report.skipped.values())


def test_all_hostsync_waivers_corroborated(repo_report):
    """Acceptance: every TM-HOSTSYNC waiver is corroborated by jaxpr evidence."""
    report, _ = repo_report
    assert report.waiver_status, "no TM-HOSTSYNC waivers were checked"
    bad = {k: v for k, v in report.waiver_status.items() if "corroborated" not in v}
    assert not bad, f"uncorroborated TM-HOSTSYNC waivers: {bad}"


def test_obs_san_namespace_counters(repo_report):
    _, snap = repo_report
    san = snap.get("san", {})
    assert san.get("traced", 0) > 100, f"san.* counters missing: {sorted(snap)}"
    assert san.get("findings", 0) >= 1  # the waived TMS-UPCAST triage is counted


def test_budget_regression_fails_ci_style(repo_report, tmp_path):
    """Perturb tmsan_costs.json (halve one recorded flops budget) and assert
    the gate produces an unwaived TMS-BUDGET finding — the CI failure mode."""
    report, _ = repo_report
    assert report.costs, "cost tier produced no entries"
    payload = costs_mod.load_costs(str(REPO_ROOT / costs_mod.COSTS_FILENAME))
    entry = next(k for k in sorted(payload["entries"]) if payload["entries"][k]["flops"] > 0)
    payload["entries"][entry]["flops"] /= 2.0
    perturbed = tmp_path / "tmsan_costs.json"
    perturbed.write_text(json.dumps(payload))

    findings, _ = costs_mod.compare_costs(report.costs, json.loads(perturbed.read_text()), anchors={})
    breached = [f for f in findings if f.rule == "TMS-BUDGET" and f.symbol == entry]
    assert breached, f"halving {entry}'s flops budget did not breach the gate"
    # CI-style: the breach must not be absorbed by the checked-in baseline
    from metrics_tpu.analysis import baseline as baseline_mod
    from metrics_tpu.analysis.findings import SAN_RULES as _SAN

    waivers = baseline_mod.scope_waivers(
        baseline_mod.load_baseline(str(REPO_ROOT / baseline_mod.BASELINE_FILENAME)), _SAN
    )
    new, _ = baseline_mod.apply_baseline(list(breached), waivers)
    assert new, "TMS-BUDGET breach was unexpectedly waived by the baseline"


def test_seeded_callback_fails_end_to_end(monkeypatch):
    """Acceptance: a pure_callback smuggled into a registered metric's update
    turns into TMS-CALLBACK (+ TMS-LINTGAP via crosscheck) and exit code 1."""
    import metrics_tpu.regression.mse as mse_mod

    orig = mse_mod._mean_squared_error_update

    def smuggled(preds, target):
        s, n = orig(preds, target)
        s = jax.pure_callback(lambda v: np.asarray(v), jax.ShapeDtypeStruct(jnp.shape(s), jnp.result_type(s)), s)
        return s, n

    monkeypatch.setattr(mse_mod, "_mean_squared_error_update", smuggled)
    report = run_san(str(REPO_ROOT / "metrics_tpu"), with_costs=False, with_lint=False)
    rules = {f.rule for f in report.new_findings}
    assert "TMS-CALLBACK" in rules, sorted(rules)
    assert any(f.symbol == "MeanSquaredError.update" for f in report.new_findings if f.rule == "TMS-CALLBACK")
    assert report.exit_code == 1


def test_san_rules_explainable():
    from metrics_tpu.analysis import explain

    for rule in SAN_RULES:
        text = explain(rule)
        assert rule in text and "Waiving" in text
