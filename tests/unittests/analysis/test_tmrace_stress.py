"""tmrace dynamic corroboration tier (marker ``race``).

Each stress test here is cross-referenced to the static TMR rule whose
verdict it corroborates at runtime: the analyzer claims a lock governs some
shared state (or that a by-design waiver is safe), and the test hammers that
state from the real thread roles with **exact-total assertions** — a lost
update, double-apply, or deadlock fails deterministically, not probabilistically.

Rule map (mirrored in docs/source/pages/static_analysis.rst):

- ``TMR-UNLOCKED``  -> concurrent ingest enqueue/flush/close (IngestQueue
  stats + Ring drain governance); sampler tick vs registry mutation
  (ObsRegistry._lock / TelemetrySampler._lock governance).
- ``TMR-ORDER``     -> async ckpt saves racing fused donation (the
  _PENDING/_INFLIGHT/_tick_lock orders the analyzer proved acyclic).
- ``TMR-HOLD-HOST`` -> the same ckpt race exercises the waived
  snapshot-before-donate device->host copy under ``_PendingSnapshot.lock``.
- ``TMR-HANDLER``   -> prom scrape storm: the ``prom-handler`` role (declared
  via ``@thread_role``) reads registry/series state while producers mutate it.
"""
import threading
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.obs as obs
from metrics_tpu.ckpt import manager
from metrics_tpu.obs import series as obs_series
from metrics_tpu.regression import MeanSquaredError
from metrics_tpu.serve import IngestQueue

pytestmark = pytest.mark.race


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    yield
    obs.prom.stop_server()
    obs.series.disable()
    obs.disable()


def _mse_batches(n, rows=4, seed=3):
    rng = np.random.RandomState(seed)
    return [
        (
            jnp.asarray(rng.rand(rows).astype(np.float32)),
            jnp.asarray(rng.rand(rows).astype(np.float32)),
        )
        for _ in range(n)
    ]


# --------------------------------------------------------------- TMR-UNLOCKED


def test_concurrent_enqueue_flush_close_exact_totals():
    """Corroborates TMR-UNLOCKED governance: ``IngestQueue.stats`` is written
    by the user role (enqueue, under ``_admit``) and the tick role (under
    ``_tick_lock`` via the ``@locked_by`` contract on ``_run_ticks``), and the
    staging ``Ring`` drains under its own lock. If any of those locks were
    decorative, 4 producers x 25 batches with concurrent flushes would lose
    or double-apply a batch — the totals are asserted exactly."""
    producers, per_producer = 4, 25
    total = producers * per_producer
    batches = _mse_batches(per_producer)
    target = MeanSquaredError()
    q = IngestQueue(target, capacity=total, start=False)
    errors = []
    go = threading.Event()

    def produce():
        try:
            go.wait(5)
            for p, t in batches:
                q.enqueue(p, t)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=produce) for _ in range(producers)]
    for t in threads:
        t.start()
    go.set()
    for _ in range(5):
        q.flush()  # user-role flush racing the producers
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads) and not errors
    q.close(drain=True)

    assert q.stats["enqueued"] == total
    assert q.stats["dropped"] == 0
    assert target._update_count == total  # every batch applied exactly once


def test_sampler_tick_racing_registry_mutation_exact_totals():
    """Corroborates TMR-UNLOCKED governance of ``ObsRegistry._lock`` (counter
    read-modify-writes) and ``TelemetrySampler._lock`` (tick bookkeeping):
    two mutator threads hammer one counter while the user role ticks the
    sampler; the final cumulative value and tick count are exact."""
    obs.enable()
    obs.series.enable(start_thread=False)
    sampler = obs.series.sampler()
    per_thread, mutators = 500, 2
    errors = []

    def mutate():
        try:
            for _ in range(per_thread):
                obs.REGISTRY.inc("fleet", "routed_launches")
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=mutate) for _ in range(mutators)]
    for t in threads:
        t.start()
    ticks = 0
    while any(t.is_alive() for t in threads):
        sampler.tick()
        ticks += 1
    for t in threads:
        t.join(timeout=10)
    sampler.tick()
    ticks += 1
    assert not errors
    assert obs.REGISTRY.get("fleet", "routed_launches") == per_thread * mutators
    assert sampler.ticks_taken == ticks


# ------------------------------------------------- TMR-ORDER + TMR-HOLD-HOST


def test_async_saves_racing_fused_donation_unique_steps(tmp_path):
    """Corroborates TMR-ORDER acyclicity of the ckpt lock order
    (``_INFLIGHT_LOCK``/``_PENDING_LOCK``/per-snapshot locks) and the waived
    TMR-HOLD-HOST device->host copy under ``_PendingSnapshot.lock``
    (snapshot-before-donate): concurrent ``blocking=False`` saves race a
    donation-backed fused update stream. Every save must commit, every step
    must be unique (the ``_LAST_ASSIGNED`` floor read outside the lock), and
    nothing may deadlock."""
    from metrics_tpu.core.fused import canonical_collection

    rng = np.random.RandomState(0)
    p = rng.rand(32).astype(np.float32)
    t = rng.randint(0, 2, 32).astype(np.int32)
    coll = canonical_collection(fused=True)
    coll.update(p, t)
    coll.update(p, t)  # warmed: further updates donate via the cached executable

    n_saves = 4
    handles, errors = [], []
    lock = threading.Lock()

    def save():
        try:
            h = coll.save_checkpoint(str(tmp_path), blocking=False)
            with lock:
                handles.append(h)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    savers = [threading.Thread(target=save) for _ in range(n_saves)]
    for s in savers:
        s.start()
    coll.update(p, t)  # donation racing the pending snapshots
    coll.update(p, t)
    for s in savers:
        s.join(timeout=60)
    assert not any(s.is_alive() for s in savers) and not errors

    for h in handles:
        h.result()  # never wedges: the lock graph is acyclic
        assert h.committed
    steps = sorted(h.step for h in handles)
    assert steps == list(range(n_saves)), f"step assignment raced: {steps}"
    assert manager.latest_step(str(tmp_path)) == n_saves - 1

    fresh = canonical_collection(fused=False)
    fresh.restore_checkpoint(str(tmp_path))
    for v in fresh.compute().values():
        assert np.all(np.isfinite(np.asarray(v)))


# ---------------------------------------------------------------- TMR-HANDLER


def test_prom_scrape_storm_during_enqueue_exact_totals():
    """Corroborates the ``prom-handler`` thread-role declaration
    (``@thread_role`` on ``_MetricsHandler.do_GET``): real HTTP scrape threads
    read registry/series state while a producer storm mutates it through the
    ingest tier. Every scrape must answer 200 with a parseable exposition and
    the queue totals stay exact — the handler role only ever reads."""
    obs.enable()
    obs.series.enable(start_thread=False)
    obs.series.sampler().tick()
    host, port = obs.prom.start_server(port=0)
    batches = _mse_batches(30)
    target = MeanSquaredError()
    q = IngestQueue(target, capacity=64, tick_interval_s=0.001)
    errors = []

    def produce():
        try:
            for p, t in batches:
                q.enqueue(p, t)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        prod = threading.Thread(target=produce)
        prod.start()
        pages = []
        for _ in range(10):
            with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                pages.append(r.read().decode("utf-8"))
        prod.join(timeout=30)
        assert not prod.is_alive() and not errors
        for page in pages:
            assert obs.prom.validate_exposition(page) > 0
        q.flush()
        assert q.stats["enqueued"] == len(batches)
        assert target._update_count == len(batches)
    finally:
        q.close()
        obs.prom.stop_server()


# --------------------------------------------------------- tm-serve lifecycle


def test_server_drain_racing_producers_exact_totals():
    """Corroborates the ``tm-serve/ticker`` role model and the server's
    counter partitioning: N producer threads (all role ``user``, counters
    under ``MetricsServer._req_lock``) race the shared DRR ticker AND a
    mid-stream ``drain()``. Admission is atomic — every enqueue either
    returns (and its batch is applied exactly once by the drain) or raises a
    typed rejection — so the drained ``update_count`` and the ``requests``
    counter both equal the number of successful enqueues exactly."""
    from metrics_tpu.serve import MetricsServer, ServerConfig, ServerStateError

    producers, per_producer = 4, 40
    batches = _mse_batches(per_producer)
    cfg = ServerConfig(
        [{"name": "q", "metrics": {"mse": "MeanSquaredError"}}],
        tick_interval_s=0.001,
        adaptive=False,
    )
    server = MetricsServer(cfg)  # real tm-serve/ticker thread
    admitted = [0] * producers
    errors = []
    go = threading.Event()

    def produce(k):
        try:
            go.wait(5)
            for p, t in batches:
                try:
                    server.enqueue("q", p, t)
                except ServerStateError:
                    return  # the drain won the race: typed rejection, no row
                except RuntimeError:
                    return  # admission lost to queue close mid-drain
                admitted[k] += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=produce, args=(k,)) for k in range(producers)]
    try:
        for t in threads:
            t.start()
        go.set()
        import time as _time

        _time.sleep(0.05)  # let the ticker interleave real applies first
        report = server.drain()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads) and not errors
        total = sum(admitted)
        assert total > 0
        # exactly-once apply: nothing admitted is lost, nothing double-applied
        assert report["q"]["update_count"] == total
        assert server.stats["requests"] == total
        assert int(server._collections["q"].queue.stats["dropped"]) == 0
    finally:
        server.stop()
