"""tmrace unit tier: per-rule seeded fixtures (each with a clean twin), the
thread-role model, annotation semantics, five-tier waiver scoping, the
repo-wide no-new-findings guard, and end-to-end CLI exit-code regressions.

The threaded *stress* corroboration of these rules lives in
``test_tmrace_stress.py`` (marker ``race``); this file is pure static
analysis and rides the ``lint`` CI step alongside it.
"""
import pathlib
import subprocess
import sys
import textwrap

import pytest

import metrics_tpu
from metrics_tpu.analysis import BASELINE_FILENAME
from metrics_tpu.analysis.race import build_model, run_race

pytestmark = [pytest.mark.lint, pytest.mark.race]

REPO_ROOT = pathlib.Path(metrics_tpu.__file__).resolve().parent.parent


def _race_snippet(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    report = run_race(str(path), repo_root=str(tmp_path))
    assert report.parse_errors == {}
    return report.new_findings


def _rules(findings):
    return sorted({f.rule for f in findings})


# -------------------------------------------------------------- TMR-UNLOCKED


def test_unlocked_bad(tmp_path):
    """A counter written by the spawned role under the lock and by the user
    role without it: no common governing lock -> TMR-UNLOCKED."""
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _LOCK = threading.Lock()
        _COUNT = {"n": 0}

        def _worker():
            with _LOCK:
                _COUNT["n"] += 1

        def start():
            threading.Thread(target=_worker, name="bg-worker", daemon=True).start()

        def bump():
            _COUNT["n"] += 1
        """,
    )
    assert _rules(findings) == ["TMR-UNLOCKED"]
    (f,) = findings
    assert f.symbol == 'mod._COUNT[n]'
    assert "bg-worker" in f.message and "user" in f.message


def test_unlocked_clean_twin(tmp_path):
    """Same shape, but every write holds the lock -> clean."""
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _LOCK = threading.Lock()
        _COUNT = {"n": 0}

        def _worker():
            with _LOCK:
                _COUNT["n"] += 1

        def start():
            threading.Thread(target=_worker, name="bg-worker", daemon=True).start()

        def bump():
            with _LOCK:
                _COUNT["n"] += 1
        """,
    )
    assert findings == []


def test_unlocked_single_role_not_flagged(tmp_path):
    """No second thread role -> no interleaving -> no finding."""
    findings = _race_snippet(
        tmp_path,
        """
        _COUNT = {"n": 0}

        def bump():
            _COUNT["n"] += 1
        """,
    )
    assert findings == []


def test_unlocked_atomic_idioms_not_flagged(tmp_path):
    """The documented GIL-atomic idioms: plain store, deque.append with
    maxlen, set.add — lock-free by design, never findings."""
    findings = _race_snippet(
        tmp_path,
        """
        import threading
        from collections import deque

        _RING = deque(maxlen=8)
        _SEEN = set()
        _LAST = None

        def _worker():
            global _LAST
            _RING.append(1)
            _SEEN.add("k")
            _LAST = 2

        def start():
            threading.Thread(target=_worker, name="bg", daemon=True).start()

        def record(x):
            global _LAST
            _RING.append(x)
            _SEEN.add(x)
            _LAST = x
        """,
    )
    assert findings == []


def test_unlocked_subscript_refinement(tmp_path):
    """Disjoint const-key counters governed by different locks must not alias
    into one racy target (the IngestQueue.stats pattern)."""
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()
        _STATS = {"in": 0, "out": 0}

        def _worker():
            with _B:
                _STATS["out"] += 1

        def start():
            threading.Thread(target=_worker, name="bg", daemon=True).start()

        def admit():
            with _A:
                _STATS["in"] += 1
        """,
    )
    assert findings == []


# ----------------------------------------------------------------- TMR-ORDER


def test_order_cycle_bad(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def fwd():
            with _A:
                with _B:
                    pass

        def rev():
            with _B:
                with _A:
                    pass
        """,
    )
    assert _rules(findings) == ["TMR-ORDER"]
    (f,) = findings
    assert f.symbol == "mod._A->mod._B->mod._A"


def test_order_consistent_clean_twin(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def fwd():
            with _A:
                with _B:
                    pass

        def also_fwd():
            with _A:
                with _B:
                    pass
        """,
    )
    assert findings == []


def test_order_interprocedural_cycle(tmp_path):
    """The cycle only exists across call edges: each function takes one lock
    directly and reaches the other through a callee."""
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def take_a():
            with _A:
                pass

        def take_b():
            with _B:
                pass

        def fwd():
            with _A:
                take_b()

        def rev():
            with _B:
                take_a()
        """,
    )
    assert "TMR-ORDER" in _rules(findings)


def test_order_rlock_reentry_exempt(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        _R = threading.RLock()

        def outer():
            with _R:
                inner()

        def inner():
            with _R:
                pass
        """,
    )
    assert findings == []


# ------------------------------------------------------------- TMR-HOLD-HOST


def test_hold_host_bad(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import os
        import threading

        _LOCK = threading.Lock()

        def scan(d):
            with _LOCK:
                names = os.listdir(d)
            return names
        """,
    )
    assert _rules(findings) == ["TMR-HOLD-HOST"]
    (f,) = findings
    assert f.symbol == "scan" and f.line == 9


def test_hold_host_clean_twin(tmp_path):
    """Disk read before the lock, only the assignment inside -> clean."""
    findings = _race_snippet(
        tmp_path,
        """
        import os
        import threading

        _LOCK = threading.Lock()
        _CACHE = {}

        def scan(d):
            names = os.listdir(d)
            with _LOCK:
                _CACHE["names"] = names
            return names
        """,
    )
    assert findings == []


def test_hold_host_through_call(tmp_path):
    """Blocking IO reached through a private helper whose every caller holds
    the lock (held-at-entry inference, no annotation needed)."""
    findings = _race_snippet(
        tmp_path,
        """
        import os
        import threading

        _LOCK = threading.Lock()

        def _read(d):
            return os.listdir(d)

        def scan(d):
            with _LOCK:
                return _read(d)
        """,
    )
    assert "TMR-HOLD-HOST" in _rules(findings)


# --------------------------------------------------------------- TMR-HANDLER


def test_handler_blocking_lock_bad(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import atexit
        import threading

        _LOCK = threading.Lock()
        _STATE = {"dumps": 0}

        def _on_exit():
            with _LOCK:
                _STATE["dumps"] += 1

        atexit.register(_on_exit)
        """,
    )
    assert _rules(findings) == ["TMR-HANDLER"]
    assert all(f.symbol == "_on_exit" for f in findings)
    # both hazards: the blocking acquire AND the non-atomic mutation
    assert any("blocking acquire" in f.message for f in findings)
    assert any("non-atomic mutation" in f.message for f in findings)


def test_handler_trylock_clean_twin(tmp_path):
    """acquire(blocking=False) + lock-free fallback: the sanctioned pattern
    (the obs/flight.py dump path)."""
    findings = _race_snippet(
        tmp_path,
        """
        import atexit
        import threading

        _LOCK = threading.Lock()
        _SOURCES = []

        def _on_exit():
            if _LOCK.acquire(blocking=False):
                try:
                    objs = [r for r in _SOURCES]
                finally:
                    _LOCK.release()
            else:
                objs = list(_SOURCES)
            return objs

        atexit.register(_on_exit)
        """,
    )
    assert findings == []


def test_handler_reachable_through_signal_install(tmp_path):
    """The hazard sits one call away from the installed signal handler."""
    findings = _race_snippet(
        tmp_path,
        """
        import signal
        import threading

        _LOCK = threading.Lock()

        def _flush():
            with _LOCK:
                pass

        def _on_signal(signum, frame):
            _flush()

        def install():
            signal.signal(signal.SIGTERM, _on_signal)
        """,
    )
    assert _rules(findings) == ["TMR-HANDLER"]
    assert findings[0].symbol == "_flush"


# ------------------------------------------------------------------ TMR-LEAK


def test_leak_bad(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        def start(fn):
            threading.Thread(target=fn).start()
        """,
    )
    assert _rules(findings) == ["TMR-LEAK"]


def test_leak_daemon_clean_twin(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        def start(fn):
            threading.Thread(target=fn, daemon=True).start()
        """,
    )
    assert findings == []


def test_leak_joined_clean_twin(tmp_path):
    findings = _race_snippet(
        tmp_path,
        """
        import threading

        def run(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        """,
    )
    assert findings == []


# ------------------------------------------------- annotations & role model


def test_locked_by_annotation_governs(tmp_path):
    """@locked_by supplies the caller-holds contract a public entry point
    cannot get from inference; without it the same code is a finding."""
    src = """
        import threading
        from metrics_tpu.utils.concurrency import locked_by, thread_role

        _LOCK = threading.Lock()
        _STATS = {"n": 0}

        @thread_role("bg")
        def loop():
            with _LOCK:
                bump()

        __DECORATOR__
        def bump():
            _STATS["n"] += 1
        """
    annotated = _race_snippet(tmp_path, src.replace("__DECORATOR__", '@locked_by("mod._LOCK")'))
    assert annotated == []
    bare = _race_snippet(tmp_path, src.replace("__DECORATOR__", "@thread_role()"))
    assert _rules(bare) == ["TMR-UNLOCKED"]


def test_thread_role_annotation_creates_role(tmp_path):
    """A @thread_role entry point (the prom-handler pattern: spawned by
    machinery the analyzer cannot see) supplies the second racing role."""
    findings = _race_snippet(
        tmp_path,
        """
        from metrics_tpu.utils.concurrency import thread_role

        _TOTALS = {"hits": 0}

        @thread_role("handler")
        def on_request():
            _TOTALS["hits"] += 1

        def reset():
            _TOTALS["hits"] = len([])
        """,
    )
    assert "TMR-UNLOCKED" in _rules(findings)


def test_annotation_decorators_are_runtime_noops():
    from metrics_tpu.utils.concurrency import locked_by, thread_role

    @thread_role("a", "b")
    @locked_by("X._lock")
    def fn():
        return 41 + 1

    assert fn() == 42
    assert fn.__thread_roles__ == ("a", "b")
    assert fn.__locked_by__ == ("X._lock",)


def test_repo_thread_role_model():
    """The linked model must discover the runtime's actual thread roles."""
    from metrics_tpu.analysis.jitmap import load_package

    files = load_package(str(REPO_ROOT / "metrics_tpu"), str(REPO_ROOT))
    model = build_model(files)
    roles = set()
    for _m, func in model.all_functions():
        roles |= func.roles
    assert {
        "user", "tm-ingest", "tm-serve/ticker", "metrics-tpu-ckpt", "tmscope-sampler",
        "prom-handler", "signal", "atexit", "excepthook",
    } <= roles
    # the locks the serving runtime is built on must all be in the model
    for lock_id in (
        "IngestQueue._tick_lock", "Ring._lock", "manager._INFLIGHT_LOCK",
        "manager._PENDING_LOCK", "flight._LOCK", "excache._LOCK",
        "TelemetrySampler._lock", "MetricsServer._lock", "MetricsServer._req_lock",
        "AdaptiveTickController._lock",
    ):
        assert lock_id in model.locks, f"missing lock {lock_id}"


# ----------------------------------------------- five-tier waiver scoping


def test_waiver_scoping_partitions_staleness():
    """Satellite contract: each tier ignores the other tiers' waivers when
    checking staleness — a TMR waiver is never 'stale' to
    tmlint/tmsan/tmown/tmshard."""
    from metrics_tpu.analysis import baseline as baseline_mod
    from metrics_tpu.analysis.findings import (
        LINT_RULES, OWN_RULES, RACE_RULES, SAN_RULES, SHARD_RULES,
    )

    waivers = {
        ("TM-HOSTSYNC", "a.py", "f"): "lint reason",
        ("TMS-F64", "b.py", "g"): "san reason",
        ("TMR-ORDER", "c.py", "x->y->x"): "race reason",
        ("TMO-DONATE-ALIAS", "d.py", "restore"): "own reason",
        ("TMH-MESH-DRIFT", "e.py", "rank.sharded_key_facet"): "shard reason",
    }
    race_scope = baseline_mod.scope_waivers(waivers, RACE_RULES)
    assert set(race_scope) == {("TMR-ORDER", "c.py", "x->y->x")}
    # a race run with zero findings: only the race-scoped waiver can be stale
    _new, unused = baseline_mod.apply_baseline([], race_scope)
    assert unused == [("TMR-ORDER", "c.py", "x->y->x")]
    assert set(baseline_mod.scope_waivers(waivers, LINT_RULES)) == {
        ("TM-HOSTSYNC", "a.py", "f")
    }
    assert set(baseline_mod.scope_waivers(waivers, SAN_RULES)) == {
        ("TMS-F64", "b.py", "g")
    }
    assert set(baseline_mod.scope_waivers(waivers, OWN_RULES)) == {
        ("TMO-DONATE-ALIAS", "d.py", "restore")
    }
    assert set(baseline_mod.scope_waivers(waivers, SHARD_RULES)) == {
        ("TMH-MESH-DRIFT", "e.py", "rank.sharded_key_facet")
    }


# ----------------------------------------------------------- repo-wide guard


def test_tmrace_no_new_findings():
    """The whole package must be race-clean against the checked-in baseline,
    with every waiver carrying a reason and none stale."""
    report = run_race(
        str(REPO_ROOT / "metrics_tpu"),
        baseline_path=str(REPO_ROOT / BASELINE_FILENAME),
    )
    assert report.parse_errors == {}
    msgs = "\n".join(f.format() for f in report.new_findings)
    assert not report.new_findings, f"new tmrace findings:\n{msgs}"
    assert not report.unused_waivers, f"stale baseline waivers: {report.unused_waivers}"
    for f in report.waived:
        assert f.waive_reason, f"waiver without a reason covers {f.key()}"
    # the ISSUE's cold-wall budget is 60s on CPU; the AST sweep is ~100x under
    assert report.stats["seconds"] < 60


# ------------------------------------------------------------- CLI end-to-end


_CLI_ENV = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO_ROOT)}


def _run_cli(pkg, tmp_path):
    return subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--race", str(pkg)],
        capture_output=True, text=True, timeout=120, env=_CLI_ENV, cwd=str(tmp_path),
    )


@pytest.mark.smoke
def test_cli_order_cycle_regression(tmp_path):
    """Acceptance regression: a seeded lock-order cycle must fail the build
    end-to-end (exit 1, rule named); the consistent twin passes."""
    pkg = tmp_path / "toypkg"
    pkg.mkdir()
    cyclic = textwrap.dedent(
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def fwd():
            with _A:
                with _B:
                    pass

        def rev():
            with _B:
                with _A:
                    pass
        """
    )
    (pkg / "mod.py").write_text(cyclic)
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TMR-ORDER" in result.stdout

    (pkg / "mod.py").write_text(cyclic.replace("with _B:\n        with _A:", "with _A:\n        with _B:"))
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.smoke
def test_cli_unlocked_mutation_regression(tmp_path):
    """Acceptance regression: a seeded unlocked cross-role mutation must fail
    the build end-to-end (exit 1, rule named)."""
    pkg = tmp_path / "toypkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        textwrap.dedent(
            """
            import threading

            _LOCK = threading.Lock()
            _COUNT = {"n": 0}

            def _worker():
                with _LOCK:
                    _COUNT["n"] += 1

            def start():
                threading.Thread(target=_worker, name="bg", daemon=True).start()

            def bump():
                _COUNT["n"] += 1
            """
        )
    )
    result = _run_cli(pkg, tmp_path)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TMR-UNLOCKED" in result.stdout
