"""Round-trip property sweep: EVERY public metric class must survive
``update -> save_checkpoint -> fresh-instance restore -> compute`` with a
result bit-identical to the uninterrupted run.

Reuses the contract sweep's exhaustive case registry
(``tests/unittests/bases/test_contract_sweep.py``) so a newly exported metric
class automatically joins this sweep too — the preemption-safety contract is
not opt-in. The default scenario checkpoints MID-stream (save after batch 1,
restore into a fresh instance, feed batch 2 there) — exactly what a preempted
pod does.

Exceptions, with reasons:
- ``BootStrapper``'s eager update draws fresh numpy subsamples per call; the
  checkpoint captures metric state, not the sampler's RNG stream, so the
  interrupted and uninterrupted runs see different samples mid-stream. It is
  checkpointed after its final update instead (state capture is still exact).
- ``KernelInceptionDistance.compute`` subsamples with a fresh RNG per call
  (random by design, like the reference); its restored STATE is compared
  instead of the compute output.
"""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from unittests.bases.test_contract_sweep import _FULL, _case_for  # noqa: E402

pytestmark = pytest.mark.ckpt

# save point moves to after the final update (see module docstring)
_SAVE_AFTER_FINAL = {"BootStrapper"}
# compare restored state instead of (random-by-design) compute output
_STATE_COMPARE = {"KernelInceptionDistance"}


def _leaves(value):
    return [np.asarray(x) for x in jax.tree.leaves(value) if not isinstance(x, str)]


def _to_dev(args):
    return tuple(jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args)


def _state_leaves(metric):
    from metrics_tpu.ckpt.serializer import snapshot_state

    return [(k, np.asarray(v)) for k, v, _ in snapshot_state(metric)]


@pytest.mark.parametrize("name", _FULL, ids=_FULL)
def test_roundtrip_bit_identical(name, tmp_path):
    kwargs, gen, upd_kwargs = _case_for(name)
    cls = getattr(metrics_tpu, name)
    kw1, kw2 = (upd_kwargs if isinstance(upd_kwargs, tuple) else (upd_kwargs, upd_kwargs))
    args1, args2 = _to_dev(gen()), _to_dev(gen())

    # oracle: the uninterrupted run
    oracle = cls(**kwargs)
    oracle.update(*args1, **kw1)
    oracle.update(*args2, **kw2)

    interrupted = cls(**kwargs)
    fresh = cls(**kwargs)
    if name in _SAVE_AFTER_FINAL:
        interrupted.update(*args1, **kw1)
        interrupted.update(*args2, **kw2)
        interrupted.save_checkpoint(str(tmp_path))
        fresh.restore_checkpoint(str(tmp_path))
    else:
        # the preemption scenario: batch 1, save, die, restore, batch 2
        interrupted.update(*args1, **kw1)
        interrupted.save_checkpoint(str(tmp_path))
        fresh.restore_checkpoint(str(tmp_path))
        fresh.update(*args2, **kw2)

    assert fresh._update_count == oracle._update_count

    if name in _STATE_COMPARE:
        want, got = _state_leaves(oracle), _state_leaves(fresh)
        assert [k for k, _ in want] == [k for k, _ in got]
        for (key, a), (_, b) in zip(want, got):
            np.testing.assert_array_equal(a, b, err_msg=f"{name}: state `{key}` drifted")
        return

    want, got = _leaves(oracle.compute()), _leaves(fresh.compute())
    assert len(want) == len(got) and len(got) > 0, f"{name}: compute shape changed"
    for a, b in zip(want, got):
        # bit-identical, NaN included: restore is raw bytes and compute is
        # the same XLA program on the same values
        np.testing.assert_array_equal(a, b, err_msg=f"{name}: round-trip drifted")
