"""Fleet-axis checkpointing (core/fleet.py x metrics_tpu.ckpt): full-fleet
roundtrip, per-stream slicing (``restore_checkpoint(..., stream=i)``), host
topology N->M re-reduce along the fleet axis, and the fleet-dim drift error.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MaxMetric, MinMetric, ckpt
from metrics_tpu.ckpt import CheckpointError, ShapeDriftError
from metrics_tpu.classification import BinaryAccuracy, MulticlassAccuracy
from metrics_tpu.core.fleet import ROWS_STATE

pytestmark = [pytest.mark.ckpt, pytest.mark.fleet]

FLEET = 4


def _fed_fleet(seed=0, steps=3, rows=32):
    rng = np.random.default_rng(seed)
    m = MulticlassAccuracy(num_classes=3, average=None, fleet_size=FLEET)
    refs = [MulticlassAccuracy(num_classes=3, average=None) for _ in range(FLEET)]
    for _ in range(steps):
        preds = jnp.asarray(rng.integers(0, 3, rows))
        target = jnp.asarray(rng.integers(0, 3, rows))
        ids = jnp.asarray(rng.integers(0, FLEET, rows), dtype=jnp.int32)
        m.update(preds, target, stream_ids=ids)
        for s, ref in enumerate(refs):
            mask = np.asarray(ids) == s
            if mask.any():
                ref.update(preds[mask], target[mask])
    return m, refs


def test_fleet_roundtrip_bit_identical(tmp_path):
    m, _ = _fed_fleet()
    m.save_checkpoint(str(tmp_path), step=0)
    fresh = MulticlassAccuracy(num_classes=3, average=None, fleet_size=FLEET)
    assert fresh.restore_checkpoint(str(tmp_path)) == 0
    assert np.array_equal(np.asarray(fresh.compute()), np.asarray(m.compute()))
    assert np.array_equal(
        np.asarray(getattr(fresh, ROWS_STATE)), np.asarray(getattr(m, ROWS_STATE))
    )
    assert fresh._update_count == m._update_count


def test_stream_slice_restores_one_tenant(tmp_path):
    m, refs = _fed_fleet(seed=1)
    m.save_checkpoint(str(tmp_path), step=0)
    for s, ref in enumerate(refs):
        single = MulticlassAccuracy(num_classes=3, average=None)
        single.restore_checkpoint(str(tmp_path), stream=s)
        assert np.array_equal(np.asarray(single.tp), np.asarray(ref.tp))
        assert np.array_equal(np.asarray(single.compute()), np.asarray(ref.compute()))


def test_stream_slice_out_of_range(tmp_path):
    m, _ = _fed_fleet()
    m.save_checkpoint(str(tmp_path), step=0)
    with pytest.raises(CheckpointError, match="out of range"):
        MulticlassAccuracy(num_classes=3, average=None).restore_checkpoint(
            str(tmp_path), stream=FLEET
        )


def test_stream_slice_requires_fleet_checkpoint(tmp_path):
    plain = BinaryAccuracy()
    plain.update(jnp.ones(4, jnp.int32), jnp.ones(4, jnp.int32))
    plain.save_checkpoint(str(tmp_path), step=0)
    with pytest.raises(CheckpointError, match="fleet"):
        BinaryAccuracy().restore_checkpoint(str(tmp_path), stream=0)


def test_fleet_size_drift_names_fleet_dim(tmp_path):
    m, _ = _fed_fleet()
    m.save_checkpoint(str(tmp_path), step=0)
    wrong = MulticlassAccuracy(num_classes=3, average=None, fleet_size=FLEET + 1)
    with pytest.raises(ShapeDriftError, match=r"fleet_size=4 != live fleet_size=5"):
        wrong.restore_checkpoint(str(tmp_path))
    plain = MulticlassAccuracy(num_classes=3, average=None)
    with pytest.raises(ShapeDriftError, match=r"fleet_size=4 != live fleet_size=None"):
        plain.restore_checkpoint(str(tmp_path))


def test_collection_restore_rejects_stream(tmp_path):
    from metrics_tpu import MetricCollection

    col = MetricCollection({"acc": BinaryAccuracy(fleet_size=2)})
    col.update(
        jnp.ones(4, jnp.int32), jnp.ones(4, jnp.int32),
        stream_ids=jnp.array([0, 1, 0, 1], dtype=jnp.int32),
    )
    ckpt.save_checkpoint(col, str(tmp_path), step=0)
    fresh = MetricCollection({"acc": BinaryAccuracy(fleet_size=2)})
    with pytest.raises(CheckpointError, match="not collections"):
        ckpt.restore_checkpoint(fresh, str(tmp_path), stream=0)
    # without stream= the collection restores normally
    assert ckpt.restore_checkpoint(fresh, str(tmp_path)) == 0


# ------------------------------------------ topology change along the fleet axis


def _save_two_hosts(metric_builder, feed, tmp_path):
    """Two per-host (replicated=False) instances of the same fleet metric, fed
    different data, saved as hosts 0/1 of one step."""
    hosts = [metric_builder() for _ in range(2)]
    for h, m in enumerate(hosts):
        feed(m, h)
        m.save_checkpoint(
            str(tmp_path), step=0, replicated=False,
            process_index=h, process_count=2, generation="gen-t",
        )
    return hosts


def test_topology_change_sum_rereduces_fleet_axis(tmp_path):
    ids = jnp.array([0, 0, 1, 1], dtype=jnp.int32)

    def feed(m, h):
        preds = jnp.asarray([1, 0, 1, 1]) if h == 0 else jnp.asarray([0, 0, 1, 0])
        target = jnp.ones(4, jnp.int32)
        m.update(preds, target, stream_ids=ids)

    hosts = _save_two_hosts(lambda: BinaryAccuracy(fleet_size=2), feed, tmp_path)
    merged = BinaryAccuracy(fleet_size=2)
    merged.restore_checkpoint(str(tmp_path), process_index=0, process_count=1)
    # sum states re-reduce elementwise, which along the fleet axis is exactly
    # per-stream summation — identical to merge_state of the two host fleets
    ref = hosts[0]
    ref.merge_state(hosts[1])
    assert np.array_equal(np.asarray(merged.tp), np.asarray(ref.tp))
    assert np.array_equal(np.asarray(merged.compute()), np.asarray(ref.compute()))


@pytest.mark.parametrize("cls,vals0,vals1,want", [
    (MaxMetric, [1.0, 5.0], [3.0, 2.0], [3.0, 5.0]),
    (MinMetric, [1.0, 5.0], [3.0, 2.0], [1.0, 2.0]),
])
def test_topology_change_minmax_rereduces_fleet_axis(tmp_path, cls, vals0, vals1, want):
    ids = jnp.array([0, 1], dtype=jnp.int32)

    def feed(m, h):
        m.update(jnp.asarray(vals0 if h == 0 else vals1), stream_ids=ids)

    _save_two_hosts(lambda: cls(fleet_size=2), feed, tmp_path)
    merged = cls(fleet_size=2)
    merged.restore_checkpoint(str(tmp_path), process_index=0, process_count=1)
    assert np.array_equal(np.asarray(merged.compute()), np.asarray(want))


def test_stream_slice_after_topology_change(tmp_path):
    """stream= slicing composes with N->M: slice host 0's stream out of a
    2-host fleet checkpoint restored onto 1 host."""
    ids = jnp.array([0, 0, 1, 1], dtype=jnp.int32)

    def feed(m, h):
        preds = jnp.asarray([1, 0, 1, 1]) if h == 0 else jnp.asarray([0, 0, 1, 0])
        m.update(preds, jnp.ones(4, jnp.int32), stream_ids=ids)

    hosts = _save_two_hosts(lambda: BinaryAccuracy(fleet_size=2), feed, tmp_path)
    single = BinaryAccuracy()
    single.restore_checkpoint(str(tmp_path), stream=1, process_index=0, process_count=1)
    ref = hosts[0]
    ref.merge_state(hosts[1])
    assert np.array_equal(np.asarray(single.compute()), np.asarray(ref.compute()[1]))
