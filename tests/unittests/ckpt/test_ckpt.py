"""Core semantics of metrics_tpu.ckpt: atomicity, versioning/retention, typed
errors, async writes, multi-host commit protocol, topology change, compute-group
re-aliasing, CatBuffer overflow survival, obs counters.

The round-trip property over every public metric class lives in
``test_roundtrip_sweep.py``; this file covers the engine itself.
"""
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import ckpt, obs
from metrics_tpu.ckpt import (
    CapacityError,
    CheckpointError,
    CheckpointNotFoundError,
    CorruptCheckpointError,
    DtypeDriftError,
    IncompleteCheckpointError,
    SchemaDriftError,
    ShapeDriftError,
    TopologyError,
)
from metrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision, MulticlassRecall
from metrics_tpu.core.metric import Metric
from metrics_tpu.core.state import CatBuffer, cat_values

pytestmark = pytest.mark.ckpt

_rng = np.random.RandomState(7)


def _acc(preds_n=64):
    m = MulticlassAccuracy(num_classes=5, average="micro")
    m.update(jnp.asarray(_rng.randint(0, 5, preds_n)), jnp.asarray(_rng.randint(0, 5, preds_n)))
    return m


class _CatSum(Metric):
    """Tiny metric with a cat state + a sum state, for buffer-level tests."""

    full_state_update = True

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("vals", [], dist_reduce_fx="cat", cat_item_shape=(), cat_dtype=jnp.float32)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        x = jnp.atleast_1d(jnp.asarray(x, jnp.float32))
        self.vals.append(x)
        self.total = self.total + x.sum()

    def compute(self):
        return cat_values(self.vals).sum()


class _Unreduced(Metric):
    """A dist_reduce_fx=None state: not re-reducible across topology change."""

    full_state_update = True

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("raw", jnp.zeros(3), dist_reduce_fx=None)

    def update(self, x):
        self.raw = self.raw + jnp.asarray(x)

    def compute(self):
        return self.raw.sum()


# ------------------------------------------------------------------ basics


def test_versioned_steps_and_retention(tmp_path):
    d = str(tmp_path)
    m = _acc()
    for expect in range(4):
        handle = m.save_checkpoint(d, retain=2)
        assert handle.step == expect
        assert handle.result().endswith(f"step_{expect:010d}")
    assert ckpt.all_steps(d) == [2, 3]  # retention pruned 0 and 1
    assert ckpt.latest_step(d) == 3


def test_explicit_step_collision_raises(tmp_path):
    m = _acc()
    m.save_checkpoint(str(tmp_path), step=5)
    with pytest.raises(CheckpointError):
        m.save_checkpoint(str(tmp_path), step=5)


def test_restore_missing_raises_not_found(tmp_path):
    with pytest.raises(CheckpointNotFoundError):
        _acc().restore_checkpoint(str(tmp_path))
    with pytest.raises(CheckpointNotFoundError):
        _acc().restore_checkpoint(str(tmp_path), step=3)


def test_async_save_roundtrip(tmp_path):
    m = _acc()
    want = float(m.compute())
    handle = m.save_checkpoint(str(tmp_path), blocking=False)
    # the snapshot captured immutable references: mutating the live metric
    # after the call must not corrupt the in-flight write
    m.update(jnp.asarray(_rng.randint(0, 5, 64)), jnp.asarray(_rng.randint(0, 5, 64)))
    handle.result(timeout=60)
    ckpt.wait_for_all_saves()
    fresh = MulticlassAccuracy(num_classes=5, average="micro")
    fresh.restore_checkpoint(str(tmp_path))
    assert float(fresh.compute()) == want


def test_async_auto_step_saves_never_collide(tmp_path):
    """Back-to-back non-blocking saves with auto-stepping must each get a fresh
    step even though none has committed yet — two writers assigned the same
    step would race on one tmp dir (regression: ``latest + 1`` alone reused
    in-flight steps when dispatch outpaced commit)."""
    d = str(tmp_path)
    m = _acc()
    handles = [m.save_checkpoint(d, blocking=False) for _ in range(10)]
    ckpt.wait_for_all_saves()
    assert [h.step for h in handles] == list(range(10))
    assert ckpt.all_steps(d) == list(range(10))
    for step in range(10):  # every one must be complete and uncorrupted
        fresh = MulticlassAccuracy(num_classes=5, average="micro")
        assert fresh.restore_checkpoint(d, step=step) == step


# --------------------------------------------------------------- atomicity


def test_kill_before_commit_leaves_no_readable_checkpoint(tmp_path, monkeypatch):
    """A death anywhere between save start and commit must leave nothing a
    reader would accept: the step dir only becomes visible via the final
    rename, which happens after the COMMIT record exists."""
    from metrics_tpu.ckpt import manager

    d = str(tmp_path)
    m = _acc()

    # kill point 1: before any bytes are written
    monkeypatch.setattr(
        manager._serializer, "write_payload",
        lambda *a, **k: (_ for _ in ()).throw(KeyboardInterrupt("preempted")),
    )
    with pytest.raises(KeyboardInterrupt):
        m.save_checkpoint(d)
    monkeypatch.undo()

    # kill point 2: payload + manifest written, rename never happens
    monkeypatch.setattr(manager.os, "rename", lambda *a: (_ for _ in ()).throw(OSError("preempted")))
    with pytest.raises(OSError):
        m.save_checkpoint(d, step=9)
    monkeypatch.undo()

    assert ckpt.all_steps(d) == []
    with pytest.raises(CheckpointNotFoundError):
        _acc().restore_checkpoint(d)
    # the explicitly-requested half-written step is typed as incomplete
    with pytest.raises(IncompleteCheckpointError):
        _acc().restore_checkpoint(d, step=9)

    # a later, uninterrupted save of the same series works and restores
    m.save_checkpoint(d, step=10)
    fresh = MulticlassAccuracy(num_classes=5, average="micro")
    assert fresh.restore_checkpoint(d) == 10
    assert float(fresh.compute()) == float(m.compute())


def test_committed_dir_without_commit_record_is_incomplete(tmp_path):
    d = str(tmp_path)
    m = _acc()
    m.save_checkpoint(d, step=0)
    os.remove(os.path.join(d, "step_0000000000", "COMMIT"))
    assert ckpt.all_steps(d) == []
    with pytest.raises(IncompleteCheckpointError):
        _acc().restore_checkpoint(d, step=0)


# ------------------------------------------------------------ typed errors


def test_truncated_payload_raises_corrupt(tmp_path):
    d = str(tmp_path)
    _acc().save_checkpoint(d)
    payload = os.path.join(d, "step_0000000000", "arrays-h0000.bin")
    with open(payload, "r+b") as fh:
        fh.truncate(os.path.getsize(payload) // 2)
    with pytest.raises(CorruptCheckpointError, match="truncated"):
        _acc().restore_checkpoint(d)


def test_bitrot_payload_raises_corrupt(tmp_path):
    d = str(tmp_path)
    _acc().save_checkpoint(d)
    payload = os.path.join(d, "step_0000000000", "arrays-h0000.bin")
    with open(payload, "r+b") as fh:
        fh.seek(0)
        first = fh.read(1)
        fh.seek(0)
        fh.write(bytes([first[0] ^ 0xFF]))
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        _acc().restore_checkpoint(d)


def test_corrupt_manifest_raises_corrupt(tmp_path):
    d = str(tmp_path)
    _acc().save_checkpoint(d)
    manifest = os.path.join(d, "step_0000000000", "manifest-h0000.json")
    with open(manifest, "w") as fh:
        fh.write('{"format": "metrics_tpu.ck')  # truncated JSON
    with pytest.raises(CorruptCheckpointError, match="manifest"):
        _acc().restore_checkpoint(d)


class _Vec(Metric):
    """Configurable state schema, for drift tests: shape/dtype/reduce knobs."""

    full_state_update = True

    def __init__(self, n=3, dtype=jnp.float32, reduce="sum", **kwargs):
        super().__init__(**kwargs)
        self.add_state("v", jnp.zeros(n, dtype), dist_reduce_fx=reduce)

    def update(self, x):
        self.v = self.v + jnp.asarray(x, self.v.dtype)

    def compute(self):
        return self.v.sum()


def test_schema_drift_typed_errors(tmp_path):
    d = str(tmp_path)
    m = _Vec(n=3)
    m.update(jnp.ones(3))
    m.save_checkpoint(d)

    with pytest.raises(ShapeDriftError):
        _Vec(n=4).restore_checkpoint(d)
    with pytest.raises(DtypeDriftError):
        _Vec(n=3, dtype=jnp.int32).restore_checkpoint(d)
    with pytest.raises(SchemaDriftError):
        _Vec(n=3, reduce="max").restore_checkpoint(d)
    with pytest.raises(SchemaDriftError):
        # different metric class entirely
        MulticlassPrecision(num_classes=5, average="micro").restore_checkpoint(d)

    # drift raises BEFORE any assignment: the live metric stays untouched
    clean = _Vec(n=4)
    clean.update(jnp.ones(4))
    before = float(clean.compute())
    with pytest.raises(ShapeDriftError):
        clean.restore_checkpoint(d)
    assert float(clean.compute()) == before


def test_lazy_reshaped_state_is_not_drift(tmp_path):
    """Metrics that reshape a placeholder state on first update (image metrics
    with data-dependent map shapes) must restore into a FRESH instance: the
    validation compares registered defaults, not live values."""
    from metrics_tpu.image import RelativeAverageSpectralError

    d = str(tmp_path)
    img = jnp.asarray(_rng.rand(2, 3, 16, 16).astype(np.float32)) + 0.1
    m = RelativeAverageSpectralError(window_size=4)
    m.update(img, img + 0.01)
    want = float(m.compute())
    m.save_checkpoint(d)
    fresh = RelativeAverageSpectralError(window_size=4)
    fresh.restore_checkpoint(d)
    assert float(fresh.compute()) == want


# ------------------------------------------------------------- cat buffers


def test_catbuffer_count_and_overflow_survive_roundtrip(tmp_path):
    d = str(tmp_path)
    m = _CatSum(cat_capacity=4)
    m.update(jnp.arange(3.0))
    m.update(jnp.arange(3.0))  # true count 6 > capacity 4: overflow
    assert bool(m.vals.overflowed())
    m.save_checkpoint(d)

    same = _CatSum(cat_capacity=4)
    same.restore_checkpoint(d)
    # exact resume: the TRUE over-capacity count and flag survive bit-for-bit
    assert int(same.vals.count) == 6
    assert bool(same.vals.overflowed())
    np.testing.assert_array_equal(np.asarray(same.vals.data), np.asarray(m.vals.data))

    bigger = _CatSum(cat_capacity=16)
    bigger.restore_checkpoint(d)
    # re-packed: only the valid rows transfer, the sticky flag still survives
    assert int(bigger.vals.count) == 4
    assert bool(bigger.vals.overflowed())


def test_catbuffer_capacity_too_small_raises(tmp_path):
    d = str(tmp_path)
    m = _CatSum(cat_capacity=8)
    m.update(jnp.arange(6.0))
    m.save_checkpoint(d)
    with pytest.raises(CapacityError):
        _CatSum(cat_capacity=2).restore_checkpoint(d)


def test_list_cat_state_roundtrip_ragged(tmp_path):
    d = str(tmp_path)
    m = _CatSum()  # no cat_capacity: plain list state, ragged items
    m.update(jnp.arange(3.0))
    m.update(jnp.arange(5.0))
    want = float(m.compute())
    m.save_checkpoint(d)
    fresh = _CatSum()
    fresh.restore_checkpoint(d)
    assert [tuple(v.shape) for v in fresh.vals] == [(3,), (5,)]
    assert float(fresh.compute()) == want


# ------------------------------------------------------- collections/groups


def _make_collection():
    return metrics_tpu.MetricCollection(
        [
            MulticlassAccuracy(num_classes=5),
            MulticlassPrecision(num_classes=5),
            MulticlassRecall(num_classes=5),
        ]
    )


def test_collection_roundtrip_and_group_realiasing(tmp_path):
    d = str(tmp_path)
    mc = _make_collection()
    assert any(len(g) > 1 for g in mc.compute_groups.values())  # premise: grouped
    mc.update(jnp.asarray(_rng.randint(0, 5, 64)), jnp.asarray(_rng.randint(0, 5, 64)))
    want = {k: float(v) for k, v in mc.compute().items()}
    mc.save_checkpoint(d)

    # the payload contains ONE copy of the shared group state (leader only)
    manifest = json.load(open(os.path.join(d, "step_0000000000", "manifest-h0000.json")))
    prefixes = {k.split("/")[0] for k in manifest["payload"]["index"]}
    leaders = {g[0] for g in manifest["tree"]["groups"]}
    assert prefixes == leaders

    mc2 = _make_collection()
    mc2.restore_checkpoint(d)
    assert {k: float(v) for k, v in mc2.compute().items()} == want
    for group in mc2.compute_groups.values():
        leader = mc2._modules[group[0]]
        for name in group[1:]:
            member = mc2._modules[name]
            assert all(getattr(member, s) is getattr(leader, s) for s in leader._defaults)
            assert member._update_count == leader._update_count

    # accumulation continues correctly after restore (aliasing is live)
    extra_p, extra_t = _rng.randint(0, 5, 32), _rng.randint(0, 5, 32)
    mc.update(jnp.asarray(extra_p), jnp.asarray(extra_t))
    mc2.update(jnp.asarray(extra_p), jnp.asarray(extra_t))
    got = {k: float(v) for k, v in mc2.compute().items()}
    assert got == {k: float(v) for k, v in mc.compute().items()}


def test_collection_name_drift_raises(tmp_path):
    d = str(tmp_path)
    mc = _make_collection()
    mc.update(jnp.asarray(_rng.randint(0, 5, 16)), jnp.asarray(_rng.randint(0, 5, 16)))
    mc.save_checkpoint(d)
    other = metrics_tpu.MetricCollection([MulticlassAccuracy(num_classes=5)])
    with pytest.raises(SchemaDriftError, match="names"):
        other.restore_checkpoint(d)


# ------------------------------------------------------- wrappers / nesting


def test_nested_wrapper_children_roundtrip(tmp_path):
    from metrics_tpu.wrappers import MinMaxMetric

    d = str(tmp_path)
    m = MinMaxMetric(MulticlassAccuracy(num_classes=5, average="micro"))
    for _ in range(3):
        m.update(jnp.asarray(_rng.randint(0, 5, 32)), jnp.asarray(_rng.randint(0, 5, 32)))
    want = {k: float(v) for k, v in m.compute().items()}
    m.save_checkpoint(d)
    fresh = MinMaxMetric(MulticlassAccuracy(num_classes=5, average="micro"))
    fresh.restore_checkpoint(d)
    # the child metric's states rode along under the `_base_metric/` prefix
    assert {k: float(v) for k, v in fresh.compute().items()} == want
    assert fresh._base_metric._update_count == m._base_metric._update_count


# ------------------------------------------------- multi-host coordination


def test_multihost_commit_requires_all_manifests(tmp_path):
    d = str(tmp_path)
    m0, m1 = _acc(), _acc()
    # host 1 saves first: no commit yet (host 0's manifest missing)
    m1.save_checkpoint(d, step=3, process_index=1, process_count=2)
    assert ckpt.all_steps(d) == []
    with pytest.raises(CheckpointNotFoundError):
        _acc().restore_checkpoint(d)
    # host 0 arrives: its commit check sees both manifests and commits
    m0.save_checkpoint(d, step=3, process_index=0, process_count=2)
    assert ckpt.all_steps(d) == [3]
    step_dir = os.path.join(d, "step_0000000003")
    assert json.load(open(os.path.join(step_dir, "COMMIT")))["world"] == 2


def test_stale_manifest_from_dead_incarnation_never_commits(tmp_path):
    """Preemption mid-save leaves some hosts' manifests in the tmp dir; the
    restarted job reuses the step number, and those stale manifests must NOT
    count toward the new generation's commit — a commit mixing shards from
    two save generations would read as a valid checkpoint with wrong state."""
    d = str(tmp_path)
    # incarnation 1: host 1 wrote its manifest, host 0 was preempted before
    # writing — step 0 never commits
    _acc().save_checkpoint(d, step=0, process_index=1, process_count=2, generation="gen-dead")
    assert ckpt.all_steps(d) == []
    # incarnation 2 reuses step 0: host 0 writes and runs the commit check;
    # the stale manifest-h0001 is present but from the dead generation
    h0 = _acc().save_checkpoint(d, step=0, process_index=0, process_count=2, generation="gen-live")
    assert ckpt.all_steps(d) == []
    assert not h0.committed
    # host 1 of the live generation overwrites its stale shard: now commit
    h1 = _acc().save_checkpoint(d, step=0, process_index=1, process_count=2, generation="gen-live")
    assert ckpt.all_steps(d) == [0]
    assert h1.committed
    assert h0.committed  # the earlier handle observes the later commit live
    step_dir = os.path.join(d, "step_0000000000")
    for host in range(2):
        man = json.load(open(os.path.join(step_dir, f"manifest-h{host:04d}.json")))
        assert man["generation"] == "gen-live"
    assert json.load(open(os.path.join(step_dir, "COMMIT")))["generation"] == "gen-live"


def test_commit_sweeps_stale_bigger_world_shards(tmp_path):
    """A preempted 2-host incarnation leaves host-1 shards in the tmp dir; the
    restarted job runs on 1 host and reuses the step. Its commit must both
    ignore the stale shards and remove them, so the committed dir holds one
    generation only."""
    d = str(tmp_path)
    _acc().save_checkpoint(d, step=0, process_index=1, process_count=2, generation="gen-dead")
    m = _acc()
    want = float(m.compute())
    m.save_checkpoint(d, step=0)  # world 1: its own fresh manifest suffices
    assert ckpt.all_steps(d) == [0]
    step_dir = os.path.join(d, "step_0000000000")
    assert not os.path.exists(os.path.join(step_dir, "manifest-h0001.json"))
    assert not os.path.exists(os.path.join(step_dir, "arrays-h0001.bin"))
    fresh = MulticlassAccuracy(num_classes=5, average="micro")
    fresh.restore_checkpoint(d)
    assert float(fresh.compute()) == want


def test_wait_for_all_saves_surfaces_uncommitted_steps(tmp_path):
    """A drained multi-host save whose peers never arrived must not read as
    plain success: warn by default, raise with require_committed=True."""
    from metrics_tpu.ckpt import manager

    d = str(tmp_path)
    h = _acc().save_checkpoint(d, step=0, process_index=1, process_count=2)
    assert h.done() and not h.committed  # write finished, commit pending peers
    with manager._INFLIGHT_LOCK:
        manager._INFLIGHT.append(h)  # as if the async writer had not drained yet
    try:
        with pytest.warns(RuntimeWarning, match="not committed"):
            ckpt.wait_for_all_saves()
        with pytest.raises(IncompleteCheckpointError, match="not committed"):
            ckpt.wait_for_all_saves(require_committed=True)
    finally:
        with manager._INFLIGHT_LOCK:
            manager._INFLIGHT.remove(h)
    # the peer arrives later: the commit is observed, nothing pending anymore
    _acc().save_checkpoint(d, step=0, process_index=0, process_count=2)
    assert h.committed
    ckpt.wait_for_all_saves()


def test_commit_write_losing_rename_race_is_success(tmp_path, monkeypatch):
    """Between a host's completeness check and its COMMIT write, a racing host
    can rename the tmp dir away; the resulting FileNotFoundError must read as
    success (the step IS committed), not as a failed save."""
    from metrics_tpu.ckpt import manager

    d = str(tmp_path)
    _acc().save_checkpoint(d, step=0, process_index=1, process_count=2)
    real = manager._atomic_write_json
    tmp_dir = os.path.join(d, ".tmp-step_0000000000")
    final_dir = os.path.join(d, "step_0000000000")

    def racing(path, payload):
        if os.path.basename(path) == "COMMIT" and os.path.isdir(tmp_dir):
            real(path, payload)  # the racing peer completes the commit...
            os.rename(tmp_dir, final_dir)  # ...and wins the rename,
            raise FileNotFoundError(path + ".part")  # so our write finds no dir
        return real(path, payload)

    monkeypatch.setattr(manager, "_atomic_write_json", racing)
    h = _acc().save_checkpoint(d, step=0, process_index=0, process_count=2)
    assert h.committed
    assert ckpt.all_steps(d) == [0]


def test_multihost_replicated_rank0_writes_arrays_once(tmp_path):
    d = str(tmp_path)
    m0, m1 = _acc(), _acc()
    m1.save_checkpoint(d, step=0, process_index=1, process_count=2)
    m0.save_checkpoint(d, step=0, process_index=0, process_count=2)
    step_dir = os.path.join(d, "step_0000000000")
    m_h0 = json.load(open(os.path.join(step_dir, "manifest-h0000.json")))
    m_h1 = json.load(open(os.path.join(step_dir, "manifest-h0001.json")))
    # replicated array states appear only in host 0's payload
    assert "tp" in m_h0["payload"]["index"]
    assert "tp" not in m_h1["payload"]["index"]


# --------------------------------------------------------- topology change


def test_topology_change_sum_states_rereduce(tmp_path):
    d = str(tmp_path)
    data = [(_rng.randint(0, 5, 40), _rng.randint(0, 5, 40)) for _ in range(2)]
    for rank, (p, t) in enumerate(data):
        m = MulticlassAccuracy(num_classes=5, average="micro")
        m.update(jnp.asarray(p), jnp.asarray(t))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)

    oracle = MulticlassAccuracy(num_classes=5, average="micro")
    for p, t in data:
        oracle.update(jnp.asarray(p), jnp.asarray(t))

    # 2 hosts -> 1 host: the single host owns the re-reduced total
    single = MulticlassAccuracy(num_classes=5, average="micro")
    single.restore_checkpoint(d, process_index=0, process_count=1)
    assert float(single.compute()) == float(oracle.compute())

    # 2 hosts -> 3 hosts: rank 0 owns the total, others hold reset defaults,
    # so a cross-host sum still yields the global state
    shards = []
    for rank in range(3):
        h = MulticlassAccuracy(num_classes=5, average="micro")
        h.restore_checkpoint(d, process_index=rank, process_count=3)
        shards.append(np.asarray(h.tp))
    np.testing.assert_array_equal(sum(shards), np.asarray(oracle.tp))


def test_topology_change_cat_rows_repack(tmp_path):
    d = str(tmp_path)
    chunks = [np.arange(5.0), np.arange(5.0, 8.0)]
    for rank, chunk in enumerate(chunks):
        m = _CatSum(cat_capacity=8)
        m.update(jnp.asarray(chunk))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)

    # 2 hosts -> 3 hosts: every row lands on exactly one host, in order
    rows = []
    for rank in range(3):
        h = _CatSum(cat_capacity=8)
        h.restore_checkpoint(d, process_index=rank, process_count=3)
        rows.extend(np.asarray(h.vals.values()).tolist())
    assert rows == np.concatenate(chunks).tolist()


def test_topology_change_same_world_exact(tmp_path):
    d = str(tmp_path)
    states = []
    for rank in range(2):
        m = _CatSum(cat_capacity=8)
        m.update(jnp.arange(float(rank + 2)))
        states.append(np.asarray(m.vals.values()))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)
    for rank in range(2):
        h = _CatSum(cat_capacity=8)
        h.restore_checkpoint(d, process_index=rank, process_count=2)
        np.testing.assert_array_equal(np.asarray(h.vals.values()), states[rank])


def test_topology_change_collection_member_counts_take_max(tmp_path):
    """Per-member update counts restored across a host-count change follow the
    conservative-max policy (counts differ per host under non-replicated
    accumulation), mirroring the single-metric merged_update_count path —
    not host 0's counts verbatim."""
    d = str(tmp_path)
    for rank, n_updates in enumerate((1, 3)):
        mc = metrics_tpu.MetricCollection(
            [MulticlassAccuracy(num_classes=5, average="micro")]
        )
        for _ in range(n_updates):
            mc.update(jnp.asarray(_rng.randint(0, 5, 8)), jnp.asarray(_rng.randint(0, 5, 8)))
        mc.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)
    single = metrics_tpu.MetricCollection(
        [MulticlassAccuracy(num_classes=5, average="micro")]
    )
    single.restore_checkpoint(d, process_index=0, process_count=1)
    [member] = list(single._modules.values())
    assert member._update_count == 3  # max across hosts, not host 0's count of 1


def test_topology_change_hll_max_states_rereduce(tmp_path):
    """The sketch family's `max` re-reduce in the N→M matrix: HLL registers
    saved from 2 hosts restore onto 1 host as the elementwise max — which IS
    the HLL merge, so the restored estimate equals the single-stream oracle
    bit-identically (restore.py's max rule merges on every host, not rank 0)."""
    from metrics_tpu.sketches import DistinctCount

    d = str(tmp_path)
    chunks = [_rng.randint(0, 3000, 4000), _rng.randint(2000, 8000, 4000)]
    for rank, chunk in enumerate(chunks):
        m = DistinctCount(p=10)
        m.update(jnp.asarray(chunk))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)

    oracle = DistinctCount(p=10)
    oracle.update(jnp.asarray(np.concatenate(chunks)))

    # 2 hosts -> 1 host
    single = DistinctCount(p=10)
    single.restore_checkpoint(d, process_index=0, process_count=1)
    np.testing.assert_array_equal(np.asarray(single.registers), np.asarray(oracle.registers))
    assert float(single.compute()) == float(oracle.compute())

    # 2 hosts -> 3 hosts: max states merge on EVERY host (unlike sum, the
    # merged registers are safe to hold replicated — pmax is idempotent)
    for rank in range(3):
        h = DistinctCount(p=10)
        h.restore_checkpoint(d, process_index=rank, process_count=3)
        np.testing.assert_array_equal(np.asarray(h.registers), np.asarray(oracle.registers))


def test_topology_change_quantile_sketch_sum_states_rereduce(tmp_path):
    """QuantileSketch's `sum` re-reduce across N→M: bucket histograms saved
    from 2 hosts re-reduce so that a cross-host sum still equals the oracle's
    single-stream histogram, and the restored quantiles match exactly."""
    from metrics_tpu.sketches import QuantileSketch

    d = str(tmp_path)
    chunks = [
        _rng.lognormal(0.0, 1.5, 3000).astype(np.float32),
        _rng.lognormal(1.0, 1.0, 3000).astype(np.float32),
    ]
    for rank, chunk in enumerate(chunks):
        m = QuantileSketch()
        m.update(jnp.asarray(chunk))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)

    oracle = QuantileSketch()
    oracle.update(jnp.asarray(np.concatenate(chunks)))

    # 2 hosts -> 1 host: the single host owns the re-reduced totals
    single = QuantileSketch()
    single.restore_checkpoint(d, process_index=0, process_count=1)
    for state in ("pos_buckets", "neg_buckets", "edge_counts", "nan_count"):
        np.testing.assert_array_equal(np.asarray(getattr(single, state)), np.asarray(getattr(oracle, state)))
    np.testing.assert_array_equal(
        np.asarray(single.compute()["quantiles"]), np.asarray(oracle.compute()["quantiles"])
    )

    # 2 hosts -> 3 hosts: rank 0 owns the total, others reset defaults, so the
    # cross-host sum reproduces the global histogram
    shards = []
    for rank in range(3):
        h = QuantileSketch()
        h.restore_checkpoint(d, process_index=rank, process_count=3)
        shards.append(np.asarray(h.pos_buckets))
    np.testing.assert_array_equal(sum(shards), np.asarray(oracle.pos_buckets))


def test_topology_change_unreduced_state_raises(tmp_path):
    d = str(tmp_path)
    for rank in range(2):
        m = _Unreduced()
        m.update(jnp.ones(3) * (rank + 1))
        m.save_checkpoint(d, step=0, process_index=rank, process_count=2, replicated=False)
    # same world: exact per-rank restore is fine
    ok = _Unreduced()
    ok.restore_checkpoint(d, process_index=1, process_count=2)
    np.testing.assert_array_equal(np.asarray(ok.raw), 2 * np.ones(3))
    # changed world: no way to re-reduce a None-reduction state
    with pytest.raises(TopologyError):
        _Unreduced().restore_checkpoint(d, process_index=0, process_count=1)


# ------------------------------------------------------------- persistence


def test_persistent_only_saves_subset(tmp_path):
    d = str(tmp_path)
    m = _CatSum(cat_capacity=8)
    m.persistent(True)
    m._persistent["vals"] = False  # only `total` is persistent
    m.update(jnp.arange(4.0))
    m.save_checkpoint(d, persistent_only=True)

    manifest = json.load(open(os.path.join(d, "step_0000000000", "manifest-h0000.json")))
    assert set(manifest["tree"]["schema"]["states"]) == {"total"}

    fresh = _CatSum(cat_capacity=8)
    fresh.restore_checkpoint(d)
    assert float(fresh.total) == 6.0
    assert int(fresh.vals.count) == 0  # non-persistent state kept its default


# -------------------------------------------------------------------- obs


def test_obs_counters_and_jsonl_export(tmp_path):
    d = str(tmp_path)
    m = _acc()
    with obs.observe(clear=True):
        m.save_checkpoint(d)
        fresh = MulticlassAccuracy(num_classes=5, average="micro")
        fresh.restore_checkpoint(d)
        snap = obs.snapshot()
        assert snap["ckpt"]["saves"] == 1
        assert snap["ckpt"]["restores"] == 1
        assert snap["ckpt"]["bytes"] > 0
        assert snap["ckpt"]["save_ms"] > 0
        assert snap["ckpt"]["restore_ms"] > 0
        # the JSONL export carries the same counters
        record = obs.dump_jsonl(str(tmp_path / "obs.jsonl"))
        assert record["registry"]["ckpt"]["saves"] == 1
    line = json.loads(open(tmp_path / "obs.jsonl").read().splitlines()[-1])
    assert line["registry"]["ckpt"]["restores"] == 1


def test_obs_disabled_writes_nothing(tmp_path):
    obs.disable()
    obs.REGISTRY.clear()
    m = _acc()
    m.save_checkpoint(str(tmp_path))
    fresh = MulticlassAccuracy(num_classes=5, average="micro")
    fresh.restore_checkpoint(str(tmp_path))
    assert obs.snapshot() == {}


def test_state_report_carries_ckpt_latency(tmp_path):
    m = _acc()
    m.save_checkpoint(str(tmp_path))
    report = m.state_report()
    assert report["ckpt"]["last_save_step"] == 0
    assert report["ckpt"]["last_save_ms"] > 0
    assert report["ckpt"]["last_save_bytes"] > 0
    fresh = MulticlassAccuracy(num_classes=5, average="micro")
    fresh.restore_checkpoint(str(tmp_path))
    assert fresh.state_report()["ckpt"]["last_restore_step"] == 0


# --------------------------------------------- fused donation vs async saves


def test_async_save_racing_fused_donation_serializes_pre_donation_state(
    tmp_path, monkeypatch
):
    """Regression (ISSUE 6 satellite): ``save_checkpoint(blocking=False)``
    snapshots array *references*; a donation-backed fused update racing the
    writer thread invalidates exactly those arrays. The engine must secure the
    pending snapshot (device->host) BEFORE donating, so the checkpoint that
    lands on disk is the pre-donation state — not a crash on deleted buffers.

    The race is made deterministic by capturing the writer thread instead of
    starting it: the fused update runs while the snapshot still holds device
    references, then the writer runs.
    """
    import threading

    from metrics_tpu.ckpt import manager
    from metrics_tpu.core.fused import canonical_collection

    rng = np.random.RandomState(0)
    p = rng.rand(64).astype(np.float32)
    t = rng.randint(0, 2, 64).astype(np.int32)
    coll = canonical_collection(fused=True)
    coll.update(p, t)
    coll.update(p, t)  # warmed: the next update donates via the cached executable
    pre = {k: np.asarray(v) for k, v in coll.compute().items()}

    captured = []

    class _DeferredThread:
        def __init__(self, target=None, **kwargs):
            captured.append(target)

        def start(self):
            pass

    monkeypatch.setattr(manager.threading, "Thread", _DeferredThread)
    handle = coll.save_checkpoint(str(tmp_path), blocking=False)
    monkeypatch.undo()
    assert len(manager._PENDING_SNAPSHOTS) == 1
    snap = manager._PENDING_SNAPSHOTS[0]
    assert any(not isinstance(v, np.ndarray) for _, v, _ in snap.entries)

    coll.update(p, t)  # donates the snapshotted arrays -> engine secures first
    # every entry the donation touched is now a host array; nothing deleted
    for _, value, _ in snap.entries:
        assert isinstance(value, np.ndarray) or not value.is_deleted()

    captured[0]()  # run the deferred writer
    handle.result()
    assert handle.committed

    fresh = canonical_collection(fused=False)
    fresh.restore_checkpoint(str(tmp_path))
    post = {k: np.asarray(v) for k, v in fresh.compute().items()}
    assert pre.keys() == post.keys()
    for k in pre:
        assert pre[k].tobytes() == post[k].tobytes()


def test_async_save_without_race_still_materializes_on_writer(tmp_path):
    """The writer thread itself materializes the snapshot first, so an async
    save with no racing donation behaves exactly as before (and the pending
    registry drains)."""
    from metrics_tpu.ckpt import manager
    from metrics_tpu.core.fused import canonical_collection

    rng = np.random.RandomState(1)
    p = rng.rand(64).astype(np.float32)
    t = rng.randint(0, 2, 64).astype(np.int32)
    coll = canonical_collection(fused=True)
    coll.update(p, t)
    handle = coll.save_checkpoint(str(tmp_path), blocking=False)
    handle.result()
    ckpt.wait_for_all_saves()
    assert not manager._PENDING_SNAPSHOTS
    fresh = canonical_collection(fused=False)
    fresh.restore_checkpoint(str(tmp_path))
    assert {k: np.asarray(v).tobytes() for k, v in coll.compute().items()} == {
        k: np.asarray(v).tobytes() for k, v in fresh.compute().items()
    }
