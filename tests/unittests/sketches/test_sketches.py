"""Property tests for the mergeable streaming sketch family (sketches/).

Every sketch is checked against a numpy/scipy oracle on the full input stream:

- QuantileSketch: every returned certified quantile is within the DECLARED
  relative error of the exact ``np.quantile`` of the same data (the γ-bound),
  across dtypes, distributions, and adversarial values;
- DistinctCount: estimate within 3σ of the HLL standard error 1.04/sqrt(m)
  of the true cardinality (and exactly order/merge-invariant);
- HistogramDrift: KL/PSI/TV equal to scipy/numpy recomputation from the same
  histograms;
- StreamingAUROCBound: the exact-tier AUROC/AP (ops/clf_curve.py) lies inside
  the certified bracket, and the bracket collapses to the exact value on
  quantized score domains.

Merge laws hold for all four: merge-then-compute equals compute-on-concat
(bit-identically at the state level), under arbitrary split/merge orderings.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.sketches import (
    DistinctCount,
    HistogramDrift,
    QuantileSketch,
    SketchMetric,
    StreamingAUROCBound,
)
from metrics_tpu.utils.exceptions import MetricsUserError

pytestmark = pytest.mark.sketch

_rng = np.random.RandomState(202)

#: fp slack on top of the declared certificate: bucket-boundary assignment can
#: shift one bucket on the ~1-ulp log rounding, costing at most ~α extra on the
#: two affected values; everything observed is far inside this
_CERT_SLACK = 1.10


def _leaves(value):
    return [np.asarray(x) for x in jax.tree.leaves(value)]


# ------------------------------------------------------------- QuantileSketch


@pytest.mark.parametrize("alpha", [0.05, 0.01])
@pytest.mark.parametrize(
    "sampler",
    [
        lambda n: _rng.lognormal(0.0, 2.0, n),
        lambda n: _rng.exponential(37.0, n) + 1e-3,
        lambda n: np.concatenate([_rng.lognormal(0, 1, n // 2), -_rng.lognormal(2, 1, n - n // 2)]),
    ],
    ids=["lognormal", "latency-like", "two-sided"],
)
def test_quantile_certified_relative_error(alpha, sampler):
    x = sampler(60_000).astype(np.float32)
    qs = (0.01, 0.25, 0.5, 0.9, 0.99, 0.999)
    sk = QuantileSketch(relative_error=alpha, quantiles=qs)
    sk.update(jnp.asarray(x))
    out = sk.compute()
    est, cert = np.asarray(out["quantiles"]), np.asarray(out["certified"])
    true = np.quantile(x, qs, method="lower")
    assert cert.all(), "in-range data must produce certified quantiles"
    rel = np.abs(est - true) / np.abs(true)
    assert (rel <= alpha * _CERT_SLACK).all(), f"relative errors {rel} exceed the α={alpha} certificate"


@pytest.mark.parametrize("dtype", [np.float32, np.float16, "bfloat16"])
def test_quantile_dtypes(dtype):
    x = _rng.lognormal(0.0, 1.0, 20_000).astype(np.float32)
    xj = jnp.asarray(x).astype(jnp.bfloat16 if dtype == "bfloat16" else dtype)
    sk = QuantileSketch(relative_error=0.02, quantiles=(0.5, 0.99))
    sk.update(xj)
    out = sk.compute()
    # oracle over the values the sketch actually saw (narrow dtypes round)
    true = np.quantile(np.asarray(xj, np.float32), (0.5, 0.99), method="lower")
    rel = np.abs(np.asarray(out["quantiles"]) - true) / true
    assert (rel <= 0.02 * _CERT_SLACK).all()
    assert np.asarray(out["certified"]).all()


def test_quantile_adversarial_values():
    sk = QuantileSketch(quantiles=(0.0, 0.5, 1.0))
    sk.update(jnp.asarray([np.inf, -np.inf, 0.0, -0.0, 1e-40, -1e-40, 1e38, -1e38, np.nan, 2.0]))
    out = sk.compute()
    est, cert = np.asarray(out["quantiles"]), np.asarray(out["certified"])
    assert int(sk.nan_count) == 1  # NaN tallied, excluded from ranks
    assert est[0] == -float(sk.max_value) and not cert[0]  # -inf: overflow bin, uncertified
    assert est[2] == float(sk.max_value) and not cert[2]  # +inf
    assert np.isfinite(est).all()
    # exact zeros are certified with zero error (denormals flush into the zero
    # class on this backend's float pipeline, like the rank engine documents)
    mid_ok = cert[1] and abs(est[1]) <= float(sk.min_value)
    assert mid_ok


def test_quantile_empty_and_single():
    sk = QuantileSketch()
    out = sk.compute()
    assert np.isnan(np.asarray(out["quantiles"])).all()
    assert not np.asarray(out["certified"]).any()
    sk.update(jnp.asarray([42.0]))
    out = sk.compute()
    assert (np.abs(np.asarray(out["quantiles"]) - 42.0) / 42.0 <= 0.01 * _CERT_SLACK).all()


def test_quantile_merge_orderings_match_concat():
    chunks = [
        _rng.lognormal(0, 1, 5000).astype(np.float32),
        _rng.lognormal(2, 1, 3000).astype(np.float32),
        -_rng.lognormal(1, 1, 4000).astype(np.float32),
        _rng.exponential(5.0, 2000).astype(np.float32),
    ]
    whole = QuantileSketch()
    whole.update(jnp.asarray(np.concatenate(chunks)))

    def sketch_of(c):
        s = QuantileSketch()
        s.update(jnp.asarray(c))
        return s

    # left fold, right fold, and pairwise tree must all equal the single stream
    for order in ([0, 1, 2, 3], [3, 2, 1, 0], [2, 0, 3, 1]):
        acc = sketch_of(chunks[order[0]])
        for i in order[1:]:
            acc.merge(sketch_of(chunks[i]))
        for state in ("pos_buckets", "neg_buckets", "edge_counts", "nan_count"):
            np.testing.assert_array_equal(
                np.asarray(getattr(acc, state)), np.asarray(getattr(whole, state)),
                err_msg=f"merge order {order}, state {state}",
            )
        np.testing.assert_array_equal(
            np.asarray(acc.compute()["quantiles"]), np.asarray(whole.compute()["quantiles"])
        )


# -------------------------------------------------------------- DistinctCount


@pytest.mark.parametrize("p", [10, 12])
@pytest.mark.parametrize("true_n", [500, 20_000, 300_000])
def test_hll_within_three_sigma(p, true_n):
    vals = np.arange(true_n, dtype=np.int64) * 2654435761 % (1 << 31)  # distinct, scattered
    stream = np.concatenate([vals, vals[: true_n // 2]]).astype(np.int32)  # duplicates too
    dc = DistinctCount(p=p)
    dc.update(jnp.asarray(stream))
    est = float(dc.compute())
    sigma = 1.04 / np.sqrt(1 << p)
    assert abs(est - true_n) / true_n <= 3 * sigma, (
        f"p={p} n={true_n}: estimate {est:.0f} off by {abs(est - true_n) / true_n:.4f}"
        f" > 3σ={3 * sigma:.4f}"
    )


def test_hll_float_inputs_and_dtype_consistency():
    vals = _rng.rand(10_000).astype(np.float32)
    a, b = DistinctCount(), DistinctCount()
    a.update(jnp.asarray(vals))
    # bf16 widens exactly into f32: counting the bf16-rounded values directly
    # or their f32 widening must hash identically
    bf = jnp.asarray(vals).astype(jnp.bfloat16)
    b.update(bf)
    c = DistinctCount()
    c.update(bf.astype(jnp.float32))
    np.testing.assert_array_equal(np.asarray(b.registers), np.asarray(c.registers))
    true_bf = len(np.unique(np.asarray(bf.astype(jnp.float32))))
    assert abs(float(b.compute()) - true_bf) / true_bf <= 3 * 1.04 / np.sqrt(1 << 12)


def test_hll_zero_negzero_collapse_and_empty():
    a = DistinctCount()
    a.update(jnp.asarray([0.0, -0.0]))
    assert int(np.sum(np.asarray(a.registers) > 0)) == 1  # one distinct value
    assert float(DistinctCount().compute()) == 0.0


def test_hll_merge_bit_identical_any_order():
    chunks = [_rng.randint(0, 40_000, 30_000).astype(np.int32) for _ in range(3)]
    whole = DistinctCount()
    whole.update(jnp.asarray(np.concatenate(chunks)))
    for order in ([0, 1, 2], [2, 0, 1], [1, 2, 0]):
        acc = DistinctCount()
        for i in order:
            part = DistinctCount()
            part.update(jnp.asarray(chunks[i]))
            acc.merge(part)
        np.testing.assert_array_equal(np.asarray(acc.registers), np.asarray(whole.registers))
        assert float(acc.compute()) == float(whole.compute())


def test_hll_seed_mismatch_is_callers_contract():
    # same data, different seeds -> different registers (the docs' "share the
    # seed to merge" rule has observable teeth)
    a, b = DistinctCount(seed=0), DistinctCount(seed=1)
    data = jnp.arange(1000)
    a.update(data)
    b.update(data)
    assert not np.array_equal(np.asarray(a.registers), np.asarray(b.registers))


# ------------------------------------------------------------- HistogramDrift


def test_drift_divergences_match_scipy():
    scipy_stats = pytest.importorskip("scipy.stats")
    ref = _rng.beta(2, 2, 30_000).astype(np.float32)
    live = _rng.beta(2, 5, 30_000).astype(np.float32)
    hd = HistogramDrift(num_bins=32)
    hd.update(jnp.asarray(ref), reference=True)
    hd.update(jnp.asarray(live))
    out = {k: float(v) for k, v in hd.compute().items()}

    # oracle: same binning, Jeffreys smoothing, scipy entropy for the KL
    bins = np.concatenate([[-np.inf], np.linspace(0, 1, 33), [np.inf]])
    href = np.histogram(ref, bins)[0].astype(np.float64)
    hlive = np.histogram(live, bins)[0].astype(np.float64)
    p = (hlive + 0.5) / (hlive.sum() + 0.5 * len(hlive))
    q = (href + 0.5) / (href.sum() + 0.5 * len(href))
    np.testing.assert_allclose(out["kl"], scipy_stats.entropy(p, q), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out["psi"], np.sum((p - q) * np.log(p / q)), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        out["tv"], 0.5 * np.abs(hlive / hlive.sum() - href / href.sum()).sum(), rtol=1e-5, atol=1e-6
    )


def test_drift_identical_distributions_near_zero():
    x = _rng.rand(20_000).astype(np.float32)
    hd = HistogramDrift()
    hd.update(jnp.asarray(x), reference=True)
    hd.update(jnp.asarray(x))
    out = {k: float(v) for k, v in hd.compute().items()}
    assert out["tv"] == 0.0 and out["kl"] < 1e-6 and abs(out["psi"]) < 1e-6


def test_drift_out_of_range_and_window_reset():
    hd = HistogramDrift(num_bins=8, low=0.0, high=1.0)
    hd.update(jnp.asarray([-5.0, -np.inf, 0.5, np.inf, 7.0, np.nan]), reference=True)
    ref = np.asarray(hd.ref_hist)
    assert ref[0] == 2 and ref[-1] == 2 and ref.sum() == 5  # NaN dropped, ±inf in edge bins
    hd.update(jnp.asarray([0.9, 0.9]))
    assert np.asarray(hd.live_hist).sum() == 2
    hd.reset_live()
    assert np.asarray(hd.live_hist).sum() == 0
    assert np.asarray(hd.ref_hist).sum() == 5  # reference survives the window slide


def test_drift_merge_matches_concat():
    r1, r2 = _rng.rand(4000).astype(np.float32), _rng.rand(4000).astype(np.float32)
    l1, l2 = (_rng.rand(4000) ** 2).astype(np.float32), (_rng.rand(4000) ** 2).astype(np.float32)
    a, b = HistogramDrift(), HistogramDrift()
    a.update(jnp.asarray(r1), reference=True)
    a.update(jnp.asarray(l1))
    b.update(jnp.asarray(r2), reference=True)
    b.update(jnp.asarray(l2))
    whole = HistogramDrift()
    whole.update(jnp.asarray(np.concatenate([r1, r2])), reference=True)
    whole.update(jnp.asarray(np.concatenate([l1, l2])))
    a.merge(b)
    np.testing.assert_array_equal(np.asarray(a.ref_hist), np.asarray(whole.ref_hist))
    np.testing.assert_array_equal(np.asarray(a.live_hist), np.asarray(whole.live_hist))
    for k in ("kl", "psi", "tv"):
        assert float(a.compute()[k]) == float(whole.compute()[k])


# -------------------------------------------------------- StreamingAUROCBound


def _exact_auroc_ap(preds, target):
    from metrics_tpu.ops.clf_curve import binary_auroc_exact, binary_average_precision_exact

    return (
        float(binary_auroc_exact(jnp.asarray(preds), jnp.asarray(target))),
        float(binary_average_precision_exact(jnp.asarray(preds), jnp.asarray(target))),
    )


@pytest.mark.parametrize(
    ("skew", "max_auroc_width", "max_ap_width"),
    # AP's bracket widens when positives are rare: the top-rank precisions
    # that dominate AP are exactly the within-bucket orderings the histogram
    # lost. AUROC's bracket only carries pair mass, so it stays tight.
    [(0.5, 0.06, 0.09), (0.05, 0.06, 0.25)],
    ids=["balanced", "rare-positives"],
)
def test_streaming_auroc_bracket_contains_exact(skew, max_auroc_width, max_ap_width):
    n = 60_000
    preds = _rng.rand(n).astype(np.float32)
    target = (_rng.rand(n) < preds * skew * 2).astype(np.int32)
    m = StreamingAUROCBound(bits=12)
    # stream in batches — the accumulating path, not one-shot
    for lo in range(0, n, 7_000):
        m.update(jnp.asarray(preds[lo : lo + 7_000]), jnp.asarray(target[lo : lo + 7_000]))
    out = {k: float(v) for k, v in m.compute().items()}
    ex_auroc, ex_ap = _exact_auroc_ap(preds, target)
    eps = 1e-5
    assert out["auroc_lower"] - eps <= ex_auroc <= out["auroc_upper"] + eps
    assert out["ap_lower"] - eps <= ex_ap <= out["ap_upper"] + eps
    # continuous uniform scores: bucketing is per-BINADE (2^(bits-9) buckets
    # per binade), and half of U[0,1) mass sits in [0.5, 1) — one binade, 8
    # sub-buckets at bits=12 — so the predicted same-bucket pair fraction is
    # ~0.03, not the 1/2^bits a uniform-key intuition suggests (the class
    # docstring carries this caveat).
    assert out["auroc_upper"] - out["auroc_lower"] < max_auroc_width
    assert out["ap_upper"] - out["ap_lower"] < max_ap_width


def test_streaming_auroc_quantized_domain_collapses_to_exact():
    # a score domain whose distinct values never share a bucket (here: 64
    # powers of two — one exponent each, and the top 12 key bits contain the
    # full exponent) -> residual same-bucket mass is true ties, which score
    # exactly 1/2, so the midpoint IS the exact AUROC (rank_engine docs)
    n = 50_000
    preds = (2.0 ** -_rng.randint(0, 64, n)).astype(np.float32)
    target = (_rng.rand(n) < preds ** 0.05).astype(np.int32)
    m = StreamingAUROCBound(bits=12)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    out = {k: float(v) for k, v in m.compute().items()}
    ex_auroc, _ = _exact_auroc_ap(preds, target)
    np.testing.assert_allclose(out["auroc_mid"], ex_auroc, rtol=2e-5, atol=2e-6)


def test_streaming_auroc_degenerate_single_class():
    m = StreamingAUROCBound()
    m.update(jnp.asarray([0.1, 0.9, 0.5]), jnp.asarray([1, 1, 1]))
    out = {k: float(v) for k, v in m.compute().items()}
    assert out["auroc_lower"] == out["auroc_upper"] == 0.0  # documented degenerate
    empty = {k: float(v) for k, v in StreamingAUROCBound().compute().items()}
    assert all(v == 0.0 for v in empty.values())


def test_streaming_auroc_merge_bit_identical():
    n = 30_000
    preds = _rng.rand(n).astype(np.float32)
    target = _rng.randint(0, 2, n).astype(np.int32)
    a, b = StreamingAUROCBound(), StreamingAUROCBound()
    a.update(jnp.asarray(preds[: n // 2]), jnp.asarray(target[: n // 2]))
    b.update(jnp.asarray(preds[n // 2 :]), jnp.asarray(target[n // 2 :]))
    whole = StreamingAUROCBound()
    whole.update(jnp.asarray(preds), jnp.asarray(target))
    a.merge(b)
    np.testing.assert_array_equal(np.asarray(a.pos_hist), np.asarray(whole.pos_hist))
    np.testing.assert_array_equal(np.asarray(a.neg_hist), np.asarray(whole.neg_hist))
    for k, v in a.compute().items():
        assert float(v) == float(whole.compute()[k])


def test_ap_bound_psi_diff_stability_at_stream_scale():
    """The ψ-difference AP form must stay accurate where a naive digamma
    difference catastrophically cancels (prefix counts ~1e7)."""
    from metrics_tpu.ops.rank import average_precision_bounds_from_hists

    pos = np.zeros(4096, np.int32)
    neg = np.zeros(4096, np.int32)
    # 10M negatives ranked first, then interleaved tail — prefix counts hit 1e7
    neg[:100] = 100_000
    pos[100:200] = 5_000
    neg[100:200] = 5_000
    lo, hi = average_precision_bounds_from_hists(jnp.asarray(pos), jnp.asarray(neg))
    lo, hi = float(lo), float(hi)
    # brute-force oracle on the worst/best arrangements (f64)
    def arrangement_ap(pos_first):
        total_p = pos.sum()
        ap = 0.0
        p_prev = n_prev = 0
        for b in range(4096):
            pb, nb = int(pos[b]), int(neg[b])
            if pb:
                k = n_prev + (0 if pos_first else nb)
                i = np.arange(1, pb + 1, dtype=np.float64)
                ap += np.sum((p_prev + i) / (p_prev + k + i))
            p_prev += pb
            n_prev += nb
        return ap / total_p

    np.testing.assert_allclose(lo, arrangement_ap(False), rtol=1e-4)
    np.testing.assert_allclose(hi, arrangement_ap(True), rtol=1e-4)
    assert lo <= hi


# --------------------------------------------------------- mesh merge = psum


def test_mesh_collective_merge_is_the_sketch_merge():
    """The headline claim: psum/pmax over a mesh axis IS the sketch merge.

    HLL registers are cross-program stable (integer hashing), so the mesh-pmax
    state must equal single-stream ingestion bit-identically. QuantileSketch's
    bucket assignment is float (deterministic per executable), so its mesh-psum
    state is compared against SAME-PROGRAM per-shard ingestion merged on host —
    also bit-identical (the docs' precise form of the claim)."""
    from functools import partial

    from metrics_tpu.parallel.collective import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    world = len(jax.devices())
    assert world >= 2, "conftest forces 8 virtual host devices"
    mesh = Mesh(np.array(jax.devices()), ("hosts",))

    ids = jnp.asarray(_rng.randint(0, 30_000, (world, 8_000)).astype(np.int32))
    lat = jnp.asarray(_rng.lognormal(0, 1, (world, 8_000)).astype(np.float32))

    for metric, data in ((DistinctCount(), ids), (QuantileSketch(), lat)):
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=P("hosts"), out_specs=P())
        def synced_state(x, _m=metric):
            return _m.sync_state(_m.local_update(_m.init_state(), x[0]), axis_name="hosts")

        synced = synced_state(data)
        if isinstance(metric, DistinctCount):
            oracle = DistinctCount()
            oracle.update(data.reshape(-1))
            want = {"registers": np.asarray(oracle.registers)}
        else:
            upd = jax.jit(lambda s, x, _m=metric: _m.local_update(s, x))
            shard_states = [upd(metric.init_state(), data[i]) for i in range(world)]
            want = {k: sum(np.asarray(s[k]) for s in shard_states) for k in shard_states[0]}
        for k, v in want.items():
            np.testing.assert_array_equal(np.asarray(synced[k]), v, err_msg=f"{type(metric).__name__}.{k}")


# ------------------------------------------------------------- family contract


def test_sketch_base_rejects_float_state_and_bad_reduce():
    class _BadDtype(SketchMetric):
        def __init__(self):
            super().__init__()
            self.add_sketch_state("x", jnp.zeros((4,), jnp.float32), "sum")

        def update(self):  # pragma: no cover - never reached
            pass

        def compute(self):  # pragma: no cover
            pass

    with pytest.raises(MetricsUserError, match="integer"):
        _BadDtype()

    class _BadReduce(SketchMetric):
        def __init__(self):
            super().__init__()
            self.add_sketch_state("x", jnp.zeros((4,), jnp.int32), "cat")

        def update(self):  # pragma: no cover
            pass

        def compute(self):  # pragma: no cover
            pass

    with pytest.raises(MetricsUserError, match="mergeable"):
        _BadReduce()


def test_merge_rejects_cross_class_and_counts_updates():
    a, b = DistinctCount(), QuantileSketch()
    with pytest.raises(MetricsUserError, match="same class"):
        a.merge(b)
    c, d = DistinctCount(), DistinctCount()
    c.update(jnp.arange(10))
    d.update(jnp.arange(10))
    d.update(jnp.arange(5))
    c.merge(d)
    assert c._update_count == 3  # merge carries the peer's update count


def test_state_bytes_reports_fixed_cost():
    assert DistinctCount(p=12).state_bytes() == 4096
    qs = QuantileSketch(bits=11)
    assert qs.state_bytes() == 2 * 2048 * 4 + 5 * 4 + 4
    # and it never grows with data — the whole point of a sketch
    qs.update(jnp.asarray(_rng.rand(100_000).astype(np.float32)))
    assert qs.state_bytes() == 2 * 2048 * 4 + 5 * 4 + 4
