"""Bucketed rank engine (ops/rank.py): adversarial bit-parity vs the lax.sort
oracle, the key bijection's total-order contract, bucket pair-count machinery,
sort-slimming helpers, and dispatch/obs behavior.

The load-bearing property: for EVERY adversarial input class, the rank tier's
AUROC/AP must equal the f32 oracle tier BIT-FOR-BIT (``==`` on the f32 result,
NaN matching NaN) — the tiers share the float tail, so this reduces to the
integer (fps, tps) construction and the reconstructed sort keys being
identical, which is asserted directly too.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.ops import clf_curve as cc
from metrics_tpu.ops import rank

_rng = np.random.RandomState(1234)
_TINY = np.finfo(np.float32).tiny


def _labels(n, p=0.4, seed=None):
    r = _rng if seed is None else np.random.RandomState(seed)
    return (r.rand(n) < p).astype(np.int32)


# every entry: name -> (preds, target); the suite demands bit-parity on each
ADVERSARIAL = {
    "random": (_rng.rand(777).astype(np.float32), _labels(777)),
    "tie_heavy": ((_rng.randint(0, 5, 1500) / 4.0).astype(np.float32), _labels(1500)),
    "all_equal": (np.full(300, 0.25, np.float32), _labels(300)),
    "two_values": (np.where(_rng.rand(512) < 0.5, 0.1, 0.9).astype(np.float32), _labels(512)),
    "pm_inf": (
        np.where(_rng.rand(600) < 0.2, np.inf, np.where(_rng.rand(600) < 0.2, -np.inf, _rng.randn(600))).astype(np.float32),
        _labels(600),
    ),
    "denormal": ((_rng.randn(500) * 1e-38).astype(np.float32), _labels(500)),
    "negative_zero": (
        np.where(_rng.rand(400) < 0.3, -0.0, np.where(_rng.rand(400) < 0.3, 0.0, _rng.randn(400))).astype(np.float32),
        _labels(400),
    ),
    "all_positive_labels": (_rng.rand(200).astype(np.float32), np.ones(200, np.int32)),
    "all_negative_labels": (_rng.rand(200).astype(np.float32), np.zeros(200, np.int32)),
    "extreme_magnitudes": (
        np.concatenate([[np.finfo(np.float32).max, -np.finfo(np.float32).max, _TINY, -_TINY, 0.0, -0.0],
                        _rng.randn(250).astype(np.float32) * 1e30]).astype(np.float32),
        _labels(256),
    ),
}
# ignore_index padding: negative targets are excluded rows
_pads = _labels(800)
_pads[_rng.rand(800) < 0.25] = -1
ADVERSARIAL["ignore_index"] = (_rng.randn(800).astype(np.float32), _pads)


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.array_equal(a, b, equal_nan=True) and np.array_equal(np.signbit(a), np.signbit(b))


# ------------------------------------------------------------- key bijection


def test_bijection_is_order_preserving_and_invertible():
    vals = np.unique(np.concatenate([
        _rng.randn(2000).astype(np.float32) * np.exp(_rng.randn(2000) * 20).astype(np.float32),
        np.array([0.0, 1.0, -1.0, np.inf, -np.inf, _TINY, -_TINY,
                  np.finfo(np.float32).max, -np.finfo(np.float32).max], np.float32),
    ]))
    keys = np.asarray(rank.monotone_key_descending(jnp.asarray(vals)))
    # descending floats -> strictly ascending u32 keys
    assert (np.diff(keys.astype(np.int64)[np.argsort(-vals)]) > 0).all()
    inv = np.asarray(rank.key_to_f32_descending(jnp.asarray(keys)))
    assert _bitwise_equal(inv, vals)


def test_bijection_collapses_the_flushed_zero_class():
    # XLA's sort comparator flushes denormals on CPU and TPU: the oracle treats
    # {±0, ±denormal} as ONE tie run, so they must share one key (+0.0's)
    z = np.array([0.0, -0.0, 1e-40, -1e-40, _TINY / 2], np.float32)
    keys = np.asarray(rank.monotone_key_descending(jnp.asarray(z)))
    assert (keys == keys[0]).all()
    inv = np.asarray(rank.key_to_f32_descending(jnp.asarray(keys)))
    assert (inv == 0.0).all() and not np.signbit(inv).any()
    # smallest NORMAL stays distinct from the zero class
    kt = np.asarray(rank.monotone_key_descending(jnp.asarray(np.array([_TINY], np.float32))))
    assert kt[0] != keys[0]


def test_invalid_rows_share_the_neg_inf_run():
    p = np.array([0.5, -np.inf, 0.1], np.float32)
    keys = np.asarray(rank.monotone_key_descending(jnp.asarray(p), jnp.asarray([True, True, False])))
    assert keys[1] == keys[2] == np.uint32(rank.NEG_INF_KEY)


# ------------------------------------------------- adversarial tier bit-parity


@pytest.mark.parametrize("case", sorted(ADVERSARIAL))
def test_run_end_counts_bit_identical(case):
    preds, target = ADVERSARIAL[case]
    p, t = jnp.asarray(preds), jnp.asarray(target)
    valid = t >= 0
    oracle = cc._run_end_counts(p, t, valid, tier="sort")
    ranked = cc._run_end_counts(p, t, valid, tier="rank")
    for name, a, b in zip(("fps", "tps", "boundary"), oracle[:2] + oracle[3:], ranked[:2] + ranked[3:]):
        assert _bitwise_equal(a, b), f"{case}: {name} diverged"
    # sk: numerically equal everywhere; bitwise equal OUTSIDE the flushed-zero
    # class, where the rank tier canonicalizes {-0, ±denormal} to +0.0 (this is
    # exactly why the curve-shaped outputs keep the oracle tier — their
    # thresholds surface sk to users)
    sk_o, sk_r = np.asarray(oracle[2]), np.asarray(ranked[2])
    flushed = np.abs(sk_o) < np.finfo(np.float32).tiny  # ±0 and ±denormals
    assert _bitwise_equal(sk_o[~flushed], sk_r[~flushed]), f"{case}: sk diverged outside zero class"
    assert (sk_r[flushed] == 0.0).all() and not np.signbit(sk_r[flushed]).any()


@pytest.mark.parametrize("case", sorted(ADVERSARIAL))
def test_auroc_and_ap_bit_identical(case):
    preds, target = ADVERSARIAL[case]
    p, t = jnp.asarray(preds), jnp.asarray(target)
    with rank.force_tier("sort"):
        s = (cc.binary_auroc_exact(p, t), cc.binary_average_precision_exact(p, t),
             cc.binary_auroc_exact(p, t, max_fpr=0.5))
    with rank.force_tier("rank"):
        r = (cc.binary_auroc_exact(p, t), cc.binary_average_precision_exact(p, t),
             cc.binary_auroc_exact(p, t, max_fpr=0.5))
    for name, a, b in zip(("auroc", "ap", "partial_auroc"), s, r):
        assert _bitwise_equal(a, b), f"{case}: {name} diverged"


def test_multiclass_and_multilabel_tiers_bit_identical():
    probs = _rng.rand(300, 5).astype(np.float32)
    tmc = _rng.randint(0, 5, 300).astype(np.int32)
    tml = _rng.randint(0, 2, (300, 5)).astype(np.int32)
    for fn, tgt in (
        (cc.multiclass_auroc_exact, tmc),
        (cc.multiclass_average_precision_exact, tmc),
        (cc.multilabel_auroc_exact, tml),
        (cc.multilabel_average_precision_exact, tml),
    ):
        with rank.force_tier("sort"):
            rs, ws = fn(jnp.asarray(probs), jnp.asarray(tgt))
        with rank.force_tier("rank"):
            rr, wr = fn(jnp.asarray(probs), jnp.asarray(tgt))
        assert _bitwise_equal(rs, rr) and _bitwise_equal(ws, wr), fn.__name__


def test_jit_and_vmap_compose_with_the_rank_tier():
    p = jnp.asarray(_rng.rand(256).astype(np.float32))
    t = jnp.asarray(_labels(256))
    f = jax.jit(lambda p, t: cc._binary_auroc_kernel(p, t, t >= 0, None, tier="rank"))
    g = jax.jit(lambda p, t: cc._binary_auroc_kernel(p, t, t >= 0, None, tier="sort"))
    assert _bitwise_equal(f(p, t), g(p, t))


# ------------------------------------------------------- bucket histogram side


def test_bucket_counts_totals_and_reference():
    preds = _rng.rand(4096).astype(np.float32)
    keys = rank.monotone_key_descending(jnp.asarray(preds))
    for bits in (4, 8, 12):
        h = np.asarray(rank.bucket_counts(keys, bits))
        assert h.shape == (1 << bits,) and h.sum() == 4096
        ref = np.bincount(np.asarray(keys) >> (32 - bits), minlength=1 << bits)
        assert np.array_equal(h, ref)


def test_cross_bucket_pair_stats_vs_bruteforce():
    preds = _rng.rand(200).astype(np.float32)
    target = _labels(200)
    keys = rank.monotone_key_descending(jnp.asarray(preds))
    bits = 6
    pos_h, neg_h = rank.class_bucket_counts(keys, jnp.asarray(target) == 1, jnp.ones(200, bool), bits)
    cross, same = rank.cross_bucket_pair_stats(pos_h, neg_h)
    b = np.asarray(keys) >> (32 - bits)
    pos_b, neg_b = b[target == 1], b[target == 0]
    brute_cross = sum(int((neg_b > pb).sum()) for pb in pos_b)  # lower bucket == higher score
    brute_same = sum(int((neg_b == pb).sum()) for pb in pos_b)
    assert int(cross) == brute_cross and int(same) == brute_same


def test_bucketed_auroc_bounds_bracket_the_exact_value():
    preds = _rng.rand(8192).astype(np.float32)
    target = _labels(8192, 0.3)
    exact = float(cc.binary_auroc_exact(jnp.asarray(preds), jnp.asarray(target)))
    lo, hi = rank.bucketed_auroc_bounds(jnp.asarray(preds), jnp.asarray(target), bits=12)
    assert float(lo) - 1e-6 <= exact <= float(hi) + 1e-6
    # quantized domain: <= 2^bits distinct scores -> the residual same-bucket
    # mass is pure ties, so the bracket MIDPOINT is the exact AUROC
    q = (_rng.randint(0, 16, 2048) / 16.0).astype(np.float32)
    tq = _labels(2048)
    lo_q, hi_q = rank.bucketed_auroc_bounds(jnp.asarray(q), jnp.asarray(tq), bits=12)
    exact_q = float(cc.binary_auroc_exact(jnp.asarray(q), jnp.asarray(tq)))
    assert float(lo_q) - 1e-6 <= exact_q <= float(hi_q) + 1e-6
    assert abs((float(lo_q) + float(hi_q)) / 2 - exact_q) < 1e-5


# ------------------------------------------------------- sort-slim helpers


def test_ranked_targets_matches_argsort_gather():
    for seed in range(3):
        r = np.random.RandomState(seed)
        preds = (r.randint(0, 7, 400) / 7.0).astype(np.float32)  # heavy ties
        target = r.randint(0, 5, 400).astype(np.int32)
        ref = target[np.argsort(-preds, kind="stable")]
        got = np.asarray(rank.ranked_targets(jnp.asarray(preds), jnp.asarray(target)))
        assert np.array_equal(got, ref)


def test_stable_front_pack_matches_argsort_take():
    mask = _rng.rand(500) < 0.4
    cols = [_rng.rand(500).astype(np.float32) for _ in range(3)]
    order = np.argsort(~mask, kind="stable")
    got = rank.stable_front_pack(jnp.asarray(mask), *(jnp.asarray(c) for c in cols))
    for g, c in zip(got, cols):
        assert np.array_equal(np.asarray(g), c[order])


# ----------------------------------------------------------- dispatch + obs


def test_dispatch_defaults_to_oracle_on_cpu_and_force_overrides():
    x = jnp.zeros((1 << 10,), jnp.float32)
    assert rank.select_tier(x) == "sort"  # CPU backend: oracle regardless of size
    with rank.force_tier("rank"):
        assert rank.select_tier(x) == "rank"
        with rank.force_tier("sort"):
            assert rank.select_tier(x) == "sort"
        assert rank.select_tier(x) == "rank"
    assert rank.select_tier(x) == "sort"
    with pytest.raises(ValueError):
        with rank.force_tier("bogus"):
            pass


def test_dispatch_counters_and_scopes_visible_in_obs():
    from metrics_tpu import obs
    from metrics_tpu.obs import export

    p = jnp.asarray(_rng.rand(128).astype(np.float32))
    t = jnp.asarray(_labels(128))
    with obs.observe(clear=True) as reg:
        with rank.force_tier("rank"):
            cc.binary_auroc_exact(p, t)
        cc.binary_average_precision_exact(p, t)  # auto -> sort on CPU
        snap = export.snapshot()
    assert reg.get("rank", "dispatch/rank") == 1
    assert reg.get("rank", "dispatch/sort") == 1
    assert reg.get("rank", "op/binary_auroc") == 1
    assert snap["registry"]["rank"]["dispatch/rank"] == 1
    assert snap["registry"]["scopes"]["tm.rank/rank"] == 1


def test_disabled_obs_records_nothing():
    from metrics_tpu.obs import registry as reg

    reg.REGISTRY.clear()
    with rank.force_tier("rank"):
        cc.binary_auroc_exact(jnp.asarray(_rng.rand(64).astype(np.float32)), jnp.asarray(_labels(64)))
    assert reg.REGISTRY.get("rank", "dispatch/rank") == 0


# ------------------------------------------------- metric classes x both tiers


@pytest.mark.parametrize("cls_name,ctor,args_fn", [
    ("BinaryAUROC", {}, lambda: (_rng.rand(96).astype(np.float32), _labels(96))),
    ("BinaryAveragePrecision", {}, lambda: (_rng.rand(96).astype(np.float32), _labels(96))),
    ("MulticlassAUROC", {"num_classes": 4},
     lambda: (_rng.rand(96, 4).astype(np.float32), _rng.randint(0, 4, 96).astype(np.int32))),
    ("MulticlassAveragePrecision", {"num_classes": 4},
     lambda: (_rng.rand(96, 4).astype(np.float32), _rng.randint(0, 4, 96).astype(np.int32))),
    ("MultilabelAUROC", {"num_labels": 3},
     lambda: (_rng.rand(96, 3).astype(np.float32), _rng.randint(0, 2, (96, 3)).astype(np.int32))),
    ("MultilabelAveragePrecision", {"num_labels": 3},
     lambda: (_rng.rand(96, 3).astype(np.float32), _rng.randint(0, 2, (96, 3)).astype(np.int32))),
])
def test_metric_classes_agree_across_dispatch_tiers(cls_name, ctor, args_fn):
    """The contract-sweep hook: every AUROC/AP metric class must compute the
    same value whichever rank-engine tier serves its exact-mode kernel."""
    import metrics_tpu

    cls = getattr(metrics_tpu, cls_name)
    args = args_fn()
    vals = {}
    for tier in ("sort", "rank"):
        m = cls(**ctor, validate_args=False)
        with rank.force_tier(tier):
            m.update(*(jnp.asarray(a) for a in args))
            vals[tier] = np.asarray(m.compute())
    assert _bitwise_equal(vals["sort"], vals["rank"]), cls_name
