"""Differential tests for accuracy vs sklearn (reference: tests/unittests/classification/test_accuracy.py)."""
import numpy as np
import pytest
from scipy.special import expit, softmax
from sklearn.metrics import accuracy_score, confusion_matrix

from metrics_tpu.classification import Accuracy, BinaryAccuracy, MulticlassAccuracy, MultilabelAccuracy
from metrics_tpu.functional.classification import binary_accuracy, multiclass_accuracy, multilabel_accuracy

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers import seed_all  # noqa: E402
from helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, THRESHOLD, MetricTester  # noqa: E402

seed_all(42)

_rng = np.random.default_rng(42)
_binary_prob = (_rng.random((NUM_BATCHES, BATCH_SIZE)), _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_binary_logits = (_rng.normal(size=(NUM_BATCHES, BATCH_SIZE)) * 3, _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_binary_labels = (_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)), _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_mc_probs = (
    softmax(_rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)), axis=-1),
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_mc_labels = (
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_ml_probs = (
    _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _ref_binary_accuracy(preds, target):
    preds = np.asarray(preds)
    if preds.dtype.kind == "f":
        if not ((preds >= 0) & (preds <= 1)).all():
            preds = expit(preds)
        preds = (preds > THRESHOLD).astype(int)
    return accuracy_score(target.ravel(), preds.ravel())


def _ref_multiclass_accuracy(average):
    def fn(preds, target):
        preds = np.asarray(preds)
        if preds.ndim == target.ndim + 1:
            preds = preds.argmax(1)
        preds, target = preds.ravel(), np.asarray(target).ravel()
        if average == "micro":
            return accuracy_score(target, preds)
        cm = confusion_matrix(target, preds, labels=np.arange(NUM_CLASSES))
        support = cm.sum(1)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_class = np.where(support == 0, 0.0, cm.diagonal() / np.maximum(support, 1))
        if average == "macro":
            return per_class.mean()
        if average == "weighted":
            return (per_class * support / support.sum()).sum()
        return per_class

    return fn


def _ref_multilabel_accuracy(average):
    def fn(preds, target):
        preds = np.asarray(preds)
        if preds.dtype.kind == "f":
            if not ((preds >= 0) & (preds <= 1)).all():
                preds = expit(preds)
            preds = (preds > THRESHOLD).astype(int)
        target = np.asarray(target)
        preds = preds.reshape(-1, preds.shape[1]) if preds.ndim == 2 else preds.reshape(preds.shape[0], preds.shape[1], -1).transpose(0, 2, 1).reshape(-1, preds.shape[1])
        target = target.reshape(-1, target.shape[1]) if target.ndim == 2 else target.reshape(target.shape[0], target.shape[1], -1).transpose(0, 2, 1).reshape(-1, target.shape[1])
        correct = preds == target
        per_label = correct.mean(0)
        if average == "micro":
            return correct.mean()
        if average == "macro":
            return per_label.mean()
        return per_label

    return fn


class TestBinaryAccuracy(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("inputs", [_binary_prob, _binary_logits, _binary_labels])
    def test_class(self, inputs):
        preds, target = inputs
        self.run_class_metric_test(preds, target, BinaryAccuracy, _ref_binary_accuracy, sharded=True)

    @pytest.mark.parametrize("inputs", [_binary_prob, _binary_logits, _binary_labels])
    def test_functional(self, inputs):
        preds, target = inputs
        self.run_functional_metric_test(preds, target, binary_accuracy, _ref_binary_accuracy)


class TestMulticlassAccuracy(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    @pytest.mark.parametrize("inputs", [_mc_probs, _mc_labels])
    def test_class(self, inputs, average):
        preds, target = inputs
        self.run_class_metric_test(
            preds,
            target,
            MulticlassAccuracy,
            _ref_multiclass_accuracy(average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            sharded=True,
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    @pytest.mark.parametrize("inputs", [_mc_probs, _mc_labels])
    def test_functional(self, inputs, average):
        preds, target = inputs
        self.run_functional_metric_test(
            preds,
            target,
            multiclass_accuracy,
            _ref_multiclass_accuracy(average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )

    def test_ignore_index(self):
        preds, target = _mc_labels
        target = np.where(target == 0, -1, target)
        res = multiclass_accuracy(preds[0], target[0], num_classes=NUM_CLASSES, average="micro", ignore_index=-1)
        mask = target[0] != -1
        expected = accuracy_score(target[0][mask], preds[0][mask])
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_top_k(self):
        preds, target = _mc_probs
        res = multiclass_accuracy(preds[0], target[0], num_classes=NUM_CLASSES, average="micro", top_k=2)
        topk = np.argsort(-preds[0], axis=1)[:, :2]
        expected = np.mean([t in row for t, row in zip(target[0], topk)])
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_samplewise(self):
        rng = np.random.default_rng(1)
        preds = rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM := 3))
        target = rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))
        res = multiclass_accuracy(
            preds[0], target[0], num_classes=NUM_CLASSES, average="micro", multidim_average="samplewise"
        )
        expected = np.array([accuracy_score(t, p) for p, t in zip(preds[0], target[0])])
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


class TestMultilabelAccuracy(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("average", ["micro", "macro", None])
    def test_class(self, average):
        preds, target = _ml_probs
        self.run_class_metric_test(
            preds,
            target,
            MultilabelAccuracy,
            _ref_multilabel_accuracy(average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
            sharded=True,
        )

    @pytest.mark.parametrize("average", ["micro", "macro", None])
    def test_functional(self, average):
        preds, target = _ml_probs
        self.run_functional_metric_test(
            preds,
            target,
            multilabel_accuracy,
            _ref_multilabel_accuracy(average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
        )


def test_accuracy_dispatcher():
    assert isinstance(Accuracy(task="binary"), BinaryAccuracy)
    assert isinstance(Accuracy(task="multiclass", num_classes=3), MulticlassAccuracy)
    assert isinstance(Accuracy(task="multilabel", num_labels=3), MultilabelAccuracy)
    with pytest.raises(ValueError):
        Accuracy(task="unknown")
