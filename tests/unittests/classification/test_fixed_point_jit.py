"""Fixed-point curve metrics (recall@precision, precision@recall,
specificity@sensitivity) must compute INSIDE jit (round-5 lift: branchless
constrained-max reduce), matching the eager host-side selection exactly —
both paths operate on the same f32 curve values, so every comparison decides
identically and results must be bit-equal."""
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.classification import (
    BinaryPrecisionAtFixedRecall,
    BinaryRecallAtFixedPrecision,
    BinarySpecificityAtSensitivity,
    MulticlassRecallAtFixedPrecision,
    MulticlassSpecificityAtSensitivity,
    MultilabelPrecisionAtFixedRecall,
)

_rng = np.random.RandomState(7)


def _binary_batch(n=64):
    return jnp.asarray(_rng.rand(n).astype(np.float32)), jnp.asarray((_rng.rand(n) > 0.6).astype(np.int32))


def _mc_batch(n=64, c=4):
    p = _rng.rand(n, c).astype(np.float32)
    return jnp.asarray(p / p.sum(1, keepdims=True)), jnp.asarray(_rng.randint(0, c, n))


def _ml_batch(n=64, l=3):
    return jnp.asarray(_rng.rand(n, l).astype(np.float32)), jnp.asarray((_rng.rand(n, l) > 0.5).astype(np.int32))


CASES = [
    (BinaryRecallAtFixedPrecision, {"min_precision": 0.5}, _binary_batch),
    # 0.7 is not f32-representable: the traced compare must use the smallest
    # f32 >= 0.7 to match the eager float64 boundary decision exactly
    (BinaryRecallAtFixedPrecision, {"min_precision": 0.7}, _binary_batch),
    (BinarySpecificityAtSensitivity, {"min_sensitivity": 0.7}, _binary_batch),
    (BinaryRecallAtFixedPrecision, {"min_precision": 1.0}, _binary_batch),  # nothing qualifies -> (0, 1e6)
    (BinaryPrecisionAtFixedRecall, {"min_recall": 0.5}, _binary_batch),
    (BinarySpecificityAtSensitivity, {"min_sensitivity": 0.5}, _binary_batch),
    (MulticlassRecallAtFixedPrecision, {"num_classes": 4, "min_precision": 0.5}, _mc_batch),
    (MulticlassSpecificityAtSensitivity, {"num_classes": 4, "min_sensitivity": 0.5}, _mc_batch),
    (MultilabelPrecisionAtFixedRecall, {"num_labels": 3, "min_recall": 0.5}, _ml_batch),
]


@pytest.mark.parametrize("thresholds", [11, None], ids=["binned", "exact"])
@pytest.mark.parametrize("cls,kwargs,gen", CASES, ids=lambda c: getattr(c, "__name__", None))
def test_jit_compute_matches_eager(cls, kwargs, gen, thresholds):
    kw = dict(kwargs, thresholds=thresholds)
    if thresholds is None:
        kw["cat_capacity"] = 256  # exact mode under jit needs a static curve buffer
    metric = cls(**kw)
    batches = [gen() for _ in range(3)]

    state = metric.init_state()
    update = jax.jit(partial(metric.local_update))
    for p, t in batches:
        state = update(state, p, t)
    val_jit = jax.jit(metric.compute_from)(state)

    eager = cls(**dict(kwargs, thresholds=thresholds))
    for p, t in batches:
        eager.update(p, t)
    val_eager = eager.compute()

    for a, b in zip(jax.tree.leaves(val_jit), jax.tree.leaves(val_eager)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_boundary_cutoff_matches_eager_exactly():
    """A curve point landing exactly on the f32 grid value of a non-representable
    cutoff (7/10 vs min=0.7): jit and eager must make the same include/exclude
    decision (eager compares in f64, where f32(0.7) < 0.7)."""
    # 10 predictions above threshold 0.5, 7 of them positive -> precision exactly 0.7
    preds = jnp.asarray([0.9] * 10 + [0.1] * 4)
    target = jnp.asarray([1] * 7 + [0] * 3 + [1] * 2 + [0] * 2)
    for mp in (0.7, 0.7000000000000001, float(np.float32(0.7))):
        metric = BinaryRecallAtFixedPrecision(min_precision=mp, thresholds=[0.5])
        state = jax.jit(metric.local_update)(metric.init_state(), preds, target)
        jit_out = [float(x) for x in jax.jit(metric.compute_from)(state)]
        eager = BinaryRecallAtFixedPrecision(min_precision=mp, thresholds=[0.5])
        eager.update(preds, target)
        eager_out = [float(x) for x in eager.compute()]
        assert jit_out == eager_out, (mp, jit_out, eager_out)


def test_nothing_qualifies_sentinel_under_jit():
    metric = BinaryRecallAtFixedPrecision(min_precision=1.0, thresholds=5)
    p = jnp.asarray([0.9, 0.8, 0.7, 0.2])
    t = jnp.asarray([0, 0, 1, 1])  # high scores are all negatives: precision < 1 everywhere
    state = jax.jit(metric.local_update)(metric.init_state(), p, t)
    best, thr = jax.jit(metric.compute_from)(state)
    assert float(best) == 0.0
    assert float(thr) == 1e6
