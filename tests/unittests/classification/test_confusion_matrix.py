"""Differential tests for confusion matrix + derived metrics vs sklearn.

Mirrors reference tests/unittests/classification/{test_confusion_matrix,
test_cohen_kappa,test_jaccard,test_matthews_corrcoef}.py coverage.
"""
import numpy as np
import pytest
from scipy.special import expit
from sklearn.metrics import (
    cohen_kappa_score,
    confusion_matrix as sk_confusion_matrix,
    jaccard_score,
    matthews_corrcoef as sk_matthews_corrcoef,
    multilabel_confusion_matrix as sk_multilabel_confusion_matrix,
)

from metrics_tpu.classification import (
    BinaryConfusionMatrix,
    MulticlassCohenKappa,
    MulticlassConfusionMatrix,
    MulticlassMatthewsCorrCoef,
    MultilabelConfusionMatrix,
)
from metrics_tpu.functional.classification import (
    binary_cohen_kappa,
    binary_confusion_matrix,
    binary_jaccard_index,
    binary_matthews_corrcoef,
    multiclass_cohen_kappa,
    multiclass_confusion_matrix,
    multiclass_jaccard_index,
    multiclass_matthews_corrcoef,
    multilabel_confusion_matrix,
    multilabel_jaccard_index,
    multilabel_matthews_corrcoef,
)

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers import seed_all  # noqa: E402
from helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, THRESHOLD, MetricTester  # noqa: E402

seed_all(42)
_rng = np.random.default_rng(11)
_binary = (_rng.random((NUM_BATCHES, BATCH_SIZE)), _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_mc = (
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_ml = (
    _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _binarize(preds):
    preds = np.asarray(preds)
    if preds.dtype.kind == "f":
        if not ((preds >= 0) & (preds <= 1)).all():
            preds = expit(preds)
        preds = (preds > THRESHOLD).astype(int)
    return preds


def _ref_binary_cm(preds, target):
    return sk_confusion_matrix(target.ravel(), _binarize(preds).ravel(), labels=[0, 1])


def _ref_mc_cm(preds, target):
    return sk_confusion_matrix(target.ravel(), preds.ravel(), labels=np.arange(NUM_CLASSES))


def _ref_ml_cm(preds, target):
    return sk_multilabel_confusion_matrix(
        np.asarray(target).reshape(-1, NUM_CLASSES), _binarize(preds).reshape(-1, NUM_CLASSES)
    )


class TestConfusionMatrix(MetricTester):
    atol = 1e-6

    def test_binary(self):
        preds, target = _binary
        self.run_class_metric_test(preds, target, BinaryConfusionMatrix, _ref_binary_cm, sharded=True)
        self.run_functional_metric_test(preds, target, binary_confusion_matrix, _ref_binary_cm)

    def test_multiclass(self):
        preds, target = _mc
        self.run_class_metric_test(preds, target, MulticlassConfusionMatrix, _ref_mc_cm,
                                   metric_args={"num_classes": NUM_CLASSES}, sharded=True)
        self.run_functional_metric_test(preds, target, multiclass_confusion_matrix, _ref_mc_cm,
                                        metric_args={"num_classes": NUM_CLASSES})

    def test_multilabel(self):
        preds, target = _ml
        self.run_class_metric_test(preds, target, MultilabelConfusionMatrix, _ref_ml_cm,
                                   metric_args={"num_labels": NUM_CLASSES}, sharded=True)
        self.run_functional_metric_test(preds, target, multilabel_confusion_matrix, _ref_ml_cm,
                                        metric_args={"num_labels": NUM_CLASSES})

    @pytest.mark.parametrize("normalize", ["true", "pred", "all", "none"])
    def test_multiclass_normalize(self, normalize):
        preds, target = _mc
        res = multiclass_confusion_matrix(preds[0], target[0], num_classes=NUM_CLASSES, normalize=normalize)
        ref = sk_confusion_matrix(
            target[0], preds[0], labels=np.arange(NUM_CLASSES), normalize=normalize if normalize != "none" else None
        )
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_ignore_index(self):
        preds, target = _mc
        t = np.where(target[0] == 1, -1, target[0])
        res = multiclass_confusion_matrix(preds[0], t, num_classes=NUM_CLASSES, ignore_index=-1)
        mask = t != -1
        ref = sk_confusion_matrix(t[mask], preds[0][mask], labels=np.arange(NUM_CLASSES))
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)


class TestCohenKappa(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_binary_functional(self, weights):
        preds, target = _binary
        ref = lambda p, t: cohen_kappa_score(t.ravel(), _binarize(p).ravel(), weights=weights)
        self.run_functional_metric_test(preds, target, binary_cohen_kappa, ref, metric_args={"weights": weights})

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_multiclass(self, weights):
        preds, target = _mc
        ref = lambda p, t: cohen_kappa_score(t.ravel(), p.ravel(), weights=weights)
        self.run_functional_metric_test(
            preds, target, multiclass_cohen_kappa, ref, metric_args={"num_classes": NUM_CLASSES, "weights": weights}
        )

    def test_multiclass_class(self):
        preds, target = _mc
        ref = lambda p, t: cohen_kappa_score(t.ravel(), p.ravel())
        self.run_class_metric_test(
            preds, target, MulticlassCohenKappa, ref, metric_args={"num_classes": NUM_CLASSES}, sharded=True
        )


class TestJaccard(MetricTester):
    atol = 1e-6

    def test_binary(self):
        preds, target = _binary
        ref = lambda p, t: jaccard_score(t.ravel(), _binarize(p).ravel(), zero_division=0)
        self.run_functional_metric_test(preds, target, binary_jaccard_index, ref)

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_multiclass(self, average):
        preds, target = _mc

        def ref(p, t):
            return jaccard_score(
                t.ravel(), p.ravel(), labels=np.arange(NUM_CLASSES),
                average=average if average != "none" else None, zero_division=0,
            )

        self.run_functional_metric_test(
            preds, target, multiclass_jaccard_index, ref, metric_args={"num_classes": NUM_CLASSES, "average": average}
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "none"])
    def test_multilabel(self, average):
        preds, target = _ml

        def ref(p, t):
            return jaccard_score(
                np.asarray(t).reshape(-1, NUM_CLASSES), _binarize(p).reshape(-1, NUM_CLASSES),
                average=average if average != "none" else None, zero_division=0,
            )

        self.run_functional_metric_test(
            preds, target, multilabel_jaccard_index, ref, metric_args={"num_labels": NUM_CLASSES, "average": average}
        )


class TestMatthews(MetricTester):
    atol = 1e-6

    def test_binary(self):
        preds, target = _binary
        ref = lambda p, t: sk_matthews_corrcoef(t.ravel(), _binarize(p).ravel())
        self.run_functional_metric_test(preds, target, binary_matthews_corrcoef, ref)

    def test_multiclass(self):
        preds, target = _mc
        ref = lambda p, t: sk_matthews_corrcoef(t.ravel(), p.ravel())
        self.run_functional_metric_test(
            preds, target, multiclass_matthews_corrcoef, ref, metric_args={"num_classes": NUM_CLASSES}
        )
        self.run_class_metric_test(
            preds, target, MulticlassMatthewsCorrCoef, ref, metric_args={"num_classes": NUM_CLASSES}, sharded=True
        )

    def test_multilabel_runs(self):
        preds, target = _ml
        res = multilabel_matthews_corrcoef(preds[0], target[0], num_labels=NUM_CLASSES)
        assert np.isfinite(np.asarray(res))
