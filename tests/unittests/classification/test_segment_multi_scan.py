"""Fused segmented multi-scan (ops/segment.py): bit-parity of every tier —
native per-lane XLA scans, associative_scan tuple carry, Pallas kernel
(interpret mode on CPU), and the legacy unfused per-statistic scans — across
the adversarial input suite.

The load-bearing property: ``segment_multi_scan`` is integer-only, and int
add/min/max are exact under any association, so ALL tiers must agree
bit-for-bit on every input class — ties, ±inf-driven segment boundaries,
single-segment and every-row-a-segment extremes, and sizes that pad/straddle
the Pallas block.

The Pallas interpreter executes block-by-block in Python, so the full
case × op × reverse cross product only runs it on ``PALLAS_CASES`` — the
cases that exercise its distinct machinery (padding, multi-block carries,
every-row flags); the dedicated carry test covers the long-segment splice.
"""
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.ops import segment as seg
from metrics_tpu.ops.segment import (
    SEGSCAN_BLOCK,
    _segment_cumsum_nonneg,
    _segment_suffix_sum_nonneg,
    force_scan_impl,
    segment_multi_scan,
)

_rng = np.random.RandomState(4321)

_NP_OP = {"sum": np.add, "min": np.minimum, "max": np.maximum}


def _np_segment_scan(values, flags, op, reverse=False):
    """Per-element reference: inclusive within-segment running statistic."""
    v = np.asarray(values).copy()
    f = np.asarray(flags).astype(bool).copy()
    if reverse:
        v, f = v[::-1], f[::-1]
    out = np.empty_like(v)
    acc = None
    for i in range(len(v)):
        acc = v[i] if (f[i] or acc is None) else _NP_OP[op](acc, v[i])
        out[i] = acc
    return out[::-1] if reverse else out


def _flags_from_preds(preds):
    """Segment-start flags the rank/retrieval pipelines build: boundaries where
    the sorted score changes (ties collapse into one segment)."""
    order = np.argsort(-preds, kind="stable")
    s = preds[order]
    flags = np.ones(len(s), bool)
    flags[1:] = s[1:] != s[:-1]
    return flags


# name -> (values int32, flags bool); sizes chosen to pad and straddle the
# Pallas block (777 and 900 pad, 1300 crosses one boundary, 3072 is a multiple)
def _cases():
    cases = {}
    for name, preds in {
        "tie_heavy": (_rng.randint(0, 5, 1300) / 4.0).astype(np.float32),
        "pm_inf": np.where(
            _rng.rand(777) < 0.2, np.inf, np.where(_rng.rand(777) < 0.2, -np.inf, _rng.randn(777))
        ).astype(np.float32),
        "random": _rng.randn(900).astype(np.float32),
    }.items():
        flags = _flags_from_preds(preds)
        vals = _rng.randint(-7, 8, len(preds)).astype(np.int32)
        cases[name] = (vals, flags)
    n = 2048  # exactly two Pallas blocks, no padding
    cases["every_row_a_segment"] = (_rng.randint(0, 100, n).astype(np.int32), np.ones(n, bool))
    cases["one_global_segment"] = (_rng.randint(-100, 100, n).astype(np.int32), np.eye(1, n, 0, dtype=bool)[0])
    cases["block_multiple"] = (_rng.randint(0, 3, SEGSCAN_BLOCK * 3).astype(np.int32), _rng.rand(SEGSCAN_BLOCK * 3) < 0.01)
    cases["tiny"] = (np.array([5, -2, 3], np.int32), np.array([True, False, True]))
    return cases


CASES = _cases()
# the interpreter-run Pallas subset: padding (tiny, pm_inf), multi-block
# carries (block_multiple), densest flag pattern (every_row_a_segment)
PALLAS_CASES = ("tiny", "pm_inf", "block_multiple", "every_row_a_segment")
OPS3 = ("sum", "min", "max")


@partial(jax.jit, static_argnames=("ops", "reverse", "impl"))
def _scan_jit(values, flags, ops, reverse, impl):
    # jit matters for suite runtime: EAGER associative_scan pays one tiny-kernel
    # compile per slice/concat per new shape (3-7 s per first case visit);
    # jitted, each (shape, ops, reverse, impl) signature compiles once
    with force_scan_impl(impl):
        return segment_multi_scan(values, flags, ops=ops, reverse=reverse)


@pytest.mark.parametrize("case", sorted(CASES))
@pytest.mark.parametrize("reverse", (False, True))
def test_all_tiers_match_reference(case, reverse):
    vals, flags = CASES[case]
    refs = [_np_segment_scan(vals, flags, op, reverse=reverse) for op in OPS3]
    vj, fj = jnp.asarray(vals), jnp.asarray(flags)
    # reverse is a value/flag flip in the dispatcher, outside the tiers — the
    # python-per-block interpreter only needs to see the forward direction
    impls = ("assoc", "pallas_interpret") if case in PALLAS_CASES and not reverse else ("assoc",)
    for impl in impls:
        outs = _scan_jit((vj, vj, vj), fj, OPS3, reverse, impl)
        for op, out, ref in zip(OPS3, outs, refs):
            assert np.array_equal(np.asarray(out), ref), f"{case}/{op}/{impl} reverse={reverse}"
    # the native tier serves sum lanes over real flags
    (out,) = _scan_jit((vj,), fj, ("sum",), reverse, "native")
    assert np.array_equal(np.asarray(out), refs[0]), f"{case}/sum/native reverse={reverse}"


@pytest.mark.parametrize("case", sorted(CASES))
def test_fused_tuple_equals_independent_scans(case):
    """The tentpole contract: k statistics in ONE pass == k independent scans."""
    vals, flags = CASES[case]
    ones = np.ones_like(vals)
    big = np.where(flags, vals, vals * 2).astype(np.int32)
    triples = ((jnp.asarray(ones), "sum"), (jnp.asarray(vals), "min"), (jnp.asarray(big), "max"))
    fj = jnp.asarray(flags)
    # interpret-mode singles are pure-python-per-block slow; two cases (padding
    # + multi-block carry) cover the kernel's combine logic, the rest ride assoc
    impls = ("assoc", "pallas_interpret") if case in ("tiny", "block_multiple") else ("assoc",)
    for impl in impls:
        fused = _scan_jit(tuple(v for v, _ in triples), fj, OPS3, False, impl)
        singles = [_scan_jit((v,), fj, (o,), False, impl)[0] for v, o in triples]
        for f, s, (_, o) in zip(fused, singles, triples):
            assert np.array_equal(np.asarray(f), np.asarray(s)), f"{case}/{impl}/{o}"


@pytest.mark.parametrize("reverse", (False, True))
def test_global_segment_none_matches_explicit_flags(reverse):
    """``new_seg=None`` (static single-segment claim) must equal the same scan
    over explicit one-segment flags, on every tier that accepts the request."""
    vals, _ = CASES["random"]
    flags = np.zeros(len(vals), bool)
    flags[-1 if reverse else 0] = True
    refs = [_np_segment_scan(vals, flags, op, reverse=reverse) for op in OPS3]
    vj = jnp.asarray(vals)
    # auto dispatch (native off-TPU), the generic carry, and the kernel
    for impl in (None, "assoc", "pallas_interpret"):
        outs = _scan_jit((vj, vj, vj), None, OPS3, reverse, impl)
        for op, out, ref in zip(OPS3, outs, refs):
            assert np.array_equal(np.asarray(out), ref), f"{op}/{impl} reverse={reverse}"


def test_native_tier_rejects_min_over_real_flags():
    vals, flags = CASES["tiny"]
    with force_scan_impl("native"):
        with pytest.raises(ValueError, match="native tier"):
            segment_multi_scan((jnp.asarray(vals),), jnp.asarray(flags), ops=("min",))


@pytest.mark.parametrize("case", sorted(CASES))
def test_matches_legacy_unfused_helpers(case):
    """sum forward == _segment_cumsum_nonneg; sum reverse == _segment_suffix_sum_nonneg."""
    vals, flags = CASES[case]
    nonneg = np.abs(vals).astype(np.int32)
    (fwd,) = segment_multi_scan((jnp.asarray(nonneg),), jnp.asarray(flags))
    legacy_fwd = _segment_cumsum_nonneg(jnp.asarray(nonneg).astype(jnp.float32), jnp.asarray(flags))
    assert np.array_equal(np.asarray(fwd), np.asarray(legacy_fwd).astype(np.int32)), case

    # reverse flags mark segment LAST rows: derive them from the start flags
    last = np.roll(flags, -1)
    last[-1] = True
    (rev,) = segment_multi_scan((jnp.asarray(nonneg),), jnp.asarray(last), reverse=True)
    legacy_rev = _segment_suffix_sum_nonneg(jnp.asarray(nonneg).astype(jnp.float32), jnp.asarray(last))
    assert np.array_equal(np.asarray(rev), np.asarray(legacy_rev).astype(np.int32)), case


def test_jit_parity_and_trace_safety():
    # a short slice keeps the EAGER side cheap (eager associative_scan pays a
    # per-slice-kernel compile storm on each new shape)
    vals, flags = (a[:64] for a in CASES["tie_heavy"])
    args = (jnp.asarray(vals), jnp.asarray(np.ones_like(vals)))

    @jax.jit
    def fused(v, ones, f):
        return segment_multi_scan((v, ones), f, ops=("min", "sum"))

    eager = segment_multi_scan(args, jnp.asarray(flags), ops=("min", "sum"))
    jitted = fused(args[0], args[1], jnp.asarray(flags))
    for a, b in zip(eager, jitted):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pallas_interpret_carry_across_blocks():
    """A single segment spanning many blocks forces the carry splice on every
    block after the first — the exact path the register carry optimizes."""
    n = SEGSCAN_BLOCK * 4 + 123
    vals = _rng.randint(0, 2, n).astype(np.int32)
    flags = np.zeros(n, bool)
    flags[0] = True
    ref = np.cumsum(vals).astype(np.int32)
    with force_scan_impl("pallas_interpret"):
        (out,) = segment_multi_scan((jnp.asarray(vals),), jnp.asarray(flags))
    assert np.array_equal(np.asarray(out), ref)


def test_rejects_float_values_and_bad_ops():
    v = jnp.arange(8, dtype=jnp.float32)
    f = jnp.zeros(8, bool)
    with pytest.raises(ValueError, match="integer-only"):
        segment_multi_scan((v,), f)
    vi = v.astype(jnp.int32)
    with pytest.raises(ValueError, match="unknown scan op"):
        segment_multi_scan((vi,), f, ops=("prod",))
    with pytest.raises(ValueError, match="ops"):
        segment_multi_scan((vi, vi), f, ops=("sum",))
    with pytest.raises(ValueError, match="at least one"):
        segment_multi_scan((), f)


def test_force_scan_impl_restores_dispatch():
    assert seg._FORCED_SCAN_IMPL is None
    with force_scan_impl("assoc"):
        assert seg._FORCED_SCAN_IMPL == "assoc"
    assert seg._FORCED_SCAN_IMPL is None
