"""Differential tests for precision/recall/F-beta/specificity/hamming vs sklearn.

Mirrors reference tests/unittests/classification/{test_precision_recall,test_f_beta,
test_specificity,test_hamming_distance}.py coverage.
"""
import numpy as np
import pytest
from scipy.special import expit
from sklearn.metrics import fbeta_score as sk_fbeta, precision_score, recall_score

from metrics_tpu.classification import (
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    BinarySpecificity,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
)
from metrics_tpu.functional.classification import (
    binary_f1_score,
    binary_hamming_distance,
    binary_precision,
    binary_recall,
    binary_specificity,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multiclass_hamming_distance,
    multiclass_precision,
    multiclass_recall,
    multiclass_specificity,
    multilabel_f1_score,
    multilabel_precision,
    multilabel_recall,
)

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers import seed_all  # noqa: E402
from helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, THRESHOLD, MetricTester  # noqa: E402

seed_all(42)

_rng = np.random.default_rng(7)
_binary = (_rng.random((NUM_BATCHES, BATCH_SIZE)), _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_mc = (
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_ml = (
    _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _binarize(preds):
    preds = np.asarray(preds)
    if preds.dtype.kind == "f":
        if not ((preds >= 0) & (preds <= 1)).all():
            preds = expit(preds)
        preds = (preds > THRESHOLD).astype(int)
    return preds


def _sk_binary(fn):
    return lambda preds, target: fn(target.ravel(), _binarize(preds).ravel(), zero_division=0)


def _sk_multiclass(fn, average):
    def wrapped(preds, target):
        return fn(
            target.ravel(),
            np.asarray(preds).ravel(),
            average=average if average != "none" else None,
            labels=np.arange(NUM_CLASSES),
            zero_division=0,
        )

    return wrapped


def _sk_multilabel(fn, average):
    def wrapped(preds, target):
        p = _binarize(preds).reshape(-1, NUM_CLASSES)
        t = np.asarray(target).reshape(-1, NUM_CLASSES)
        return fn(t, p, average=average if average != "none" else None, zero_division=0)

    return wrapped


class TestBinaryPrecisionRecall(MetricTester):
    atol = 1e-6

    def test_precision_class(self):
        preds, target = _binary
        self.run_class_metric_test(preds, target, BinaryPrecision, _sk_binary(precision_score), sharded=True)

    def test_recall_class(self):
        preds, target = _binary
        self.run_class_metric_test(preds, target, BinaryRecall, _sk_binary(recall_score), sharded=True)

    def test_precision_functional(self):
        preds, target = _binary
        self.run_functional_metric_test(preds, target, binary_precision, _sk_binary(precision_score))

    def test_recall_functional(self):
        preds, target = _binary
        self.run_functional_metric_test(preds, target, binary_recall, _sk_binary(recall_score))


class TestMulticlassPrecisionRecall(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_precision_class(self, average):
        preds, target = _mc
        self.run_class_metric_test(
            preds,
            target,
            MulticlassPrecision,
            _sk_multiclass(precision_score, average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            sharded=True,
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_recall_functional(self, average):
        preds, target = _mc
        self.run_functional_metric_test(
            preds,
            target,
            multiclass_recall,
            _sk_multiclass(recall_score, average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_precision_functional(self, average):
        preds, target = _mc
        self.run_functional_metric_test(
            preds,
            target,
            multiclass_precision,
            _sk_multiclass(precision_score, average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
    def test_recall_class(self, average):
        preds, target = _mc
        self.run_class_metric_test(
            preds,
            target,
            MulticlassRecall,
            _sk_multiclass(recall_score, average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            sharded=True,
        )


class TestMultilabelPrecisionRecall(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("average", ["micro", "macro", "none"])
    def test_precision(self, average):
        preds, target = _ml
        self.run_class_metric_test(
            preds,
            target,
            MultilabelPrecision,
            _sk_multilabel(precision_score, average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
            sharded=True,
        )
        self.run_functional_metric_test(
            preds,
            target,
            multilabel_precision,
            _sk_multilabel(precision_score, average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "none"])
    def test_recall(self, average):
        preds, target = _ml
        self.run_class_metric_test(
            preds,
            target,
            MultilabelRecall,
            _sk_multilabel(recall_score, average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
            sharded=True,
        )


class TestFBeta(MetricTester):
    atol = 1e-6

    def test_binary_f1(self):
        preds, target = _binary
        ref = lambda p, t: sk_fbeta(t.ravel(), _binarize(p).ravel(), beta=1.0, zero_division=0)
        self.run_class_metric_test(preds, target, BinaryF1Score, ref, sharded=True)
        self.run_functional_metric_test(preds, target, binary_f1_score, ref)

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    @pytest.mark.parametrize("beta", [0.5, 1.0, 2.0])
    def test_multiclass_fbeta(self, average, beta):
        preds, target = _mc

        def ref(p, t):
            return sk_fbeta(
                t.ravel(),
                p.ravel(),
                beta=beta,
                average=average if average != "none" else None,
                labels=np.arange(NUM_CLASSES),
                zero_division=0,
            )

        self.run_functional_metric_test(
            preds,
            target,
            multiclass_fbeta_score,
            ref,
            metric_args={"num_classes": NUM_CLASSES, "average": average, "beta": beta},
        )

    def test_multiclass_f1_class(self):
        preds, target = _mc
        ref = lambda p, t: sk_fbeta(
            t.ravel(), p.ravel(), beta=1.0, average="macro", labels=np.arange(NUM_CLASSES), zero_division=0
        )
        self.run_class_metric_test(
            preds, target, MulticlassF1Score, ref, metric_args={"num_classes": NUM_CLASSES}, sharded=True
        )

    def test_multilabel_f1(self):
        preds, target = _ml

        def ref(p, t):
            return sk_fbeta(
                t.reshape(-1, NUM_CLASSES), _binarize(p).reshape(-1, NUM_CLASSES), beta=1.0, average="macro", zero_division=0
            )

        self.run_functional_metric_test(
            preds, target, multilabel_f1_score, ref, metric_args={"num_labels": NUM_CLASSES, "average": "macro"}
        )


class TestSpecificityHamming(MetricTester):
    atol = 1e-6

    def test_binary_specificity(self):
        preds, target = _binary

        def ref(p, t):
            p, t = _binarize(p).ravel(), t.ravel()
            tn = ((p == 0) & (t == 0)).sum()
            fp = ((p == 1) & (t == 0)).sum()
            return tn / (tn + fp)

        self.run_class_metric_test(preds, target, BinarySpecificity, ref, sharded=True)
        self.run_functional_metric_test(preds, target, binary_specificity, ref)

    def test_multiclass_specificity(self):
        preds, target = _mc

        def ref(p, t):
            p, t = p.ravel(), t.ravel()
            out = []
            for c in range(NUM_CLASSES):
                tn = ((p != c) & (t != c)).sum()
                fp = ((p == c) & (t != c)).sum()
                out.append(tn / (tn + fp))
            return np.mean(out)

        self.run_functional_metric_test(
            preds, target, multiclass_specificity, ref, metric_args={"num_classes": NUM_CLASSES, "average": "macro"}
        )

    def test_binary_hamming(self):
        preds, target = _binary
        ref = lambda p, t: (_binarize(p).ravel() != t.ravel()).mean()
        self.run_functional_metric_test(preds, target, binary_hamming_distance, ref)

    def test_multiclass_hamming_micro(self):
        preds, target = _mc
        ref = lambda p, t: (p.ravel() != t.ravel()).mean()
        self.run_functional_metric_test(
            preds, target, multiclass_hamming_distance, ref, metric_args={"num_classes": NUM_CLASSES, "average": "micro"}
        )
