"""Confusion-count kernel tier tests (ops/confmat.py).

The one-hot MXU matmul tier must be bit-identical to the weighted-bincount path —
bf16 one-hots are exact and each per-chunk f32 count stays below 2^19 < 2^24.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.confmat import _CHUNK, _confmat_matmul, confusion_counts

rng = np.random.RandomState(77)


@pytest.mark.parametrize("n", [100, _CHUNK, _CHUNK + 17, 3 * _CHUNK])
@pytest.mark.parametrize("c", [7, 64])
def test_matmul_tier_equals_bincount(n, c):
    preds = jnp.asarray(rng.randint(0, c, n), jnp.int32)
    target = jnp.asarray(rng.randint(0, c, n), jnp.int32)
    valid = jnp.asarray(rng.rand(n) > 0.2)
    got = _confmat_matmul(preds, target, valid, c)
    expected = np.zeros((c, c), np.int64)
    p_np, t_np, v_np = np.asarray(preds), np.asarray(target), np.asarray(valid)
    np.add.at(expected, (t_np[v_np], p_np[v_np]), 1)
    np.testing.assert_array_equal(np.asarray(got), expected)


def test_dispatch_clips_out_of_range():
    c = 6
    preds = jnp.asarray([0, 1, 99, -5], jnp.int32)
    target = jnp.asarray([0, 1, 2, 3], jnp.int32)
    got = np.asarray(confusion_counts(preds, target, None, c))
    assert got.sum() == 4
    assert got[2, c - 1] == 1  # 99 clipped to C-1
    assert got[3, 0] == 1  # -5 clipped to 0


def test_dispatch_matches_masked_semantics():
    c = 10
    preds = jnp.asarray(rng.randint(0, c, 500), jnp.int32)
    target = jnp.asarray(rng.randint(-1, c, 500), jnp.int32)  # -1 = ignored
    got = np.asarray(confusion_counts(preds, target, target >= 0, c))
    assert got.sum() == int((np.asarray(target) >= 0).sum())
