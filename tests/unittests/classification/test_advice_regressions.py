"""Regression tests for the advisor findings (ADVICE.md).

Round 1:
1. Nominal metrics silently mis-counted non-contiguous / 1-based labels.
2. `and`-instead-of-`or` validation let num_groups=0/1 and min_precision=1.5 through.
3. Fairness selection could key a phantom empty group with non-contiguous group ids.

Round 2:
4. Exact-mode binary AUROC with max_fpr=1.0 on single-class data must match the
   reference's max_fpr==1 -> full-AUC short-circuit (0.0, not NaN).
5. `_fid_from_moments` must not emit Inf for n==1 states on the jit path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.classification import BinaryGroupStatRates
from metrics_tpu.functional.classification import (
    binary_recall_at_fixed_precision,
    demographic_parity,
    equal_opportunity,
)
from metrics_tpu.functional.nominal import (
    cramers_v,
    pearsons_contingency_coefficient,
    theils_u,
    tschuprows_t,
)
from metrics_tpu.nominal import CramersV


@pytest.mark.parametrize("fn", [cramers_v, pearsons_contingency_coefficient, theils_u, tschuprows_t])
def test_nominal_label_shift_invariance(fn):
    """Statistics over categorical series must not depend on the label encoding."""
    rng = np.random.default_rng(3)
    target = rng.integers(0, 4, 400)
    preds = (target + (rng.random(400) < 0.3)) % 4
    base = float(fn(jnp.asarray(preds), jnp.asarray(target)))
    shifted = float(fn(jnp.asarray(preds + 1), jnp.asarray(target + 1)))  # 1-based
    sparse = float(fn(jnp.asarray(preds * 3), jnp.asarray(target * 3)))  # {0,3,6,9}
    assert base == pytest.approx(shifted, abs=1e-6)
    assert base == pytest.approx(sparse, abs=1e-6)


def test_nominal_class_rejects_out_of_range_labels():
    metric = CramersV(num_classes=4)
    with pytest.raises(ValueError, match="dense 0-based labels"):
        metric.update(jnp.asarray([1, 2, 3, 4]), jnp.asarray([1, 2, 3, 4]))


@pytest.mark.parametrize("bad", [0, 1, 1.5, "2"])
def test_num_groups_validation(bad):
    with pytest.raises(ValueError):
        BinaryGroupStatRates(num_groups=bad)


@pytest.mark.parametrize("bad", [-0.5, 1.5, 1])
def test_min_precision_validation(bad):
    preds = jnp.asarray([0.2, 0.8, 0.6, 0.4])
    target = jnp.asarray([0, 1, 1, 0])
    with pytest.raises(ValueError):
        binary_recall_at_fixed_precision(preds, target, min_precision=bad, thresholds=5)


def test_exact_auroc_max_fpr_one_single_class():
    """max_fpr=1.0 takes the full-AUC path: 0.0 on single-class data, not NaN."""
    from metrics_tpu.functional.classification import binary_auroc

    preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
    target = jnp.asarray([1, 1, 1, 1])  # only positives
    full = float(binary_auroc(preds, target, max_fpr=None))
    capped = float(binary_auroc(preds, target, max_fpr=1.0))
    assert full == 0.0
    assert capped == 0.0
    # and on well-posed data max_fpr=1.0 still equals the full AUC
    target2 = jnp.asarray([0, 0, 1, 1])
    assert float(binary_auroc(preds, target2, max_fpr=1.0)) == pytest.approx(
        float(binary_auroc(preds, target2)), abs=1e-6
    )


def test_fid_jit_path_single_sample_is_nan_not_inf():
    """n<2 states produce an explicit NaN through the jit moments path."""
    from metrics_tpu.image.fid import _fid_from_moments

    d = 4
    rm = jnp.zeros(d)
    rm2 = jnp.zeros((d, d))
    out = _fid_from_moments(rm, rm2, jnp.asarray(1.0), rm, rm2, jnp.asarray(1.0))
    assert bool(jnp.isnan(out))
    assert not bool(jnp.isinf(out))


def test_fairness_non_contiguous_groups_skip_empty():
    preds = jnp.array([0.9, 0.8, 0.2, 0.7, 0.1, 0.9])
    groups = jnp.array([0, 2, 0, 2, 0, 2])  # group 1 empty
    dp = demographic_parity(preds, groups, validate_args=False)
    ((key, val),) = dp.items()
    assert "1" not in key.split("_")[1:]
    assert float(val) > 0
    target = jnp.array([1, 1, 0, 1, 0, 1])
    eo = equal_opportunity(preds, target, groups, validate_args=False)
    ((key, _),) = eo.items()
    assert "1" not in key.split("_")[1:]


def test_jit_exact_curve_zero_positive_recall_is_nan_like_eager():
    """ADVICE r3: the jit padded exact curve must return the same degenerate
    recall (NaN from 0/0) as the eager/host path when a batch has no positives."""
    import numpy as np

    from metrics_tpu.ops.clf_curve import binary_precision_recall_curve_padded

    preds = jnp.asarray(np.random.default_rng(0).random(17), jnp.float32)
    target = jnp.zeros(17, jnp.int32)  # zero positives
    _, recall, _, k = jax.jit(binary_precision_recall_curve_padded)(preds, target)
    assert bool(jnp.isnan(recall[: int(k)]).all()), "0-positive recall must be NaN (0/0) under jit too"


def test_fixed_point_metrics_compute_under_jit():
    """ADVICE r3 asked for a clear eager-only error here; round 5 lifted the
    reduce into jit entirely (branchless constrained max, see
    functional/classification/recall_fixed_precision.py) — jitted compute must
    now return the eager value, not raise."""
    from metrics_tpu.classification import BinaryRecallAtFixedPrecision

    m = BinaryRecallAtFixedPrecision(min_precision=0.5)
    state = m.local_update(m.init_state(), jnp.asarray([0.2, 0.8, 0.6]), jnp.asarray([0, 1, 1]))
    best, thr = jax.jit(m.compute_from)(state)
    eager = BinaryRecallAtFixedPrecision(min_precision=0.5)
    eager.update(jnp.asarray([0.2, 0.8, 0.6]), jnp.asarray([0, 1, 1]))
    e_best, e_thr = eager.compute()
    assert float(best) == float(e_best)
    assert float(thr) == float(e_thr)


@pytest.mark.parametrize("as_logits", [False, True])
def test_calibration_and_hinge_updates_are_jit_safe(as_logits):
    """Softmax-iff-logits must be branchless: a host bool on traced preds raised
    TracerBoolConversionError under jit/shard_map (found via evaluate_sharded)."""
    import numpy as np

    from metrics_tpu.classification import MulticlassCalibrationError, MulticlassHingeLoss

    rng = np.random.default_rng(0)
    p = rng.normal(size=(32, 4)).astype(np.float32)
    if not as_logits:
        p = np.exp(p) / np.exp(p).sum(-1, keepdims=True)
    t = rng.integers(0, 4, 32).astype(np.int32)

    for cls in (MulticlassCalibrationError, MulticlassHingeLoss):
        m = cls(num_classes=4, validate_args=False)
        state = jax.jit(m.local_update)(m.init_state(), jnp.asarray(p), jnp.asarray(t))
        jit_val = float(m.compute_from(jax.tree.map(jnp.asarray, jax.device_get(state))))
        m2 = cls(num_classes=4, validate_args=False)
        m2.update(jnp.asarray(p), jnp.asarray(t))
        eager_val = float(m2.compute())
        assert abs(jit_val - eager_val) < 1e-6, (cls.__name__, jit_val, eager_val)
