"""Device-side padded exact-mode curves (VERDICT r2 item 7).

``thresholds=None`` curve outputs are data-dependent on host; under jit the
padded kernel emits static-shape (N+1,) arrays whose first K entries equal the
reference curve, K recoverable as ``(~isnan(thresholds)).sum()``.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.classification import BinaryPrecisionRecallCurve
from metrics_tpu.functional.classification import binary_precision_recall_curve
from metrics_tpu.ops.clf_curve import binary_precision_recall_curve_padded

_rng = np.random.RandomState(77)


def _host_curve(preds, target):
    return binary_precision_recall_curve(jnp.asarray(preds), jnp.asarray(target), thresholds=None)


@pytest.mark.parametrize("n", [16, 100, 257])
@pytest.mark.parametrize("ties", [False, True])
def test_padded_kernel_matches_host_curve(n, ties):
    preds = _rng.rand(n).astype(np.float32)
    if ties:
        preds = np.round(preds * 8) / 8  # force duplicate scores
    target = (_rng.rand(n) > 0.4).astype(np.int32)

    p_host, r_host, t_host = _host_curve(preds, target)
    prec, rec, thr, k = jax.jit(binary_precision_recall_curve_padded)(jnp.asarray(preds), jnp.asarray(target))

    k = int(k)
    assert k == np.asarray(t_host).shape[0]
    assert int(jnp.sum(~jnp.isnan(thr))) == k
    np.testing.assert_allclose(np.asarray(prec)[:k], np.asarray(p_host)[:k], atol=1e-6)
    np.testing.assert_allclose(np.asarray(rec)[:k], np.asarray(r_host)[:k], atol=1e-6)
    np.testing.assert_allclose(np.asarray(thr)[:k], np.asarray(t_host), atol=1e-6)
    # the K-th entry closes the curve exactly like the reference's appended point
    assert float(prec[k]) == 1.0 and float(rec[k]) == 0.0
    # pads are zero-width repeats of the final point
    assert bool(jnp.all(prec[k:] == 1.0)) and bool(jnp.all(rec[k:] == 0.0))


def test_padded_kernel_respects_ignore_mask():
    preds = _rng.rand(64).astype(np.float32)
    target = (_rng.rand(64) > 0.5).astype(np.int32)
    target[::5] = -1  # masked rows
    keep = target >= 0
    p_host, r_host, t_host = _host_curve(preds[keep], target[keep])
    prec, rec, thr, k = binary_precision_recall_curve_padded(jnp.asarray(preds), jnp.asarray(target))
    k = int(k)
    assert k == np.asarray(t_host).shape[0]
    np.testing.assert_allclose(np.asarray(thr)[:k], np.asarray(t_host), atol=1e-6)
    np.testing.assert_allclose(np.asarray(prec)[:k], np.asarray(p_host)[:k], atol=1e-6)


def test_exact_class_compute_from_under_jit():
    """The VERDICT item's Done criterion: BinaryPrecisionRecallCurve with
    thresholds=None computable INSIDE jit via fixed-capacity states."""
    preds = _rng.rand(48).astype(np.float32)
    target = (_rng.rand(48) > 0.5).astype(np.int32)

    metric = BinaryPrecisionRecallCurve(thresholds=None, validate_args=False, cat_capacity=64)
    state = jax.jit(metric.local_update)(metric.init_state(), jnp.asarray(preds), jnp.asarray(target))
    prec, rec, thr = jax.jit(metric.compute_from)(state)

    p_host, r_host, t_host = _host_curve(preds, target)
    k = int(jnp.sum(~jnp.isnan(thr)))
    assert k == np.asarray(t_host).shape[0]
    np.testing.assert_allclose(np.asarray(prec)[:k], np.asarray(p_host)[:k], atol=1e-6)
    np.testing.assert_allclose(np.asarray(rec)[:k], np.asarray(r_host)[:k], atol=1e-6)
    np.testing.assert_allclose(np.asarray(thr)[:k], np.asarray(t_host), atol=1e-6)


def test_exact_class_eager_path_unchanged():
    """Eagerly the ragged host API is preserved (no padding in the output)."""
    preds = _rng.rand(32).astype(np.float32)
    target = (_rng.rand(32) > 0.5).astype(np.int32)
    metric = BinaryPrecisionRecallCurve(thresholds=None)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    prec, rec, thr = metric.compute()
    assert prec.shape[0] == thr.shape[0] + 1
    assert not bool(jnp.any(jnp.isnan(thr)))


def test_multiclass_exact_compute_from_under_jit():
    from metrics_tpu.classification import MulticlassPrecisionRecallCurve
    from metrics_tpu.functional.classification import multiclass_precision_recall_curve

    preds = _rng.rand(48, 3).astype(np.float32)
    preds = preds / preds.sum(-1, keepdims=True)
    target = _rng.randint(0, 3, 48).astype(np.int32)

    metric = MulticlassPrecisionRecallCurve(num_classes=3, thresholds=None, validate_args=False, cat_capacity=64)
    state = jax.jit(metric.local_update)(metric.init_state(), jnp.asarray(preds), jnp.asarray(target))
    prec, rec, thr = jax.jit(metric.compute_from)(state)

    p_host, r_host, t_host = multiclass_precision_recall_curve(
        jnp.asarray(preds), jnp.asarray(target), num_classes=3, thresholds=None
    )
    for c in range(3):
        k = int(jnp.sum(~jnp.isnan(thr[c])))
        assert k == np.asarray(t_host[c]).shape[0]
        np.testing.assert_allclose(np.asarray(thr[c])[:k], np.asarray(t_host[c]), atol=1e-6)
        np.testing.assert_allclose(np.asarray(prec[c])[:k], np.asarray(p_host[c])[:k], atol=1e-6)


def test_multilabel_exact_compute_from_under_jit():
    from metrics_tpu.classification import MultilabelPrecisionRecallCurve
    from metrics_tpu.functional.classification import multilabel_precision_recall_curve

    preds = _rng.rand(48, 3).astype(np.float32)
    target = (_rng.rand(48, 3) > 0.5).astype(np.int32)

    metric = MultilabelPrecisionRecallCurve(num_labels=3, thresholds=None, validate_args=False, cat_capacity=64)
    state = jax.jit(metric.local_update)(metric.init_state(), jnp.asarray(preds), jnp.asarray(target))
    prec, rec, thr = jax.jit(metric.compute_from)(state)

    p_host, r_host, t_host = multilabel_precision_recall_curve(
        jnp.asarray(preds), jnp.asarray(target), num_labels=3, thresholds=None
    )
    for c in range(3):
        k = int(jnp.sum(~jnp.isnan(thr[c])))
        assert k == np.asarray(t_host[c]).shape[0]
        np.testing.assert_allclose(np.asarray(thr[c])[:k], np.asarray(t_host[c]), atol=1e-6)
        np.testing.assert_allclose(np.asarray(prec[c])[:k], np.asarray(p_host[c])[:k], atol=1e-6)
