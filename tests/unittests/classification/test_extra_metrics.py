"""Differential tests for the remaining classification metrics: calibration error,
exact match, hinge, ranking, group fairness, dice, *-at-fixed-* families.

References: sklearn where available; hand-checked reference doctest values otherwise
(reference: tests/unittests/classification/test_{calibration_error,exact_match,
hinge,ranking,group_fairness,dice,recall_fixed_precision}.py).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.special import expit, softmax
from sklearn.metrics import coverage_error, label_ranking_average_precision_score, label_ranking_loss

from metrics_tpu.classification import (
    BinaryCalibrationError,
    BinaryFairness,
    BinaryGroupStatRates,
    BinaryHingeLoss,
    BinaryPrecisionAtFixedRecall,
    BinaryRecallAtFixedPrecision,
    BinarySpecificityAtSensitivity,
    CalibrationError,
    Dice,
    ExactMatch,
    HingeLoss,
    MulticlassCalibrationError,
    MulticlassExactMatch,
    MulticlassHingeLoss,
    MulticlassRecallAtFixedPrecision,
    MultilabelCoverageError,
    MultilabelExactMatch,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from metrics_tpu.functional.classification import (
    binary_calibration_error,
    binary_hinge_loss,
    dice,
    multiclass_calibration_error,
    multiclass_exact_match,
    multiclass_hinge_loss,
    multilabel_coverage_error,
    multilabel_exact_match,
    multilabel_ranking_average_precision,
    multilabel_ranking_loss,
)

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers import seed_all  # noqa: E402
from helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, MetricTester  # noqa: E402

seed_all(42)

_rng = np.random.default_rng(42)


def _ref_calibration_error(confidences, accuracies, n_bins, norm):
    """NumPy reimplementation of binned ECE, matching sklearn-style binning."""
    bins = np.linspace(0, 1, n_bins + 1)
    idx = np.clip(np.searchsorted(bins, confidences, side="right") - 1, 0, n_bins)
    acc_bin = np.zeros(n_bins + 1)
    conf_bin = np.zeros(n_bins + 1)
    count = np.zeros(n_bins + 1)
    np.add.at(count, idx, 1)
    np.add.at(conf_bin, idx, confidences)
    np.add.at(acc_bin, idx, accuracies)
    with np.errstate(invalid="ignore"):
        conf_bin = np.nan_to_num(conf_bin / count)
        acc_bin = np.nan_to_num(acc_bin / count)
    prop = count / count.sum()
    if norm == "l1":
        return np.sum(np.abs(acc_bin - conf_bin) * prop)
    if norm == "max":
        return np.max(np.abs(acc_bin - conf_bin))
    return np.sqrt(max(np.sum((acc_bin - conf_bin) ** 2 * prop), 0.0))


class TestBinaryCalibrationError(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    def test_functional(self, norm):
        preds = _rng.random((NUM_BATCHES, BATCH_SIZE))
        target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
        self.run_functional_metric_test(
            preds,
            target,
            binary_calibration_error,
            lambda p, t: _ref_calibration_error(p, t, 15, norm),
            metric_args={"n_bins": 15, "norm": norm},
        )

    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    def test_class(self, norm):
        preds = _rng.random((NUM_BATCHES, BATCH_SIZE))
        target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
        self.run_class_metric_test(
            preds,
            target,
            BinaryCalibrationError,
            lambda p, t: _ref_calibration_error(np.asarray(p).ravel(), np.asarray(t).ravel(), 15, norm),
            metric_args={"n_bins": 15, "norm": norm},
        )


class TestMulticlassCalibrationError(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("norm", ["l1", "l2"])
    def test_functional(self, norm):
        preds = softmax(_rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)), axis=-1)
        target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))

        def ref(p, t):
            conf = p.max(axis=1)
            acc = (p.argmax(axis=1) == t).astype(float)
            return _ref_calibration_error(conf, acc, 15, norm)

        self.run_functional_metric_test(
            preds, target, multiclass_calibration_error, ref,
            metric_args={"num_classes": NUM_CLASSES, "n_bins": 15, "norm": norm},
        )


class TestExactMatch(MetricTester):
    atol = 1e-6

    def test_multiclass_global(self):
        preds = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, 4))
        target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, 4))

        def ref(p, t):
            return ((p == t).all(axis=1)).mean()

        self.run_functional_metric_test(
            preds, target, multiclass_exact_match, ref, metric_args={"num_classes": NUM_CLASSES}
        )
        self.run_class_metric_test(
            preds,
            target,
            MulticlassExactMatch,
            lambda p, t: ((np.asarray(p) == np.asarray(t)).all(axis=1)).mean(),
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_multilabel_global(self):
        preds = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))
        target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))

        def ref(p, t):
            ph = (p > 0.5).astype(int)
            return (ph == t).all(axis=1).mean()

        self.run_functional_metric_test(
            preds, target, multilabel_exact_match, ref, metric_args={"num_labels": NUM_CLASSES}
        )

    def test_dispatcher(self):
        m = ExactMatch(task="multiclass", num_classes=3)
        assert isinstance(m, MulticlassExactMatch)
        m = ExactMatch(task="multilabel", num_labels=3)
        assert isinstance(m, MultilabelExactMatch)


def _ref_binary_hinge(preds, target, squared):
    p = np.asarray(preds, dtype=np.float64)
    if not ((p >= 0) & (p <= 1)).all():
        p = expit(p)
    t = 2 * np.asarray(target) - 1
    margin = 1 - t * p
    margin = np.clip(margin, 0, None)
    if squared:
        margin = margin**2
    return margin.mean()


class TestHingeLoss(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("squared", [False, True])
    def test_binary(self, squared):
        preds = _rng.random((NUM_BATCHES, BATCH_SIZE))
        target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
        self.run_functional_metric_test(
            preds, target, binary_hinge_loss, lambda p, t: _ref_binary_hinge(p, t, squared),
            metric_args={"squared": squared},
        )
        self.run_class_metric_test(
            preds, target, BinaryHingeLoss, lambda p, t: _ref_binary_hinge(p, t, squared),
            metric_args={"squared": squared},
        )

    def test_multiclass_reference_values(self):
        # reference doctest values (functional/classification/hinge.py:225-236)
        preds = jnp.array([[0.25, 0.20, 0.55], [0.55, 0.05, 0.40], [0.10, 0.30, 0.60], [0.90, 0.05, 0.05]])
        target = jnp.array([0, 1, 2, 0])
        assert np.isclose(float(multiclass_hinge_loss(preds, target, num_classes=3)), 0.9125, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(multiclass_hinge_loss(preds, target, num_classes=3, multiclass_mode="one-vs-all")),
            [0.8750, 1.1250, 1.1000],
            atol=1e-6,
        )
        m = HingeLoss(task="multiclass", num_classes=3)
        assert isinstance(m, MulticlassHingeLoss)
        assert np.isclose(float(m(preds, target)), 0.9125, atol=1e-6)


class TestRanking(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize(
        ("functional", "klass", "ref"),
        [
            (multilabel_coverage_error, MultilabelCoverageError, coverage_error),
            (
                multilabel_ranking_average_precision,
                MultilabelRankingAveragePrecision,
                label_ranking_average_precision_score,
            ),
            (multilabel_ranking_loss, MultilabelRankingLoss, label_ranking_loss),
        ],
    )
    def test_vs_sklearn(self, functional, klass, ref):
        preds = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
        target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))
        self.run_functional_metric_test(
            preds, target, functional, lambda p, t: ref(t, p), metric_args={"num_labels": NUM_CLASSES}
        )
        self.run_class_metric_test(
            preds,
            target,
            klass,
            lambda p, t: ref(np.asarray(t).reshape(-1, NUM_CLASSES), np.asarray(p).reshape(-1, NUM_CLASSES)),
            metric_args={"num_labels": NUM_CLASSES},
        )


class TestGroupFairness(MetricTester):
    atol = 1e-6

    def test_stat_rates(self):
        target = jnp.array([0, 1, 0, 1, 0, 1])
        preds = jnp.array([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        groups = jnp.array([0, 1, 0, 1, 0, 1])
        metric = BinaryGroupStatRates(num_groups=2)
        out = metric(preds, target, groups)
        np.testing.assert_allclose(np.asarray(out["group_0"]), [0, 0, 1, 0])
        np.testing.assert_allclose(np.asarray(out["group_1"]), [1, 0, 0, 0])

    def test_fairness_ratios(self):
        rng = np.random.default_rng(0)
        preds = rng.random(200)
        target = rng.integers(0, 2, 200)
        groups = rng.integers(0, 3, 200)
        metric = BinaryFairness(3, task="all")
        out = metric(jnp.array(preds), jnp.array(target), jnp.array(groups))

        ph = (preds > 0.5).astype(int)
        pos_rates = np.array([(ph[groups == g]).mean() for g in range(3)])
        dp_key = f"DP_{pos_rates.argmin()}_{pos_rates.argmax()}"
        assert dp_key in out
        np.testing.assert_allclose(float(out[dp_key]), pos_rates.min() / pos_rates.max(), atol=1e-6)

        tprs = np.array([(ph[(groups == g) & (target == 1)]).mean() for g in range(3)])
        eo_key = f"EO_{tprs.argmin()}_{tprs.argmax()}"
        assert eo_key in out
        np.testing.assert_allclose(float(out[eo_key]), tprs.min() / tprs.max(), atol=1e-6)


class TestDice(MetricTester):
    atol = 1e-6

    def test_micro_vs_f1(self):
        from sklearn.metrics import f1_score

        # micro dice == micro f1 on multiclass labels
        preds = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
        target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
        for i in range(NUM_BATCHES):
            val = dice(jnp.array(preds[i]), jnp.array(target[i]), average="micro")
            ref = f1_score(target[i], preds[i], average="micro")
            np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_macro(self):
        from sklearn.metrics import f1_score

        preds = _rng.integers(0, NUM_CLASSES, 200)
        target = _rng.integers(0, NUM_CLASSES, 200)
        val = dice(jnp.array(preds), jnp.array(target), average="macro", num_classes=NUM_CLASSES)
        ref = f1_score(target, preds, average="macro")
        np.testing.assert_allclose(float(val), ref, atol=1e-6)

    def test_class(self):
        from sklearn.metrics import f1_score

        preds = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
        target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
        metric = Dice(average="micro")
        for i in range(NUM_BATCHES):
            metric.update(jnp.array(preds[i]), jnp.array(target[i]))
        ref = f1_score(target.ravel(), preds.ravel(), average="micro")
        np.testing.assert_allclose(float(metric.compute()), ref, atol=1e-6)


class TestFixedPointMetrics(MetricTester):
    atol = 1e-6

    def _sk_curve(self, preds, target):
        from sklearn.metrics import precision_recall_curve as sk_prc

        return sk_prc(target, preds)

    def test_binary_recall_at_fixed_precision_exact_vs_sklearn(self):
        rng = np.random.default_rng(1234)  # own rng: sklearn tie-breaks are data-sensitive
        preds = rng.random(200).astype(np.float32)
        target = rng.integers(0, 2, 200)
        prec, rec, thr = self._sk_curve(preds, target)
        min_precision = 0.6
        valid = [(r, p, t) for p, r, t in zip(prec, rec, thr) if p >= min_precision]
        exp_recall, _, exp_thr = max(valid)

        metric = BinaryRecallAtFixedPrecision(min_precision=min_precision, thresholds=None)
        res_recall, res_thr = metric(jnp.array(preds), jnp.array(target))
        np.testing.assert_allclose(float(res_recall), exp_recall, atol=1e-6)
        np.testing.assert_allclose(float(res_thr), exp_thr, atol=1e-6)

    def test_binary_precision_at_fixed_recall_exact_vs_sklearn(self):
        rng = np.random.default_rng(5678)  # own rng: sklearn tie-breaks are data-sensitive
        preds = rng.random(200).astype(np.float32)
        target = rng.integers(0, 2, 200)
        prec, rec, thr = self._sk_curve(preds, target)
        min_recall = 0.5
        valid = [(p, r, t) for p, r, t in zip(prec, rec, thr) if r >= min_recall]
        exp_precision, _, exp_thr = max(valid)

        metric = BinaryPrecisionAtFixedRecall(min_recall=min_recall, thresholds=None)
        res_precision, res_thr = metric(jnp.array(preds), jnp.array(target))
        np.testing.assert_allclose(float(res_precision), exp_precision, atol=1e-6)

    def test_binary_specificity_at_sensitivity_exact_vs_sklearn(self):
        from sklearn.metrics import roc_curve

        preds = _rng.random(200).astype(np.float32)
        target = _rng.integers(0, 2, 200)
        fpr, tpr, thr = roc_curve(target, preds)
        spec = 1 - fpr
        min_sensitivity = 0.5
        mask = tpr >= min_sensitivity
        exp_spec = spec[mask].max()

        metric = BinarySpecificityAtSensitivity(min_sensitivity=min_sensitivity, thresholds=None)
        res_spec, res_thr = metric(jnp.array(preds), jnp.array(target))
        np.testing.assert_allclose(float(res_spec), exp_spec, atol=1e-6)

    def test_multiclass_recall_at_fixed_precision_shapes(self):
        preds = softmax(_rng.normal(size=(BATCH_SIZE, NUM_CLASSES)), axis=-1)
        target = _rng.integers(0, NUM_CLASSES, BATCH_SIZE)
        metric = MulticlassRecallAtFixedPrecision(num_classes=NUM_CLASSES, min_precision=0.5, thresholds=20)
        rec, thr = metric(jnp.array(preds), jnp.array(target))
        assert rec.shape == (NUM_CLASSES,)
        assert thr.shape == (NUM_CLASSES,)
        # binned vs exact should roughly agree
        metric2 = MulticlassRecallAtFixedPrecision(num_classes=NUM_CLASSES, min_precision=0.5, thresholds=None)
        rec2, _ = metric2(jnp.array(preds), jnp.array(target))
        assert np.all(np.asarray(rec2) >= np.asarray(rec) - 1e-6)

    def test_dispatchers(self):
        m = CalibrationError(task="binary")
        assert isinstance(m, BinaryCalibrationError)
        m = CalibrationError(task="multiclass", num_classes=4)
        assert isinstance(m, MulticlassCalibrationError)
