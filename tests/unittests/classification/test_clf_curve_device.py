"""Device-side exact-mode curve kernel tests (ops/clf_curve.py).

The exact (``thresholds=None``) AUROC/AP path is a TPU redesign: sort + cumsum +
tie-run collapsing entirely under jit with static shapes, where the reference (and
round-1 of this framework) dropped to host NumPy. These tests pin the kernel against
sklearn on adversarial tie patterns, verify the ignore-mask and padding semantics,
and verify jit/shard_map compatibility that the host path could never have.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_auc_score

from metrics_tpu.ops import clf_curve as cc

rng = np.random.RandomState(99)


@pytest.mark.parametrize("n", [2, 3, 17, 256, 1000])
@pytest.mark.parametrize("tie_grid", [None, 2, 10])
def test_binary_auroc_vs_sklearn(n, tie_grid):
    for trial in range(3):
        p = rng.rand(n).astype(np.float32)
        if tie_grid:
            p = np.round(p * tie_grid) / tie_grid
        t = rng.randint(0, 2, n)
        if t.min() == t.max():
            t[0] = 1 - t[0]
        ours = float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t)))
        assert abs(ours - roc_auc_score(t, p)) < 1e-6


@pytest.mark.parametrize("tie_grid", [None, 4])
def test_binary_ap_vs_sklearn(tie_grid):
    for n in (5, 64, 500):
        p = rng.rand(n).astype(np.float32)
        if tie_grid:
            p = np.round(p * tie_grid) / tie_grid
        t = rng.randint(0, 2, n)
        if t.sum() == 0:
            t[0] = 1
        ours = float(cc.binary_average_precision_exact(jnp.asarray(p), jnp.asarray(t)))
        assert abs(ours - average_precision_score(t, p)) < 1e-6


def test_all_scores_identical():
    """One giant tie run: AUROC must be exactly 0.5 (the chance diagonal)."""
    p = np.full(100, 0.7, np.float32)
    t = rng.randint(0, 2, 100)
    t[:2] = [0, 1]
    assert abs(float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t))) - 0.5) < 1e-7


def test_degenerate_single_class():
    """Reference parity: degenerate AUROC is 0.0 (zeroed curve, participates in
    macro averages); degenerate AP is NaN (dropped from macro averages)."""
    p = rng.rand(32).astype(np.float32)
    assert float(cc.binary_auroc_exact(jnp.asarray(p), jnp.ones(32, np.int32))) == 0.0
    assert float(cc.binary_auroc_exact(jnp.asarray(p), jnp.zeros(32, np.int32))) == 0.0
    assert np.isnan(float(cc.binary_average_precision_exact(jnp.asarray(p), jnp.zeros(32, np.int32))))
    # partial AUC of single-class data is meaningless (reference IndexErrors) -> NaN
    assert np.isnan(float(cc.binary_auroc_exact(jnp.asarray(p), jnp.ones(32, np.int32), max_fpr=0.5)))


def test_absent_class_macro_parity():
    """Multiclass macro AUROC with an absent class averages IN the 0.0 score."""
    from metrics_tpu.functional.classification import multiclass_auroc

    probs = rng.dirichlet(np.ones(4), 60).astype(np.float32)
    t = rng.randint(0, 3, 60)  # class 3 absent
    res = np.asarray(
        multiclass_auroc(jnp.asarray(probs), jnp.asarray(t), num_classes=4, average="none")
    )
    assert res[3] == 0.0
    macro = float(multiclass_auroc(jnp.asarray(probs), jnp.asarray(t), num_classes=4, average="macro"))
    assert abs(macro - res.mean()) < 1e-6


def test_negative_targets_are_masked():
    p = rng.rand(128).astype(np.float32)
    t = rng.randint(0, 2, 128)
    t[::5] = -1
    keep = t >= 0
    ours = float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t)))
    assert abs(ours - roc_auc_score(t[keep], p[keep])) < 1e-6


def test_padding_equals_unpadded():
    """pow2 padding (n=100 -> 128) must not move the result at all."""
    p = rng.rand(100).astype(np.float32)
    t = rng.randint(0, 2, 100)
    a = float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t)))
    b = float(cc.binary_auroc_exact(jnp.asarray(p[:64]), jnp.asarray(t[:64])))  # exact pow2, no pad
    assert abs(a - roc_auc_score(t, p)) < 1e-6
    assert abs(b - roc_auc_score(t[:64], p[:64])) < 1e-6


@pytest.mark.parametrize("max_fpr", [0.1, 0.5, 0.9, 1.0])
def test_max_fpr_partial_auc(max_fpr):
    """McClish-corrected partial AUC against a host trapezoid recomputation."""
    p = np.round(rng.rand(300), 2).astype(np.float32)
    t = rng.randint(0, 2, 300)
    ours = float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t), max_fpr=max_fpr))
    if max_fpr == 1.0:
        assert abs(ours - roc_auc_score(t, p)) < 1e-6
    else:
        assert abs(ours - roc_auc_score(t, p, max_fpr=max_fpr)) < 1e-6


def test_ovr_multiclass_vs_sklearn():
    probs = rng.dirichlet(np.ones(6), 400).astype(np.float32)
    probs = np.round(probs, 2)
    t = rng.randint(0, 6, 400)
    res, pos = cc.multiclass_auroc_exact(jnp.asarray(probs), jnp.asarray(t))
    for c in range(6):
        sk = roc_auc_score((t == c).astype(int), probs[:, c])
        assert abs(float(res[c]) - sk) < 1e-6
    np.testing.assert_array_equal(np.asarray(pos), np.bincount(t, minlength=6))


def test_exact_mode_is_jittable():
    """The whole point of the redesign: exact AUROC under jit (host path could not)."""
    p = jnp.asarray(rng.rand(256).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, 256))

    @jax.jit
    def f(p, t):
        return cc.binary_auroc_exact(p, t), cc.binary_average_precision_exact(p, t)

    auroc, ap = f(p, t)
    assert abs(float(auroc) - roc_auc_score(np.asarray(t), np.asarray(p))) < 1e-6
    assert abs(float(ap) - average_precision_score(np.asarray(t), np.asarray(p))) < 1e-6


def test_exact_auroc_large_n_drift():
    """1M samples: f32 ratio arithmetic must stay within the 1e-6 drift budget."""
    n = 1 << 20
    p = rng.rand(n).astype(np.float32)
    t = (rng.rand(n) < 0.3).astype(np.int32)
    ours = float(cc.binary_auroc_exact(jnp.asarray(p), jnp.asarray(t)))
    assert abs(ours - roc_auc_score(t, p)) < 1e-6


def test_functional_entrypoints_use_device_path_under_jit():
    """binary_auroc / binary_average_precision with thresholds=None now jit."""
    from metrics_tpu.functional.classification import binary_auroc, binary_average_precision

    p = jnp.asarray(rng.rand(128).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, 128))

    a = jax.jit(lambda p, t: binary_auroc(p, t, validate_args=False))(p, t)
    b = jax.jit(lambda p, t: binary_average_precision(p, t, validate_args=False))(p, t)
    assert abs(float(a) - roc_auc_score(np.asarray(t), np.asarray(p))) < 1e-6
    assert abs(float(b) - average_precision_score(np.asarray(t), np.asarray(p))) < 1e-6
