"""Streaming kernels: fusion shapes agree with the naive reductions."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.streaming import _ZIP_MIN, argmax_correct_count, eq_count


@pytest.mark.parametrize(
    "n",
    [
        0,
        1,
        257,
        1 << 10,            # plain branch
        _ZIP_MIN,           # zip branch, exact multiple of 4
        _ZIP_MIN + 3,       # zip branch with remainder tail
    ],
)
def test_eq_count_matches_naive(n):
    rng = np.random.default_rng(n)
    a = rng.integers(0, 5, n).astype(np.int8)
    b = rng.integers(0, 5, n).astype(np.int8)
    got = int(eq_count(jnp.asarray(a), jnp.asarray(b)))
    assert got == int((a == b).sum())


def test_eq_count_negative_labels():
    n = _ZIP_MIN + 1
    rng = np.random.default_rng(0)
    a = rng.integers(-128, 128, n).astype(np.int8)
    b = rng.integers(-128, 128, n).astype(np.int8)
    got = int(eq_count(jnp.asarray(a), jnp.asarray(b)))
    assert got == int((a == b).sum())


@pytest.mark.parametrize("c", [2, 5, 7, 128])
@pytest.mark.parametrize("jit", [False, True])
def test_argmax_correct_count_matches_argmax(c, jit):
    rng = np.random.default_rng(c)
    n = 1025
    p = rng.normal(size=(n, c)).astype(np.float32)
    t = rng.integers(0, c, n).astype(np.int32)
    fn = jax.jit(argmax_correct_count) if jit else argmax_correct_count
    got = int(fn(jnp.asarray(p), jnp.asarray(t)))
    assert got == int((p.argmax(-1) == t).sum())


def test_argmax_correct_count_tie_first_occurrence():
    # exact ties must resolve to the SMALLEST column, like jnp/np argmax
    p = np.array([[1.0, 3.0, 3.0], [2.0, 2.0, 2.0], [0.0, -1.0, 0.0]], np.float32)
    t = np.array([1, 0, 0], np.int32)  # argmax picks cols 1, 0, 0
    assert int(argmax_correct_count(jnp.asarray(p), jnp.asarray(t))) == 3
    t2 = np.array([2, 1, 2], np.int32)  # the later tied columns must NOT win
    assert int(argmax_correct_count(jnp.asarray(p), jnp.asarray(t2))) == 0


def test_argmax_correct_count_nan_is_maximal():
    # jnp.argmax treats NaN as the max (first NaN wins); the fused kernel must too
    p = np.array([[1.0, np.nan, 5.0], [np.nan, np.nan, 1.0], [0.0, 1.0, 2.0]], np.float32)
    t_nan = np.asarray(jnp.argmax(jnp.asarray(p), axis=1))
    got = int(argmax_correct_count(jnp.asarray(p), jnp.asarray(t_nan.astype(np.int32))))
    assert got == 3


def test_argmax_correct_count_valid_mask():
    rng = np.random.default_rng(0)
    n, c = 513, 4
    p = rng.normal(size=(n, c)).astype(np.float32)
    t = rng.integers(0, c, n).astype(np.int32)
    valid = rng.random(n) > 0.3
    got = int(argmax_correct_count(jnp.asarray(p), jnp.asarray(t), jnp.asarray(valid)))
    assert got == int(((p.argmax(-1) == t) & valid).sum())


@pytest.mark.parametrize("ignore_index", [None, 1, -1])
def test_fused_micro_accuracy_matches_label_path(ignore_index):
    # the fused float-logits micro path must agree exactly with argmax-then-update
    from metrics_tpu.functional.classification import multiclass_accuracy

    rng = np.random.default_rng(3)
    n, c = 999, 6
    p = rng.normal(size=(n, c)).astype(np.float32)
    t = rng.integers(0, c, n).astype(np.int32)
    if ignore_index is not None:
        t[rng.random(n) < 0.2] = ignore_index
    fused = multiclass_accuracy(
        jnp.asarray(p), jnp.asarray(t), num_classes=c, average="micro",
        ignore_index=ignore_index, validate_args=False,
    )
    labeled = multiclass_accuracy(
        jnp.asarray(p.argmax(-1)), jnp.asarray(t), num_classes=c, average="micro",
        ignore_index=ignore_index, validate_args=False,
    )
    assert float(fused) == float(labeled)


def test_fused_micro_accuracy_multidim_inputs():
    # (N, C, d) float preds with (N, d) target: the fused path must flatten the
    # extra dim exactly like format's reshape
    from metrics_tpu.functional.classification import multiclass_accuracy

    rng = np.random.default_rng(4)
    n, c, d = 64, 5, 9
    p = rng.normal(size=(n, c, d)).astype(np.float32)
    t = rng.integers(0, c, (n, d)).astype(np.int32)
    fused = multiclass_accuracy(jnp.asarray(p), jnp.asarray(t), num_classes=c, average="micro")
    want = (p.argmax(1) == t).mean()
    np.testing.assert_allclose(float(fused), want, rtol=1e-6)
