"""eq_count streaming kernel: both fusion shapes agree with the naive reduction."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.streaming import _ZIP_MIN, eq_count


@pytest.mark.parametrize(
    "n",
    [
        0,
        1,
        257,
        1 << 10,            # plain branch
        _ZIP_MIN,           # zip branch, exact multiple of 4
        _ZIP_MIN + 3,       # zip branch with remainder tail
    ],
)
def test_eq_count_matches_naive(n):
    rng = np.random.default_rng(n)
    a = rng.integers(0, 5, n).astype(np.int8)
    b = rng.integers(0, 5, n).astype(np.int8)
    got = int(eq_count(jnp.asarray(a), jnp.asarray(b)))
    assert got == int((a == b).sum())


def test_eq_count_negative_labels():
    n = _ZIP_MIN + 1
    rng = np.random.default_rng(0)
    a = rng.integers(-128, 128, n).astype(np.int8)
    b = rng.integers(-128, 128, n).astype(np.int8)
    got = int(eq_count(jnp.asarray(a), jnp.asarray(b)))
    assert got == int((a == b).sum())
