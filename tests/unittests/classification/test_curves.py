"""Differential tests for PR-curve / ROC / AUROC / AveragePrecision vs sklearn.

Mirrors reference tests/unittests/classification/{test_precision_recall_curve,
test_roc,test_auroc,test_average_precision}.py coverage.
"""
import numpy as np
import pytest
from scipy.special import expit, softmax
from sklearn.metrics import (
    average_precision_score as sk_average_precision,
    precision_recall_curve as sk_precision_recall_curve,
    roc_auc_score as sk_roc_auc,
    roc_curve as sk_roc_curve,
)

from metrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassAUROC,
    MulticlassAveragePrecision,
)
from metrics_tpu.functional.classification import (
    binary_auroc,
    binary_average_precision,
    binary_precision_recall_curve,
    binary_roc,
    multiclass_auroc,
    multiclass_average_precision,
    multiclass_precision_recall_curve,
    multiclass_roc,
    multilabel_auroc,
    multilabel_average_precision,
    multilabel_precision_recall_curve,
    multilabel_roc,
)

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers import seed_all  # noqa: E402
from helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, MetricTester  # noqa: E402

seed_all(42)
_rng = np.random.default_rng(23)
_binary = (_rng.random((NUM_BATCHES, BATCH_SIZE)), _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_binary_logits = (_rng.normal(size=(NUM_BATCHES, BATCH_SIZE)) * 2, _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))
_mc = (
    softmax(_rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)), axis=-1),
    _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_ml = (
    _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _probs(preds):
    preds = np.asarray(preds)
    if not ((preds >= 0) & (preds <= 1)).all():
        preds = expit(preds)
    return preds


class TestBinaryCurves(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("inputs", [_binary, _binary_logits])
    def test_pr_curve_exact(self, inputs):
        preds, target = inputs
        p, r, t = binary_precision_recall_curve(preds[0], target[0], thresholds=None)
        sk_p, sk_r, sk_t = sk_precision_recall_curve(target[0], _probs(preds[0]))
        np.testing.assert_allclose(np.asarray(p), sk_p, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r), sk_r, atol=1e-6)
        np.testing.assert_allclose(np.asarray(t), sk_t, atol=1e-6)

    def test_roc_exact(self):
        preds, target = _binary
        fpr, tpr, thr = binary_roc(preds[0], target[0], thresholds=None)
        sk_fpr, sk_tpr, sk_thr = sk_roc_curve(target[0], preds[0], drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)

    def test_auroc_exact(self):
        preds, target = _binary
        res = binary_auroc(preds[0], target[0], thresholds=None)
        np.testing.assert_allclose(np.asarray(res), sk_roc_auc(target[0], preds[0]), atol=1e-6)

    def test_auroc_class_accumulated(self):
        preds, target = _binary
        ref = lambda p, t: sk_roc_auc(t.ravel(), _probs(p).ravel())
        self.run_class_metric_test(preds, target, BinaryAUROC, ref, check_batch=True)

    def test_auroc_binned_close(self):
        # binned mode approximates the exact value as thresholds densify
        preds, target = _binary
        exact = float(binary_auroc(preds[0], target[0], thresholds=None))
        binned = float(binary_auroc(preds[0], target[0], thresholds=1000))
        assert abs(exact - binned) < 5e-3

    def test_ap_exact(self):
        preds, target = _binary
        res = binary_average_precision(preds[0], target[0], thresholds=None)
        np.testing.assert_allclose(np.asarray(res), sk_average_precision(target[0], preds[0]), atol=1e-6)

    def test_ap_class(self):
        preds, target = _binary
        ref = lambda p, t: sk_average_precision(t.ravel(), _probs(p).ravel())
        self.run_class_metric_test(preds, target, BinaryAveragePrecision, ref, check_batch=True)

    def test_pr_curve_binned_class_sharded(self):
        preds, target = _binary
        m = BinaryPrecisionRecallCurve(thresholds=11)
        for i in range(NUM_BATCHES):
            m.update(preds[i], target[i])
        p1, r1, t1 = m.compute()
        p2, r2, t2 = binary_precision_recall_curve(
            np.concatenate(preds), np.concatenate(target), thresholds=11
        )
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=1e-6)


class TestMulticlassCurves(MetricTester):
    atol = 1e-6

    def test_auroc_exact(self):
        preds, target = _mc
        for average in ["macro", "weighted"]:
            res = multiclass_auroc(preds[0], target[0], num_classes=NUM_CLASSES, average=average, thresholds=None)
            ref = sk_roc_auc(target[0], preds[0], multi_class="ovr", average=average, labels=np.arange(NUM_CLASSES))
            np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_auroc_class_accumulated(self):
        preds, target = _mc
        ref = lambda p, t: sk_roc_auc(t, p, multi_class="ovr", labels=np.arange(NUM_CLASSES))
        self.run_class_metric_test(
            preds, target, MulticlassAUROC, ref, metric_args={"num_classes": NUM_CLASSES}, check_batch=True
        )

    def test_ap_exact(self):
        preds, target = _mc
        res = multiclass_average_precision(preds[0], target[0], num_classes=NUM_CLASSES, average="macro", thresholds=None)
        onehot = np.eye(NUM_CLASSES)[target[0]]
        ref = sk_average_precision(onehot, preds[0], average="macro")
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_pr_curve_exact_runs(self):
        preds, target = _mc
        p, r, t = multiclass_precision_recall_curve(preds[0], target[0], num_classes=NUM_CLASSES, thresholds=None)
        assert len(p) == NUM_CLASSES
        for i in range(NUM_CLASSES):
            sk_p, sk_r, _ = sk_precision_recall_curve((target[0] == i).astype(int), preds[0][:, i])
            np.testing.assert_allclose(np.asarray(p[i]), sk_p, atol=1e-6)
            np.testing.assert_allclose(np.asarray(r[i]), sk_r, atol=1e-6)

    def test_roc_binned_vs_exact(self):
        preds, target = _mc
        fpr_b, tpr_b, _ = multiclass_roc(preds[0], target[0], num_classes=NUM_CLASSES, thresholds=200)
        fpr_e, tpr_e, _ = multiclass_roc(preds[0], target[0], num_classes=NUM_CLASSES, thresholds=None)
        # binned AUC close to exact AUC per class
        from metrics_tpu.utils.compute import _auc_compute_without_check
        for i in range(NUM_CLASSES):
            a_b = float(_auc_compute_without_check(fpr_b[i], tpr_b[i], 1.0))
            a_e = float(_auc_compute_without_check(fpr_e[i], tpr_e[i], 1.0))
            assert abs(a_b - a_e) < 2e-2


class TestMultilabelCurves(MetricTester):
    atol = 1e-6

    def test_auroc_exact(self):
        preds, target = _ml
        for average in ["micro", "macro"]:
            res = multilabel_auroc(preds[0], target[0], num_labels=NUM_CLASSES, average=average, thresholds=None)
            ref = sk_roc_auc(target[0], preds[0], average=average)
            np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_ap_exact(self):
        preds, target = _ml
        res = multilabel_average_precision(preds[0], target[0], num_labels=NUM_CLASSES, average="macro", thresholds=None)
        ref = sk_average_precision(target[0], preds[0], average="macro")
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_roc_exact(self):
        preds, target = _ml
        fpr, tpr, thr = multilabel_roc(preds[0], target[0], num_labels=NUM_CLASSES, thresholds=None)
        for i in range(NUM_CLASSES):
            sk_fpr, sk_tpr, _ = sk_roc_curve(target[0][:, i], preds[0][:, i], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fpr[i]), sk_fpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tpr[i]), sk_tpr, atol=1e-6)

    def test_pr_curve_binned_runs(self):
        preds, target = _ml
        p, r, t = multilabel_precision_recall_curve(preds[0], target[0], num_labels=NUM_CLASSES, thresholds=20)
        assert p.shape == (NUM_CLASSES, 21)
        assert r.shape == (NUM_CLASSES, 21)
