"""Tolerance-routed sketch dispatch contract sweep (ISSUE 18 satellite).

Three contracts, enforced for every AUROC/AP Metric class and the scalar ops
entry points:

1. ``tolerance=0`` (the default) is BIT-IDENTICAL to the exact tier — passing
   the knob explicitly changes nothing, state registration included.
2. A routed metric's result is the certified-bracket midpoint, the f32 oracle
   lies inside the bracket, and the true error is ≤ width/2.
3. Routing is O(1)-state: the only registered states are the two class
   histograms (no cat buffer ever exists), their byte size never grows with
   the stream, and the ``rank.dispatch/sketch`` obs counter records the route.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import obs
from metrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryPrecisionRecallCurve,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAUROC,
    MultilabelAveragePrecision,
)
from metrics_tpu.ops import rank as _rank
from metrics_tpu.ops.clf_curve import binary_auroc_exact, binary_average_precision_exact

_rng = np.random.RandomState(99)

N = 1 << 12
NC = 4

PREDS_B = jnp.asarray(_rng.rand(N), jnp.float32)
TARGET_B = jnp.asarray(_rng.randint(0, 2, N), jnp.int32)
PREDS_MC = jax.nn.softmax(jnp.asarray(_rng.randn(N, NC), jnp.float32), axis=-1)
TARGET_MC = jnp.asarray(_rng.randint(0, NC, N), jnp.int32)
PREDS_ML = jnp.asarray(_rng.rand(N, NC), jnp.float32)
TARGET_ML = jnp.asarray(_rng.randint(0, 2, (N, NC)), jnp.int32)

SWEEP = [
    ("binary_auroc", BinaryAUROC, {}, PREDS_B, TARGET_B),
    ("binary_ap", BinaryAveragePrecision, {}, PREDS_B, TARGET_B),
    ("multiclass_auroc", MulticlassAUROC, {"num_classes": NC}, PREDS_MC, TARGET_MC),
    ("multiclass_ap", MulticlassAveragePrecision, {"num_classes": NC}, PREDS_MC, TARGET_MC),
    ("multilabel_auroc", MultilabelAUROC, {"num_labels": NC}, PREDS_ML, TARGET_ML),
    ("multilabel_ap", MultilabelAveragePrecision, {"num_labels": NC}, PREDS_ML, TARGET_ML),
]


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.array_equal(a, b, equal_nan=True) and np.array_equal(np.signbit(a), np.signbit(b))


# ------------------------------------------------- contract 1: tolerance=0


@pytest.mark.parametrize("name,klass,kw,preds,target", SWEEP, ids=[s[0] for s in SWEEP])
def test_tolerance_zero_is_bit_identical(name, klass, kw, preds, target):
    plain = klass(**kw)
    explicit = klass(tolerance=0.0, **kw)
    for m in (plain, explicit):
        m.update(preds, target)
    assert _bitwise_equal(plain.compute(), explicit.compute())
    # tolerance=0 must leave the exact cat-state layout untouched
    assert hasattr(explicit, "preds") and not hasattr(explicit, "pos_hist")


def test_ops_level_tolerance_zero_and_fallback_bit_identical():
    base_auroc = binary_auroc_exact(PREDS_B, TARGET_B)
    base_ap = binary_average_precision_exact(PREDS_B, TARGET_B)
    assert _bitwise_equal(base_auroc, binary_auroc_exact(PREDS_B, TARGET_B, tolerance=0.0))
    assert _bitwise_equal(base_ap, binary_average_precision_exact(PREDS_B, TARGET_B, tolerance=0.0))
    # a tolerance the certificate cannot meet falls back to the exact tier
    assert _bitwise_equal(base_auroc, binary_auroc_exact(PREDS_B, TARGET_B, tolerance=1e-12))
    assert _bitwise_equal(base_ap, binary_average_precision_exact(PREDS_B, TARGET_B, tolerance=1e-12))


# --------------------------------------------- contract 2: certified bracket


@pytest.mark.parametrize("name,klass,kw,preds,target", SWEEP, ids=[s[0] for s in SWEEP])
def test_routed_result_is_midpoint_and_oracle_inside_bracket(name, klass, kw, preds, target):
    oracle_kw = dict(kw)
    if "num_classes" in kw or "num_labels" in kw:
        oracle_kw["average"] = "none"
        kw = {**kw, "average": "none"}
    oracle_m = klass(**oracle_kw)
    oracle_m.update(preds, target)
    oracle = np.asarray(oracle_m.compute())

    m = klass(tolerance=0.05, tolerance_bits=12, **kw)
    m.update(preds, target)
    got = np.asarray(m.compute())

    bounds_fn = _rank.hist_auroc_bounds if "auroc" in name else _rank.hist_ap_bounds
    lo, hi = (np.asarray(a) for a in bounds_fn(m.pos_hist, m.neg_hist))
    eps = 1e-6
    finite = np.isfinite(oracle)
    assert np.all((oracle[finite] >= lo[np.broadcast_to(finite, lo.shape)] - eps))
    assert np.all((oracle[finite] <= hi[np.broadcast_to(finite, hi.shape)] + eps))
    mid = 0.5 * (lo + hi)
    assert np.allclose(got[finite], mid[np.broadcast_to(finite, mid.shape)], atol=eps, equal_nan=True)
    assert np.all(np.abs(got[finite] - oracle[finite]) <= 0.5 * (hi - lo)[np.broadcast_to(finite, lo.shape)] + eps)


def test_multilabel_micro_bracket_uses_summed_lanes():
    oracle_m = MultilabelAUROC(num_labels=NC, average="micro")
    oracle_m.update(PREDS_ML, TARGET_ML)
    oracle = float(np.asarray(oracle_m.compute()))

    m = MultilabelAUROC(num_labels=NC, average="micro", tolerance=0.05)
    m.update(PREDS_ML, TARGET_ML)
    got = float(np.asarray(m.compute()))
    lo, hi = (float(a) for a in _rank.hist_auroc_bounds(m.pos_hist.sum(0), m.neg_hist.sum(0)))
    assert lo - 1e-6 <= oracle <= hi + 1e-6
    assert abs(got - 0.5 * (lo + hi)) <= 1e-6


def test_degenerate_lanes_match_exact_conventions():
    # class 3 never appears -> exact multiclass AUROC reports 0.0 for it;
    # a label with no positives -> exact AP reports NaN
    target = jnp.asarray(_rng.randint(0, NC - 1, N), jnp.int32)
    m = MulticlassAUROC(num_classes=NC, average="none", tolerance=0.1)
    m.update(PREDS_MC, target)
    assert float(np.asarray(m.compute())[NC - 1]) == 0.0

    tml = TARGET_ML.at[:, 0].set(0)
    m2 = MultilabelAveragePrecision(num_labels=NC, average="none", tolerance=0.1)
    m2.update(PREDS_ML, tml)
    res = np.asarray(m2.compute())
    assert np.isnan(res[0]) and not np.any(np.isnan(res[1:]))


# ------------------------------------- contract 3: O(1) state, no cat buffer


def test_streaming_is_o1_state_with_obs_dispatch_counter():
    m = BinaryAUROC(tolerance=0.02, tolerance_bits=12)
    assert not hasattr(m, "preds") and not hasattr(m, "target")
    assert set(m._defaults) >= {"pos_hist", "neg_hist"}

    chunks_p, chunks_t = [], []
    state_bytes = None
    for i in range(32):
        p = _rng.rand(2048).astype(np.float32)
        t = _rng.randint(0, 2, 2048).astype(np.int32)
        chunks_p.append(p)
        chunks_t.append(t)
        m.update(jnp.asarray(p), jnp.asarray(t))
        nbytes = int(m.pos_hist.nbytes + m.neg_hist.nbytes)
        if state_bytes is None:
            state_bytes = nbytes
        assert nbytes == state_bytes == 2 * 4 * (1 << 12)  # O(1): never grows

    with obs.observe(clear=True) as reg:
        got = float(np.asarray(m.compute()))
        snap = reg.snapshot()
    assert snap["rank"]["dispatch/sketch"] >= 1
    assert snap["rank"]["op/binary_auroc"] >= 1

    oracle_m = BinaryAUROC()
    oracle_m.update(jnp.asarray(np.concatenate(chunks_p)), jnp.asarray(np.concatenate(chunks_t)))
    oracle = float(np.asarray(oracle_m.compute()))
    lo, hi = (float(a) for a in _rank.hist_auroc_bounds(m.pos_hist, m.neg_hist))
    assert lo - 1e-6 <= oracle <= hi + 1e-6
    assert abs(got - oracle) <= 0.5 * (hi - lo) + 1e-6


@pytest.mark.slow
def test_2pow24_stream_never_materializes_cat_buffer():
    """ISSUE 18 acceptance: a 2^24-row AUROC stream at tolerance=0.01 keeps
    O(1) state (two 2^12-bucket int32 hists), the result lands inside the
    certified bracket, and dispatch is observable."""
    m = BinaryAUROC(tolerance=0.01, tolerance_bits=12)
    total = 1 << 24
    batch = 1 << 16
    rng = np.random.default_rng(7)
    # separable scores so the certificate at 12 bits can actually meet 0.01
    for _ in range(total // batch):
        t = rng.integers(0, 2, batch).astype(np.int32)
        p = (rng.random(batch) * 0.5 + t * 0.4).astype(np.float32)
        m.update(jnp.asarray(p), jnp.asarray(t))
        assert not hasattr(m, "preds")
        assert int(m.pos_hist.nbytes + m.neg_hist.nbytes) == 2 * 4 * (1 << 12)
    with obs.observe(clear=True) as reg:
        got = float(np.asarray(m.compute()))
        snap = reg.snapshot()
    assert snap["rank"]["dispatch/sketch"] >= 1
    lo, hi = (float(a) for a in _rank.hist_auroc_bounds(m.pos_hist, m.neg_hist))
    assert hi - lo <= 2 * 0.01 + 1e-6  # certificate met the tolerance
    assert lo - 1e-6 <= got <= hi + 1e-6


def test_checkpoint_roundtrip_is_o1_and_exactly_resumable():
    m = BinaryAUROC(tolerance=0.05)
    m.update(PREDS_B, TARGET_B)
    ph, nh = np.asarray(m.pos_hist), np.asarray(m.neg_hist)
    m2 = BinaryAUROC(tolerance=0.05)
    m2.pos_hist = jnp.asarray(ph)
    m2.neg_hist = jnp.asarray(nh)
    assert _bitwise_equal(m.compute(), m2.compute())


# ----------------------------------------------------- constructor contracts


def test_structural_validation_errors():
    with pytest.raises(ValueError):
        BinaryPrecisionRecallCurve(tolerance=0.1)  # curves need full state
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=0.1, thresholds=5)  # binned tier is already O(1)
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=-0.5)
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=0.1, tolerance_bits=2)
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=0.1, tolerance_bits=20)
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=0.1, max_fpr=0.5)  # partial AUC needs exact tier
    # validate_args=False must NOT disable the structural checks
    with pytest.raises(ValueError):
        BinaryAUROC(tolerance=0.1, thresholds=5, validate_args=False)


def test_tolerance_participates_in_update_signature():
    assert "tolerance" in BinaryAUROC._update_signature_attrs
    assert "tolerance_bits" in BinaryAUROC._update_signature_attrs


# ----------------------------------------------- serving-layer integration


def test_collection_spec_injects_tolerance_into_sketch_members():
    from metrics_tpu.serve.server import CollectionSpec

    spec = CollectionSpec(
        "rank",
        {"auroc": "BinaryAUROC", "ap": "BinaryAveragePrecision", "acc": "BinaryAccuracy"},
        tolerance=0.05,
        tolerance_bits=13,
    )
    col = spec.build()
    assert col["auroc"].tolerance == 0.05 and col["auroc"].tolerance_bits == 13
    assert col["ap"].tolerance == 0.05
    assert hasattr(col["auroc"], "pos_hist") and not hasattr(col["auroc"], "preds")
    assert not hasattr(col["acc"], "pos_hist")

    # per-metric kwargs beat the spec default; binned members stay exact
    spec2 = CollectionSpec(
        "rank2", {"auroc": {"class": "BinaryAUROC", "kwargs": {"tolerance": 0.0}}}, tolerance=0.05
    )
    assert spec2.build()["auroc"].tolerance == 0.0
    spec3 = CollectionSpec(
        "rank3", {"auroc": {"class": "BinaryAUROC", "kwargs": {"thresholds": 5}}}, tolerance=0.05
    )
    assert spec3.build()["auroc"].tolerance == 0.0

    with pytest.raises(ValueError):
        CollectionSpec("bad", {"a": "BinaryAUROC"}, tolerance=-1.0)
    with pytest.raises(ValueError):
        CollectionSpec("bad", {"a": "BinaryAUROC"}, tolerance_bits=12)  # bits need tolerance


def test_excache_records_and_replays_sketch_entries():
    from metrics_tpu.serve import excache

    excache.enable_recording(clear=True)
    m = BinaryAUROC(tolerance=0.05, tolerance_bits=12)
    m.update(PREDS_B, TARGET_B)
    m.compute()
    binary_auroc_exact(PREDS_B, TARGET_B, tolerance=0.5, tolerance_bits=10)
    excache.disable_recording()

    rank_entries = [e for e in excache.manifest_entries() if e.get("engine") == "rank"]
    ops = {(e["op"], e.get("tier"), e.get("bits")) for e in rank_entries}
    assert ("hist_class_counts", "sketch", 12) in ops, ops
    assert ("hist_auroc_bounds", "sketch", 12) in ops, ops
    assert ("binary_auroc_exact", "sketch", 10) in ops, ops

    payload = json.loads(json.dumps(excache.manifest_payload()))  # disk round-trip
    report = excache.prewarm(None, payload)
    assert report["failed"] == 0
    assert report["compiled"] >= len(rank_entries)
