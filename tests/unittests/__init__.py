from collections import namedtuple

NUM_BATCHES = 4
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5

Input = namedtuple("Input", ["preds", "target"])
