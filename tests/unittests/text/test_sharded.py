"""8-device sharded equivalence for text scalar-state metrics (VERDICT r2 item 3)."""
import numpy as np

from tests.helpers.testers import MetricTester

from metrics_tpu.text import Perplexity

_rng = np.random.RandomState(7)
NUM_BATCHES, BATCH, SEQ, VOCAB = 4, 16, 12, 30
PREDS = _rng.randn(NUM_BATCHES, BATCH, SEQ, VOCAB).astype(np.float32)
TARGET = _rng.randint(0, VOCAB, (NUM_BATCHES, BATCH, SEQ)).astype(np.int32)


def _ref_perplexity(logits, target, ignore_index=None):
    logits = logits.reshape(-1, logits.shape[-1]).astype(np.float64)
    target = target.reshape(-1)
    if ignore_index is not None:
        keep = target != ignore_index
        logits, target = logits[keep], target[keep]
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(
        -1, keepdims=True
    )
    nll = -logp[np.arange(target.shape[0]), target]
    return float(np.exp(nll.mean()))


class TestShardedPerplexity(MetricTester):
    atol = 1e-3

    def test_perplexity_sharded(self):
        self.run_class_metric_test(PREDS, TARGET, Perplexity, _ref_perplexity, sharded=True)

    def test_perplexity_sharded_ignore_index(self):
        target = TARGET.copy()
        target[:, :, -2:] = -100
        self.run_class_metric_test(
            PREDS,
            target,
            Perplexity,
            lambda p, t: _ref_perplexity(p, t, ignore_index=-100),
            metric_args={"ignore_index": -100},
            sharded=True,
        )
