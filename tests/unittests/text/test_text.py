"""Text-domain differential tests vs the reference implementation.

Reference test model: tests/unittests/text/* (differential against jiwer/
sacrebleu/etc.); here the oracle is the reference library itself, importable from
/root/reference (skipped if absent).
"""
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.text import (
    bleu_score,
    char_error_rate,
    chrf_score,
    extended_edit_distance,
    match_error_rate,
    perplexity,
    rouge_score,
    sacre_bleu_score,
    squad,
    translation_edit_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from metrics_tpu.functional.text.helper import _edit_distance
from metrics_tpu.functional.text.sacre_bleu import _SacreBLEUTokenizer, _intl_tokenize_fallback
from metrics_tpu.text import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers.reference import import_reference_text, reference_available  # noqa: E402

ref = import_reference_text()
needs_ref = pytest.mark.skipif(not reference_available(), reason="reference tree not mounted")

PREDS = ["this is the prediction", "there is an other sample", "a b", ""]
TARGET = ["this is the reference", "there is another one", "a b c d", "x"]

BLEU_PREDS = ["the cat is on the mat", "there is a big tree near the house"]
BLEU_TARGET = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["a big tree is near the house", "there is a tree close to the house"],
]


def test_edit_distance_kernel():
    # vectorized prefix-min DP vs naive DP
    def naive(a, b):
        dp = list(range(len(b) + 1))
        for i in range(1, len(a) + 1):
            prev, dp[0] = dp[0], i
            for j in range(1, len(b) + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return dp[-1]

    rng = np.random.RandomState(0)
    for _ in range(50):
        a = [str(x) for x in rng.randint(0, 5, rng.randint(0, 12))]
        b = [str(x) for x in rng.randint(0, 5, rng.randint(0, 12))]
        assert _edit_distance(a, b) == naive(a, b)


@needs_ref
@pytest.mark.parametrize(
    "mine_name, ref_name",
    [
        ("word_error_rate", "word_error_rate"),
        ("char_error_rate", "char_error_rate"),
        ("match_error_rate", "match_error_rate"),
        ("word_information_lost", "word_information_lost"),
        ("word_information_preserved", "word_information_preserved"),
    ],
)
def test_wer_family_vs_reference(mine_name, ref_name):
    mine = globals()[mine_name]
    theirs = getattr(ref, ref_name)
    assert abs(float(mine(PREDS, TARGET)) - float(theirs(PREDS, TARGET))) < 1e-6


@pytest.mark.parametrize(
    "cls, fn",
    [
        (WordErrorRate, word_error_rate),
        (CharErrorRate, char_error_rate),
        (MatchErrorRate, match_error_rate),
        (WordInfoLost, word_information_lost),
        (WordInfoPreserved, word_information_preserved),
    ],
)
def test_wer_family_class_accumulation(cls, fn):
    metric = cls()
    for i in range(len(PREDS)):
        metric.update([PREDS[i]], [TARGET[i]])
    assert abs(float(metric.compute()) - float(fn(PREDS, TARGET))) < 1e-6
    metric.reset()
    metric.update(PREDS, TARGET)
    assert abs(float(metric.compute()) - float(fn(PREDS, TARGET))) < 1e-6
    # pickle round-trip
    m2 = pickle.loads(pickle.dumps(metric))
    assert abs(float(m2.compute()) - float(metric.compute())) < 1e-6


@needs_ref
@pytest.mark.parametrize("n_gram", [1, 2, 4])
@pytest.mark.parametrize("smooth", [False, True])
def test_bleu_vs_reference(n_gram, smooth):
    m = float(bleu_score(BLEU_PREDS, BLEU_TARGET, n_gram=n_gram, smooth=smooth))
    t = float(ref.bleu_score(BLEU_PREDS, BLEU_TARGET, n_gram=n_gram, smooth=smooth))
    assert abs(m - t) < 1e-5


def test_bleu_class_accumulation():
    metric = BLEUScore(n_gram=2, smooth=True)
    for p, t in zip(BLEU_PREDS, BLEU_TARGET):
        metric.update([p], [t])
    assert abs(float(metric.compute()) - float(bleu_score(BLEU_PREDS, BLEU_TARGET, n_gram=2, smooth=True))) < 1e-6


@needs_ref
@pytest.mark.parametrize("tokenize", ["none", "13a", "intl", "char"])
@pytest.mark.parametrize("lowercase", [False, True])
def test_sacre_bleu_vs_reference(tokenize, lowercase):
    preds = ["the cat is on the mat.", "Hello, World! it's 3.50 dollars"]
    target = [["there is a cat on the mat."], ["Hello world, it is 3.50 dollars!"]]
    m = float(sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=lowercase, smooth=True))
    t = float(ref.sacre_bleu_score(preds, target, tokenize=tokenize, lowercase=lowercase, smooth=True))
    assert abs(m - t) < 1e-5


@needs_ref
def test_sacre_bleu_zh_vs_reference():
    preds, target = ["猫在垫子上 the cat"], [["猫在垫子上面 a cat"]]
    m = float(sacre_bleu_score(preds, target, tokenize="zh", smooth=True, n_gram=2))
    t = float(ref.sacre_bleu_score(preds, target, tokenize="zh", smooth=True, n_gram=2))
    assert abs(m - t) < 1e-5


def test_sacre_bleu_class():
    preds = ["the cat is on the mat."]
    target = [["there is a cat on the mat."]]
    metric = SacreBLEUScore(tokenize="13a", smooth=True)
    metric.update(preds, target)
    expected = sacre_bleu_score(preds, target, tokenize="13a", smooth=True)
    assert abs(float(metric.compute()) - float(expected)) < 1e-6


def test_intl_tokenizer_fallback_matches_regex_path():
    import random, string

    random.seed(0)
    pool = string.ascii_letters + string.digits + ".,!?'\"$%+«»- ()[]@#&*;:~^|<>=/\\" + "éüñ中文猫"
    for _ in range(300):
        line = "".join(random.choice(pool) for _ in range(random.randint(0, 40)))
        a = _SacreBLEUTokenizer._tokenize_international(line)
        b = " ".join(_intl_tokenize_fallback(line).split())
        assert a == b, repr(line)


@needs_ref
@pytest.mark.parametrize("accumulate", ["best", "avg"])
@pytest.mark.parametrize("use_stemmer", [False, True])
def test_rouge_vs_reference(accumulate, use_stemmer):
    keys = ("rouge1", "rouge2", "rougeL")
    preds = ["My name is John", "The quick brown fox jumps over the lazy dog and runs away"]
    target = [
        ["Is your name John", "John is my name"],
        ["A quick brown fox jumped over the lazy dogs", "the fox runs away quickly"],
    ]
    m = rouge_score(preds, target, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    t = ref.rouge_score(preds, target, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=keys)
    for k in m:
        assert abs(float(m[k]) - float(t[k])) < 1e-6, k


def test_rouge_lsum_single_sentence_equals_rouge_l():
    m = rouge_score("My name is John", "Is your name John", rouge_keys=("rougeL", "rougeLsum"))
    assert abs(float(m["rougeLsum_fmeasure"]) - float(m["rougeL_fmeasure"])) < 1e-7
    assert abs(float(m["rougeLsum_fmeasure"]) - 0.5) < 1e-6


def test_rouge_class_accumulation():
    preds = ["My name is John", "The quick brown fox"]
    target = ["Is your name John", "The fast brown fox"]
    metric = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
    for p, t in zip(preds, target):
        metric.update(p, t)
    batch = rouge_score(preds, [[t] for t in target], rouge_keys=("rouge1", "rougeL"))
    out = metric.compute()
    for k in batch:
        assert abs(float(out[k]) - float(batch[k])) < 1e-6


@needs_ref
@pytest.mark.parametrize("n_char_order, n_word_order", [(6, 2), (6, 0), (4, 1)])
@pytest.mark.parametrize("whitespace", [False, True])
def test_chrf_vs_reference(n_char_order, n_word_order, whitespace):
    preds = ["the cat is on the mat", "Hello, World! don't panic"]
    target = [["there is a cat on the mat", "a cat is on the mat"], ["Hello world, do not panic!", "hello world"]]
    m = float(chrf_score(preds, target, n_char_order=n_char_order, n_word_order=n_word_order, whitespace=whitespace))
    t = float(
        ref.chrf_score(preds, target, n_char_order=n_char_order, n_word_order=n_word_order, whitespace=whitespace)
    )
    assert abs(m - t) < 1e-6


@needs_ref
def test_chrf_sentence_level_vs_reference():
    preds = ["the cat is on the mat", "Hello, World!"]
    target = [["there is a cat on the mat"], ["Hello world!"]]
    m, ms = chrf_score(preds, target, return_sentence_level_score=True)
    t, ts = ref.chrf_score(preds, target, return_sentence_level_score=True)
    assert abs(float(m) - float(t)) < 1e-6
    assert np.allclose(np.asarray(ms), ts.numpy(), atol=1e-6)


def test_chrf_class_accumulation():
    preds = ["the cat is on the mat", "hello there world"]
    target = [["there is a cat on the mat"], ["hello world"]]
    metric = CHRFScore()
    for p, t in zip(preds, target):
        metric.update([p], [t])
    assert abs(float(metric.compute()) - float(chrf_score(preds, target))) < 1e-6


@needs_ref
@pytest.mark.parametrize(
    "kwargs", [{}, {"normalize": True}, {"lowercase": False}, {"no_punctuation": True}]
)
def test_ter_vs_reference(kwargs):
    cases = [
        (["the cat is on the mat"], [["there is a cat on the mat", "a cat is on the mat"]]),
        (["a b c d e f", "hello there world"], [["b c d a e f", "f e d c b a"], ["hello world there"]]),
        (
            ["the new law will be passed by the parliament next week"],
            [["next week the parliament will pass the new law", "the new law will pass in parliament next week"]],
        ),
    ]
    for preds, target in cases:
        m = float(translation_edit_rate(preds, target, **kwargs))
        t = float(ref.translation_edit_rate(preds, target, **kwargs))
        assert abs(m - t) < 1e-6, (preds, kwargs)


def test_ter_class_accumulation():
    preds = ["the cat is on the mat", "hello there"]
    target = [["there is a cat on the mat"], ["hello world"]]
    metric = TranslationEditRate()
    for p, t in zip(preds, target):
        metric.update([p], [t])
    assert abs(float(metric.compute()) - float(translation_edit_rate(preds, target))) < 1e-6


@needs_ref
@pytest.mark.parametrize("rho", [0.3, 0.5])
def test_eed_vs_reference(rho):
    preds = ["this is the prediction", "here is an other sample"]
    target = ["this is the reference", "here is another one"]
    m = float(extended_edit_distance(preds, target, rho=rho))
    t = float(ref.extended_edit_distance(preds, target, rho=rho))
    assert abs(m - t) < 1e-6


@needs_ref
def test_eed_ja_vs_reference():
    preds, target = ["ｈｅｌｌｏ　ｗｏｒｌｄ"], [["hello world"]]
    m = float(extended_edit_distance(preds, target, language="ja"))
    t = float(ref.extended_edit_distance(preds, target, language="ja"))
    assert abs(m - t) < 1e-6


def test_eed_class_accumulation():
    preds = ["this is the prediction", "here is an other sample"]
    target = ["this is the reference", "here is another one"]
    metric = ExtendedEditDistance()
    for p, t in zip(preds, target):
        metric.update([p], [t])
    assert abs(float(metric.compute()) - float(extended_edit_distance(preds, target))) < 1e-6


@needs_ref
def test_squad_vs_reference():
    sq_p = [{"prediction_text": "1976", "id": "a"}, {"prediction_text": "the big dog", "id": "b"}]
    sq_t = [
        {"answers": {"answer_start": [1], "text": ["1976"]}, "id": "a"},
        {"answers": {"answer_start": [1], "text": ["a big dog", "big cat"]}, "id": "b"},
    ]
    m = squad(sq_p, sq_t)
    t = ref.squad(sq_p, sq_t)
    assert abs(float(m["f1"]) - float(t["f1"])) < 1e-4
    assert abs(float(m["exact_match"]) - float(t["exact_match"])) < 1e-4


def test_squad_class_accumulation():
    sq_p = [{"prediction_text": "1976", "id": "a"}, {"prediction_text": "wrong", "id": "b"}]
    sq_t = [
        {"answers": {"answer_start": [1], "text": ["1976"]}, "id": "a"},
        {"answers": {"answer_start": [1], "text": ["right"]}, "id": "b"},
    ]
    metric = SQuAD()
    for p, t in zip(sq_p, sq_t):
        metric.update(p, t)
    out = metric.compute()
    batch = squad(sq_p, sq_t)
    assert abs(float(out["f1"]) - float(batch["f1"])) < 1e-5
    assert abs(float(out["exact_match"]) - float(batch["exact_match"])) < 1e-5


@needs_ref
def test_perplexity_vs_reference():
    import torch

    g = torch.Generator().manual_seed(0)
    logits = torch.randn(2, 8, 5, generator=g)
    tgt = torch.randint(0, 5, (2, 8), generator=g)
    tgt[0, 6:] = -100
    m = float(perplexity(jnp.asarray(logits.numpy()), jnp.asarray(tgt.numpy()), ignore_index=-100))
    t = float(ref.perplexity(logits, tgt, ignore_index=-100))
    assert abs(m - t) < 1e-4


def test_perplexity_class_jit_path():
    import jax

    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(4, 6, 7).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 7, (4, 6)).astype(np.int32))
    metric = Perplexity(validate_args=False)
    update = jax.jit(metric.local_update)
    state = metric.init_state()
    state = update(state, logits[:2], target[:2])
    state = update(state, logits[2:], target[2:])
    got = float(metric.compute_from(state))
    want = float(perplexity(logits, target))
    assert abs(got - want) < 1e-4

    # eager class path agrees
    metric2 = Perplexity()
    metric2.update(logits, jnp.asarray(target, jnp.int32))
    assert abs(float(metric2.compute()) - want) < 1e-4


def test_perplexity_validation():
    with pytest.raises(ValueError, match="expected to have 3 dimensions"):
        perplexity(jnp.zeros((2, 3)), jnp.zeros((2, 3), jnp.int32))
    with pytest.raises(TypeError, match="integer dtype"):
        perplexity(jnp.zeros((2, 3, 4)), jnp.zeros((2, 3)))
