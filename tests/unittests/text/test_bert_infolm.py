"""BERTScore + InfoLM tests with deterministic fake encoders (no model downloads).

Reference test model: tests/unittests/text/test_bertscore.py / test_infolm.py use
real HF checkpoints; offline here, the oracle is the reference's own math driven
through its user-model path (dict inputs + ``user_forward_fn``).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.functional.text.bert import bert_score
from metrics_tpu.functional.text.infolm import (
    _InformationMeasure,
    _input_ids_idf,
    _tokens_idf,
    infolm,
    masked_lm_distribution,
)
from metrics_tpu.text import BERTScore, InfoLM

import sys, os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from helpers.reference import import_reference_text, reference_available  # noqa: E402

import_reference_text()  # sets up sys.path for `torchmetrics` imports inside tests
needs_ref = pytest.mark.skipif(not reference_available(), reason="reference tree not mounted")

_rng = np.random.RandomState(0)
EMB = _rng.randn(200, 16).astype(np.float32)
MLM_W = _rng.randn(50, 30).astype(np.float32)
SPECIAL = {"mask_token_id": 4, "pad_token_id": 0, "sep_token_id": 3, "cls_token_id": 2}

PREDS = ["the cat sat on the mat", "hello world"]
TARGET = ["a cat sat on a mat quietly", "hello there world"]


def fake_tokenize(texts, max_length=None):
    rows = [[101] + [hash(w) % 90 + 10 for w in t.split()] + [102] for t in texts]
    length = max_length or max(len(r) for r in rows)
    input_ids = np.zeros((len(rows), length), np.int64)
    mask = np.zeros((len(rows), length), np.int64)
    for i, r in enumerate(rows):
        input_ids[i, : len(r)] = r
        mask[i, : len(r)] = 1
    return input_ids, mask


def fake_encoder(sentences):
    ids, mask = fake_tokenize(sentences)
    return jnp.asarray(EMB[ids]), ids, mask


def mlm_tokenize(sentences, max_length):
    rows = [[2] + [hash(w) % 40 + 5 for w in s.split()] + [3] for s in sentences]
    input_ids = np.zeros((len(rows), max_length), np.int64)
    mask = np.zeros((len(rows), max_length), np.int64)
    for i, r in enumerate(rows):
        input_ids[i, : len(r)] = r
        mask[i, : len(r)] = 1
    return input_ids, mask


def mlm_logits_fn(input_ids, attention_mask):
    return jnp.asarray(MLM_W[np.asarray(input_ids) % 50])


@needs_ref
@pytest.mark.parametrize("idf", [False, True])
def test_bert_score_vs_reference(idf):
    import torch
    from torchmetrics.functional.text.bert import bert_score as ref_bert

    class FakeModel(torch.nn.Module):
        def forward(self, *a, **k):
            pass

    def fwd(model, batch):
        return torch.tensor(EMB[batch["input_ids"].numpy()])

    pi, pm = fake_tokenize(PREDS)
    ti, tm = fake_tokenize(TARGET)
    t = ref_bert(
        {"input_ids": torch.tensor(pi), "attention_mask": torch.tensor(pm)},
        {"input_ids": torch.tensor(ti), "attention_mask": torch.tensor(tm)},
        model=FakeModel(),
        user_forward_fn=fwd,
        idf=idf,
    )
    m = bert_score(PREDS, TARGET, encoder=fake_encoder, idf=idf)
    for k in ("precision", "recall", "f1"):
        assert np.allclose(np.asarray(m[k]), np.asarray(t[k]), atol=1e-5), k


def test_bert_score_class_accumulation():
    metric = BERTScore(encoder=fake_encoder, idf=True)
    for p, t in zip(PREDS, TARGET):
        metric.update([p], [t])
    out = metric.compute()
    batch = bert_score(PREDS, TARGET, encoder=fake_encoder, idf=True)
    for k in ("precision", "recall", "f1"):
        assert np.allclose(np.asarray(out[k]), np.asarray(batch[k]), atol=1e-6)
    metric.reset()
    assert len(metric._preds_corpus) == 0


def test_bert_score_rescale_with_baseline():
    out = bert_score(PREDS, TARGET, encoder=fake_encoder, rescale_with_baseline=True, baseline=[0.5, 0.5, 0.5])
    raw = bert_score(PREDS, TARGET, encoder=fake_encoder)
    assert np.allclose(np.asarray(out["f1"]), (np.asarray(raw["f1"]) - 0.5) / 0.5, atol=1e-6)


@needs_ref
@pytest.mark.parametrize("idf", [False, True])
def test_infolm_distribution_vs_reference(idf):
    import torch
    from torchmetrics.functional.text.infolm import _get_batch_distribution

    class FakeOut:
        def __init__(self, logits):
            self.logits = logits

    class FakeModel:
        def __call__(self, input_ids, attention_mask):
            return FakeOut(torch.tensor(MLM_W[input_ids.numpy() % 50]))

    p_ids, p_mask = mlm_tokenize(PREDS, 10)
    if idf:
        idf_map = _tokens_idf(p_ids)
        p_idf = _input_ids_idf(p_ids, idf_map)
        batch = {
            "input_ids": torch.tensor(p_ids),
            "attention_mask": torch.tensor(p_mask),
            "input_ids_idf": torch.tensor(p_idf),
        }
    else:
        p_idf = None
        batch = {"input_ids": torch.tensor(p_ids), "attention_mask": torch.tensor(p_mask)}
    ref_dist = _get_batch_distribution(FakeModel(), batch, 0.25, idf, SPECIAL).numpy()
    my_dist = np.asarray(masked_lm_distribution(p_ids, p_mask, mlm_logits_fn, SPECIAL, 0.25, p_idf))
    assert np.allclose(my_dist, ref_dist, atol=1e-5)


@needs_ref
@pytest.mark.parametrize(
    "name, alpha, beta",
    [
        ("kl_divergence", None, None),
        ("alpha_divergence", 0.5, None),
        ("beta_divergence", None, 0.5),
        ("ab_divergence", 0.5, 0.3),
        ("renyi_divergence", 0.5, None),
        ("l1_distance", None, None),
        ("l2_distance", None, None),
        ("l_infinity_distance", None, None),
        ("fisher_rao_distance", None, None),
    ],
)
def test_infolm_measures_vs_reference(name, alpha, beta):
    import torch
    from torchmetrics.functional.text.infolm import _InformationMeasure as RefIM

    rng = np.random.RandomState(7)
    p = rng.dirichlet(np.ones(30), size=4).astype(np.float32)
    t = rng.dirichlet(np.ones(30), size=4).astype(np.float32)
    mine = np.asarray(_InformationMeasure(name, alpha, beta)(jnp.asarray(p), jnp.asarray(t)))
    theirs = RefIM(name, alpha, beta)(torch.tensor(p), torch.tensor(t)).numpy()
    assert np.allclose(mine, theirs, atol=1e-4), name


def test_infolm_measure_validation():
    with pytest.raises(ValueError, match="alpha"):
        _InformationMeasure("alpha_divergence", None)
    with pytest.raises(ValueError, match="beta"):
        _InformationMeasure("beta_divergence", None, None)
    with pytest.raises(ValueError, match="information_measure"):
        _InformationMeasure("not_a_measure")


def test_infolm_class_accumulation():
    kwargs = dict(
        logits_fn=mlm_logits_fn, tokenizer_fn=mlm_tokenize, special_tokens_map=SPECIAL, idf=True, max_length=10
    )
    metric = InfoLM(**kwargs)
    for p, t in zip(PREDS, TARGET):
        metric.update([p], [t])
    out = float(metric.compute())
    batch = float(infolm(PREDS, TARGET, **kwargs))
    assert abs(out - batch) < 1e-6
