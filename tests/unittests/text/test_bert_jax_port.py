"""Differential test: pure-JAX BERT port vs the real HF torch module.

Random weights, tiny config — the architecture (embeddings, post-LN attention
blocks, masking, position-id schemes) is what is being verified, exactly like
the Inception/LPIPS ports (tests/unittests/image/test_inception_model.py).
"""
import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from metrics_tpu.models.bert import bert_forward, bert_position_ids, params_from_state_dict

HIDDEN = 64
HEADS = 4
LAYERS = 2
VOCAB = 50
SEQ = 12
BATCH = 3


def _rand_inputs(seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, VOCAB, (BATCH, SEQ)).astype(np.int64)
    mask = np.ones((BATCH, SEQ), np.int64)
    mask[0, 8:] = 0
    mask[2, 5:] = 0
    ids[mask == 0] = 1  # pad token
    return ids, mask


@pytest.mark.parametrize("variant", ["bert", "roberta"])
def test_jax_bert_matches_hf_torch(variant):
    if variant == "bert":
        config = transformers.BertConfig(
            vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
            intermediate_size=4 * HIDDEN, max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0,
        )
        ref = transformers.BertModel(config).eval()
        eps = config.layer_norm_eps
    else:
        config = transformers.RobertaConfig(
            vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
            intermediate_size=4 * HIDDEN, max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, pad_token_id=1,
        )
        ref = transformers.RobertaModel(config).eval()
        eps = config.layer_norm_eps

    state = {k: v.numpy() for k, v in ref.state_dict().items()}
    params = params_from_state_dict(state)

    ids, mask = _rand_inputs()
    pos = bert_position_ids(mask, variant)
    ours = np.asarray(
        bert_forward(params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos), HEADS, float(eps))
    )
    with torch.no_grad():
        theirs = ref(torch.from_numpy(ids), attention_mask=torch.from_numpy(mask)).last_hidden_state.numpy()

    # compare attended positions only (HF computes garbage embeddings for pads too,
    # but BERTScore masks them; our pad rows differ via the position-id freeze)
    m = mask.astype(bool)
    np.testing.assert_allclose(ours[m], theirs[m], atol=2e-4)


def test_jax_encoder_plugs_into_bert_score(tmp_path):
    """End-to-end: converted checkpoint + fake tokenizer -> BERTScore numbers."""
    config = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
        intermediate_size=4 * HIDDEN, max_position_embeddings=64,
    )
    ref = transformers.BertModel(config).eval()
    ckpt = tmp_path / "bert.pth"
    torch.save(ref.state_dict(), str(ckpt))

    class _Tok:
        def __call__(self, sentences, padding=True, truncation=True, max_length=512, return_tensors="np"):
            ids = [[2] + [(hash(w) % (VOCAB - 3)) + 3 for w in s.split()][: max_length - 2] + [0] for s in sentences]
            longest = max(len(i) for i in ids)
            out = np.ones((len(ids), longest), np.int64)
            mask = np.zeros((len(ids), longest), np.int64)
            for r, row in enumerate(ids):
                out[r, : len(row)] = row
                mask[r, : len(row)] = 1
            return {"input_ids": out, "attention_mask": mask}

    from metrics_tpu.functional.text.bert import bert_score
    from metrics_tpu.models.bert import jax_bert_encoder

    encoder = jax_bert_encoder(str(ckpt), _Tok(), variant="bert", num_heads=HEADS)
    res = bert_score(["the cat sat on the mat", "hello world"], ["a cat sat on the mat", "hello world"], encoder=encoder)
    f1 = np.asarray(res["f1"])
    assert f1.shape == (2,) and np.all(np.isfinite(f1))
    assert float(f1[1]) == pytest.approx(1.0, abs=1e-4)  # identical sentence


@pytest.mark.parametrize("variant", ["bert", "roberta"])
def test_jax_mlm_head_matches_hf_torch(variant):
    from metrics_tpu.models.bert import bert_mlm_logits, mlm_params_from_state_dict

    if variant == "bert":
        config = transformers.BertConfig(
            vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
            intermediate_size=4 * HIDDEN, max_position_embeddings=64,
        )
        ref = transformers.BertForMaskedLM(config).eval()
        eps = config.layer_norm_eps
    else:
        config = transformers.RobertaConfig(
            vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
            intermediate_size=4 * HIDDEN, max_position_embeddings=64, pad_token_id=1,
        )
        ref = transformers.RobertaForMaskedLM(config).eval()
        eps = config.layer_norm_eps

    params = mlm_params_from_state_dict({k: v.numpy() for k, v in ref.state_dict().items()})
    ids, mask = _rand_inputs(3)
    pos = bert_position_ids(mask, variant)
    ours = np.asarray(
        bert_mlm_logits(params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos), HEADS, float(eps))
    )
    with torch.no_grad():
        theirs = ref(torch.from_numpy(ids), attention_mask=torch.from_numpy(mask)).logits.numpy()
    m = mask.astype(bool)
    np.testing.assert_allclose(ours[m], theirs[m], atol=3e-4)


def test_jax_mlm_plugs_into_infolm(tmp_path):
    from metrics_tpu.functional.text.infolm import infolm
    from metrics_tpu.models.bert import jax_mlm_logits_fn

    config = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
        intermediate_size=4 * HIDDEN, max_position_embeddings=64,
    )
    ref = transformers.BertForMaskedLM(config).eval()
    ckpt = tmp_path / "mlm.pth"
    torch.save(ref.state_dict(), str(ckpt))

    logits_fn = jax_mlm_logits_fn(str(ckpt), variant="bert", num_heads=HEADS)

    def tokenize(sentences, max_length=None):
        ids = [[2] + [(hash(w) % (VOCAB - 5)) + 5 for w in s.split()] + [3] for s in sentences]
        longest = max(len(i) for i in ids)
        out = np.zeros((len(ids), longest), np.int64)
        mask = np.zeros((len(ids), longest), np.int64)
        for r, row in enumerate(ids):
            out[r, : len(row)] = row
            mask[r, : len(row)] = 1
        return out, mask

    score = infolm(
        ["the cat sat on the mat"],
        ["a cat sat on a mat"],
        logits_fn=logits_fn,
        tokenizer_fn=tokenize,
        special_tokens_map={"pad_token_id": 0, "cls_token_id": 2, "sep_token_id": 3, "mask_token_id": 4},
        information_measure="kl_divergence",
    )
    assert np.isfinite(float(np.asarray(score)))


def test_mlm_tied_decoder_fallback():
    """Checkpoints saved via save_pretrained strip tied weights: the loader must
    tie the decoder to the word embeddings and still match HF exactly."""
    from metrics_tpu.models.bert import bert_mlm_logits, mlm_params_from_state_dict

    config = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=LAYERS, num_attention_heads=HEADS,
        intermediate_size=4 * HIDDEN, max_position_embeddings=64,
    )
    ref = transformers.BertForMaskedLM(config).eval()
    state = {k: v.numpy() for k, v in ref.state_dict().items()}
    state.pop("cls.predictions.decoder.weight")  # simulate tied-weight stripping
    params = mlm_params_from_state_dict(state)

    ids, mask = _rand_inputs(4)
    pos = bert_position_ids(mask, "bert")
    ours = np.asarray(
        bert_mlm_logits(params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos), HEADS, float(config.layer_norm_eps))
    )
    with torch.no_grad():
        theirs = ref(torch.from_numpy(ids), attention_mask=torch.from_numpy(mask)).logits.numpy()
    m = mask.astype(bool)
    np.testing.assert_allclose(ours[m], theirs[m], atol=3e-4)
